"""Fused Pallas TPU kernels for the CIFAR-geometry ResNet conv blocks.

WHY: the compiled step is bandwidth-bound at 0.85 of its measured mixed
roofline, and the residual lives in XLA's conv emitter — conv fusions carry
82% of step time (49.0 of 59.5 ms) at 69% of peak HBM BW, with the stage-1
BN-backward and residual/ReLU fusions topping the per-fusion traffic table
(docs/PERF.md round 4, ``docs/evidence/xplane_bw_r4.json`` fusion.81/74/75
and the fusion.160/161/162 trio). PERF.md's own conclusion: raising MFU
"requires reducing bytes, not faster matmuls". At 32x32 the activations are
too thin per byte for XLA's generic conv emitter, and every inter-op
boundary (conv -> BN stats -> normalize/ReLU -> conv -> BN -> residual add)
funds a full HBM round trip of a ``[2B, H, W, C]`` activation array.

WHAT: four fused ops that keep those boundaries in VMEM/registers —

- ``fused_conv_bn_relu``: the ResNet stem (conv3x3/s1 + train-mode BN +
  ReLU) as one kernel;
- ``fused_basic_block``: the identity-shortcut BasicBlock
  (conv3x3 -> BN -> ReLU -> conv3x3 -> BN -> +residual -> ReLU) as one
  kernel, forward and custom-VJP backward;
- ``fused_projection_block``: the projection-shortcut / stride-2
  BasicBlock — the main path plus the 1x1-conv-BN shortcut and the
  add-ReLU in the same sequential grid (the shortcut's strided 1x1 is a
  slice of the already-resident x tile, so it adds no HBM traversals);
- ``fused_bottleneck_block``: the rn50-class Bottleneck
  (1x1 -> 3x3/s -> 1x1, expansion 4) with identity or fused-projection
  shortcut; its 1x1 convs are pure ``[N*H*W, C] @ [C, C']`` contractions
  needing no im2col scratch.

Every op admits fp32 and bf16 compute (inferred from ``x.dtype`` or via
``compute_dtype``): bf16 carries activations/weights at half the HBM
bytes and feeds bf16 MXU matmuls, while every matmul accumulates fp32
(``preferred_element_type``) and BN statistics / folded scale-shift rows
/ dW accumulators / running stats stay fp32 exactly as models/norm.py
pins — so the param/variable trees are dtype- and impl-independent and
checkpoints keep swapping impls.

HOW: the conv is an MXU matmul over VMEM-resident im2col tiles (the
crop-as-matmul precedent, docs/PERF.md 227x): each 3x3 window offset is one
``[bn*H*W, Cin] @ [Cin, Cout]`` contraction against a spatially-shifted
slice of a zero-padded VMEM scratch tile. Train-mode BN needs batch
statistics BEFORE it can normalize, so each kernel runs a sequential
PHASE-major grid ``(phases, batch_tiles)`` over the same input tiles:
stats phases accumulate per-channel sums in VMEM scratch and the emit
phase recomputes the convs in-register with the now-known scale/shift —
a FLOPs-for-bytes trade (the convs here are bandwidth-bound, the MXU is
62% idle). Per-activation-array HBM traffic of the block forward drops
from the ~9 traversals XLA's fusion decomposition pays to
``FWD_HBM_TRAVERSALS_BLOCK`` (3 reads of x + 1 write of out); the backward
keeps only O(C) residuals (saved batch moments) and recomputes everything
else, ``BWD_HBM_TRAVERSALS_BLOCK`` vs the ~12 of the separate BN-backward /
conv-backward / residual fusions.

BN semantics are models/norm.py's torch-matching whole-batch train mode:
biased variance for normalization, fp32 statistics, running-stat update
(UNBIASED variance, momentum-weighted) applied by the caller
(``models.norm.running_stats_update``) from the returned batch moments —
the kernels never touch running stats. Cross-replica semantics are
preserved by construction: the kernel computes stats over exactly the
array it is given (per-device = whole batch on the single-chip mesh the
resolution ladder admits; grouped/multi-device BN configurations are
gated off in ``supports_block``/``resolve_conv_impl``).

The VJP treats the returned batch moments as ancillary (their cotangents
are discarded): they feed only the mutable running-stat buffers, exactly
like Flax's BN variables, while the normalization statistics' gradient
contribution is fully inside the standard train-mode BN backward the
kernel implements.

``interpret=True`` runs the Pallas interpreter — the CPU path used by the
tier-1 parity suite (tests/test_pallas_conv.py) and by ``--conv_impl
pallas`` on non-TPU backends (slow; for tests and the checkpoint
round-trip smoke, not for training throughput).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Per-activation-array HBM traversals of ONE block apply, by path. The
# Pallas counts are properties of the kernels' BlockSpecs below (each
# phase re-reads its input tiles; outputs are written once via the
# phase-gated index maps); the XLA counts are read off the round-4 xplane
# fusion decomposition (docs/PERF.md: conv kernel writes y1; BN-stat
# fusion reads y1; normalize+ReLU fusion reads y1, writes a1; conv reads
# a1, writes y2; BN-stat reads y2; normalize+residual+ReLU fusion reads
# y2 + x, writes out — and the backward's fusion.81/74/75-class stat +
# dx chains). scripts/convblock_ab.py's CPU proxy injects one modeled
# delay per traversal; docs/PERF.md round 15 carries the derivation.
FWD_HBM_TRAVERSALS_BLOCK = 4   # 3 phase-reads of x + 1 write of out
FWD_HBM_TRAVERSALS_XLA = 9    # see derivation above
BWD_HBM_TRAVERSALS_BLOCK = 7   # 3 reads of x + 3 reads of g + 1 write of dx
BWD_HBM_TRAVERSALS_XLA = 12   # BN-bwd stat reads x2, dx chains, residual adds

# Projection-shortcut BasicBlock (conv-BN-ReLU-conv-BN + 1x1-conv-BN
# shortcut + add-ReLU): the shortcut's 1x1 conv and BN ride the SAME
# phase-reads of x the main path already pays (the strided view is a
# slice of the tile in VMEM), so the Pallas traversal counts match the
# identity block. The XLA decomposition pays three extra fusions each
# way (shortcut conv, shortcut BN-stat, shortcut normalize folded into
# the residual add) — derivation in docs/PERF.md round 19.
FWD_HBM_TRAVERSALS_PROJ = 4
FWD_HBM_TRAVERSALS_PROJ_XLA = 12
BWD_HBM_TRAVERSALS_PROJ = 7
BWD_HBM_TRAVERSALS_PROJ_XLA = 16

# Bottleneck (1x1 -> 3x3 -> 1x1, expansion 4): four phases each re-read
# x (+1 output write forward; four re-reads of x, four of g, +1 dx write
# backward). Its 1x1 convs are pure [N*H*W, C] @ [C, C'] contractions
# with no im2col scratch, so the per-phase resident set stays small
# despite the 4x-wide output. XLA's decomposition pays one conv + one
# BN-stat + one normalize boundary per stage plus the residual trio —
# derivation in docs/PERF.md round 19.
FWD_HBM_TRAVERSALS_BOTTLENECK = 5
FWD_HBM_TRAVERSALS_BOTTLENECK_XLA = 14
BWD_HBM_TRAVERSALS_BOTTLENECK = 9
BWD_HBM_TRAVERSALS_BOTTLENECK_XLA = 18

# VMEM budget the geometry gate admits against (bytes). Deliberately
# conservative vs the ~16 MB/core physical VMEM: the estimate below is a
# model of the kernel's resident set, not the compiler's exact allocation.
VMEM_BUDGET = 10 * 1024 * 1024


# Compute dtypes the kernels admit. Activations/weights are carried in
# the compute dtype; BN statistics, folded scale/shift rows, matmul
# accumulators (``preferred_element_type``) and dW accumulators stay
# fp32 regardless, matching models/norm.py's fp32-stats pin.
_COMPUTE_DTYPES = (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))


def _itemsize(dtype) -> Optional[int]:
    """Bytes per element of an admitted compute dtype, else None."""
    dt = jnp.dtype(dtype)
    return dt.itemsize if dt in _COMPUTE_DTYPES else None


def _pick_tile(n: int, fits) -> Optional[int]:
    """Largest batch-tile size (<= 8) dividing ``n`` for which ``fits(bn)``
    holds, or None."""
    for bn in (8, 4, 2, 1):
        if n % bn:
            continue
        if fits(bn):
            return bn
    return None


def _pick_batch_tile(n: int, h: int, w: int, cin: int, cout: int,
                     *, residual: bool, itemsize: int = 4) -> Optional[int]:
    return _pick_tile(
        n,
        lambda bn: _vmem_estimate(
            bn, h, w, cin, cout, residual=residual, itemsize=itemsize
        ) <= VMEM_BUDGET,
    )


def _vmem_estimate(bn: int, h: int, w: int, cin: int, cout: int,
                   *, residual: bool, itemsize: int = 4) -> int:
    """Modeled peak VMEM bytes of the WORST kernel (the backward) at this
    geometry: padded scratch tiles, weight blocks (incl. the flipped
    copies), dW accumulators, and a conservative multiplier for the
    per-step activation values the compiler keeps live. ``itemsize`` is
    the compute dtype's width — pads and weight blocks are carried in it;
    accumulators and live fp32 intermediates are not."""
    pad = bn * (h + 2) * (w + 2) * itemsize
    tile = bn * h * w * 4
    if not residual:  # stem: one conv, cin != cout
        pads = 2 * pad * max(cin, cout)  # xpad + gpad
        weights = 2 * 9 * cin * cout * itemsize  # k + kt
        dw_acc = 9 * cin * cout * 4
        live = 6 * tile * max(cin, cout)
    else:  # basic block: two cin==cout convs
        pads = 3 * pad * cout            # xpad + apad + gpad
        weights = 4 * 9 * cout * cout * itemsize  # k1, k2, k1t, k2t
        dw_acc = 2 * 9 * cout * cout * 4
        live = 8 * tile * cout
    return pads + weights + dw_acc + live


def _vmem_estimate_proj(bn: int, hi: int, wi: int, cin: int, c: int,
                        stride: int, itemsize: int = 4) -> int:
    """Modeled backward resident set of the projection-shortcut block:
    one input-resolution x pad, two output-resolution pads (a1 / dy2),
    one input-resolution pad for the dilated dy1 (stride-2 dx), the
    weight blocks incl. flipped copies and the 1x1 shortcut, fp32 dW
    accumulators, and the live fp32 intermediates at the wider of the
    two resolutions."""
    ho, wo = hi // stride, wi // stride
    pad_in = bn * (hi + 2) * (wi + 2) * itemsize
    pad_out = bn * (ho + 2) * (wo + 2) * itemsize
    pads = pad_in * cin + 2 * pad_out * c + pad_in * c
    weights = (2 * 9 * cin * c + 2 * 9 * c * c + cin * c) * itemsize
    dw_acc = (9 * cin * c + 9 * c * c + cin * c) * 4
    live = 8 * bn * max(hi * wi * cin, ho * wo * c) * 4
    return pads + weights + dw_acc + live


def _vmem_estimate_bottleneck(bn: int, hi: int, wi: int, cin: int,
                              planes: int, stride: int, proj: bool,
                              itemsize: int = 4) -> int:
    """Modeled backward resident set of the Bottleneck: its 1x1 convs are
    pure [N*H*W, C] @ [C, C'] contractions needing NO im2col pad scratch,
    so only the middle 3x3 pays two input-resolution pads (a1 / dilated
    dy2); weights incl. the flipped 3x3 copy and the optional 1x1
    shortcut, fp32 dW accumulators, and live fp32 intermediates at the
    wider of input resolution (cin/planes channels) and output resolution
    (4*planes channels)."""
    ho, wo = hi // stride, wi // stride
    pad_in = bn * (hi + 2) * (wi + 2) * itemsize
    pads = 2 * pad_in * planes
    weights = (cin * planes + 2 * 9 * planes * planes
               + planes * 4 * planes
               + (cin * 4 * planes if proj else 0)) * itemsize
    dw_acc = (cin * planes + 9 * planes * planes + planes * 4 * planes
              + (cin * 4 * planes if proj else 0)) * 4
    live = 8 * bn * max(hi * wi * max(cin, planes), ho * wo * 4 * planes) * 4
    return pads + weights + dw_acc + live


def supports_block(n: int, h: int, w: int, c: int, *, stride: int = 1,
                   in_channels: Optional[int] = None,
                   dtype=jnp.float32) -> bool:
    """True if a fused BasicBlock kernel admits this geometry.

    ``h``/``w`` are the block's INPUT spatial dims (the pre-stride shape —
    the convention `models.resnet.fused_site_plan` single-sources).
    Identity-shortcut sites (stride 1, in==out channels) use the identity
    kernel; stride-2 and/or channel-changing sites use the
    projection-shortcut kernel, which additionally requires even spatial
    dims for stride 2 (the kernel's dilated transposed-conv backward
    assumes ho == h // 2 exactly)."""
    itemsize = _itemsize(dtype)
    if itemsize is None:
        return False
    cin = c if in_channels is None else in_channels
    if stride not in (1, 2):
        return False
    if h < 3 or w < 3 or n < 1 or c < 1 or cin < 1:
        return False
    if stride == 1 and cin == c:
        return _pick_batch_tile(
            n, h, w, c, c, residual=True, itemsize=itemsize
        ) is not None
    if stride == 2 and (h % 2 or w % 2):
        return False
    return _pick_tile(
        n,
        lambda bn: _vmem_estimate_proj(
            bn, h, w, cin, c, stride, itemsize
        ) <= VMEM_BUDGET,
    ) is not None


def supports_stem(n: int, h: int, w: int, cin: int, cout: int,
                  *, dtype=jnp.float32) -> bool:
    """True if the fused stem kernel admits this geometry (conv3x3/s1)."""
    itemsize = _itemsize(dtype)
    if itemsize is None:
        return False
    if h < 3 or w < 3 or n < 1 or cin < 1 or cout < 1:
        return False
    return _pick_batch_tile(
        n, h, w, cin, cout, residual=False, itemsize=itemsize
    ) is not None


def supports_bottleneck(n: int, h: int, w: int, planes: int, *,
                        stride: int = 1, in_channels: int,
                        dtype=jnp.float32) -> bool:
    """True if the fused Bottleneck kernel (1x1 -> 3x3/s -> 1x1,
    expansion 4) admits this geometry. ``h``/``w`` are the block's INPUT
    spatial dims; identity sites (stride 1, in == 4*planes) skip the
    shortcut conv, all others use the fused 1x1-conv-BN projection."""
    itemsize = _itemsize(dtype)
    if itemsize is None:
        return False
    if stride not in (1, 2):
        return False
    if h < 3 or w < 3 or n < 1 or planes < 1 or in_channels < 1:
        return False
    if stride == 2 and (h % 2 or w % 2):
        return False
    proj = stride != 1 or in_channels != 4 * planes
    return _pick_tile(
        n,
        lambda bn: _vmem_estimate_bottleneck(
            bn, h, w, in_channels, planes, stride, proj, itemsize
        ) <= VMEM_BUDGET,
    ) is not None


def _vmem_spec(block_shape=None, index_map=None):
    if block_shape is None:
        return pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)


def _fill_pad(pad_ref, x):
    """Zero-pad ``x`` by 1 pixel on each spatial edge into VMEM scratch,
    cast to the scratch's (compute) dtype."""
    pad_ref[:] = jnp.zeros(pad_ref.shape, pad_ref.dtype)
    pad_ref[:, 1:-1, 1:-1, :] = x.astype(pad_ref.dtype)


def _win(pv, di: int, dj: int, ho: int, wo: int, stride: int):
    """The (di, dj) 3x3-window view of a padded tile VALUE at the given
    stride: output position o reads padded input index ``stride*o + d``."""
    if stride == 1:
        return pv[:, di:di + ho, dj:dj + wo, :]
    return pv[:, di:di + stride * ho:stride, dj:dj + stride * wo:stride, :]


def _conv3x3(pad_ref, w, ho: int, wo: int, stride: int = 1):
    """3x3 conv (pad 1, stride ``stride``) as 9 shifted MXU matmuls over
    the padded VMEM tile.

    ``pad_ref``: scratch ref ``[bn, hi+2, wi+2, cin]`` (already filled);
    ``w``: kernel VALUE ``[3, 3, cin, cout]``; ``ho``/``wo`` the OUTPUT
    spatial dims (``hi // stride``). Each window offset is one
    ``[bn*ho*wo, cin] @ [cin, cout]`` contraction with fp32 accumulation
    (``preferred_element_type``) — the im2col matrix is never
    materialized, only its (strided) shifted views are read back out of
    the same padded tile.
    """
    bn, _, _, cin = pad_ref.shape
    cout = w.shape[3]
    pv = pad_ref[:]
    acc = None
    for di in range(3):
        for dj in range(3):
            xs = _win(pv, di, dj, ho, wo, stride).reshape(bn * ho * wo, cin)
            t = jnp.dot(xs, w[di, dj], preferred_element_type=jnp.float32)
            acc = t if acc is None else acc + t
    return acc.reshape(bn, ho, wo, cout)


def _dw_accumulate(dw_ref, pad_ref, dy, ho: int, wo: int, stride: int = 1):
    """dW[di,dj] += x_window(di,dj)^T @ dy for all 9 offsets, into the
    ``[9*cin, cout]`` fp32 scratch accumulator. ``dy`` is rounded to the
    pad's compute dtype first (the XLA cast-VJP boundary)."""
    bn, _, _, cin = pad_ref.shape
    cout = dy.shape[3]
    pv = pad_ref[:]
    dyf = dy.reshape(bn * ho * wo, cout).astype(pad_ref.dtype)
    for di in range(3):
        for dj in range(3):
            xs = _win(pv, di, dj, ho, wo, stride).reshape(bn * ho * wo, cin)
            k = di * 3 + dj
            dw_ref[k * cin:(k + 1) * cin, :] += jnp.dot(
                xs.T, dyf, preferred_element_type=jnp.float32
            )


def _mm(v, w2):
    """1x1 conv as a pure ``[bn*h*w, cin] @ [cin, cout]`` MXU contraction
    with fp32 accumulation (no im2col scratch needed)."""
    bn, h, w, cin = v.shape
    out = jnp.dot(
        v.reshape(bn * h * w, cin), w2, preferred_element_type=jnp.float32
    )
    return out.reshape(bn, h, w, w2.shape[1])


def _dilate2(v):
    """Zero-dilate a ``[bn, ho, wo, c]`` value by 2 in both spatial dims:
    ``out[:, 2i, 2j] = v[:, i, j]``, zeros elsewhere — the scatter of a
    stride-2 transposed conv, built from stack+reshape (no strided
    stores)."""
    bn, ho, wo, c = v.shape
    z = jnp.zeros_like(v)
    a = jnp.stack([v, z], axis=2).reshape(bn, 2 * ho, wo, c)
    za = jnp.zeros_like(a)
    return jnp.stack([a, za], axis=3).reshape(bn, 2 * ho, 2 * wo, c)


def _channel_sums(v, c: int):
    """``(1, C)`` per-channel sum over (batch-tile, H, W)."""
    return jnp.sum(v.reshape(-1, c), axis=0, keepdims=True)


def _flip_transpose(k):
    """Spatially-flipped, channel-transposed kernel: the weight of the
    transposed conv that computes dx from dy (computed OUTSIDE the kernel;
    O(9*Cin*Cout) bytes)."""
    return jnp.transpose(k[::-1, ::-1, :, :], (0, 1, 3, 2))


# ---------------------------------------------------------------------------
# Fused stem: conv3x3/s1 + train-mode BN + ReLU.
# ---------------------------------------------------------------------------


def _stem_fwd_kernel(
    x_ref, k_ref, g_ref, b_ref,
    out_ref, m_ref, v_ref,
    xpad, acc_s, acc_q, sc_s, sc_t,
    *, h: int, w: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    cout = out_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        acc_s[:] = jnp.zeros_like(acc_s)
        acc_q[:] = jnp.zeros_like(acc_q)

    # stage-1 finalize: batch moments -> folded scale/shift, once, before
    # the first emit-phase tile consumes them
    @pl.when((p == 1) & (i == 0))
    def _():
        m = acc_s[:] / count
        v = acc_q[:] / count - m * m  # biased (norm.py convention)
        m_ref[:] = m
        v_ref[:] = v
        s = g_ref[:] * jax.lax.rsqrt(v + eps)
        sc_s[:] = s
        sc_t[:] = b_ref[:] - m * s

    _fill_pad(xpad, x_ref[:])
    y = _conv3x3(xpad, k_ref[:], h, w)

    @pl.when(p == 0)
    def _():
        acc_s[:] += _channel_sums(y, cout)
        acc_q[:] += _channel_sums(jnp.square(y), cout)

    @pl.when(p == 1)
    def _():
        out_ref[:] = jnp.maximum(y * sc_s[:] + sc_t[:], 0.0).astype(
            out_ref.dtype
        )


def _stem_bwd_kernel(
    x_ref, k_ref, kt_ref, g_ref, b_ref, m_ref, v_ref, gout_ref,
    dx_ref, dw_ref, dg_ref, db_ref,
    xpad, gpad, dw_acc, acc_db, acc_dg,
    *, h: int, w: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    nt = pl.num_programs(1)
    cin = x_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        acc_db[:] = jnp.zeros_like(acc_db)
        acc_dg[:] = jnp.zeros_like(acc_dg)
        dw_acc[:] = jnp.zeros_like(dw_acc)

    # recompute the tile's forward from the saved batch moments
    m, v, g = m_ref[:], v_ref[:], g_ref[:]
    rs = jax.lax.rsqrt(v + eps)
    _fill_pad(xpad, x_ref[:])
    y = _conv3x3(xpad, k_ref[:], h, w)
    yh = (y - m) * rs
    pre = yh * g + b_ref[:]
    dp = gout_ref[:].astype(jnp.float32) * (pre > 0.0)

    @pl.when(p == 0)
    def _():
        acc_db[:] += _channel_sums(dp, dp.shape[3])
        acc_dg[:] += _channel_sums(dp * yh, dp.shape[3])

    @pl.when(p == 1)
    def _():
        # standard train-mode BN backward (biased variance): the batch
        # moments' own gradient contribution is the two mean-subtractions
        dy = rs * g * (dp - acc_db[:] / count - yh * acc_dg[:] / count)
        _dw_accumulate(dw_acc, xpad, dy, h, w)
        _fill_pad(gpad, dy)
        dx_ref[:] = _conv3x3(gpad, kt_ref[:], h, w).astype(dx_ref.dtype)

    @pl.when((p == 1) & (i == nt - 1))
    def _():
        dw_ref[:] = dw_acc[:].reshape(3, 3, cin, dw_ref.shape[3]).astype(
            dw_ref.dtype
        )
        dg_ref[:] = acc_dg[:]
        db_ref[:] = acc_db[:]


def _stem_call(x, k, g, b, eps, interpret, bn):
    n, h, w, cin = x.shape
    cout = k.shape[3]
    nt = n // bn
    count = float(n * h * w)
    kernel = functools.partial(
        _stem_fwd_kernel, h=h, w=w, count=count, eps=eps
    )
    tile = _vmem_spec((bn, h, w, cin), lambda p, i: (i, 0, 0, 0))
    out_tile = _vmem_spec(
        (bn, h, w, cout), lambda p, i: ((p == 1) * i, 0, 0, 0)
    )
    full = _vmem_spec((3, 3, cin, cout), lambda p, i: (0, 0, 0, 0))
    row = _vmem_spec((1, cout), lambda p, i: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(2, nt),
        in_specs=[tile, full, row, row],
        out_specs=[out_tile, row, row],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, w, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, h + 2, w + 2, cin), x.dtype),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )(x, k, g[None, :], b[None, :])


def _stem_bwd_call(x, k, g, b, m, v, gout, eps, interpret, bn):
    n, h, w, cin = x.shape
    cout = k.shape[3]
    nt = n // bn
    count = float(n * h * w)
    kernel = functools.partial(
        _stem_bwd_kernel, h=h, w=w, count=count, eps=eps
    )
    in_tile = _vmem_spec((bn, h, w, cin), lambda p, i: (i, 0, 0, 0))
    g_tile = _vmem_spec((bn, h, w, cout), lambda p, i: (i, 0, 0, 0))
    dx_tile = _vmem_spec(
        (bn, h, w, cin), lambda p, i: ((p == 1) * i, 0, 0, 0)
    )
    kfull = _vmem_spec((3, 3, cin, cout), lambda p, i: (0, 0, 0, 0))
    ktfull = _vmem_spec((3, 3, cout, cin), lambda p, i: (0, 0, 0, 0))
    row = _vmem_spec((1, cout), lambda p, i: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(2, nt),
        in_specs=[in_tile, kfull, ktfull, row, row, row, row, g_tile],
        out_specs=[dx_tile, kfull, row, row],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, w, cin), x.dtype),
            jax.ShapeDtypeStruct((3, 3, cin, cout), k.dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, h + 2, w + 2, cin), x.dtype),
            pltpu.VMEM((bn, h + 2, w + 2, cout), x.dtype),
            pltpu.VMEM((9 * cin, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )(
        x, k, _flip_transpose(k), g[None, :], b[None, :],
        m[None, :], v[None, :], gout,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _stem(x, k, g, b, eps, interpret, bn):
    out, _ = _stem_fwd(x, k, g, b, eps, interpret, bn)
    return out


def _stem_fwd(x, k, g, b, eps, interpret, bn):
    out, m, v = _stem_call(x, k, g, b, eps, interpret, bn)
    return (out, m[0], v[0]), (x, k, g, b, m[0], v[0])


def _stem_bwd(eps, interpret, bn, res, ct):
    x, k, g, b, m, v = res
    gout = ct[0]  # batch-moment cotangents discarded (module docstring)
    dx, dw, dg, db = _stem_bwd_call(x, k, g, b, m, v, gout, eps, interpret, bn)
    return dx, dw, dg[0], db[0]


_stem.defvjp(_stem_fwd, _stem_bwd)


def _compute_dtype(x: jax.Array, compute_dtype) -> jnp.dtype:
    """Resolve the kernel compute dtype: explicit override, else inferred
    from the activation dtype (bf16 in, bf16 compute; anything else
    computes fp32)."""
    if compute_dtype is not None:
        return jnp.dtype(compute_dtype)
    if x.dtype == jnp.bfloat16:
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(jnp.float32)


def fused_conv_bn_relu(
    x: jax.Array, kernel: jax.Array, scale: jax.Array, bias: jax.Array,
    *, eps: float = 1e-5, interpret: bool = False, compute_dtype=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused stem: ``relu(bn_train(conv3x3_s1(x, kernel)))`` in one kernel.

    Returns ``(out, batch_mean, batch_var_biased)``; the caller applies the
    running-stat update (``models.norm.running_stats_update``). Gradients
    flow to ``x``/``kernel``/``scale``/``bias``; the returned moments are
    ancillary (zero cotangent, like Flax BN variables).

    The compute dtype (activations/weights; default: follow ``x.dtype``,
    bf16 in means bf16 MXU matmuls) never touches BN: statistics, the
    returned moments and the scale/bias parameters are fp32 regardless,
    so the param/variable trees stay impl- and dtype-independent.
    """
    n, h, w, cin = x.shape
    cout = kernel.shape[3]
    cdt = _compute_dtype(x, compute_dtype)
    bn = _pick_batch_tile(
        n, h, w, cin, cout, residual=False, itemsize=cdt.itemsize
    )
    if bn is None:
        raise ValueError(
            f"fused stem does not admit geometry [{n},{h},{w},{cin}]->{cout}"
            " (supports_stem gate)"
        )
    return _stem(
        x.astype(cdt), kernel.astype(cdt),
        scale.astype(jnp.float32), bias.astype(jnp.float32),
        float(eps), bool(interpret), bn,
    )


# ---------------------------------------------------------------------------
# Fused BasicBlock: conv-BN-ReLU-conv-BN-(+x)-ReLU, identity shortcut.
# ---------------------------------------------------------------------------


def _block_fwd_kernel(
    x_ref, k1_ref, k2_ref, g1_ref, b1_ref, g2_ref, b2_ref,
    out_ref, m1_ref, v1_ref, m2_ref, v2_ref,
    xpad, apad, acc1s, acc1q, acc2s, acc2q, scA, shA, scB, shB,
    *, h: int, w: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    c = out_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        acc1s[:] = jnp.zeros_like(acc1s)
        acc1q[:] = jnp.zeros_like(acc1q)
        acc2s[:] = jnp.zeros_like(acc2s)
        acc2q[:] = jnp.zeros_like(acc2q)

    # stage-1 stats finalize (before the first phase-1 tile reads scA/shA)
    @pl.when((p == 1) & (i == 0))
    def _():
        m = acc1s[:] / count
        v = acc1q[:] / count - m * m
        m1_ref[:] = m
        v1_ref[:] = v
        s = g1_ref[:] * jax.lax.rsqrt(v + eps)
        scA[:] = s
        shA[:] = b1_ref[:] - m * s

    # stage-2 stats finalize (before the first phase-2 tile reads scB/shB)
    @pl.when((p == 2) & (i == 0))
    def _():
        m = acc2s[:] / count
        v = acc2q[:] / count - m * m
        m2_ref[:] = m
        v2_ref[:] = v
        s = g2_ref[:] * jax.lax.rsqrt(v + eps)
        scB[:] = s
        shB[:] = b2_ref[:] - m * s

    x = x_ref[:].astype(jnp.float32)
    _fill_pad(xpad, x)
    y1 = _conv3x3(xpad, k1_ref[:], h, w)

    @pl.when(p == 0)
    def _():
        acc1s[:] += _channel_sums(y1, c)
        acc1q[:] += _channel_sums(jnp.square(y1), c)

    @pl.when(p >= 1)
    def _():
        a1 = jnp.maximum(y1 * scA[:] + shA[:], 0.0)
        _fill_pad(apad, a1)
        y2 = _conv3x3(apad, k2_ref[:], h, w)

        @pl.when(p == 1)
        def _():
            acc2s[:] += _channel_sums(y2, c)
            acc2q[:] += _channel_sums(jnp.square(y2), c)

        @pl.when(p == 2)
        def _():
            out_ref[:] = jnp.maximum(y2 * scB[:] + shB[:] + x, 0.0).astype(
                out_ref.dtype
            )


def _block_bwd_kernel(
    x_ref, k1_ref, k2_ref, k1t_ref, k2t_ref,
    g1_ref, b1_ref, g2_ref, b2_ref,
    m1_ref, v1_ref, m2_ref, v2_ref, gout_ref,
    dx_ref, dw1_ref, dw2_ref, dg1_ref, db1_ref, dg2_ref, db2_ref,
    xpad, apad, gpad, dw1_acc, dw2_acc, s_dz, s_dzy, s_dp, s_dpy,
    *, h: int, w: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    nt = pl.num_programs(1)
    c = x_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        s_dz[:] = jnp.zeros_like(s_dz)
        s_dzy[:] = jnp.zeros_like(s_dzy)
        s_dp[:] = jnp.zeros_like(s_dp)
        s_dpy[:] = jnp.zeros_like(s_dpy)
        dw1_acc[:] = jnp.zeros_like(dw1_acc)
        dw2_acc[:] = jnp.zeros_like(dw2_acc)

    # recompute the tile's whole forward from the saved batch moments —
    # the FLOPs-for-bytes trade: no activation residual was ever stored
    g1, g2 = g1_ref[:], g2_ref[:]
    rs1 = jax.lax.rsqrt(v1_ref[:] + eps)
    rs2 = jax.lax.rsqrt(v2_ref[:] + eps)
    x = x_ref[:].astype(jnp.float32)
    _fill_pad(xpad, x)
    y1 = _conv3x3(xpad, k1_ref[:], h, w)
    yh1 = (y1 - m1_ref[:]) * rs1
    p1 = yh1 * g1 + b1_ref[:]
    a1 = jnp.maximum(p1, 0.0)
    _fill_pad(apad, a1)
    y2 = _conv3x3(apad, k2_ref[:], h, w)
    yh2 = (y2 - m2_ref[:]) * rs2
    z = yh2 * g2 + b2_ref[:] + x
    dz = gout_ref[:].astype(jnp.float32) * (z > 0.0)

    @pl.when(p == 0)
    def _():
        s_dz[:] += _channel_sums(dz, c)
        s_dzy[:] += _channel_sums(dz * yh2, c)

    @pl.when(p >= 1)
    def _():
        # train-mode BN2 backward, then back through conv2 to the stage-1
        # pre-activation
        dy2 = rs2 * g2 * (dz - s_dz[:] / count - yh2 * s_dzy[:] / count)

        @pl.when(p == 1)
        def _():
            _dw_accumulate(dw2_acc, apad, dy2, h, w)

        _fill_pad(gpad, dy2)
        da1 = _conv3x3(gpad, k2t_ref[:], h, w)
        dp1 = da1 * (p1 > 0.0)

        @pl.when(p == 1)
        def _():
            s_dp[:] += _channel_sums(dp1, c)
            s_dpy[:] += _channel_sums(dp1 * yh1, c)

        @pl.when(p == 2)
        def _():
            dy1 = rs1 * g1 * (dp1 - s_dp[:] / count - yh1 * s_dpy[:] / count)
            _dw_accumulate(dw1_acc, xpad, dy1, h, w)
            _fill_pad(gpad, dy1)
            # residual shortcut gradient + conv1 transpose
            dx_ref[:] = (dz + _conv3x3(gpad, k1t_ref[:], h, w)).astype(
                dx_ref.dtype
            )

    @pl.when((p == 2) & (i == nt - 1))
    def _():
        dw1_ref[:] = dw1_acc[:].reshape(3, 3, c, c).astype(dw1_ref.dtype)
        dw2_ref[:] = dw2_acc[:].reshape(3, 3, c, c).astype(dw2_ref.dtype)
        dg1_ref[:] = s_dpy[:]
        db1_ref[:] = s_dp[:]
        dg2_ref[:] = s_dzy[:]
        db2_ref[:] = s_dz[:]


def _block_call(x, k1, g1, b1, k2, g2, b2, eps, interpret, bn):
    n, h, w, c = x.shape
    nt = n // bn
    count = float(n * h * w)
    kernel = functools.partial(
        _block_fwd_kernel, h=h, w=w, count=count, eps=eps
    )
    tile = _vmem_spec((bn, h, w, c), lambda p, i: (i, 0, 0, 0))
    out_tile = _vmem_spec(
        (bn, h, w, c), lambda p, i: ((p == 2) * i, 0, 0, 0)
    )
    kfull = _vmem_spec((3, 3, c, c), lambda p, i: (0, 0, 0, 0))
    row = _vmem_spec((1, c), lambda p, i: (0, 0))
    row_out = [row] * 4
    return pl.pallas_call(
        kernel,
        grid=(3, nt),
        in_specs=[tile, kfull, kfull, row, row, row, row],
        out_specs=[out_tile] + row_out,
        out_shape=[jax.ShapeDtypeStruct((n, h, w, c), x.dtype)]
        + [jax.ShapeDtypeStruct((1, c), jnp.float32)] * 4,
        scratch_shapes=[
            pltpu.VMEM((bn, h + 2, w + 2, c), x.dtype),
            pltpu.VMEM((bn, h + 2, w + 2, c), x.dtype),
        ] + [pltpu.VMEM((1, c), jnp.float32)] * 8,
        interpret=interpret,
    )(x, k1, k2, g1[None, :], b1[None, :], g2[None, :], b2[None, :])


def _block_bwd_call(
    x, k1, g1, b1, k2, g2, b2, m1, v1, m2, v2, gout, eps, interpret, bn
):
    n, h, w, c = x.shape
    nt = n // bn
    count = float(n * h * w)
    kernel = functools.partial(
        _block_bwd_kernel, h=h, w=w, count=count, eps=eps
    )
    tile = _vmem_spec((bn, h, w, c), lambda p, i: (i, 0, 0, 0))
    dx_tile = _vmem_spec(
        (bn, h, w, c), lambda p, i: ((p == 2) * i, 0, 0, 0)
    )
    kfull = _vmem_spec((3, 3, c, c), lambda p, i: (0, 0, 0, 0))
    row = _vmem_spec((1, c), lambda p, i: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(3, nt),
        in_specs=[tile, kfull, kfull, kfull, kfull,
                  row, row, row, row, row, row, row, row, tile],
        out_specs=[dx_tile, kfull, kfull, row, row, row, row],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, w, c), x.dtype),
            jax.ShapeDtypeStruct((3, 3, c, c), k1.dtype),
            jax.ShapeDtypeStruct((3, 3, c, c), k2.dtype),
        ] + [jax.ShapeDtypeStruct((1, c), jnp.float32)] * 4,
        scratch_shapes=[
            pltpu.VMEM((bn, h + 2, w + 2, c), x.dtype),
            pltpu.VMEM((bn, h + 2, w + 2, c), x.dtype),
            pltpu.VMEM((bn, h + 2, w + 2, c), x.dtype),
            pltpu.VMEM((9 * c, c), jnp.float32),
            pltpu.VMEM((9 * c, c), jnp.float32),
        ] + [pltpu.VMEM((1, c), jnp.float32)] * 4,
        interpret=interpret,
    )(
        x, k1, k2, _flip_transpose(k1), _flip_transpose(k2),
        g1[None, :], b1[None, :], g2[None, :], b2[None, :],
        m1[None, :], v1[None, :], m2[None, :], v2[None, :], gout,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9))
def _block(x, k1, g1, b1, k2, g2, b2, eps, interpret, bn):
    out, _ = _block_fwd(x, k1, g1, b1, k2, g2, b2, eps, interpret, bn)
    return out


def _block_fwd(x, k1, g1, b1, k2, g2, b2, eps, interpret, bn):
    out, m1, v1, m2, v2 = _block_call(
        x, k1, g1, b1, k2, g2, b2, eps, interpret, bn
    )
    res = (x, k1, g1, b1, k2, g2, b2, m1[0], v1[0], m2[0], v2[0])
    return (out, m1[0], v1[0], m2[0], v2[0]), res


def _block_bwd(eps, interpret, bn, res, ct):
    x, k1, g1, b1, k2, g2, b2, m1, v1, m2, v2 = res
    gout = ct[0]  # batch-moment cotangents discarded (module docstring)
    dx, dw1, dw2, dg1, db1, dg2, db2 = _block_bwd_call(
        x, k1, g1, b1, k2, g2, b2, m1, v1, m2, v2, gout, eps, interpret, bn
    )
    return dx, dw1, dg1[0], db1[0], dw2, dg2[0], db2[0]


_block.defvjp(_block_fwd, _block_bwd)


def fused_basic_block(
    x: jax.Array,
    kernel1: jax.Array, scale1: jax.Array, bias1: jax.Array,
    kernel2: jax.Array, scale2: jax.Array, bias2: jax.Array,
    *, eps: float = 1e-5, interpret: bool = False, compute_dtype=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused identity-shortcut BasicBlock, train mode, one kernel each way.

    ``relu(bn2(conv3x3(relu(bn1(conv3x3(x, k1))), k2)) + x)`` with both BNs
    in whole-batch train mode. Returns
    ``(out, mean1, var1_biased, mean2, var2_biased)``; the caller applies
    the running-stat updates. Differentiable w.r.t. every array argument
    (custom VJP; the backward kernel recomputes the forward per phase and
    stores no activation residual — only the O(C) batch moments).

    Compute dtype follows ``x.dtype`` (bf16 in, bf16 MXU matmuls with
    fp32 accumulation) unless overridden; BN statistics, returned
    moments, and scale/bias stay fp32 regardless.
    """
    n, h, w, c = x.shape
    cdt = _compute_dtype(x, compute_dtype)
    if not supports_block(n, h, w, c, dtype=cdt):
        raise ValueError(
            f"fused basic block does not admit geometry [{n},{h},{w},{c}] "
            "(supports_block gate)"
        )
    bn = _pick_batch_tile(n, h, w, c, c, residual=True, itemsize=cdt.itemsize)
    f32 = jnp.float32
    return _block(
        x.astype(cdt), kernel1.astype(cdt), scale1.astype(f32),
        bias1.astype(f32), kernel2.astype(cdt), scale2.astype(f32),
        bias2.astype(f32), float(eps), bool(interpret), bn,
    )


# ---------------------------------------------------------------------------
# Fused projection-shortcut BasicBlock: conv3x3/s-BN-ReLU-conv3x3-BN plus
# a 1x1-conv/s-BN shortcut and the add-ReLU, one kernel each way. Admits
# stride 1 (channel change only) and stride 2 (even input dims — the
# backward's dilated transposed conv assumes ho == hi // 2 exactly). The
# shortcut gets its own accumulator set inside the SAME sequential-grid
# phases: its 1x1 conv is a strided slice of the x tile already resident
# for the main path, so the shortcut adds no HBM traversals.
# ---------------------------------------------------------------------------


def _proj_fwd_kernel(
    x_ref, k1_ref, k2_ref, ks_ref,
    g1_ref, b1_ref, g2_ref, b2_ref, gs_ref, bs_ref,
    out_ref, m1_ref, v1_ref, m2_ref, v2_ref, ms_ref, vs_ref,
    xpad, apad,
    acc1s, acc1q, acc2s, acc2q, accSs, accSq,
    sc1, sh1, sc2, sh2, scS, shS,
    *, ho: int, wo: int, stride: int, count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    c = out_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        for acc in (acc1s, acc1q, acc2s, acc2q, accSs, accSq):
            acc[:] = jnp.zeros_like(acc)

    # stage-1 + shortcut finalize (both consumed first in phase 1 / 2)
    @pl.when((p == 1) & (i == 0))
    def _():
        m = acc1s[:] / count
        v = acc1q[:] / count - m * m
        m1_ref[:] = m
        v1_ref[:] = v
        s = g1_ref[:] * jax.lax.rsqrt(v + eps)
        sc1[:] = s
        sh1[:] = b1_ref[:] - m * s
        mS = accSs[:] / count
        vS = accSq[:] / count - mS * mS
        ms_ref[:] = mS
        vs_ref[:] = vS
        sS = gs_ref[:] * jax.lax.rsqrt(vS + eps)
        scS[:] = sS
        shS[:] = bs_ref[:] - mS * sS

    @pl.when((p == 2) & (i == 0))
    def _():
        m = acc2s[:] / count
        v = acc2q[:] / count - m * m
        m2_ref[:] = m
        v2_ref[:] = v
        s = g2_ref[:] * jax.lax.rsqrt(v + eps)
        sc2[:] = s
        sh2[:] = b2_ref[:] - m * s

    xv = x_ref[:]
    _fill_pad(xpad, xv)
    y1 = _conv3x3(xpad, k1_ref[:], ho, wo, stride)
    xs = xv[:, ::stride, ::stride, :] if stride != 1 else xv
    yS = _mm(xs, ks_ref[:])

    @pl.when(p == 0)
    def _():
        acc1s[:] += _channel_sums(y1, c)
        acc1q[:] += _channel_sums(jnp.square(y1), c)
        accSs[:] += _channel_sums(yS, c)
        accSq[:] += _channel_sums(jnp.square(yS), c)

    @pl.when(p >= 1)
    def _():
        a1 = jnp.maximum(y1 * sc1[:] + sh1[:], 0.0)
        _fill_pad(apad, a1)
        y2 = _conv3x3(apad, k2_ref[:], ho, wo)

        @pl.when(p == 1)
        def _():
            acc2s[:] += _channel_sums(y2, c)
            acc2q[:] += _channel_sums(jnp.square(y2), c)

        @pl.when(p == 2)
        def _():
            z = y2 * sc2[:] + sh2[:] + yS * scS[:] + shS[:]
            out_ref[:] = jnp.maximum(z, 0.0).astype(out_ref.dtype)


def _proj_bwd_kernel(
    x_ref, k1_ref, k2_ref, ks_ref, k1t_ref, k2t_ref,
    g1_ref, b1_ref, g2_ref, b2_ref, gs_ref, bs_ref,
    m1_ref, v1_ref, m2_ref, v2_ref, ms_ref, vs_ref, gout_ref,
    dx_ref, dw1_ref, dw2_ref, dws_ref,
    dg1_ref, db1_ref, dg2_ref, db2_ref, dgs_ref, dbs_ref,
    xpad, apad, gpadA, gpadB, dw1_acc, dw2_acc, dws_acc,
    s_dz, s_dzy2, s_dzyS, s_dp, s_dpy,
    *, hi: int, wi: int, ho: int, wo: int, stride: int,
    count: float, eps: float,
):
    p = pl.program_id(0)
    i = pl.program_id(1)
    nt = pl.num_programs(1)
    cin = x_ref.shape[3]
    c = gout_ref.shape[3]

    @pl.when((p == 0) & (i == 0))
    def _():
        for acc in (s_dz, s_dzy2, s_dzyS, s_dp, s_dpy,
                    dw1_acc, dw2_acc, dws_acc):
            acc[:] = jnp.zeros_like(acc)

    # recompute the tile's whole forward from the saved batch moments
    g1, g2, gS = g1_ref[:], g2_ref[:], gs_ref[:]
    rs1 = jax.lax.rsqrt(v1_ref[:] + eps)
    rs2 = jax.lax.rsqrt(v2_ref[:] + eps)
    rsS = jax.lax.rsqrt(vs_ref[:] + eps)
    xv = x_ref[:]
    _fill_pad(xpad, xv)
    y1 = _conv3x3(xpad, k1_ref[:], ho, wo, stride)
    xs = xv[:, ::stride, ::stride, :] if stride != 1 else xv
    yS = _mm(xs, ks_ref[:])
    yh1 = (y1 - m1_ref[:]) * rs1
    p1 = yh1 * g1 + b1_ref[:]
    a1 = jnp.maximum(p1, 0.0)
    _fill_pad(apad, a1)
    y2 = _conv3x3(apad, k2_ref[:], ho, wo)
    yh2 = (y2 - m2_ref[:]) * rs2
    yhS = (yS - ms_ref[:]) * rsS
    z = yh2 * g2 + b2_ref[:] + yhS * gS + bs_ref[:]
    dz = gout_ref[:].astype(jnp.float32) * (z > 0.0)

    @pl.when(p == 0)
    def _():
        s_dz[:] += _channel_sums(dz, c)
        s_dzy2[:] += _channel_sums(dz * yh2, c)
        s_dzyS[:] += _channel_sums(dz * yhS, c)

    @pl.when(p >= 1)
    def _():
        # BN2 + shortcut-BN backward share the post-add dz
        dy2 = rs2 * g2 * (dz - s_dz[:] / count - yh2 * s_dzy2[:] / count)
        dyS = rsS * gS * (dz - s_dz[:] / count - yhS * s_dzyS[:] / count)

        @pl.when(p == 1)
        def _():
            _dw_accumulate(dw2_acc, apad, dy2, ho, wo)
            dws_acc[:] += jnp.dot(
                xs.reshape(-1, cin).T,
                dyS.reshape(-1, c).astype(xv.dtype),
                preferred_element_type=jnp.float32,
            )

        _fill_pad(gpadA, dy2)
        da1 = _conv3x3(gpadA, k2t_ref[:], ho, wo)
        dp1 = da1 * (p1 > 0.0)

        @pl.when(p == 1)
        def _():
            s_dp[:] += _channel_sums(dp1, c)
            s_dpy[:] += _channel_sums(dp1 * yh1, c)

        @pl.when(p == 2)
        def _():
            dy1 = rs1 * g1 * (dp1 - s_dp[:] / count - yh1 * s_dpy[:] / count)
            _dw_accumulate(dw1_acc, xpad, dy1, ho, wo, stride)
            # dx: transposed conv1 (dilated for stride 2) + the shortcut's
            # 1x1 transpose scattered back to input resolution
            vS = _mm(dyS.astype(xv.dtype), ks_ref[:].T)
            if stride == 1:
                gfill, dxs = dy1, vS
            else:
                gfill, dxs = _dilate2(dy1), _dilate2(vS)
            _fill_pad(gpadB, gfill)
            dx_ref[:] = (_conv3x3(gpadB, k1t_ref[:], hi, wi) + dxs).astype(
                dx_ref.dtype
            )

    @pl.when((p == 2) & (i == nt - 1))
    def _():
        dw1_ref[:] = dw1_acc[:].reshape(3, 3, cin, c).astype(dw1_ref.dtype)
        dw2_ref[:] = dw2_acc[:].reshape(3, 3, c, c).astype(dw2_ref.dtype)
        dws_ref[:] = dws_acc[:].astype(dws_ref.dtype)
        dg1_ref[:] = s_dpy[:]
        db1_ref[:] = s_dp[:]
        dg2_ref[:] = s_dzy2[:]
        db2_ref[:] = s_dz[:]
        dgs_ref[:] = s_dzyS[:]
        dbs_ref[:] = s_dz[:]  # both BN biases add directly into z


def _proj_call(x, k1, g1, b1, k2, g2, b2, ks, gs, bs,
               eps, interpret, bn, stride):
    n, hi, wi, cin = x.shape
    c = k1.shape[3]
    ho, wo = hi // stride, wi // stride
    nt = n // bn
    count = float(n * ho * wo)
    kernel = functools.partial(
        _proj_fwd_kernel, ho=ho, wo=wo, stride=stride, count=count, eps=eps
    )
    x_tile = _vmem_spec((bn, hi, wi, cin), lambda p, i: (i, 0, 0, 0))
    out_tile = _vmem_spec(
        (bn, ho, wo, c), lambda p, i: ((p == 2) * i, 0, 0, 0)
    )
    k1full = _vmem_spec((3, 3, cin, c), lambda p, i: (0, 0, 0, 0))
    k2full = _vmem_spec((3, 3, c, c), lambda p, i: (0, 0, 0, 0))
    ksfull = _vmem_spec((cin, c), lambda p, i: (0, 0))
    row = _vmem_spec((1, c), lambda p, i: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(3, nt),
        in_specs=[x_tile, k1full, k2full, ksfull] + [row] * 6,
        out_specs=[out_tile] + [row] * 6,
        out_shape=[jax.ShapeDtypeStruct((n, ho, wo, c), x.dtype)]
        + [jax.ShapeDtypeStruct((1, c), jnp.float32)] * 6,
        scratch_shapes=[
            pltpu.VMEM((bn, hi + 2, wi + 2, cin), x.dtype),
            pltpu.VMEM((bn, ho + 2, wo + 2, c), x.dtype),
        ] + [pltpu.VMEM((1, c), jnp.float32) for _ in range(12)],
        interpret=interpret,
    )(
        x, k1, k2, ks, g1[None, :], b1[None, :], g2[None, :], b2[None, :],
        gs[None, :], bs[None, :],
    )


def _proj_bwd_call(x, k1, g1, b1, k2, g2, b2, ks, gs, bs,
                   m1, v1, m2, v2, mS, vS, gout, eps, interpret, bn, stride):
    n, hi, wi, cin = x.shape
    c = k1.shape[3]
    ho, wo = hi // stride, wi // stride
    nt = n // bn
    count = float(n * ho * wo)
    kernel = functools.partial(
        _proj_bwd_kernel, hi=hi, wi=wi, ho=ho, wo=wo, stride=stride,
        count=count, eps=eps,
    )
    x_tile = _vmem_spec((bn, hi, wi, cin), lambda p, i: (i, 0, 0, 0))
    g_tile = _vmem_spec((bn, ho, wo, c), lambda p, i: (i, 0, 0, 0))
    dx_tile = _vmem_spec(
        (bn, hi, wi, cin), lambda p, i: ((p == 2) * i, 0, 0, 0)
    )
    k1full = _vmem_spec((3, 3, cin, c), lambda p, i: (0, 0, 0, 0))
    k2full = _vmem_spec((3, 3, c, c), lambda p, i: (0, 0, 0, 0))
    k1tfull = _vmem_spec((3, 3, c, cin), lambda p, i: (0, 0, 0, 0))
    ksfull = _vmem_spec((cin, c), lambda p, i: (0, 0))
    row = _vmem_spec((1, c), lambda p, i: (0, 0))
    return pl.pallas_call(
        kernel,
        grid=(3, nt),
        in_specs=[x_tile, k1full, k2full, ksfull, k1tfull, k2full]
        + [row] * 12 + [g_tile],
        out_specs=[dx_tile, k1full, k2full, ksfull] + [row] * 6,
        out_shape=[
            jax.ShapeDtypeStruct((n, hi, wi, cin), x.dtype),
            jax.ShapeDtypeStruct((3, 3, cin, c), k1.dtype),
            jax.ShapeDtypeStruct((3, 3, c, c), k2.dtype),
            jax.ShapeDtypeStruct((cin, c), ks.dtype),
        ] + [jax.ShapeDtypeStruct((1, c), jnp.float32)] * 6,
        scratch_shapes=[
            pltpu.VMEM((bn, hi + 2, wi + 2, cin), x.dtype),
            pltpu.VMEM((bn, ho + 2, wo + 2, c), x.dtype),
            pltpu.VMEM((bn, ho + 2, wo + 2, c), x.dtype),
            pltpu.VMEM((bn, hi + 2, wi + 2, c), x.dtype),
            pltpu.VMEM((9 * cin, c), jnp.float32),
            pltpu.VMEM((9 * c, c), jnp.float32),
            pltpu.VMEM((cin, c), jnp.float32),
        ] + [pltpu.VMEM((1, c), jnp.float32) for _ in range(5)],
        interpret=interpret,
    )(
        x, k1, k2, ks, _flip_transpose(k1), _flip_transpose(k2),
        g1[None, :], b1[None, :], g2[None, :], b2[None, :],
        gs[None, :], bs[None, :],
        m1[None, :], v1[None, :], m2[None, :], v2[None, :],
        mS[None, :], vS[None, :], gout,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(10, 11, 12, 13))
def _proj(x, k1, g1, b1, k2, g2, b2, ks, gs, bs, eps, interpret, bn, stride):
    out, _ = _proj_fwd(
        x, k1, g1, b1, k2, g2, b2, ks, gs, bs, eps, interpret, bn, stride
    )
    return out


def _proj_fwd(x, k1, g1, b1, k2, g2, b2, ks, gs, bs,
              eps, interpret, bn, stride):
    out, m1, v1, m2, v2, mS, vS = _proj_call(
        x, k1, g1, b1, k2, g2, b2, ks, gs, bs, eps, interpret, bn, stride
    )
    res = (x, k1, g1, b1, k2, g2, b2, ks, gs, bs,
           m1[0], v1[0], m2[0], v2[0], mS[0], vS[0])
    return (out, m1[0], v1[0], m2[0], v2[0], mS[0], vS[0]), res


def _proj_bwd(eps, interpret, bn, stride, res, ct):
    (x, k1, g1, b1, k2, g2, b2, ks, gs, bs,
     m1, v1, m2, v2, mS, vS) = res
    gout = ct[0]  # batch-moment cotangents discarded (module docstring)
    dx, dw1, dw2, dws, dg1, db1, dg2, db2, dgs, dbs = _proj_bwd_call(
        x, k1, g1, b1, k2, g2, b2, ks, gs, bs,
        m1, v1, m2, v2, mS, vS, gout, eps, interpret, bn, stride,
    )
    return (dx, dw1, dg1[0], db1[0], dw2, dg2[0], db2[0],
            dws, dgs[0], dbs[0])


_proj.defvjp(_proj_fwd, _proj_bwd)


def fused_projection_block(
    x: jax.Array,
    kernel1: jax.Array, scale1: jax.Array, bias1: jax.Array,
    kernel2: jax.Array, scale2: jax.Array, bias2: jax.Array,
    kernel_sc: jax.Array, scale_sc: jax.Array, bias_sc: jax.Array,
    *, stride: int = 1, eps: float = 1e-5, interpret: bool = False,
    compute_dtype=None,
):
    """Fused projection-shortcut BasicBlock, train mode, one kernel each
    way: ``relu(bn2(conv3x3(relu(bn1(conv3x3_s(x, k1))), k2))
    + bn_sc(conv1x1_s(x, k_sc)))`` with all three BNs in whole-batch train
    mode. ``kernel_sc`` may be ``(1, 1, cin, c)`` or ``(cin, c)``.

    Returns ``(out, mean1, var1, mean2, var2, mean_sc, var_sc)`` (biased
    variances); the caller applies the running-stat updates. Compute
    dtype follows ``x.dtype`` unless overridden; BN state stays fp32.
    """
    n, h, w, cin = x.shape
    c = kernel1.shape[3]
    cdt = _compute_dtype(x, compute_dtype)
    if stride == 1 and cin == c:
        raise ValueError(
            "projection block requires stride 2 or a channel change; "
            "use fused_basic_block for identity-shortcut sites"
        )
    if not supports_block(n, h, w, c, stride=stride, in_channels=cin,
                          dtype=cdt):
        raise ValueError(
            f"fused projection block does not admit geometry "
            f"[{n},{h},{w},{cin}]->{c}/s{stride} (supports_block gate)"
        )
    bn = _pick_tile(
        n,
        lambda b: _vmem_estimate_proj(
            b, h, w, cin, c, stride, cdt.itemsize
        ) <= VMEM_BUDGET,
    )
    f32 = jnp.float32
    return _proj(
        x.astype(cdt), kernel1.astype(cdt), scale1.astype(f32),
        bias1.astype(f32), kernel2.astype(cdt), scale2.astype(f32),
        bias2.astype(f32), kernel_sc.reshape(cin, c).astype(cdt),
        scale_sc.astype(f32), bias_sc.astype(f32),
        float(eps), bool(interpret), bn, int(stride),
    )


# ---------------------------------------------------------------------------
# Fused Bottleneck (rn50-class): 1x1 -> BN-ReLU -> 3x3/s -> BN-ReLU -> 1x1
# -> BN -> (+shortcut) -> ReLU, expansion 4, one kernel each way. The 1x1
# convs are pure [bn*H*W, C] @ [C, C'] MXU contractions straight off the
# resident x / a2 tiles — no im2col pad scratch; only the middle 3x3 pays
# the padded-tile treatment. Four phases: y1+shortcut stats, y2 stats,
# y3 stats, emit. BN1 normalizes at input resolution (count1); BN2/BN3
# and the shortcut BN at output resolution (count2). A static ``proj``
# flag selects the identity (stride 1, cin == 4*planes) or fused
# 1x1-conv-BN projection shortcut variant.
# ---------------------------------------------------------------------------


def _bot_fwd_kernel(*refs, proj: bool, ho: int, wo: int, stride: int,
                    count1: float, count2: float, eps: float):
    n_in = 13 if proj else 10
    n_out = 9 if proj else 7
    if proj:
        (x_ref, k1_ref, k2_ref, k3_ref, ks_ref,
         g1_ref, b1_ref, g2_ref, b2_ref, g3_ref, b3_ref,
         gs_ref, bs_ref) = refs[:n_in]
        (out_ref, m1_ref, v1_ref, m2_ref, v2_ref, m3_ref, v3_ref,
         ms_ref, vs_ref) = refs[n_in:n_in + n_out]
        (apad, acc1s, acc1q, acc2s, acc2q, acc3s, acc3q,
         sc1, sh1, sc2, sh2, sc3, sh3,
         accSs, accSq, scS, shS) = refs[n_in + n_out:]
    else:
        (x_ref, k1_ref, k2_ref, k3_ref,
         g1_ref, b1_ref, g2_ref, b2_ref, g3_ref, b3_ref) = refs[:n_in]
        (out_ref, m1_ref, v1_ref, m2_ref, v2_ref,
         m3_ref, v3_ref) = refs[n_in:n_in + n_out]
        (apad, acc1s, acc1q, acc2s, acc2q, acc3s, acc3q,
         sc1, sh1, sc2, sh2, sc3, sh3) = refs[n_in + n_out:]

    p = pl.program_id(0)
    i = pl.program_id(1)
    pln = k1_ref.shape[1]
    cout = k3_ref.shape[1]

    @pl.when((p == 0) & (i == 0))
    def _():
        accs = [acc1s, acc1q, acc2s, acc2q, acc3s, acc3q]
        if proj:
            accs += [accSs, accSq]
        for acc in accs:
            acc[:] = jnp.zeros_like(acc)

    # stage-1 (input-resolution count) + shortcut finalize
    @pl.when((p == 1) & (i == 0))
    def _():
        m = acc1s[:] / count1
        v = acc1q[:] / count1 - m * m
        m1_ref[:] = m
        v1_ref[:] = v
        s = g1_ref[:] * jax.lax.rsqrt(v + eps)
        sc1[:] = s
        sh1[:] = b1_ref[:] - m * s
        if proj:
            mS = accSs[:] / count2
            vS = accSq[:] / count2 - mS * mS
            ms_ref[:] = mS
            vs_ref[:] = vS
            sS = gs_ref[:] * jax.lax.rsqrt(vS + eps)
            scS[:] = sS
            shS[:] = bs_ref[:] - mS * sS

    @pl.when((p == 2) & (i == 0))
    def _():
        m = acc2s[:] / count2
        v = acc2q[:] / count2 - m * m
        m2_ref[:] = m
        v2_ref[:] = v
        s = g2_ref[:] * jax.lax.rsqrt(v + eps)
        sc2[:] = s
        sh2[:] = b2_ref[:] - m * s

    @pl.when((p == 3) & (i == 0))
    def _():
        m = acc3s[:] / count2
        v = acc3q[:] / count2 - m * m
        m3_ref[:] = m
        v3_ref[:] = v
        s = g3_ref[:] * jax.lax.rsqrt(v + eps)
        sc3[:] = s
        sh3[:] = b3_ref[:] - m * s

    xv = x_ref[:]
    y1 = _mm(xv, k1_ref[:])
    if proj:
        xs = xv[:, ::stride, ::stride, :] if stride != 1 else xv
        yS = _mm(xs, ks_ref[:])

    @pl.when(p == 0)
    def _():
        acc1s[:] += _channel_sums(y1, pln)
        acc1q[:] += _channel_sums(jnp.square(y1), pln)
        if proj:
            accSs[:] += _channel_sums(yS, cout)
            accSq[:] += _channel_sums(jnp.square(yS), cout)

    @pl.when(p >= 1)
    def _():
        a1 = jnp.maximum(y1 * sc1[:] + sh1[:], 0.0)
        _fill_pad(apad, a1)
        y2 = _conv3x3(apad, k2_ref[:], ho, wo, stride)

        @pl.when(p == 1)
        def _():
            acc2s[:] += _channel_sums(y2, pln)
            acc2q[:] += _channel_sums(jnp.square(y2), pln)

        @pl.when(p >= 2)
        def _():
            a2 = jnp.maximum(y2 * sc2[:] + sh2[:], 0.0).astype(apad.dtype)
            y3 = _mm(a2, k3_ref[:])

            @pl.when(p == 2)
            def _():
                acc3s[:] += _channel_sums(y3, cout)
                acc3q[:] += _channel_sums(jnp.square(y3), cout)

            @pl.when(p == 3)
            def _():
                if proj:
                    short = yS * scS[:] + shS[:]
                else:
                    short = xv.astype(jnp.float32)
                out_ref[:] = jnp.maximum(
                    y3 * sc3[:] + sh3[:] + short, 0.0
                ).astype(out_ref.dtype)


def _bot_bwd_kernel(*refs, proj: bool, hi: int, wi: int, ho: int, wo: int,
                    stride: int, count1: float, count2: float, eps: float):
    n_in = 23 if proj else 18
    n_out = 13 if proj else 10
    (x_ref, k1_ref, k2_ref, k3_ref, k2t_ref,
     g1_ref, b1_ref, g2_ref, b2_ref, g3_ref, b3_ref,
     m1_ref, v1_ref, m2_ref, v2_ref, m3_ref, v3_ref, gout_ref) = refs[:18]
    if proj:
        ks_ref, gs_ref, bs_ref, ms_ref, vs_ref = refs[18:23]
    outs = refs[n_in:n_in + n_out]
    (dx_ref, dw1_ref, dw2_ref, dw3_ref,
     dg1_ref, db1_ref, dg2_ref, db2_ref, dg3_ref, db3_ref) = outs[:10]
    if proj:
        dws_ref, dgs_ref, dbs_ref = outs[10:]
    scratch = refs[n_in + n_out:]
    (apad, gpad, dw1_acc, dw2_acc, dw3_acc,
     s_dz, s_dzy3, s_dp2, s_dp2y, s_dp1, s_dp1y) = scratch[:11]
    if proj:
        dws_acc, s_dzyS = scratch[11:]

    p = pl.program_id(0)
    i = pl.program_id(1)
    nt = pl.num_programs(1)
    cin = x_ref.shape[3]
    pln = k1_ref.shape[1]
    cout = k3_ref.shape[1]

    @pl.when((p == 0) & (i == 0))
    def _():
        accs = [dw1_acc, dw2_acc, dw3_acc, s_dz, s_dzy3,
                s_dp2, s_dp2y, s_dp1, s_dp1y]
        if proj:
            accs += [dws_acc, s_dzyS]
        for acc in accs:
            acc[:] = jnp.zeros_like(acc)

    # recompute the tile's whole forward from the saved batch moments
    g1, g2, g3 = g1_ref[:], g2_ref[:], g3_ref[:]
    rs1 = jax.lax.rsqrt(v1_ref[:] + eps)
    rs2 = jax.lax.rsqrt(v2_ref[:] + eps)
    rs3 = jax.lax.rsqrt(v3_ref[:] + eps)
    xv = x_ref[:]
    y1 = _mm(xv, k1_ref[:])
    yh1 = (y1 - m1_ref[:]) * rs1
    p1 = yh1 * g1 + b1_ref[:]
    a1 = jnp.maximum(p1, 0.0)
    _fill_pad(apad, a1)
    y2 = _conv3x3(apad, k2_ref[:], ho, wo, stride)
    yh2 = (y2 - m2_ref[:]) * rs2
    p2 = yh2 * g2 + b2_ref[:]
    a2 = jnp.maximum(p2, 0.0).astype(xv.dtype)
    y3 = _mm(a2, k3_ref[:])
    yh3 = (y3 - m3_ref[:]) * rs3
    z = yh3 * g3 + b3_ref[:]
    if proj:
        gS = gs_ref[:]
        rsS = jax.lax.rsqrt(vs_ref[:] + eps)
        xs = xv[:, ::stride, ::stride, :] if stride != 1 else xv
        yS = _mm(xs, ks_ref[:])
        yhS = (yS - ms_ref[:]) * rsS
        z = z + yhS * gS + bs_ref[:]
    else:
        z = z + xv.astype(jnp.float32)
    dz = gout_ref[:].astype(jnp.float32) * (z > 0.0)

    @pl.when(p == 0)
    def _():
        s_dz[:] += _channel_sums(dz, cout)
        s_dzy3[:] += _channel_sums(dz * yh3, cout)
        if proj:
            s_dzyS[:] += _channel_sums(dz * yhS, cout)

    @pl.when(p >= 1)
    def _():
        dy3 = rs3 * g3 * (dz - s_dz[:] / count2 - yh3 * s_dzy3[:] / count2)
        if proj:
            dyS = rsS * gS * (
                dz - s_dz[:] / count2 - yhS * s_dzyS[:] / count2
            )

        @pl.when(p == 1)
        def _():
            dw3_acc[:] += jnp.dot(
                a2.reshape(-1, pln).T,
                dy3.reshape(-1, cout).astype(xv.dtype),
                preferred_element_type=jnp.float32,
            )
            if proj:
                dws_acc[:] += jnp.dot(
                    xs.reshape(-1, cin).T,
                    dyS.reshape(-1, cout).astype(xv.dtype),
                    preferred_element_type=jnp.float32,
                )

        da2 = _mm(dy3.astype(xv.dtype), k3_ref[:].T)
        dp2 = da2 * (p2 > 0.0)

        @pl.when(p == 1)
        def _():
            s_dp2[:] += _channel_sums(dp2, pln)
            s_dp2y[:] += _channel_sums(dp2 * yh2, pln)

        @pl.when(p >= 2)
        def _():
            dy2 = rs2 * g2 * (
                dp2 - s_dp2[:] / count2 - yh2 * s_dp2y[:] / count2
            )

            @pl.when(p == 2)
            def _():
                _dw_accumulate(dw2_acc, apad, dy2, ho, wo, stride)

            gfill = _dilate2(dy2) if stride != 1 else dy2
            _fill_pad(gpad, gfill)
            da1 = _conv3x3(gpad, k2t_ref[:], hi, wi)
            dp1 = da1 * (p1 > 0.0)

            @pl.when(p == 2)
            def _():
                s_dp1[:] += _channel_sums(dp1, pln)
                s_dp1y[:] += _channel_sums(dp1 * yh1, pln)

            @pl.when(p == 3)
            def _():
                dy1 = rs1 * g1 * (
                    dp1 - s_dp1[:] / count1 - yh1 * s_dp1y[:] / count1
                )
                dw1_acc[:] += jnp.dot(
                    xv.reshape(-1, cin).T,
                    dy1.reshape(-1, pln).astype(xv.dtype),
                    preferred_element_type=jnp.float32,
                )
                dxm = _mm(dy1.astype(xv.dtype), k1_ref[:].T)
                if proj:
                    vSx = _mm(dyS.astype(xv.dtype), ks_ref[:].T)
                    dxs = _dilate2(vSx) if stride != 1 else vSx
                else:
                    dxs = dz  # identity shortcut: cout == cin, in-res
                dx_ref[:] = (dxm + dxs).astype(dx_ref.dtype)

    @pl.when((p == 3) & (i == nt - 1))
    def _():
        dw1_ref[:] = dw1_acc[:].astype(dw1_ref.dtype)
        dw2_ref[:] = dw2_acc[:].reshape(3, 3, pln, pln).astype(dw2_ref.dtype)
        dw3_ref[:] = dw3_acc[:].astype(dw3_ref.dtype)
        dg1_ref[:] = s_dp1y[:]
        db1_ref[:] = s_dp1[:]
        dg2_ref[:] = s_dp2y[:]
        db2_ref[:] = s_dp2[:]
        dg3_ref[:] = s_dzy3[:]
        db3_ref[:] = s_dz[:]
        if proj:
            dws_ref[:] = dws_acc[:].astype(dws_ref.dtype)
            dgs_ref[:] = s_dzyS[:]
            dbs_ref[:] = s_dz[:]  # both BN biases add directly into z


def _bot_call(x, k1, g1, b1, k2, g2, b2, k3, g3, b3, short,
              eps, interpret, bn, stride):
    n, hi, wi, cin = x.shape
    pln = k1.shape[1]
    cout = k3.shape[1]
    ho, wo = hi // stride, wi // stride
    nt = n // bn
    proj = short is not None
    kernel = functools.partial(
        _bot_fwd_kernel, proj=proj, ho=ho, wo=wo, stride=stride,
        count1=float(n * hi * wi), count2=float(n * ho * wo), eps=eps,
    )
    x_tile = _vmem_spec((bn, hi, wi, cin), lambda p, i: (i, 0, 0, 0))
    out_tile = _vmem_spec(
        (bn, ho, wo, cout), lambda p, i: ((p == 3) * i, 0, 0, 0)
    )
    k1full = _vmem_spec((cin, pln), lambda p, i: (0, 0))
    k2full = _vmem_spec((3, 3, pln, pln), lambda p, i: (0, 0, 0, 0))
    k3full = _vmem_spec((pln, cout), lambda p, i: (0, 0))
    rowp = _vmem_spec((1, pln), lambda p, i: (0, 0))
    rowo = _vmem_spec((1, cout), lambda p, i: (0, 0))
    in_specs = [x_tile, k1full, k2full, k3full]
    args = [x, k1, k2, k3]
    if proj:
        ks, gs, bs = short
        in_specs.append(_vmem_spec((cin, cout), lambda p, i: (0, 0)))
        args.append(ks)
    in_specs += [rowp, rowp, rowp, rowp, rowo, rowo]
    args += [g1[None, :], b1[None, :], g2[None, :], b2[None, :],
             g3[None, :], b3[None, :]]
    if proj:
        in_specs += [rowo, rowo]
        args += [gs[None, :], bs[None, :]]
    out_specs = [out_tile, rowp, rowp, rowp, rowp, rowo, rowo]
    out_shape = (
        [jax.ShapeDtypeStruct((n, ho, wo, cout), x.dtype)]
        + [jax.ShapeDtypeStruct((1, pln), jnp.float32)] * 4
        + [jax.ShapeDtypeStruct((1, cout), jnp.float32)] * 2
    )
    if proj:
        out_specs += [rowo, rowo]
        out_shape += [jax.ShapeDtypeStruct((1, cout), jnp.float32)] * 2
    scratch = (
        [pltpu.VMEM((bn, hi + 2, wi + 2, pln), x.dtype)]
        + [pltpu.VMEM((1, pln), jnp.float32) for _ in range(4)]
        + [pltpu.VMEM((1, cout), jnp.float32) for _ in range(2)]
        + [pltpu.VMEM((1, pln), jnp.float32) for _ in range(4)]
        + [pltpu.VMEM((1, cout), jnp.float32) for _ in range(2)]
    )
    if proj:
        scratch += [pltpu.VMEM((1, cout), jnp.float32) for _ in range(4)]
    return pl.pallas_call(
        kernel,
        grid=(4, nt),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)


def _bot_bwd_call(x, k1, g1, b1, k2, g2, b2, k3, g3, b3, short,
                  m1, v1, m2, v2, m3, v3, gout, eps, interpret, bn, stride):
    n, hi, wi, cin = x.shape
    pln = k1.shape[1]
    cout = k3.shape[1]
    ho, wo = hi // stride, wi // stride
    nt = n // bn
    proj = short is not None
    kernel = functools.partial(
        _bot_bwd_kernel, proj=proj, hi=hi, wi=wi, ho=ho, wo=wo,
        stride=stride, count1=float(n * hi * wi),
        count2=float(n * ho * wo), eps=eps,
    )
    x_tile = _vmem_spec((bn, hi, wi, cin), lambda p, i: (i, 0, 0, 0))
    g_tile = _vmem_spec((bn, ho, wo, cout), lambda p, i: (i, 0, 0, 0))
    dx_tile = _vmem_spec(
        (bn, hi, wi, cin), lambda p, i: ((p == 3) * i, 0, 0, 0)
    )
    k1full = _vmem_spec((cin, pln), lambda p, i: (0, 0))
    k2full = _vmem_spec((3, 3, pln, pln), lambda p, i: (0, 0, 0, 0))
    k3full = _vmem_spec((pln, cout), lambda p, i: (0, 0))
    rowp = _vmem_spec((1, pln), lambda p, i: (0, 0))
    rowo = _vmem_spec((1, cout), lambda p, i: (0, 0))
    in_specs = [x_tile, k1full, k2full, k3full, k2full,
                rowp, rowp, rowp, rowp, rowo, rowo,
                rowp, rowp, rowp, rowp, rowo, rowo, g_tile]
    args = [x, k1, k2, k3, _flip_transpose(k2),
            g1[None, :], b1[None, :], g2[None, :], b2[None, :],
            g3[None, :], b3[None, :],
            m1[None, :], v1[None, :], m2[None, :], v2[None, :],
            m3[None, :], v3[None, :], gout]
    if proj:
        ks, gs, bs, mS, vS = short
        in_specs += [_vmem_spec((cin, cout), lambda p, i: (0, 0)),
                     rowo, rowo, rowo, rowo]
        args += [ks, gs[None, :], bs[None, :], mS[None, :], vS[None, :]]
    out_specs = [dx_tile, k1full, k2full, k3full,
                 rowp, rowp, rowp, rowp, rowo, rowo]
    out_shape = [
        jax.ShapeDtypeStruct((n, hi, wi, cin), x.dtype),
        jax.ShapeDtypeStruct((cin, pln), k1.dtype),
        jax.ShapeDtypeStruct((3, 3, pln, pln), k2.dtype),
        jax.ShapeDtypeStruct((pln, cout), k3.dtype),
    ] + [jax.ShapeDtypeStruct((1, pln), jnp.float32)] * 4 \
      + [jax.ShapeDtypeStruct((1, cout), jnp.float32)] * 2
    if proj:
        out_specs += [_vmem_spec((cin, cout), lambda p, i: (0, 0)),
                      rowo, rowo]
        out_shape += [jax.ShapeDtypeStruct((cin, cout), ks.dtype)] \
            + [jax.ShapeDtypeStruct((1, cout), jnp.float32)] * 2
    scratch = [
        pltpu.VMEM((bn, hi + 2, wi + 2, pln), x.dtype),
        pltpu.VMEM((bn, hi + 2, wi + 2, pln), x.dtype),
        pltpu.VMEM((cin, pln), jnp.float32),
        pltpu.VMEM((9 * pln, pln), jnp.float32),
        pltpu.VMEM((pln, cout), jnp.float32),
        pltpu.VMEM((1, cout), jnp.float32),
        pltpu.VMEM((1, cout), jnp.float32),
        pltpu.VMEM((1, pln), jnp.float32),
        pltpu.VMEM((1, pln), jnp.float32),
        pltpu.VMEM((1, pln), jnp.float32),
        pltpu.VMEM((1, pln), jnp.float32),
    ]
    if proj:
        scratch += [pltpu.VMEM((cin, cout), jnp.float32),
                    pltpu.VMEM((1, cout), jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid=(4, nt),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(10, 11, 12))
def _bot_id(x, k1, g1, b1, k2, g2, b2, k3, g3, b3, eps, interpret, bn):
    out, _ = _bot_id_fwd(
        x, k1, g1, b1, k2, g2, b2, k3, g3, b3, eps, interpret, bn
    )
    return out


def _bot_id_fwd(x, k1, g1, b1, k2, g2, b2, k3, g3, b3, eps, interpret, bn):
    out, m1, v1, m2, v2, m3, v3 = _bot_call(
        x, k1, g1, b1, k2, g2, b2, k3, g3, b3, None, eps, interpret, bn, 1
    )
    res = (x, k1, g1, b1, k2, g2, b2, k3, g3, b3,
           m1[0], v1[0], m2[0], v2[0], m3[0], v3[0])
    return (out, m1[0], v1[0], m2[0], v2[0], m3[0], v3[0]), res


def _bot_id_bwd(eps, interpret, bn, res, ct):
    (x, k1, g1, b1, k2, g2, b2, k3, g3, b3,
     m1, v1, m2, v2, m3, v3) = res
    gout = ct[0]  # batch-moment cotangents discarded (module docstring)
    dx, dw1, dw2, dw3, dg1, db1, dg2, db2, dg3, db3 = _bot_bwd_call(
        x, k1, g1, b1, k2, g2, b2, k3, g3, b3, None,
        m1, v1, m2, v2, m3, v3, gout, eps, interpret, bn, 1,
    )
    return (dx, dw1, dg1[0], db1[0], dw2, dg2[0], db2[0],
            dw3, dg3[0], db3[0])


_bot_id.defvjp(_bot_id_fwd, _bot_id_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(13, 14, 15, 16))
def _bot_proj(x, k1, g1, b1, k2, g2, b2, k3, g3, b3, ks, gs, bs,
              eps, interpret, bn, stride):
    out, _ = _bot_proj_fwd(
        x, k1, g1, b1, k2, g2, b2, k3, g3, b3, ks, gs, bs,
        eps, interpret, bn, stride,
    )
    return out


def _bot_proj_fwd(x, k1, g1, b1, k2, g2, b2, k3, g3, b3, ks, gs, bs,
                  eps, interpret, bn, stride):
    out, m1, v1, m2, v2, m3, v3, mS, vS = _bot_call(
        x, k1, g1, b1, k2, g2, b2, k3, g3, b3, (ks, gs, bs),
        eps, interpret, bn, stride,
    )
    res = (x, k1, g1, b1, k2, g2, b2, k3, g3, b3, ks, gs, bs,
           m1[0], v1[0], m2[0], v2[0], m3[0], v3[0], mS[0], vS[0])
    return (out, m1[0], v1[0], m2[0], v2[0], m3[0], v3[0],
            mS[0], vS[0]), res


def _bot_proj_bwd(eps, interpret, bn, stride, res, ct):
    (x, k1, g1, b1, k2, g2, b2, k3, g3, b3, ks, gs, bs,
     m1, v1, m2, v2, m3, v3, mS, vS) = res
    gout = ct[0]  # batch-moment cotangents discarded (module docstring)
    (dx, dw1, dw2, dw3, dg1, db1, dg2, db2, dg3, db3,
     dws, dgs, dbs) = _bot_bwd_call(
        x, k1, g1, b1, k2, g2, b2, k3, g3, b3, (ks, gs, bs, mS, vS),
        m1, v1, m2, v2, m3, v3, gout, eps, interpret, bn, stride,
    )
    return (dx, dw1, dg1[0], db1[0], dw2, dg2[0], db2[0],
            dw3, dg3[0], db3[0], dws, dgs[0], dbs[0])


_bot_proj.defvjp(_bot_proj_fwd, _bot_proj_bwd)


def fused_bottleneck_block(
    x: jax.Array,
    kernel1: jax.Array, scale1: jax.Array, bias1: jax.Array,
    kernel2: jax.Array, scale2: jax.Array, bias2: jax.Array,
    kernel3: jax.Array, scale3: jax.Array, bias3: jax.Array,
    shortcut: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    *, stride: int = 1, eps: float = 1e-5, interpret: bool = False,
    compute_dtype=None,
):
    """Fused rn50-class Bottleneck (1x1 -> 3x3/s -> 1x1, expansion 4),
    train mode, one kernel each way.

    ``kernel1``/``kernel3`` are the 1x1 convs (``(1,1,cin,planes)`` /
    ``(1,1,planes,4*planes)`` or already 2-D); ``kernel2`` the 3x3.
    ``shortcut`` is ``(kernel_sc, scale_sc, bias_sc)`` for projection
    sites (required exactly when ``stride != 1 or cin != 4*planes``),
    else None for the identity shortcut. Returns
    ``(out, m1, v1, m2, v2, m3, v3[, m_sc, v_sc])`` with biased
    variances; the caller applies the running-stat updates. BN1
    normalizes at input resolution; BN2/BN3/shortcut-BN at output
    resolution. Compute dtype follows ``x.dtype`` unless overridden;
    BN state stays fp32.
    """
    n, h, w, cin = x.shape
    pln = kernel2.shape[2]
    cdt = _compute_dtype(x, compute_dtype)
    if not supports_bottleneck(n, h, w, pln, stride=stride,
                               in_channels=cin, dtype=cdt):
        raise ValueError(
            f"fused bottleneck does not admit geometry [{n},{h},{w},{cin}] "
            f"planes={pln}/s{stride} (supports_bottleneck gate)"
        )
    needs_proj = stride != 1 or cin != 4 * pln
    if needs_proj != (shortcut is not None):
        raise ValueError(
            "bottleneck shortcut params must be provided exactly when "
            "stride != 1 or in_channels != 4*planes"
        )
    f32 = jnp.float32
    args = (
        x.astype(cdt),
        kernel1.reshape(cin, pln).astype(cdt),
        scale1.astype(f32), bias1.astype(f32),
        kernel2.astype(cdt), scale2.astype(f32), bias2.astype(f32),
        kernel3.reshape(pln, 4 * pln).astype(cdt),
        scale3.astype(f32), bias3.astype(f32),
    )
    bn = _pick_tile(
        n,
        lambda b: _vmem_estimate_bottleneck(
            b, h, w, cin, pln, stride, needs_proj, cdt.itemsize
        ) <= VMEM_BUDGET,
    )
    if shortcut is None:
        return _bot_id(*args, float(eps), bool(interpret), bn)
    ksc, ssc, bsc = shortcut
    return _bot_proj(
        *args, ksc.reshape(cin, 4 * pln).astype(cdt),
        ssc.astype(f32), bsc.astype(f32),
        float(eps), bool(interpret), bn, int(stride),
    )
