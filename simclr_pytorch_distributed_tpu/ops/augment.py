"""Device-side SimCLR augmentations — jittable, vmapped, XLA-fused.

The reference augments on the host with 8 PIL DataLoader workers per GPU
(``main_supcon.py:170-207``). TPU-natively the whole stack runs jitted on device:
uint8 batches stream over PCIe (12x smaller than fp32), and the aug pipeline
fuses into the train step, so HBM sees each image once.

Semantics follow the recipe's torchvision stack (``main_supcon.py:170-179``):

- ``RandomResizedCrop(size, scale=(0.2, 1.0))`` — including torchvision's
  10-attempt area/aspect sampling with center-crop fallback, implemented as a
  vectorized first-valid selection (static shapes, no data-dependent loops);
- ``RandomHorizontalFlip`` (p=0.5);
- ``ColorJitter(0.4, 0.4, 0.4, 0.1)`` applied with p=0.8, with torchvision's
  uniformly-sampled factors AND randomly permuted op order;
- ``RandomGrayscale(p=0.2)`` (ITU-R 601 luma);
- normalize with per-dataset mean/std (``main_supcon.py:157-162``).

All ops take/return float images in [0, 1], HWC. Geometry uses half-pixel-center
bilinear sampling; crops are never larger than the source (32x32 -> <=32 crop ->
upscale), so PIL's antialiased downscale path never engages and plain bilinear
matches the host implementation.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

# Per-dataset normalization constants (main_supcon.py:157-162 / main_ce.py:21-26).
DATASET_STATS = {
    "cifar10": ((0.4914, 0.4822, 0.4465), (0.2023, 0.1994, 0.2010)),
    "cifar100": ((0.5071, 0.4867, 0.4408), (0.2675, 0.2565, 0.2761)),
}


def _interp_matrix(coords: jax.Array, n: int) -> jax.Array:
    """Dense bilinear interpolation matrix M[out, n]: out = M @ src.

    Row i holds the two hat-function weights for sampling at ``coords[i]`` with
    edge clamping (out-of-range coords replicate the border; at the border the
    two taps coincide and their weights sum to 1).
    """
    c = jnp.clip(coords, 0.0, n - 1.0)
    c0 = jnp.floor(c)
    frac = c - c0
    c0i = jnp.clip(c0.astype(jnp.int32), 0, n - 1)
    c1i = jnp.clip(c0i + 1, 0, n - 1)
    grid = jnp.arange(n)[None, :]
    return (
        (grid == c0i[:, None]) * (1.0 - frac)[:, None]
        + (grid == c1i[:, None]) * frac[:, None]
    )


def crop_and_resize(
    img: jax.Array, top: jax.Array, left: jax.Array, h: jax.Array, w: jax.Array,
    out_size: int,
) -> jax.Array:
    """Bilinear-resize the (top, left, h, w) crop to (out_size, out_size).

    h/w/top/left are traced scalars (dynamic_slice can't take traced sizes), so
    the crop+resize is expressed as two small dense interpolation matmuls —
    under vmap these batch onto the MXU, unlike a per-pixel gather, which TPUs
    lower poorly. Half-pixel-center convention matches PIL/torchvision bilinear.
    """
    H, W = img.shape[0], img.shape[1]
    d = jnp.arange(out_size, dtype=jnp.float32)
    ys = top + (d + 0.5) * (h / out_size) - 0.5
    xs = left + (d + 0.5) * (w / out_size) - 0.5
    # clamp to the CROP box, not the image: PIL/torchvision resize a cropped
    # image, so border samples replicate the crop edge rather than bleeding
    # into pixels outside the crop (verified against PIL in test_augment).
    ys = jnp.clip(ys, top, top + h - 1.0)
    xs = jnp.clip(xs, left, left + w - 1.0)
    wy = _interp_matrix(ys, H)  # [out, H]
    wx = _interp_matrix(xs, W)  # [out, W]
    rows = jnp.einsum("sh,hwc->swc", wy, img)
    return jnp.einsum("xw,swc->sxc", wx, rows)


def random_resized_crop(
    key: jax.Array,
    img: jax.Array,
    size: int,
    scale: Tuple[float, float] = (0.2, 1.0),
    ratio: Tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0),
    attempts: int = 10,
) -> jax.Array:
    """torchvision RandomResizedCrop: 10 area/aspect attempts, first valid wins,
    else the aspect-clamped center-crop fallback."""
    H, W = img.shape[0], img.shape[1]
    area = float(H * W)
    k_area, k_ratio, k_ij = jax.random.split(key, 3)

    target_area = area * jax.random.uniform(
        k_area, (attempts,), minval=scale[0], maxval=scale[1]
    )
    log_ratio = jax.random.uniform(
        k_ratio, (attempts,),
        minval=math.log(ratio[0]), maxval=math.log(ratio[1]),
    )
    aspect = jnp.exp(log_ratio)
    ws = jnp.round(jnp.sqrt(target_area * aspect))
    hs = jnp.round(jnp.sqrt(target_area / aspect))
    valid = (ws > 0) & (ws <= W) & (hs > 0) & (hs <= H)
    # first valid attempt (torchvision returns on first success)
    idx = jnp.argmax(valid)
    any_valid = jnp.any(valid)
    w = ws[idx]
    h = hs[idx]

    # fallback: clamp aspect to the ratio range, center crop (torchvision tail).
    # H/W are static so this resolves at trace time.
    in_ratio = W / H
    if in_ratio < ratio[0]:
        fb_w, fb_h = float(W), float(round(W / ratio[0]))
    elif in_ratio > ratio[1]:
        fb_w, fb_h = float(round(H * ratio[1])), float(H)
    else:
        fb_w, fb_h = float(W), float(H)
    w = jnp.where(any_valid, w, fb_w)
    h = jnp.where(any_valid, h, fb_h)

    u_top, u_left = jax.random.uniform(k_ij, (2,))
    top = jnp.where(any_valid, jnp.floor(u_top * (H - h + 1)), jnp.round((H - h) / 2.0))
    left = jnp.where(any_valid, jnp.floor(u_left * (W - w + 1)), jnp.round((W - w) / 2.0))
    return crop_and_resize(img, top, left, h, w, size)


def random_horizontal_flip(key: jax.Array, img: jax.Array, p: float = 0.5) -> jax.Array:
    return jnp.where(jax.random.bernoulli(key, p), img[:, ::-1, :], img)


def _grayscale(img: jax.Array) -> jax.Array:
    """ITU-R 601 luma (PIL 'L' weights), single channel kept as last dim."""
    w = jnp.array([0.299, 0.587, 0.114], img.dtype)
    return jnp.sum(img * w, axis=-1, keepdims=True)


def adjust_brightness(img: jax.Array, factor: jax.Array) -> jax.Array:
    return jnp.clip(img * factor, 0.0, 1.0)


def adjust_contrast(img: jax.Array, factor: jax.Array) -> jax.Array:
    mean = jnp.mean(_grayscale(img))
    return jnp.clip(factor * img + (1.0 - factor) * mean, 0.0, 1.0)


def adjust_saturation(img: jax.Array, factor: jax.Array) -> jax.Array:
    gray = _grayscale(img)
    return jnp.clip(factor * img + (1.0 - factor) * gray, 0.0, 1.0)


def adjust_hue(img: jax.Array, delta: jax.Array) -> jax.Array:
    """Shift hue by delta (in turns, [-0.5, 0.5]) via HSV round-trip."""
    r, g, b = img[..., 0], img[..., 1], img[..., 2]
    maxc = jnp.maximum(jnp.maximum(r, g), b)
    minc = jnp.minimum(jnp.minimum(r, g), b)
    v = maxc
    c = maxc - minc
    s = jnp.where(maxc > 0, c / jnp.maximum(maxc, 1e-12), 0.0)
    safe_c = jnp.maximum(c, 1e-12)
    rc = (maxc - r) / safe_c
    gc = (maxc - g) / safe_c
    bc = (maxc - b) / safe_c
    h = jnp.where(
        r == maxc, bc - gc, jnp.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc)
    )
    h = (h / 6.0) % 1.0
    h = jnp.where(c == 0, 0.0, h)

    h = (h + delta) % 1.0

    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(jnp.int32) % 6
    # select_n lowers to pure VPU selects (jnp.choose can emit gathers)
    r2 = jax.lax.select_n(i, v, q, p, p, t, v)
    g2 = jax.lax.select_n(i, t, v, v, q, p, p)
    b2 = jax.lax.select_n(i, p, p, t, v, v, q)
    return jnp.stack([r2, g2, b2], axis=-1)


def color_jitter(
    key: jax.Array,
    img: jax.Array,
    brightness: float = 0.4,
    contrast: float = 0.4,
    saturation: float = 0.4,
    hue: float = 0.1,
) -> jax.Array:
    """torchvision ColorJitter: uniform factors, randomly permuted op order."""
    k_perm, k_b, k_c, k_s, k_h = jax.random.split(key, 5)
    fb = jax.random.uniform(k_b, (), minval=1 - brightness, maxval=1 + brightness)
    fc = jax.random.uniform(k_c, (), minval=1 - contrast, maxval=1 + contrast)
    fs = jax.random.uniform(k_s, (), minval=1 - saturation, maxval=1 + saturation)
    fh = jax.random.uniform(k_h, (), minval=-hue, maxval=hue)

    branches = (
        lambda x: adjust_brightness(x, fb),
        lambda x: adjust_contrast(x, fc),
        lambda x: adjust_saturation(x, fs),
        lambda x: adjust_hue(x, fh),
    )
    order = jax.random.permutation(k_perm, 4)

    def body(i, x):
        return jax.lax.switch(order[i], branches, x)

    return jax.lax.fori_loop(0, 4, body, img)


def random_apply(key: jax.Array, fn, img: jax.Array, p: float) -> jax.Array:
    k_gate, k_fn = jax.random.split(key)
    return jnp.where(jax.random.bernoulli(k_gate, p), fn(k_fn, img), img)


def random_grayscale(key: jax.Array, img: jax.Array, p: float = 0.2) -> jax.Array:
    gray3 = jnp.broadcast_to(_grayscale(img), img.shape)
    return jnp.where(jax.random.bernoulli(key, p), gray3, img)


def normalize(img: jax.Array, mean: Sequence[float], std: Sequence[float]) -> jax.Array:
    mean = jnp.asarray(mean, img.dtype)
    std = jnp.asarray(std, img.dtype)
    return (img - mean) / std


def to_float(img_u8: jax.Array) -> jax.Array:
    return img_u8.astype(jnp.float32) / 255.0


@dataclasses.dataclass(frozen=True)
class AugmentConfig:
    """The contrastive-pretrain transform stack (main_supcon.py:170-179)."""

    size: int = 32
    scale: Tuple[float, float] = (0.2, 1.0)
    jitter_prob: float = 0.8
    jitter_strength: Tuple[float, float, float, float] = (0.4, 0.4, 0.4, 0.1)
    grayscale_prob: float = 0.2
    mean: Tuple[float, ...] = DATASET_STATS["cifar10"][0]
    std: Tuple[float, ...] = DATASET_STATS["cifar10"][1]
    # linear/CE stage drops jitter+grayscale (main_ce.py:31-36)
    color_ops: bool = True


def simclr_transform(key: jax.Array, img_u8: jax.Array, cfg: AugmentConfig) -> jax.Array:
    """One augmented view of one image: uint8 HWC -> normalized float HWC."""
    img = to_float(img_u8)
    k_crop, k_flip, k_jit, k_gray = jax.random.split(key, 4)
    img = random_resized_crop(k_crop, img, cfg.size, cfg.scale)
    img = random_horizontal_flip(k_flip, img)
    if cfg.color_ops:
        b, c, s, h = cfg.jitter_strength
        img = random_apply(
            k_jit, partial(color_jitter, brightness=b, contrast=c, saturation=s, hue=h),
            img, cfg.jitter_prob,
        )
        img = random_grayscale(k_gray, img)
    return normalize(img, cfg.mean, cfg.std)


def eval_transform(img_u8: jax.Array, cfg: AugmentConfig) -> jax.Array:
    """Validation path: ToTensor + normalize only (main_ce.py:38-41)."""
    return normalize(to_float(img_u8), cfg.mean, cfg.std)


def two_crop_batch(key: jax.Array, images_u8: jax.Array, cfg: AugmentConfig) -> jax.Array:
    """TwoCropTransform over a batch: [B,H,W,C] uint8 -> [B,2,size,size,C] float.

    Two independent transform draws per image (util.py:10-16).
    """
    B = images_u8.shape[0]
    keys = jax.random.split(key, 2 * B).reshape(B, 2)

    def per_image(ks, img):
        v1 = simclr_transform(ks[0], img, cfg)
        v2 = simclr_transform(ks[1], img, cfg)
        return jnp.stack([v1, v2])

    return jax.vmap(per_image)(keys, images_u8)


def augment_batch(key: jax.Array, images_u8: jax.Array, cfg: AugmentConfig) -> jax.Array:
    """Single-view augmentation over a batch (linear/CE train stage)."""
    keys = jax.random.split(key, images_u8.shape[0])
    return jax.vmap(lambda k, im: simclr_transform(k, im, cfg))(keys, images_u8)


def eval_batch(images_u8: jax.Array, cfg: AugmentConfig) -> jax.Array:
    return jax.vmap(lambda im: eval_transform(im, cfg))(images_u8)
