"""Metrics and meters.

``topk_accuracy`` matches the reference ``accuracy()`` (``util.py:37-51``): percent
of targets found in the top-k predictions, returned per requested k.
``AverageMeter`` mirrors ``util.py:19-34`` for host-side wall-clock/metric
averaging in the epoch drivers.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def topk_accuracy(
    output: jax.Array, target: jax.Array, topk: Sequence[int] = (1,)
) -> Tuple[jax.Array, ...]:
    """Top-k accuracy in percent, one scalar per k (reference ``util.py:37-51``)."""
    maxk = max(topk)
    batch_size = target.shape[0]
    # [maxk, batch] ranked predictions.
    _, pred = jax.lax.top_k(output, maxk)
    correct = pred.T == target[None, :]
    res = []
    for k in topk:
        correct_k = jnp.sum(correct[:k].astype(jnp.float32))
        res.append(correct_k * (100.0 / batch_size))
    return tuple(res)


def embedding_covariance(
    emb: jax.Array, center: bool = False, ddof: int = 0
) -> jax.Array:
    """``[D, D]`` (co)variance matrix of an ``[N, D]`` embedding batch.

    One covariance construction shared by the two consumers that must agree
    on it: the health diagnostics' effective-rank spectrum
    (train/supcon_step.contrastive_health_metrics — UNCENTERED second moment,
    ``center=False, ddof=0``, the PR-8 definition kept bitwise) and the
    VICReg covariance penalty (ops/losses.vicreg_loss — centered, unbiased:
    ``center=True, ddof=1``, the paper's estimator).
    """
    if center:
        emb = emb - jnp.mean(emb, axis=0, keepdims=True)
    return emb.T @ emb / (emb.shape[0] - ddof)


def topk_correct(logits: jax.Array, labels: jax.Array, ks=(1, 5)):
    """Per-batch top-k correct counts (sum-able across shards/batches).

    Shared by the probe/CE ring steps (train/linear.py, train/ce.py) and the
    pretrain step's online probe (train/supcon_step.py) — lives here rather
    than in train/linear.py so supcon_step can use it without an import
    cycle through the driver modules.
    """
    maxk = max(ks)
    _, pred = jax.lax.top_k(logits, maxk)
    hit = pred == labels[:, None]
    return {k: jnp.sum(jnp.any(hit[:, :k], axis=1)) for k in ks}


class AverageMeter:
    """Running value/average meter (reference ``util.py:19-34``)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1) -> None:
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count


class MetricRing:
    """Device-side ``[window, K]`` fp32 metric ring + its host bookkeeping.

    The pre-ring ``MetricBuffer`` (deleted once the last trainer moved to the
    ring) batched the per-window readback into one ``device_get`` *call*, but
    each buffered step still held ~K live device scalars, so the runtime
    issued one tiny D2H descriptor per scalar — ~window*K transfers per flush
    (~110 ms/window on a tunneled link, docs/PERF.md round 5). The ring
    closes that: the jitted step writes its
    metrics into row ``step % window`` of ONE device array
    (:meth:`write`, a ``dynamic_update_slice`` inside the compiled program,
    carried with the train state under the same donation discipline), and a
    flush is ONE contiguous D2H of that single small array
    (:meth:`resolve`). The host side records which ``(info, step)`` pairs are
    pending (:meth:`append` / :meth:`take_window`) and slices their rows out
    of the fetched block.

    ``device_get`` is injectable so tests can count transfers mechanically
    (``self.transfers`` counts flushes; each is exactly one call) or gate
    them on an event to prove dispatch/flush overlap.
    """

    def __init__(
        self,
        window: int,
        keys: Sequence[str],
        device_get: Optional[Callable] = None,
    ) -> None:
        if window <= 0:
            raise ValueError(f"ring window must be positive, got {window}")
        if len(set(keys)) != len(keys):
            raise ValueError(f"duplicate metric keys: {sorted(keys)}")
        self.window = int(window)
        # fixed column order shared by the trace-time writer and the host
        # reader — sorted so both sides derive it from the key SET alone
        self.keys = tuple(sorted(keys))
        self._device_get = device_get if device_get is not None else jax.device_get
        self._pending = []  # [(info, global_step)] appended, not yet flushed
        self.transfers = 0  # host transfers performed (== completed flushes)

    def init_buffer(self, sharding=None) -> jax.Array:
        """A fresh (zero) ring buffer; create one per epoch — the ring is
        transient driver state and is never checkpointed. ``sharding`` (the
        mesh's replicated sharding in the drivers) places the buffer where
        the jitted update expects it, so the first donation of each epoch
        doesn't relayout."""
        buf = jnp.zeros((self.window, len(self.keys)), jnp.float32)
        return buf if sharding is None else jax.device_put(buf, sharding)

    def write(self, ring: jax.Array, metrics: dict, step) -> jax.Array:
        """Trace-time: write ``metrics`` into row ``step % window``.

        Called INSIDE the jitted update with the traced ``state.step`` (the
        pre-increment global step), so the slot needs no extra carried
        counter and no host->device scalar per call.
        """
        if tuple(sorted(metrics)) != self.keys:
            raise ValueError(
                f"metric keys {sorted(metrics)} != ring keys {list(self.keys)}"
            )
        row = jnp.stack(
            [jnp.asarray(metrics[k]).astype(jnp.float32) for k in self.keys]
        )
        slot = jnp.asarray(step, jnp.int32) % self.window
        return jax.lax.dynamic_update_slice(
            ring, row[None, :], (slot, jnp.zeros((), jnp.int32))
        )

    def append(self, info, step: int) -> None:
        """Record that the step just dispatched wrote slot ``step % window``."""
        if len(self._pending) >= self.window:
            raise RuntimeError(
                f"metric ring overflow: {len(self._pending)} steps pending in "
                f"a window of {self.window} — flush at least every "
                f"{self.window} steps"
            )
        self._pending.append((info, int(step)))

    def pending_count(self) -> int:
        """Steps appended since the last flush (the current window's size)."""
        return len(self._pending)

    def take_window(self):
        """Hand the pending ``(info, step)`` list to a flush; clears it."""
        pending, self._pending = self._pending, []
        return pending

    def resolve(self, snapshot: jax.Array, pending):
        """ONE host transfer of the whole ring; returns ``[(info, {k: float})]``.

        ``snapshot`` must be a buffer later steps cannot donate away — the
        drivers hand a device-side copy taken at the window boundary.
        """
        if not pending:
            return []
        self.transfers += 1
        host = np.asarray(self._device_get(snapshot))
        out = []
        for info, step in pending:
            row = host[step % self.window]
            out.append(
                (info, {k: float(row[i]) for i, k in enumerate(self.keys)})
            )
        return out
