"""Metrics and meters.

``topk_accuracy`` matches the reference ``accuracy()`` (``util.py:37-51``): percent
of targets found in the top-k predictions, returned per requested k.
``AverageMeter`` mirrors ``util.py:19-34`` for host-side wall-clock/metric
averaging in the epoch drivers.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def topk_accuracy(
    output: jax.Array, target: jax.Array, topk: Sequence[int] = (1,)
) -> Tuple[jax.Array, ...]:
    """Top-k accuracy in percent, one scalar per k (reference ``util.py:37-51``)."""
    maxk = max(topk)
    batch_size = target.shape[0]
    # [maxk, batch] ranked predictions.
    _, pred = jax.lax.top_k(output, maxk)
    correct = pred.T == target[None, :]
    res = []
    for k in topk:
        correct_k = jnp.sum(correct[:k].astype(jnp.float32))
        res.append(correct_k * (100.0 / batch_size))
    return tuple(res)


class AverageMeter:
    """Running value/average meter (reference ``util.py:19-34``)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1) -> None:
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count
