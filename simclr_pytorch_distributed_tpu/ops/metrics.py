"""Metrics and meters.

``topk_accuracy`` matches the reference ``accuracy()`` (``util.py:37-51``): percent
of targets found in the top-k predictions, returned per requested k.
``AverageMeter`` mirrors ``util.py:19-34`` for host-side wall-clock/metric
averaging in the epoch drivers.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def topk_accuracy(
    output: jax.Array, target: jax.Array, topk: Sequence[int] = (1,)
) -> Tuple[jax.Array, ...]:
    """Top-k accuracy in percent, one scalar per k (reference ``util.py:37-51``)."""
    maxk = max(topk)
    batch_size = target.shape[0]
    # [maxk, batch] ranked predictions.
    _, pred = jax.lax.top_k(output, maxk)
    correct = pred.T == target[None, :]
    res = []
    for k in topk:
        correct_k = jnp.sum(correct[:k].astype(jnp.float32))
        res.append(correct_k * (100.0 / batch_size))
    return tuple(res)


class AverageMeter:
    """Running value/average meter (reference ``util.py:19-34``)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1) -> None:
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count


class MetricBuffer:
    """Buffers per-step device metric dicts; fetches them in ONE batched
    device->host transfer on ``flush()``.

    The reference reads ``loss.item()`` every iteration (main_supcon.py:320) —
    a sync point that stalls dispatch. Fetching only every ``print_freq`` steps
    (round-1 behavior) kept dispatch async but subsampled the meters/TB curves
    to ~1/print_freq of the steps. Buffering gives both: every step is metered
    and TB-logged at reference cadence, with one transfer per flush instead of
    one per step.
    """

    def __init__(self) -> None:
        self._steps = []  # (step_info, {name: device scalar})

    def append(self, info, metrics: dict) -> None:
        self._steps.append((info, metrics))

    def flush(self):
        """Returns [(info, {name: float})] for all buffered steps; clears."""
        if not self._steps:
            return []
        keys = sorted(self._steps[0][1])
        # jax.device_get on the plain nested list batches all the D2H copies
        # into one async sweep WITHOUT building an XLA program — a jnp.stack
        # here would compile a new program for every distinct (n_steps, n_keys)
        # buffer shape (tail windows differ), which dominated driver runtime on
        # the CPU test host.
        fetched = jax.device_get([[m[k] for k in keys] for _, m in self._steps])
        out = [
            (info, dict(zip(keys, (float(v) for v in row))))
            for (info, _), row in zip(self._steps, fetched)
        ]
        self._steps = []
        return out
