"""Fused Pallas TPU kernel for the SupCon/SimCLR contrastive loss.

The reference materializes the full ``[V*B, V*B]`` logits matrix and three more
same-sized temporaries (mask, exp_logits, log_prob — reference ``losses.py:64-90``),
all round-tripping through HBM. This kernel is the flash-attention-style
decomposition of the same math: the logits tile ``[bm, bn]`` lives only in VMEM,
a numerically exact online log-sum-exp streams over column blocks, and the
positive-pair similarities accumulate alongside. HBM traffic drops from
O((VB)^2) to O(VB·D), and the row-max subtraction (``losses.py:68-69``) is
replaced by the online max, which cancels exactly in ``logit − logsumexp``.

Semantics match ``ops.losses.supcon_loss`` (contrast_mode='all') bit-for-fp32:
the τ/τ_base final scale, self-pair exclusion, and the mean over all V·B anchor
rows. Both SimCLR (positives = other views of the same sample) and SupCon
(positives = same label) reduce to one code path by comparing per-row integer
ids (sample index or label).

The backward pass is a second Pallas kernel. With symmetric logits
``L = F·Fᵀ/τ``, the gradient is ``dF = g·(G + Gᵀ)·F/τ`` where
``G_ij = c·(softmax_ij − P_ij/cnt_i)``, ``c = (τ/τ_base)/(V·B)``; the kernel
recomputes each logits tile (no O(N²) residual is ever stored — only the
per-row ``lse`` and positive counts) and contracts both terms against the
column features in one pass.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _pick_block(n: int, cap: int) -> Optional[int]:
    for c in (512, 256, 128, 64, 32, 16, 8):
        if c <= cap and c <= n and n % c == 0:
            return c
    return None


def _vmem_spec(block_shape=None, index_map=None):
    if block_shape is None:
        return pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)


def _fwd_kernel(
    frow_ref, fcol_ref, idr_ref, idc_ref,
    loss_ref, lse_ref, cnt_ref,
    m_sc, s_sc, p_sc, c_sc,
    *, bm: int, bn: int, inv_temp: float, scale: float,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_sc[:] = jnp.full((bm, 1), _NEG_INF, jnp.float32)
        s_sc[:] = jnp.zeros((bm, 1), jnp.float32)
        p_sc[:] = jnp.zeros((bm, 1), jnp.float32)
        c_sc[:] = jnp.zeros((bm, 1), jnp.float32)

    logits = (
        jnp.dot(frow_ref[:], fcol_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )
    gi = pl.program_id(0) * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 0)
    gj = j * bn + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 1)
    self_mask = gi == gj
    pos_mask = (idr_ref[:] == idc_ref[:]) & jnp.logical_not(self_mask)

    masked = jnp.where(self_mask, _NEG_INF, logits)
    blk_max = jnp.max(masked, axis=1, keepdims=True)
    new_max = jnp.maximum(m_sc[:], blk_max)
    s_sc[:] = s_sc[:] * jnp.exp(m_sc[:] - new_max) + jnp.sum(
        jnp.exp(masked - new_max), axis=1, keepdims=True
    )
    m_sc[:] = new_max
    p_sc[:] = p_sc[:] + jnp.sum(
        jnp.where(pos_mask, logits, 0.0), axis=1, keepdims=True
    )
    c_sc[:] = c_sc[:] + jnp.sum(pos_mask.astype(jnp.float32), axis=1, keepdims=True)

    @pl.when(j == nj - 1)
    def _():
        lse = m_sc[:] + jnp.log(s_sc[:])
        lse_ref[:] = lse
        cnt_ref[:] = c_sc[:]
        loss_ref[:] = -scale * (p_sc[:] / c_sc[:] - lse)


def _bwd_kernel(
    frow_ref, fcol_ref, idr_ref, idc_ref,
    lse_r_ref, lse_c_ref, cnt_r_ref, cnt_c_ref,
    dfeat_ref, acc_sc,
    *, bm: int, bn: int, inv_temp: float, coeff: float,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    logits = (
        jnp.dot(frow_ref[:], fcol_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )
    gi = pl.program_id(0) * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 0)
    gj = j * bn + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 1)
    self_mask = gi == gj
    pos = ((idr_ref[:] == idc_ref[:]) & jnp.logical_not(self_mask)).astype(
        jnp.float32
    )

    # softmax terms for row-anchored (G) and column-anchored (Gᵀ) halves; both
    # use exp(l − lse) ≤ 1 since lse ≥ row max — no overflow.
    sm_i = jnp.where(self_mask, 0.0, jnp.exp(logits - lse_r_ref[:]))
    sm_j = jnp.where(self_mask, 0.0, jnp.exp(logits - lse_c_ref[:]))
    h = (sm_i - pos / cnt_r_ref[:]) + (sm_j - pos / cnt_c_ref[:])
    acc_sc[:] = acc_sc[:] + jnp.dot(
        h, fcol_ref[:], preferred_element_type=jnp.float32
    ) * (coeff * inv_temp)

    @pl.when(j == nj - 1)
    def _():
        dfeat_ref[:] = acc_sc[:]


def _fwd_call(feats, ids, temperature, base_temperature, interpret, bm, bn):
    n, d = feats.shape
    grid = (n // bm, n // bn)
    scale = temperature / base_temperature
    kernel = functools.partial(
        _fwd_kernel, bm=bm, bn=bn, inv_temp=1.0 / temperature, scale=scale
    )
    out_shape = [jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 3
    scratch = [pltpu.VMEM((bm, 1), jnp.float32) for _ in range(4)]
    row_out = _vmem_spec((bm, 1), lambda i, j: (i, 0))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((bm, d), lambda i, j: (i, 0)),
            _vmem_spec((bn, d), lambda i, j: (j, 0)),
            _vmem_spec((bm, 1), lambda i, j: (i, 0)),
            _vmem_spec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=[row_out, row_out, row_out],
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(feats, feats, ids[:, None], ids[None, :])


def _bwd_call(feats, ids, lse, cnt, temperature, base_temperature, interpret, bm, bn):
    n, d = feats.shape
    grid = (n // bm, n // bn)
    coeff = (temperature / base_temperature) / n
    kernel = functools.partial(
        _bwd_kernel, bm=bm, bn=bn, inv_temp=1.0 / temperature, coeff=coeff
    )
    scratch = [pltpu.VMEM((bm, d), jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((bm, d), lambda i, j: (i, 0)),
            _vmem_spec((bn, d), lambda i, j: (j, 0)),
            _vmem_spec((bm, 1), lambda i, j: (i, 0)),
            _vmem_spec((1, bn), lambda i, j: (0, j)),
            _vmem_spec((bm, 1), lambda i, j: (i, 0)),
            _vmem_spec((1, bn), lambda i, j: (0, j)),
            _vmem_spec((bm, 1), lambda i, j: (i, 0)),
            _vmem_spec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=_vmem_spec((bm, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=interpret,
        scratch_shapes=scratch,
    )(
        feats, feats, ids[:, None], ids[None, :],
        lse[:, None], lse[None, :], cnt[:, None], cnt[None, :],
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _fused_loss(feats, ids, temperature, base_temperature, interpret, bm, bn):
    loss_rows, _, _ = _fwd_call(
        feats, ids, temperature, base_temperature, interpret, bm, bn
    )
    return jnp.mean(loss_rows)


def _fused_loss_fwd(feats, ids, temperature, base_temperature, interpret, bm, bn):
    loss_rows, lse, cnt = _fwd_call(
        feats, ids, temperature, base_temperature, interpret, bm, bn
    )
    return jnp.mean(loss_rows), (feats, ids, lse[:, 0], cnt[:, 0])


def _fused_loss_bwd(temperature, base_temperature, interpret, bm, bn, res, g):
    feats, ids, lse, cnt = res
    dfeats = _bwd_call(
        feats, ids, lse, cnt, temperature, base_temperature, interpret, bm, bn
    )
    return (g * dfeats, np.zeros(ids.shape, jax.dtypes.float0))


_fused_loss.defvjp(_fused_loss_fwd, _fused_loss_bwd)


def supports(batch_size: int, n_views: int) -> bool:
    """True if the fused kernel can handle this [B, V, d] problem size."""
    n = batch_size * n_views
    return _pick_block(n, 256) is not None


def fused_supcon_loss(
    features: jax.Array,
    labels: Optional[jax.Array] = None,
    *,
    temperature: float = 0.07,
    base_temperature: float = 0.07,
    interpret: bool = False,
    block_rows: int = 256,
    block_cols: int = 512,
) -> jax.Array:
    """Drop-in fused replacement for ``supcon_loss(..., contrast_mode='all')``.

    Args:
      features: ``[B, V, d]`` L2-normalized multi-view features (same contract
        as ``ops.losses.supcon_loss``).
      labels: optional ``[B]`` integer labels (SupCon); ``None`` = SimCLR.
      interpret: run the Pallas interpreter (CPU testing).
      block_rows / block_cols: VMEM tile caps; actual tiles are the largest
        divisors of ``V*B`` within the caps.

    Returns:
      Scalar loss, differentiable w.r.t. ``features``.
    """
    batch, n_views = features.shape[0], features.shape[1]
    n = batch * n_views
    feats = jnp.transpose(features, (1, 0, 2)).reshape(n, -1).astype(jnp.float32)
    if labels is None:
        sample_ids = jnp.tile(jnp.arange(batch, dtype=jnp.int32), n_views)
    else:
        sample_ids = jnp.tile(labels.astype(jnp.int32).reshape(-1), n_views)
    bm = _pick_block(n, block_rows)
    bn = _pick_block(n, block_cols)
    if bm is None or bn is None:
        raise ValueError(
            f"fused loss needs V*B divisible by 8, got {n}; use the dense path"
        )
    return _fused_loss(
        feats, sample_ids, float(temperature), float(base_temperature),
        bool(interpret), bm, bn,
    )
