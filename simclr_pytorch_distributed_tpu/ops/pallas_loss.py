"""Fused Pallas TPU kernel for the SupCon/SimCLR contrastive loss.

The reference materializes the full ``[V*B, V*B]`` logits matrix and three more
same-sized temporaries (mask, exp_logits, log_prob — reference ``losses.py:64-90``),
all round-tripping through HBM. This kernel is the flash-attention-style
decomposition of the same math: the logits tile ``[bm, bn]`` lives only in VMEM,
a numerically exact online log-sum-exp streams over column blocks, and the
positive-pair similarities accumulate alongside. HBM traffic drops from
O((VB)^2) to O(VB·D), and the row-max subtraction (``losses.py:68-69``) is
replaced by the online max, which cancels exactly in ``logit − logsumexp``.

Semantics match ``ops.losses.supcon_loss`` (contrast_mode='all') bit-for-fp32:
the τ/τ_base final scale, self-pair exclusion, and the mean over all V·B anchor
rows. Both SimCLR (positives = other views of the same sample) and SupCon
(positives = same label) reduce to one code path by comparing per-row integer
ids (sample index or label).

The backward pass is a second Pallas kernel. With symmetric logits
``L = F·Fᵀ/τ``, the gradient is ``dF = g·(G + Gᵀ)·F/τ`` where
``G_ij = c·(softmax_ij − P_ij/cnt_i)``, ``c = (τ/τ_base)/(V·B)``; the kernel
recomputes each logits tile (no O(N²) residual is ever stored — only the
per-row ``lse`` and positive counts) and contracts both terms against the
column features in one pass.

Sharded mode (``fused_sharded_supcon_loss``): the same kernels run inside
``shard_map`` over the ``data`` mesh axis. Anchor rows stay sharded (each
device owns ``m = V·B/P`` contiguous view-major rows, the layout the reference
assembles post-gather, ``main_supcon.py:276-279``); the contrast side is the
all-gathered ``[V·B, D]`` feature matrix — the same O(V·B·D) replicated
transfer the reference's NCCL ``all_gather`` performs (``main_supcon.py:268``)
— but the ``[m, V·B]`` logits block and its softmax temporaries never touch
HBM. The grid is rectangular (local rows × global cols); self/positive masking
uses explicit global row/col indices instead of ``program_id`` so a shard's
row offset is a traced value. The backward exploits logits symmetry: row i's
full gradient ``(G + Gᵀ)_i,: · F`` needs only row-i softmax stats (local) and
col-j stats (the all-gathered O(V·B) ``lse``/``cnt`` vectors), so each device
computes the exact global gradient of its own rows with no O(N²) residual.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from simclr_pytorch_distributed_tpu.compat import axis_size, shape_dtype_struct

_NEG_INF = -1e30


def _pick_block(n: int, cap: int) -> Optional[int]:
    for c in (512, 256, 128, 64, 32, 16, 8):
        if c <= cap and c <= n and n % c == 0:
            return c
    return None


def _vmem_spec(block_shape=None, index_map=None):
    if block_shape is None:
        return pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)


def _fwd_kernel(
    frow_ref, fcol_ref, idr_ref, idc_ref, gr_ref, gc_ref,
    loss_ref, lse_ref, cnt_ref,
    m_sc, s_sc, p_sc, c_sc,
    *, bm: int, bn: int, inv_temp: float, scale: float,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_sc[:] = jnp.full((bm, 1), _NEG_INF, jnp.float32)
        s_sc[:] = jnp.zeros((bm, 1), jnp.float32)
        p_sc[:] = jnp.zeros((bm, 1), jnp.float32)
        c_sc[:] = jnp.zeros((bm, 1), jnp.float32)

    logits = (
        jnp.dot(frow_ref[:], fcol_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )
    # global row/col ids come in as data (not program_id): in sharded mode the
    # row block's global offset is a traced per-device value.
    self_mask = gr_ref[:] == gc_ref[:]
    pos_mask = (idr_ref[:] == idc_ref[:]) & jnp.logical_not(self_mask)

    masked = jnp.where(self_mask, _NEG_INF, logits)
    blk_max = jnp.max(masked, axis=1, keepdims=True)
    new_max = jnp.maximum(m_sc[:], blk_max)
    s_sc[:] = s_sc[:] * jnp.exp(m_sc[:] - new_max) + jnp.sum(
        jnp.exp(masked - new_max), axis=1, keepdims=True
    )
    m_sc[:] = new_max
    p_sc[:] = p_sc[:] + jnp.sum(
        jnp.where(pos_mask, logits, 0.0), axis=1, keepdims=True
    )
    c_sc[:] = c_sc[:] + jnp.sum(pos_mask.astype(jnp.float32), axis=1, keepdims=True)

    @pl.when(j == nj - 1)
    def _():
        lse = m_sc[:] + jnp.log(s_sc[:])
        lse_ref[:] = lse
        cnt_ref[:] = c_sc[:]
        loss_ref[:] = -scale * (p_sc[:] / c_sc[:] - lse)


def _bwd_kernel(
    frow_ref, fcol_ref, idr_ref, idc_ref, gr_ref, gc_ref,
    lse_r_ref, lse_c_ref, cnt_r_ref, cnt_c_ref,
    dfeat_ref, acc_sc,
    *, bm: int, bn: int, inv_temp: float, coeff: float,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    logits = (
        jnp.dot(frow_ref[:], fcol_ref[:].T, preferred_element_type=jnp.float32)
        * inv_temp
    )
    self_mask = gr_ref[:] == gc_ref[:]
    pos = ((idr_ref[:] == idc_ref[:]) & jnp.logical_not(self_mask)).astype(
        jnp.float32
    )

    # softmax terms for row-anchored (G) and column-anchored (Gᵀ) halves; both
    # use exp(l − lse) ≤ 1 since lse ≥ row max — no overflow.
    sm_i = jnp.where(self_mask, 0.0, jnp.exp(logits - lse_r_ref[:]))
    sm_j = jnp.where(self_mask, 0.0, jnp.exp(logits - lse_c_ref[:]))
    h = (sm_i - pos / cnt_r_ref[:]) + (sm_j - pos / cnt_c_ref[:])
    acc_sc[:] = acc_sc[:] + jnp.dot(
        h, fcol_ref[:], preferred_element_type=jnp.float32
    ) * (coeff * inv_temp)

    @pl.when(j == nj - 1)
    def _():
        dfeat_ref[:] = acc_sc[:]


def _fwd_call(
    frow, fcol, idr, idc, grow, gcol,
    temperature, base_temperature, interpret, bm, bn, vma=None,
):
    """Rectangular forward: per-row loss/lse/cnt for anchor rows ``frow``
    against contrast columns ``fcol`` (``frow is fcol`` in the dense case).

    ``vma`` is the varying-manual-axes set for the outputs when called inside
    shard_map (required by check_vma); ``None`` outside shard_map.
    """
    nr, d = frow.shape
    nc = fcol.shape[0]
    grid = (nr // bm, nc // bn)
    scale = temperature / base_temperature
    kernel = functools.partial(
        _fwd_kernel, bm=bm, bn=bn, inv_temp=1.0 / temperature, scale=scale
    )
    out_shape = [shape_dtype_struct((nr, 1), jnp.float32, vma=vma)] * 3
    scratch = [pltpu.VMEM((bm, 1), jnp.float32) for _ in range(4)]
    row_spec = _vmem_spec((bm, 1), lambda i, j: (i, 0))
    col_spec = _vmem_spec((1, bn), lambda i, j: (0, j))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((bm, d), lambda i, j: (i, 0)),
            _vmem_spec((bn, d), lambda i, j: (j, 0)),
            row_spec, col_spec, row_spec, col_spec,
        ],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(frow, fcol, idr[:, None], idc[None, :], grow[:, None], gcol[None, :])


def _bwd_call(
    frow, fcol, idr, idc, grow, gcol, lse_r, lse_c, cnt_r, cnt_c,
    temperature, coeff, interpret, bm, bn, vma=None,
):
    """Rectangular backward: exact global gradient of the anchor rows."""
    nr, d = frow.shape
    nc = fcol.shape[0]
    grid = (nr // bm, nc // bn)
    kernel = functools.partial(
        _bwd_kernel, bm=bm, bn=bn, inv_temp=1.0 / temperature, coeff=coeff
    )
    scratch = [pltpu.VMEM((bm, d), jnp.float32)]
    row_spec = _vmem_spec((bm, 1), lambda i, j: (i, 0))
    col_spec = _vmem_spec((1, bn), lambda i, j: (0, j))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((bm, d), lambda i, j: (i, 0)),
            _vmem_spec((bn, d), lambda i, j: (j, 0)),
            row_spec, col_spec, row_spec, col_spec,
            row_spec, col_spec, row_spec, col_spec,
        ],
        out_specs=_vmem_spec((bm, d), lambda i, j: (i, 0)),
        out_shape=shape_dtype_struct((nr, d), jnp.float32, vma=vma),
        interpret=interpret,
        scratch_shapes=scratch,
    )(
        frow, fcol, idr[:, None], idc[None, :], grow[:, None], gcol[None, :],
        lse_r[:, None], lse_c[None, :], cnt_r[:, None], cnt_c[None, :],
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _fused_loss(feats, ids, temperature, base_temperature, interpret, bm, bn):
    loss, _ = _fused_loss_fwd(
        feats, ids, temperature, base_temperature, interpret, bm, bn
    )
    return loss


def _fused_loss_fwd(feats, ids, temperature, base_temperature, interpret, bm, bn):
    n = feats.shape[0]
    gidx = jnp.arange(n, dtype=jnp.int32)
    loss_rows, lse, cnt = _fwd_call(
        feats, feats, ids, ids, gidx, gidx,
        temperature, base_temperature, interpret, bm, bn,
    )
    return jnp.mean(loss_rows), (feats, ids, lse[:, 0], cnt[:, 0])


def _fused_loss_bwd(temperature, base_temperature, interpret, bm, bn, res, g):
    feats, ids, lse, cnt = res
    n = feats.shape[0]
    gidx = jnp.arange(n, dtype=jnp.int32)
    coeff = (temperature / base_temperature) / n
    dfeats = _bwd_call(
        feats, feats, ids, ids, gidx, gidx, lse, lse, cnt, cnt,
        temperature, coeff, interpret, bm, bn,
    )
    return (g * dfeats, np.zeros(ids.shape, jax.dtypes.float0))


_fused_loss.defvjp(_fused_loss_fwd, _fused_loss_bwd)


# ---------------------------------------------------------------------------
# Sharded mode: the kernels inside shard_map over the data axis.
# ---------------------------------------------------------------------------


def _vma_of(x):
    """The varying-manual-axes set pallas_call outputs must carry, or None.

    Under ``shard_map(check_vma=False)`` (the supported mode for this kernel —
    the interpret-mode Pallas lowering cannot type kernel-internal constants)
    every array's vma is empty and pallas_call wants ``vma=None``.
    """
    try:
        return jax.typeof(x).vma or None
    except AttributeError:
        return None


def _vary(x, axis_name):
    """Mark a replicated array as device-varying for shard_map's vma typing.

    Idempotent: arrays already varying over ``axis_name`` (e.g. all_gather
    results, whose inputs were varying) pass through unchanged.
    """
    try:
        if axis_name in jax.typeof(x).vma:
            return x
    except AttributeError:
        pass
    from simclr_pytorch_distributed_tpu.compat import pvary

    return pvary(x, (axis_name,))  # identity on pre-vma jax


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _fused_sharded(
    feats_local, ids_global, axis_name,
    temperature, base_temperature, interpret, bm, bn,
):
    loss, _ = _fused_sharded_fwd(
        feats_local, ids_global, axis_name,
        temperature, base_temperature, interpret, bm, bn,
    )
    return loss


def _sharded_indices(feats_local, axis_name):
    m = feats_local.shape[0]
    p = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    grow = my * m + jnp.arange(m, dtype=jnp.int32)  # device-varying
    gcol = _vary(jnp.arange(m * p, dtype=jnp.int32), axis_name)
    return grow, gcol


def _fused_sharded_fwd(
    feats_local, ids_global, axis_name,
    temperature, base_temperature, interpret, bm, bn,
):
    all_feats = _vary(
        jax.lax.all_gather(feats_local, axis_name, tiled=True), axis_name
    )
    grow, gcol = _sharded_indices(feats_local, axis_name)
    ids_v = _vary(ids_global, axis_name)
    idr = jnp.take(ids_v, grow, axis=0)
    loss_rows, lse, cnt = _fwd_call(
        feats_local, all_feats, idr, ids_v, grow, gcol,
        temperature, base_temperature, interpret, bm, bn,
        vma=_vma_of(feats_local),
    )
    # mean over local anchor rows, pmean over the axis == the global mean.
    loss = jax.lax.pmean(jnp.mean(loss_rows), axis_name)
    return loss, (feats_local, ids_global, lse[:, 0], cnt[:, 0])


def _fused_sharded_bwd(
    axis_name, temperature, base_temperature, interpret, bm, bn, res, g
):
    feats_local, ids_global, lse, cnt = res
    if _vma_of(feats_local) is None:
        # check_vma=False: shard_map distributes a replicated output's
        # cotangent as per-shard 1/P shares — psum recovers the full scalar.
        g = jax.lax.psum(g, axis_name)
    m = feats_local.shape[0]
    p = axis_size(axis_name)
    n = m * p
    all_feats = _vary(
        jax.lax.all_gather(feats_local, axis_name, tiled=True), axis_name
    )
    # column-side softmax stats: O(N) vectors, the only cross-device residual.
    lse_all = _vary(jax.lax.all_gather(lse, axis_name, tiled=True), axis_name)
    cnt_all = _vary(jax.lax.all_gather(cnt, axis_name, tiled=True), axis_name)
    grow, gcol = _sharded_indices(feats_local, axis_name)
    ids_v = _vary(ids_global, axis_name)
    idr = jnp.take(ids_v, grow, axis=0)
    coeff = (temperature / base_temperature) / n
    dfeats = _bwd_call(
        feats_local, all_feats, idr, ids_v, grow, gcol,
        lse, lse_all, cnt, cnt_all,
        temperature, coeff, interpret, bm, bn,
        vma=_vma_of(feats_local),
    )
    return (g * dfeats, np.zeros(ids_global.shape, jax.dtypes.float0))


_fused_sharded.defvjp(_fused_sharded_fwd, _fused_sharded_bwd)


def supports(batch_size: int, n_views: int) -> bool:
    """True if the fused kernel can handle this [B, V, d] problem size."""
    n = batch_size * n_views
    return _pick_block(n, 256) is not None


def supports_sharded(batch_size: int, n_views: int, data_parallel: int) -> bool:
    """True if the sharded fused kernel fits this problem over P devices."""
    n = batch_size * n_views
    if data_parallel <= 0 or n % data_parallel:
        return False
    m = n // data_parallel
    return _pick_block(m, 256) is not None and _pick_block(n, 512) is not None


def fused_supcon_loss(
    features: jax.Array,
    labels: Optional[jax.Array] = None,
    *,
    temperature: float = 0.07,
    base_temperature: float = 0.07,
    interpret: bool = False,
    block_rows: int = 256,
    block_cols: int = 512,
) -> jax.Array:
    """Drop-in fused replacement for ``supcon_loss(..., contrast_mode='all')``.

    Args:
      features: ``[B, V, d]`` L2-normalized multi-view features (same contract
        as ``ops.losses.supcon_loss``).
      labels: optional ``[B]`` integer labels (SupCon); ``None`` = SimCLR.
      interpret: run the Pallas interpreter (CPU testing).
      block_rows / block_cols: VMEM tile caps; actual tiles are the largest
        divisors of ``V*B`` within the caps.

    Returns:
      Scalar loss, differentiable w.r.t. ``features``.
    """
    batch, n_views = features.shape[0], features.shape[1]
    n = batch * n_views
    feats = jnp.transpose(features, (1, 0, 2)).reshape(n, -1).astype(jnp.float32)
    if labels is None:
        sample_ids = jnp.tile(jnp.arange(batch, dtype=jnp.int32), n_views)
    else:
        sample_ids = jnp.tile(labels.astype(jnp.int32).reshape(-1), n_views)
    bm = _pick_block(n, block_rows)
    bn = _pick_block(n, block_cols)
    if bm is None or bn is None:
        raise ValueError(
            f"fused loss needs V*B divisible by 8, got {n}; use the dense path"
        )
    return _fused_loss(
        feats, sample_ids, float(temperature), float(base_temperature),
        bool(interpret), bm, bn,
    )


def fused_sharded_supcon_loss(
    feats_local: jax.Array,
    global_labels: Optional[jax.Array] = None,
    *,
    axis_name: str,
    temperature: float = 0.07,
    base_temperature: float = 0.07,
    n_views: int = 2,
    interpret: bool = False,
    block_rows: int = 256,
    block_cols: int = 512,
) -> jax.Array:
    """Fused SupCon/SimCLR loss over row-sharded features, inside shard_map.

    Same calling convention as ``parallel.collectives.ring_supcon_loss``:
    ``feats_local`` is this device's ``[m, D]`` contiguous block of the global
    view-major ``[V*B, D]`` L2-normalized feature matrix; ``global_labels`` is
    the REPLICATED ``[B]`` label vector for SupCon (``None`` = SimCLR).

    The contrast side is all-gathered (O(V·B·D), what the reference's NCCL
    gather moves anyway, ``main_supcon.py:268``); the fused kernels then keep
    every O(m·V·B) logits block in VMEM. Returns the replicated global scalar
    loss, differentiable w.r.t. ``feats_local`` — each device's backward
    computes the exact global gradient of its own rows (see module docstring).
    """
    m = feats_local.shape[0]
    p = axis_size(axis_name)
    n = m * p
    if n % n_views:
        raise ValueError(f"global rows {n} not divisible by n_views={n_views}")
    batch = n // n_views
    if global_labels is None:
        ids_global = jnp.tile(jnp.arange(batch, dtype=jnp.int32), n_views)
    else:
        ids_global = jnp.tile(
            global_labels.astype(jnp.int32).reshape(-1), n_views
        )
    bm = _pick_block(m, block_rows)
    bn = _pick_block(n, block_cols)
    if bm is None or bn is None:
        raise ValueError(
            f"sharded fused loss needs local rows {m} and global rows {n} "
            f"divisible by 8; use 'dense' or 'ring'"
        )
    return _fused_sharded(
        feats_local.astype(jnp.float32), ids_global, axis_name,
        float(temperature), float(base_temperature), bool(interpret), bm, bn,
    )
