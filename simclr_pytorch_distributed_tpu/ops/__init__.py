from simclr_pytorch_distributed_tpu.ops.losses import (  # noqa: F401
    cross_entropy_loss,
    supcon_loss,
)
from simclr_pytorch_distributed_tpu.ops.pallas_loss import fused_supcon_loss  # noqa: F401
from simclr_pytorch_distributed_tpu.ops import schedules  # noqa: F401
from simclr_pytorch_distributed_tpu.ops import metrics  # noqa: F401
