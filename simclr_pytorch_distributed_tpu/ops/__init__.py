from simclr_pytorch_distributed_tpu.ops.losses import supcon_loss  # noqa: F401
from simclr_pytorch_distributed_tpu.ops import schedules  # noqa: F401
from simclr_pytorch_distributed_tpu.ops import metrics  # noqa: F401
