"""Learning-rate schedules with the reference's exact semantics.

The reference drives LR two ways that interact (``util.py:54-76``):

- per-EPOCH base schedule ``adjust_learning_rate``: cosine with
  ``eta_min = lr * lr_decay_rate**3`` (``util.py:57-59``) or step decay counting
  boundaries already passed (``util.py:61-63``); epoch is 1-based;
- per-ITERATION linear warmup ``warmup_learning_rate`` that OVERRIDES the epoch
  schedule during the first ``warm_epochs`` epochs (``util.py:69-76``), ramping
  ``warmup_from -> warmup_to`` where ``warmup_to`` is the closed-form cosine value
  at the end of warmup (``main_supcon.py:124-131``).

Here the whole thing is a single pure function of the global step so it can live
inside the jitted train step (no Python mutation of optimizer state). A factory
returns an optax-compatible ``schedule(step) -> lr``.
"""

from __future__ import annotations

import math
from typing import Callable, Sequence

import jax.numpy as jnp


def cosine_lr(lr: float, lr_decay_rate: float, epoch, total_epochs: int):
    """Reference cosine-per-epoch schedule (``util.py:56-59``). `epoch` is 1-based."""
    eta_min = lr * (lr_decay_rate**3)
    return eta_min + (lr - eta_min) * (
        1.0 + jnp.cos(jnp.pi * epoch / total_epochs)
    ) / 2.0


def step_lr(lr: float, lr_decay_rate: float, lr_decay_epochs: Sequence[int], epoch):
    """Reference step-decay schedule (``util.py:61-63``)."""
    boundaries = jnp.asarray(lr_decay_epochs)
    steps = jnp.sum(epoch > boundaries)
    return lr * (lr_decay_rate ** steps)


def warmup_to_value(
    lr: float, lr_decay_rate: float, warm_epochs: int, total_epochs: int, cosine: bool
) -> float:
    """Closed-form warmup target (``main_supcon.py:124-131``)."""
    if cosine:
        eta_min = lr * (lr_decay_rate**3)
        return eta_min + (lr - eta_min) * (
            1 + math.cos(math.pi * warm_epochs / total_epochs)
        ) / 2
    return lr


def make_lr_schedule(
    *,
    learning_rate: float,
    epochs: int,
    steps_per_epoch: int,
    cosine: bool = False,
    lr_decay_rate: float = 0.1,
    lr_decay_epochs: Sequence[int] = (700, 800, 900),
    warm: bool = False,
    warm_epochs: int = 10,
    warmup_from: float = 0.01,
) -> Callable:
    """Build ``lr(step)`` reproducing the reference's epoch+warmup composition.

    ``step`` is the 0-based global iteration; ``epoch = step // steps_per_epoch + 1``
    and ``batch_id = step % steps_per_epoch`` recover the reference's loop variables
    (``main_supcon.py:382`` epoch loop, ``:263`` per-iter warmup call).
    """
    lr_decay_epochs = tuple(lr_decay_epochs)
    warmup_to = warmup_to_value(learning_rate, lr_decay_rate, warm_epochs, epochs, cosine)

    def schedule(step):
        step = jnp.asarray(step)
        epoch = step // steps_per_epoch + 1
        if cosine:
            base = cosine_lr(learning_rate, lr_decay_rate, epoch, epochs)
        else:
            base = step_lr(learning_rate, lr_decay_rate, lr_decay_epochs, epoch)
        if not warm:
            return base
        # Reference warmup: p = (batch_id + (epoch-1)*B) / (warm_epochs*B) == step/...
        p = step / (warm_epochs * steps_per_epoch)
        warm_lr = warmup_from + p * (warmup_to - warmup_from)
        return jnp.where(epoch <= warm_epochs, warm_lr, base)

    return schedule
