"""Host-side batch iteration: the DistributedSampler + DataLoader equivalent.

The reference shards the dataset across ranks with ``DistributedSampler`` and
reshuffles per epoch via ``sampler.set_epoch(epoch)`` (``main_supcon.py:195-199,
387``), dropping the last partial batch, and hides batch assembly inside an
8-worker DataLoader pool (``:200-207``). Here:

- one deterministic numpy permutation per epoch (seeded ``base_seed + epoch``) —
  identical on every process, so the global batch composition is well-defined
  across hosts;
- ``drop_last`` truncation to whole GLOBAL batches (``main_supcon.py:206``);
- each process slices its contiguous block of every global batch
  (``process_index * per_proc : ... + per_proc``) — the multi-host analogue of
  per-rank ``batch_size // ngpu`` (``main_supcon.py:202``). The block
  boundaries come from :func:`share_splits`, which honors the supervisor's
  ``FLEET_SHARE_HINT`` (``host:factor``): a straggling host hands part of its
  uniform share to its peers, while the UNION of all process slices stays
  exactly the global batch the epoch permutation defined — global batch
  composition is share-invariant. NOTE: the pjit trainers do not opt in —
  ``shard_host_batch`` (parallel/mesh.py) requires uniform per-process shapes
  via ``make_array_from_process_local_data`` — so uneven shares serve
  host-side consumers (data-echo staging, eval sweeps, serving warm-up) until
  the device path learns ragged shards;
- batch assembly (uint8 row gather) runs through the native C++ library
  (``native/gather.cpp``) when available — it releases the GIL, so the
  ``prefetch`` background thread genuinely overlaps staging of batch k+1 with
  the device step on batch k. Augmentation itself is NOT here: it runs jitted
  on device (ops/augment.py), so this is all the host work that remains.
"""

from __future__ import annotations

import ctypes
import queue
import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np

from simclr_pytorch_distributed_tpu.native.build import load as load_native

# The canonical name of the supervisor's share-rebalance env hint. Defined
# HERE (the consumer, jax-free) and imported by supervise/launch.py (the
# producer), so the contract has exactly one spelling.
FLEET_SHARE_ENV = "FLEET_SHARE_HINT"


def parse_share_hint(hint: Optional[str]) -> Optional[Tuple[int, float]]:
    """Parse a ``"host:factor"`` share hint; None for anything malformed.

    Malformed hints are IGNORED, not raised: the hint is advisory operator
    input that crosses a process boundary via the environment, and a typo
    must degrade to the uniform split rather than kill a relaunch the
    supervisor just decided was worth making.
    """
    if not hint:
        return None
    try:
        host_s, factor_s = str(hint).split(":", 1)
        host, factor = int(host_s), float(factor_s)
    except ValueError:
        return None
    if host < 0 or not (0.0 < factor <= 1.0) or factor != factor:
        return None
    return host, factor


def share_splits(
    global_batch_size: int,
    process_count: int,
    hint: Optional[str] = None,
) -> List[Tuple[int, int]]:
    """Per-process ``[lo, hi)`` bounds into each global batch.

    Uniform (``per_proc = gbs // P``) unless ``hint`` names a valid process
    and factor, in which case that process keeps ``round(per_proc * factor)``
    rows (floored at 1 — every process must contribute, or collectives that
    count participants by rows would wedge) and the deficit spreads evenly
    over the other processes (remainder to the lowest indices, so the split
    is deterministic). Invariants, pinned by tests/test_data.py: bounds are
    contiguous, start at 0, end at ``global_batch_size`` — the union of all
    slices is the whole global batch, whatever the hint says.
    """
    per_proc = global_batch_size // process_count
    sizes = [per_proc] * process_count
    parsed = parse_share_hint(hint)
    if parsed is not None and process_count > 1:
        host, factor = parsed
        if host < process_count:
            keep = max(1, int(round(per_proc * factor)))
            deficit = per_proc - keep
            if deficit > 0:
                sizes[host] = keep
                others = process_count - 1
                bump, rem = divmod(deficit, others)
                j = 0
                for i in range(process_count):
                    if i == host:
                        continue
                    sizes[i] += bump + (1 if j < rem else 0)
                    j += 1
    bounds = []
    lo = 0
    for size in sizes:
        bounds.append((lo, lo + size))
        lo += size
    return bounds


def _gather(images: np.ndarray, labels: np.ndarray, sel: np.ndarray):
    """Assemble (images[sel], labels[sel]); native memcpy path when available."""
    lib = load_native()
    if lib is None or not images.flags["C_CONTIGUOUS"]:
        return images[sel], labels[sel]
    sel = np.ascontiguousarray(sel, np.int64)
    out_img = np.empty((len(sel),) + images.shape[1:], images.dtype)
    row_bytes = images.dtype.itemsize * int(np.prod(images.shape[1:]))
    lib.gather_rows_u8(
        images.ctypes.data_as(ctypes.c_void_p),
        sel.ctypes.data_as(ctypes.c_void_p),
        len(sel), row_bytes,
        out_img.ctypes.data_as(ctypes.c_void_p),
    )
    labels32 = labels if labels.dtype == np.int32 else labels.astype(np.int32)
    out_lab = np.empty(len(sel), np.int32)
    lib.gather_rows_i32(
        labels32.ctypes.data_as(ctypes.c_void_p),
        sel.ctypes.data_as(ctypes.c_void_p),
        len(sel),
        out_lab.ctypes.data_as(ctypes.c_void_p),
    )
    return out_img, out_lab


class EpochLoader:
    """Iterates (images_u8, labels) process-local slices of global batches."""

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        global_batch_size: int,
        *,
        shuffle: bool = True,
        drop_last: bool = True,
        base_seed: int = 0,
        process_index: int = 0,
        process_count: int = 1,
        prefetch: int = 2,
        share_hint: Optional[str] = None,
    ):
        if global_batch_size % process_count != 0:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"{process_count} processes"
            )
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(labels)
        self.global_batch_size = global_batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.base_seed = base_seed
        self.process_index = process_index
        self.process_count = process_count
        self.prefetch = prefetch
        # this process's [lo, hi) window into every global batch; uniform
        # unless a FLEET_SHARE_HINT rebalances it (module docstring)
        self.share_hint = share_hint
        self.share_bounds = share_splits(
            global_batch_size, process_count, share_hint
        )
        self._lo, self._hi = self.share_bounds[process_index]
        n = len(images)
        if drop_last:
            self.steps_per_epoch = n // global_batch_size
        else:
            self.steps_per_epoch = (n + global_batch_size - 1) // global_batch_size
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"dataset of {n} examples smaller than one global batch "
                f"({global_batch_size})"
            )

    def check_start_step(self, start_step: int) -> None:
        """Validate a mid-epoch resume offset, loudly.

        Out of range means the checkpoint's ``step_in_epoch`` no longer fits
        this run's geometry (e.g. a changed batch size shrank
        ``steps_per_epoch``) — resuming would silently skip work. Drivers
        call this BEFORE entering their step loop: both loop shapes iterate
        ``range(start_step, steps_per_epoch)``, which an oversized offset
        would turn into a silent zero-step epoch (the generator's own check
        only fires on the first ``next``, which an empty range never does).
        """
        if not 0 <= start_step < self.steps_per_epoch:
            raise ValueError(
                f"start_step {start_step} outside [0, {self.steps_per_epoch})"
                f" — the driver must roll a full-epoch offset into `epoch`"
            )

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.images)
        if self.shuffle:
            return np.random.default_rng(self.base_seed + epoch).permutation(n)
        return np.arange(n)

    def _batches(
        self, epoch: int, start_step: int = 0
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        order = self._epoch_order(epoch)
        for step in range(start_step, self.steps_per_epoch):
            sel = order[step * self.global_batch_size:(step + 1) * self.global_batch_size]
            sel = sel[self._lo:self._hi]
            yield _gather(self.images, self.labels, sel)

    def epoch(
        self, epoch: int, start_step: int = 0
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """One pass; ``epoch`` seeds the shuffle (sampler.set_epoch equivalent).

        ``start_step`` skips the first batches of the epoch's DETERMINISTIC
        permutation — the mid-epoch resume path (utils/preempt.py): a
        checkpoint recording ``step_in_epoch = k`` restarts with
        ``epoch(e, start_step=k)`` and consumes exactly the batches the
        interrupted run never saw, in the same order.

        With ``prefetch > 0``, batch assembly runs in a daemon thread so the
        native gather for step k+1 overlaps the device step for batch k.
        """
        self.check_start_step(start_step)
        if self.prefetch <= 0:
            yield from self._batches(epoch, start_step)
            return

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        stop = threading.Event()

        def worker():
            # A raise here must not strand the consumer in q.get(): ship the
            # exception through the queue and re-raise it on the training
            # thread, where it can abort the step (and, multi-host, the job)
            # with a real traceback instead of a collective timeout.
            try:
                for item in self._batches(epoch, start_step):
                    if stop.is_set():
                        return
                    q.put(item)
            except BaseException as e:  # noqa: BLE001 — forwarded, not handled
                q.put(e)
                return
            q.put(sentinel)

        t = threading.Thread(
            target=worker, daemon=True, name="EpochLoader-prefetch"
        )
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    t.join()
                    raise item
                yield item
            t.join()
        finally:
            # A consumer that abandons the iterator mid-epoch (preemption,
            # an exception between batches, GC of the generator) closes it,
            # which raises GeneratorExit at the yield above — without this,
            # the worker would block in q.put() forever. Stop it and drain
            # the queue until it exits: a worker blocked in put() gets space,
            # then observes `stop` before producing another batch.
            stop.set()
            while t.is_alive():
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)

    def __len__(self) -> int:
        return self.steps_per_epoch
