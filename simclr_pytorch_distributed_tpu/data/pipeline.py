"""Host-side batch iteration: the DistributedSampler + DataLoader equivalent.

The reference shards the dataset across ranks with ``DistributedSampler`` and
reshuffles per epoch via ``sampler.set_epoch(epoch)`` (``main_supcon.py:195-199,
387``), dropping the last partial batch. Here:

- one deterministic permutation per epoch (seeded by ``base_seed + epoch``) —
  identical on every process, so the global batch composition is well-defined;
- ``drop_last`` truncation to whole GLOBAL batches (``main_supcon.py:206``);
- each process slices its contiguous block of every global batch
  (``process_index * per_proc : ... + per_proc``) — the multi-host analogue of
  per-rank ``batch_size // ngpu`` (``main_supcon.py:202``). Single host = the
  whole batch. The global array is reassembled on device by
  ``parallel.mesh.shard_host_batch``.

Augmentation is NOT here — it runs on device (ops/augment.py), so this loader
only permutes uint8 arrays and hands out views; there is nothing left for a
worker pool to do (the reference's ``num_workers=8`` host pipeline disappears).
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


class EpochLoader:
    """Iterates (images_u8, labels) process-local slices of global batches."""

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        global_batch_size: int,
        *,
        shuffle: bool = True,
        drop_last: bool = True,
        base_seed: int = 0,
        process_index: int = 0,
        process_count: int = 1,
    ):
        if global_batch_size % process_count != 0:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"{process_count} processes"
            )
        self.images = images
        self.labels = labels
        self.global_batch_size = global_batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.base_seed = base_seed
        self.process_index = process_index
        self.process_count = process_count
        n = len(images)
        if drop_last:
            self.steps_per_epoch = n // global_batch_size
        else:
            self.steps_per_epoch = (n + global_batch_size - 1) // global_batch_size
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"dataset of {n} examples smaller than one global batch "
                f"({global_batch_size})"
            )

    def epoch(self, epoch: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """One pass; ``epoch`` seeds the shuffle (sampler.set_epoch equivalent)."""
        n = len(self.images)
        if self.shuffle:
            order = np.random.default_rng(self.base_seed + epoch).permutation(n)
        else:
            order = np.arange(n)
        per_proc = self.global_batch_size // self.process_count
        lo = self.process_index * per_proc
        for step in range(self.steps_per_epoch):
            sel = order[step * self.global_batch_size:(step + 1) * self.global_batch_size]
            sel = sel[lo:lo + per_proc]
            yield self.images[sel], self.labels[sel]

    def __len__(self) -> int:
        return self.steps_per_epoch
