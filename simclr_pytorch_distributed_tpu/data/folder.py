"""ImageFolder-equivalent reader for ``--dataset path`` (class-per-subdirectory).

The reference feeds custom datasets through ``torchvision.datasets.ImageFolder``
(``main_supcon.py:189-191``): every immediate subdirectory of the root is a
class, sorted by name. Here images are decoded once with PIL on the host into a
uint8 array at a fixed ``store_size`` resolution; the SimCLR RandomResizedCrop
then runs on DEVICE from that stored resolution (ops/augment.py), replacing the
reference's per-epoch PIL re-decode in 8 DataLoader workers.

``store_size`` defaults to 2x the crop size so the device-side crop keeps the
scale diversity of cropping near-original resolution, while the host array
stays bounded (N * store_size^2 * 3 bytes).

Scale: small trees (CIFAR-scale, the reference's actual usage) decode into an
in-RAM array. Trees whose decoded size exceeds ``mmap_threshold_bytes`` decode
ONCE into an on-disk ``.npy`` memmap cache and are returned memory-mapped, so
host RSS stays bounded by the (reclaimable) page cache instead of anonymous
memory — an ImageNet-scale tree no longer OOMs the host. The cache is keyed by
a manifest hash (file paths, sizes, mtimes, store resolution) and reused across
runs.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import List, Optional, Tuple

import numpy as np

from simclr_pytorch_distributed_tpu.data.cifar import NumpyDataset

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp", ".ppm")

# decoded trees larger than this go through the on-disk memmap cache
DEFAULT_MMAP_THRESHOLD = 1 << 30  # 1 GiB


def find_classes(root: str) -> List[str]:
    """Sorted immediate subdirectories = classes (ImageFolder semantics)."""
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {root}")
    return classes


def _scan_tree(root: str, classes: List[str]) -> Tuple[List[str], List[int]]:
    """All image paths + class indices, in deterministic sorted order."""
    paths, labels = [], []
    for cls_idx, cls in enumerate(classes):
        cls_dir = os.path.join(root, cls)
        for dirpath, _, filenames in sorted(os.walk(cls_dir)):
            for fname in sorted(filenames):
                if fname.lower().endswith(IMG_EXTENSIONS):
                    paths.append(os.path.join(dirpath, fname))
                    labels.append(cls_idx)
    if not paths:
        raise FileNotFoundError(f"no images with {IMG_EXTENSIONS} under {root}")
    return paths, labels


def _manifest_key(paths: List[str], store: int) -> str:
    """Content key for the decode cache: path list + (size, mtime) + store res."""
    h = hashlib.sha256()
    h.update(str(store).encode())
    for p in paths:
        st = os.stat(p)
        h.update(p.encode())
        h.update(f"{st.st_size}:{int(st.st_mtime)}".encode())
    return h.hexdigest()[:32]


def _decode_one(path: str, store: int) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(
            im.convert("RGB").resize((store, store), Image.BILINEAR),
            dtype=np.uint8,
        )


def load_image_folder(
    root: str,
    size: int = 32,
    store_size: Optional[int] = None,
    cache_dir: Optional[str] = None,
    mmap_threshold_bytes: int = DEFAULT_MMAP_THRESHOLD,
) -> Tuple[NumpyDataset, List[str]]:
    """Decode a class-per-subdir image tree into uint8 [N, S, S, 3] + labels.

    Args:
      root: dataset root (each subdir is one class).
      size: the training crop size (``--size``).
      store_size: host-side storage resolution; default ``2 * size``.
      cache_dir: where the memmap decode cache lives for large trees
        (default: ``$TMPDIR/sptpu_folder_cache``).
      mmap_threshold_bytes: decoded sizes above this are decoded into an
        on-disk memmap instead of RAM.

    Returns:
      ({'images': u8 [N,S,S,3] (ndarray or read-only memmap), 'labels':
      i32 [N]}, class_names)
    """
    s = store_size or 2 * size
    classes = find_classes(root)
    paths, labels = _scan_tree(root, classes)
    labels_arr = np.asarray(labels, np.int32)
    n = len(paths)
    decoded_bytes = n * s * s * 3

    if decoded_bytes <= mmap_threshold_bytes:
        images = np.stack([_decode_one(p, s) for p in paths])
        return {"images": images, "labels": labels_arr}, classes

    # Large tree: decode once into an on-disk .npy memmap, then map read-only.
    cache_root = cache_dir or os.path.join(
        tempfile.gettempdir(), "sptpu_folder_cache"
    )
    os.makedirs(cache_root, exist_ok=True)
    key = _manifest_key(paths, s)
    arr_path = os.path.join(cache_root, f"{key}.npy")
    meta_path = os.path.join(cache_root, f"{key}.json")

    # Hit check keys on arr_path alone: os.replace commits the array whole,
    # and meta.json is a debugging aid never read on the load path — requiring
    # it too would re-decode a fully-committed cache after a crash between
    # the two writes.
    if not os.path.exists(arr_path):
        # unique per-process temp name: concurrent decoders of the same tree
        # (e.g. pretrain + probe sharing --data_folder) race benignly — each
        # writes its own file and os.replace commits whole files atomically
        fd, tmp_path = tempfile.mkstemp(suffix=".npy.tmp", dir=cache_root)
        os.close(fd)
        out = np.lib.format.open_memmap(
            tmp_path, mode="w+", dtype=np.uint8, shape=(n, s, s, 3)
        )
        for i, p in enumerate(paths):
            out[i] = _decode_one(p, s)
        out.flush()
        del out
        os.replace(tmp_path, arr_path)  # atomic: no half-decoded cache
        fd, meta_tmp = tempfile.mkstemp(suffix=".json.tmp", dir=cache_root)
        with os.fdopen(fd, "w") as f:
            json.dump({"n": n, "store": s, "root": os.path.abspath(root)}, f)
        os.replace(meta_tmp, meta_path)

    images = np.load(arr_path, mmap_mode="r")
    return {"images": images, "labels": labels_arr}, classes
