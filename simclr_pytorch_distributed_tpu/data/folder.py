"""ImageFolder-equivalent reader for ``--dataset path`` (class-per-subdirectory).

The reference feeds custom datasets through ``torchvision.datasets.ImageFolder``
(``main_supcon.py:189-191``): every immediate subdirectory of the root is a
class, sorted by name. Here images are decoded once with PIL on the host into a
uint8 array at a fixed ``store_size`` resolution; the SimCLR RandomResizedCrop
then runs on DEVICE from that stored resolution (ops/augment.py), replacing the
reference's per-epoch PIL re-decode in 8 DataLoader workers.

``store_size`` defaults to 2x the crop size so the device-side crop keeps the
scale diversity of cropping near-original resolution, while the host array
stays bounded (N * store_size^2 * 3 bytes).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from simclr_pytorch_distributed_tpu.data.cifar import NumpyDataset

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp", ".ppm")


def find_classes(root: str) -> List[str]:
    """Sorted immediate subdirectories = classes (ImageFolder semantics)."""
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {root}")
    return classes


def load_image_folder(
    root: str,
    size: int = 32,
    store_size: Optional[int] = None,
) -> Tuple[NumpyDataset, List[str]]:
    """Decode a class-per-subdir image tree into uint8 [N, S, S, 3] + labels.

    Args:
      root: dataset root (each subdir is one class).
      size: the training crop size (``--size``).
      store_size: host-side storage resolution; default ``2 * size``.

    Returns:
      ({'images': u8 [N,S,S,3], 'labels': i32 [N]}, class_names)
    """
    from PIL import Image

    s = store_size or 2 * size
    classes = find_classes(root)
    images, labels = [], []
    for cls_idx, cls in enumerate(classes):
        cls_dir = os.path.join(root, cls)
        for dirpath, _, filenames in sorted(os.walk(cls_dir)):
            for fname in sorted(filenames):
                if not fname.lower().endswith(IMG_EXTENSIONS):
                    continue
                with Image.open(os.path.join(dirpath, fname)) as im:
                    im = im.convert("RGB").resize((s, s), Image.BILINEAR)
                    images.append(np.asarray(im, dtype=np.uint8))
                labels.append(cls_idx)
    if not images:
        raise FileNotFoundError(f"no images with {IMG_EXTENSIONS} under {root}")
    data = {
        "images": np.stack(images),
        "labels": np.asarray(labels, np.int32),
    }
    return data, classes
