"""Dataset loading: CIFAR-10/100 from the standard on-disk binary distributions,
plus a synthetic generator for data-free smoke tests and benchmarks.

The reference pulls CIFAR through torchvision with ``download=True``
(``main_supcon.py:181-188``). This environment has no egress and no torchvision,
so we read the canonical python-pickle layout directly:

- ``cifar-10-batches-py/{data_batch_1..5, test_batch}``: dict with ``data``
  ``[N, 3072]`` uint8 channel-major and ``labels``;
- ``cifar-100-python/{train, test}``: same with ``fine_labels``.

Arrays come back HWC uint8 — augmentation converts to float on device
(ops/augment.py), so the host never touches float image tensors.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import shutil
import tarfile
from typing import Dict, Optional, Tuple

import numpy as np

NumpyDataset = Dict[str, np.ndarray]  # images [N,32,32,3] u8, labels [N] i32

# The canonical archives torchvision fetches (the reference's download=True,
# main_supcon.py:181-188): (archive name, md5, extracted marker dir).
CIFAR_ARCHIVES = {
    "cifar10": (
        "cifar-10-python.tar.gz",
        "c58f30108f718f92721af3b95e74349a",
        "cifar-10-batches-py",
    ),
    "cifar100": (
        "cifar-100-python.tar.gz",
        "eb9058c3a382ffc7106e4002c42a8d85",
        "cifar-100-python",
    ),
}
CIFAR_BASE_URL = "https://www.cs.toronto.edu/~kriz"


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


DOWNLOAD_ATTEMPTS = 3
DOWNLOAD_BACKOFF_BASE = 0.5  # seconds; doubles per attempt, plus jitter


def download_cifar(
    dataset: str,
    data_folder: str,
    base_url: Optional[str] = None,
    md5: Optional[str] = None,
    timeout: float = 60.0,
    attempts: int = DOWNLOAD_ATTEMPTS,
    backoff_base: float = DOWNLOAD_BACKOFF_BASE,
) -> str:
    """Fetch + verify + extract a CIFAR archive; returns the marker dir.

    torchvision-download parity for environments WITH egress (the reference
    bootstraps its own data, ``main_supcon.py:181-188``; this framework
    otherwise requires pre-placed binaries). Idempotent: an already-extracted
    marker dir or an already-downloaded md5-verified archive short-circuits.
    ``base_url``/``md5`` exist so tests can point at a local HTTP server.

    The fetch itself retries ``attempts`` times with exponential backoff plus
    jitter: a multi-host launch funnels through ONE downloader holding the
    per-filesystem flock (``ensure_dataset_available``), so a transient HTTP
    hiccup there would otherwise abort every host at once. An md5 mismatch
    retries too — it is usually a truncated transfer, and each attempt
    re-fetches into a fresh temp file.
    """
    import random
    import time
    import urllib.request

    if dataset not in CIFAR_ARCHIVES:
        raise ValueError(f"no download recipe for dataset {dataset!r}")
    fname, want_md5, marker = CIFAR_ARCHIVES[dataset]
    want_md5 = md5 or want_md5
    root = os.path.abspath(data_folder)
    os.makedirs(root, exist_ok=True)
    marker_dir = os.path.join(root, marker)
    if os.path.isdir(marker_dir):
        return marker_dir

    archive = os.path.join(root, fname)
    if not (os.path.exists(archive) and _md5(archive) == want_md5):
        url = f"{base_url or CIFAR_BASE_URL}/{fname}"
        # pid-unique temp: concurrent writers (possible after a stale-lock
        # break, ensure_dataset_available) never share an inode; the winner's
        # os.replace is atomic either way
        tmp = archive + f".partial.{os.getpid()}"
        for attempt in range(1, max(1, attempts) + 1):
            try:
                with urllib.request.urlopen(url, timeout=timeout) as r, \
                        open(tmp, "wb") as f:
                    shutil.copyfileobj(r, f)
                got = _md5(tmp)
                if got != want_md5:
                    raise ValueError(
                        f"md5 mismatch for {url}: got {got}, want {want_md5}"
                    )
                os.replace(tmp, archive)  # atomic: no torn archive on the hit path
                break
            except Exception as e:  # noqa: BLE001 — URLError/timeout/md5/...
                if attempt >= max(1, attempts):
                    raise
                delay = backoff_base * (2 ** (attempt - 1))
                delay += random.uniform(0, delay / 2)  # jitter: desync waiters
                import logging

                logging.warning(
                    "download attempt %d/%d for %s failed (%s); retrying "
                    "in %.1fs", attempt, attempts, url, e, delay,
                )
                time.sleep(delay)
            finally:
                # failed/aborted transfer: do not orphan a pid-unique partial
                if os.path.exists(tmp):
                    os.remove(tmp)

    if os.path.isdir(marker_dir):
        # a concurrent caller finished the extraction while we were fetching
        return marker_dir
    # Extract into a pid-unique staging dir and atomically rename the marker
    # into place: marker presence therefore means extraction COMPLETE, which
    # is what every fast-path marker check in this module assumes (extracting
    # straight into root would expose a half-written tree under that name).
    stage = os.path.join(root, f".extract.{os.getpid()}")
    try:
        os.makedirs(stage, exist_ok=True)
        with tarfile.open(archive, "r:gz") as tar:
            try:
                # 'data' filter: refuse abs paths / parent traversal / links
                tar.extractall(stage, filter="data")
            except TypeError:  # Python < 3.10.12 predates the filter kwarg
                base = os.path.realpath(stage)
                for m in tar.getmembers():
                    target = os.path.realpath(os.path.join(stage, m.name))
                    if not target.startswith(base + os.sep):
                        raise ValueError(f"unsafe tar member path: {m.name}")
                    if m.islnk() or m.issym():
                        raise ValueError(f"refusing tar link member: {m.name}")
                tar.extractall(stage)
        staged = os.path.join(stage, marker)
        if not os.path.isdir(staged):
            raise FileNotFoundError(
                f"{fname} extracted but {marker} did not appear under {stage}"
            )
        try:
            os.rename(staged, marker_dir)  # atomic on the same filesystem
        except OSError:
            if not os.path.isdir(marker_dir):  # not lost-the-race: real error
                raise
    finally:
        shutil.rmtree(stage, ignore_errors=True)
    return marker_dir


def _decode_rows(data: np.ndarray) -> np.ndarray:
    return data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)


def _load_pickle(path: str) -> dict:
    with open(path, "rb") as f:
        return pickle.load(f, encoding="latin1")


def load_cifar10(data_folder: str) -> Tuple[NumpyDataset, NumpyDataset]:
    root = os.path.join(data_folder, "cifar-10-batches-py")
    train_x, train_y = [], []
    for i in range(1, 6):
        d = _load_pickle(os.path.join(root, f"data_batch_{i}"))
        train_x.append(_decode_rows(np.asarray(d["data"], np.uint8)))
        train_y.append(np.asarray(d["labels"], np.int32))
    t = _load_pickle(os.path.join(root, "test_batch"))
    train = {
        "images": np.concatenate(train_x),
        "labels": np.concatenate(train_y),
    }
    test = {
        "images": _decode_rows(np.asarray(t["data"], np.uint8)),
        "labels": np.asarray(t["labels"], np.int32),
    }
    return train, test


def load_cifar100(data_folder: str) -> Tuple[NumpyDataset, NumpyDataset]:
    root = os.path.join(data_folder, "cifar-100-python")
    out = []
    for split in ("train", "test"):
        d = _load_pickle(os.path.join(root, split))
        out.append(
            {
                "images": _decode_rows(np.asarray(d["data"], np.uint8)),
                "labels": np.asarray(d["fine_labels"], np.int32),
            }
        )
    return out[0], out[1]


def synthetic_dataset(
    n: int = 2048, num_classes: int = 10, seed: int = 0, size: int = 32
) -> Tuple[NumpyDataset, NumpyDataset]:
    """Class-conditional random images: enough structure that a linear probe can
    beat chance, cheap enough for CI and throughput benches."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    # class-dependent color means + noise
    class_means = rng.uniform(48, 208, size=(num_classes, 1, 1, 3))
    noise = rng.normal(0, 32, size=(n, size, size, 3))
    images = np.clip(class_means[labels] + noise, 0, 255).astype(np.uint8)
    k = max(n // 8, 1)
    train = {"images": images[k:], "labels": labels[k:]}
    test = {"images": images[:k], "labels": labels[:k]}
    return train, test


def synthetic_texture_dataset(
    n: int = 14336, num_classes: int = 10, seed: int = 0, size: int = 32
) -> Tuple[NumpyDataset, NumpyDataset]:
    """Class-by-texture synthetic data for accuracy experiments (RESULTS.md).

    Class k is a plaid: two superimposed gratings at orientations theta_k and
    pi - theta_k (theta_k = (k+0.5) * (pi/2) / C), each with independent random
    phase, plus frequency jitter, random per-channel color gain/offset, and
    pixel noise. Design properties:

    - Horizontal flip maps orientation theta -> pi - theta, i.e. it swaps the
      two gratings of the SAME class: the class is closed under the aug
      stack's flip (unlike single-orientation classes, which flips merge).
    - Crop/resize preserves orientation; ColorJitter/grayscale only touch
      color, which is nuisance here. So the class signal survives the SimCLR
      augmentations while color (the easy shortcut) carries no signal.
    - Random phases decorrelate individual pixels from the class
      (E[pixel | class] is constant), so a LINEAR probe on raw pixels stays
      near chance — probe accuracy on frozen features measures what the
      encoder actually learned, unlike ``synthetic_dataset``'s color-mean
      classes (trivially pixel-separable, and destroyed by ColorJitter).
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    yy, xx = np.mgrid[0:size, 0:size]
    xx = xx.astype(np.float32)[None]  # [1, H, W]
    yy = yy.astype(np.float32)[None]
    theta = (labels + 0.5) * (np.pi / 2) / num_classes  # in (0, pi/2)
    cos_t = np.cos(theta)[:, None, None]
    sin_t = np.sin(theta)[:, None, None]
    freq = rng.uniform(2.5, 3.5, size=(n, 1, 1)) * (2 * np.pi / size)
    phase1 = rng.uniform(0, 2 * np.pi, size=(n, 1, 1))
    phase2 = rng.uniform(0, 2 * np.pi, size=(n, 1, 1))
    # grating 1 at theta, grating 2 at pi - theta (h-flip swaps them)
    wave = np.sin(freq * (cos_t * xx + sin_t * yy) + phase1) + np.sin(
        freq * (-cos_t * xx + sin_t * yy) + phase2
    )  # [n, H, W] in [-2, 2]
    base = rng.uniform(80, 176, size=(n, 1, 1, 3))
    gain = rng.uniform(16, 32, size=(n, 1, 1, 3))
    img = base + gain * wave[..., None] + rng.normal(0, 10, size=(n, size, size, 3))
    images = np.clip(img, 0, 255).astype(np.uint8)
    k = max(n // 8, 1)
    train = {"images": images[k:], "labels": labels[k:]}
    test = {"images": images[:k], "labels": labels[:k]}
    return train, test


def maybe_download(dataset: str, data_folder: Optional[str]) -> None:
    """Best-effort CIFAR fetch when the on-disk binaries are absent.

    The drivers call this on process 0 only (then barrier) so a multi-host
    launch downloads once; failures degrade to load_dataset's pre-placed-
    binaries error path with a warning.
    """
    import logging

    if dataset not in CIFAR_ARCHIVES or not data_folder:
        return
    marker = CIFAR_ARCHIVES[dataset][2]
    if os.path.isdir(os.path.join(data_folder, marker)):
        return
    try:
        download_cifar(dataset, data_folder)
        logging.info("downloaded %s into %s", dataset, data_folder)
    except Exception as e:  # noqa: BLE001 — URLError/timeout/md5/...
        logging.warning("could not download %s: %s", dataset, e)


def ensure_dataset_available(
    dataset: str, data_folder: Optional[str], download: bool = True
) -> None:
    """Download-if-absent with per-filesystem locking + cross-process barrier.

    Drivers call this before ``load_dataset``. Gating on the global process 0
    would strand hosts with their own local ``data_folder`` (the normal pod-VM
    layout), so instead EVERY process serializes on a kernel ``flock`` over a
    lock file in the data folder itself: exactly one downloader per
    filesystem, and download + md5 + tar extraction ALL complete while the
    lock is held, so a waiter that acquires it next either sees the finished
    marker dir (no-op) or retries the download itself — never a
    half-extracted tree. ``flock`` (not lock-file existence) is what makes
    this crash-safe: a holder killed hard (SIGKILL/OOM) has its lock released
    by the kernel immediately, so waiters neither sleep out a staleness
    window nor race to break/unlink a path that another waiter may have just
    re-acquired (the round-5 review found both races in the previous
    existence-based design). The lock FILE is deliberately never unlinked —
    removing it would reintroduce the unlink/recreate race; a leftover
    ~24-byte ``.{dataset}.download.lock`` is the cost. A waiter that cannot
    acquire the lock within an hour logs a warning and proceeds without
    downloading (``load_dataset`` stays the loud failure path).
    """
    if not download or dataset not in CIFAR_ARCHIVES or not data_folder:
        return
    import fcntl
    import logging
    import time

    from simclr_pytorch_distributed_tpu.parallel.mesh import sync_processes

    marker = os.path.join(data_folder, CIFAR_ARCHIVES[dataset][2])
    if not os.path.isdir(marker):
        os.makedirs(data_folder, exist_ok=True)
        lock = os.path.join(data_folder, f".{dataset}.download.lock")
        fd = os.open(lock, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            deadline = time.time() + 3600.0
            acquired = False
            while time.time() < deadline:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    acquired = True
                    break
                except OSError:
                    time.sleep(2)
            if acquired:
                try:
                    os.ftruncate(fd, 0)
                    os.write(fd, f"{os.getpid()} {time.time():.0f}\n".encode())
                    if not os.path.isdir(marker):  # re-check UNDER the lock
                        maybe_download(dataset, data_folder)
                finally:
                    fcntl.flock(fd, fcntl.LOCK_UN)
            else:
                try:
                    holder = os.pread(fd, 64, 0).decode("ascii", "replace")
                except OSError:
                    holder = "?"
                logging.warning(
                    "gave up waiting for %s after 3600s; proceeding without "
                    "download (holder pid/time: %s)",
                    lock, holder.strip() or "?",
                )
        finally:
            os.close(fd)
    sync_processes("dataset_ready")


def load_dataset(
    dataset: str,
    data_folder: str,
    allow_synthetic_fallback: bool = False,
    size: int = 32,
    store_size: int = 0,
    mmap_threshold_mb: int = 1024,
) -> Tuple[NumpyDataset, NumpyDataset, int]:
    """Returns (train, test, num_classes). ``dataset`` in {cifar10, cifar100,
    path, synthetic, synthetic_hard, synthetic_hard32}; with ``allow_synthetic_fallback`` a missing on-disk
    dataset degrades to synthetic data with a warning (benchmark environments).
    ``path`` reads an ImageFolder-style class-per-subdir tree (train split
    only, like the reference main_supcon.py:189-191); ``size`` sets its
    device-crop target."""
    import logging

    if dataset == "path":
        from simclr_pytorch_distributed_tpu.data.folder import load_image_folder

        train, classes = load_image_folder(
            data_folder, size=size, store_size=store_size or None,
            mmap_threshold_bytes=mmap_threshold_mb << 20,
        )
        # no val split in the reference's path mode; empty test set
        empty = {
            "images": train["images"][:0],
            "labels": train["labels"][:0],
        }
        return train, empty, len(classes)
    if dataset == "cifar10":
        n_cls, loader, marker = 10, load_cifar10, "cifar-10-batches-py"
    elif dataset == "cifar100":
        n_cls, loader, marker = 100, load_cifar100, "cifar-100-python"
    elif dataset == "synthetic":
        train, test = synthetic_dataset()
        return train, test, 10
    elif dataset == "synthetic_hard":
        train, test = synthetic_texture_dataset()
        return train, test, 10
    elif dataset == "synthetic_hard32":
        # 32 classes at 2.8-degree orientation spacing: a deliberately
        # non-saturated version of synthetic_hard for regression ratcheting
        train, test = synthetic_texture_dataset(num_classes=32)
        return train, test, 32
    else:
        raise ValueError(f"dataset not supported: {dataset}")

    if not os.path.isdir(os.path.join(data_folder, marker)):
        if allow_synthetic_fallback:
            logging.warning(
                "%s not found under %s — falling back to synthetic data",
                marker, data_folder,
            )
            train, test = synthetic_dataset(num_classes=n_cls)
            return train, test, n_cls
        raise FileNotFoundError(
            f"{marker} not found under {data_folder} (no egress to download; "
            f"place the standard python version of {dataset} there, or pass "
            f"--dataset synthetic)"
        )
    train, test = loader(data_folder)
    return train, test, n_cls
