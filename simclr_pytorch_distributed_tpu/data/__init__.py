from simclr_pytorch_distributed_tpu.data.cifar import (  # noqa: F401
    load_dataset,
    synthetic_dataset,
)
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader  # noqa: F401
