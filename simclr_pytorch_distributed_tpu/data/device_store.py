"""Device-resident data placement: the HBM-resident epoch buffer.

``docs/PERF.md`` round-5 measured the last unfixed gap between the production
driver loop and the pure compiled step: the per-step uint8 H2D transfer
(``shard_host_batch`` -> ``device_put``) costs a volatile 0-10 ms/step on the
tunneled link, while a device-resident batch sits at a stable 64.6-65.2
ms/step floor (``docs/evidence/h2d_overlap_ab_r5.json``). For datasets that
fit an HBM budget (CIFAR-10/100 train is ~150 MB uint8), this module removes
the per-step transfer entirely:

- the full uint8 dataset is uploaded ONCE at startup, replicated per device
  (replication is what keeps the per-epoch shuffle gather collective-free:
  every device gathers its own rows from its own full copy; the cost is
  bounded and pre-checked against the budget);
- per epoch the host computes the SAME numpy permutation ``EpochLoader``
  already uses (``data/pipeline.py`` ``_epoch_order`` — this class holds the
  loader and calls it, so there is exactly one permutation source) and ships
  only the int32 index matrix (~200 KB for CIFAR: ONE transfer per epoch,
  asserted mechanically via the injectable ``index_put`` hook);
- one compiled program gathers the permuted epoch into a ``[steps, batch,
  ...]`` buffer sharded batch-wise over the mesh's ``data`` axis (each
  process's devices hold only that process's slice of every global batch —
  the multi-host layout of ``EpochLoader``'s per-process slicing); the
  per-epoch gather is the ONLY row gather, so the TPU gather-lowering trap
  (the 227x crop lesson, docs/PERF.md) never applies per-step;
- each train step slices its batch with a contiguous leading-axis
  ``lax.dynamic_slice`` at ``state.step % steps_per_epoch``
  (:func:`slice_epoch_step`; the buffer is a NON-donated jit argument), so
  the hot loop is dispatch-only: no host work, no transfer, no sync.

Batch composition is bit-identical to the host loader by construction (same
permutation, same drop_last truncation, same per-process slicing), so
accuracy ratchets carry over; mid-epoch resume is a slice-offset shift
(``state.step`` restores from the checkpoint and the in-program position
follows). Proven byte-for-byte by ``tests/test_device_store.py``.

Full residency is a small-dataset (CIFAR-geometry) luxury: the real SimCLR
regime is 224x224 ImageNet-scale data that will never fit an HBM budget.
:class:`WindowStore` generalizes the same dispatch-only hot loop to datasets
that don't fit: the device trains from a resident window of
epoch-permutation-ordered batches while a host prefetch thread stages the
NEXT window into the shadow buffer, so the loop pays ONE H2D per window
instead of one per step — and the permutation source is still the driver's
own ``EpochLoader``, so the bit-identity contract (and its proof
obligations: full epochs, mid-epoch resume, multi-process slicing) carries
over unchanged. Proven by ``tests/test_window_store.py``.

``resolve_data_placement`` implements the ``--data_placement`` contract as a
three-way ladder: fully resident (``device``) when the dataset fits the
budget, windowed (``window``) when ``2 x window_bytes`` fits — memmap-backed
``data/folder.py`` trees are *windowable* (each window's host gather reads
only that window's rows), not host-degraded — and ``host`` only as the true
fallback (one startup banner naming the reason); it never OOMs, and the
verdict is collective across processes because placement selects which
collective programs a process runs.
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from simclr_pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    epoch_buffer_sharding,
    replicated_sharding,
)
from simclr_pytorch_distributed_tpu.utils import tracing

logger = logging.getLogger(__name__)

# Budget used when the backend reports no memory stats (CPU, some drivers):
# conservative vs any real accelerator HBM, far above CIFAR-scale data.
DEFAULT_BUDGET_BYTES = 4 << 30
# Fraction of the reported free per-device memory the store may claim — the
# model, optimizer state, activations, and the XLA allocator's slack own the
# rest. Deliberately conservative: 'auto' must degrade, never OOM.
BUDGET_FRACTION = 0.4
# Batches per resident window when --data_window_batches is not given: large
# enough that the per-window upload amortizes to noise (the A/B expectation
# removes delay * (1 - 1/W) of a per-step penalty), small enough that
# 2x window bytes stays far under any real HBM budget at 224x224 geometry.
DEFAULT_WINDOW_BATCHES = 32


def budget_override_bytes(mb) -> Optional[int]:
    """``--device_budget_mb`` -> a ``resolve_data_placement`` budget override
    in bytes; 0/None (the flag default) keeps the computed budget."""
    return int(mb) << 20 if mb else None


def dataset_nbytes(images: np.ndarray, labels: np.ndarray) -> int:
    return int(images.nbytes) + int(np.asarray(labels).nbytes)


def _is_memmap_backed(arr) -> bool:
    """True if ``arr`` is an ``np.memmap`` or a view over one.

    Wrappers strip the subclass without copying: ``np.ascontiguousarray`` on
    a C-contiguous memmap (``EpochLoader.__init__``) returns a plain
    ``ndarray`` VIEW whose ``base`` chain still ends at the on-disk file —
    a bare ``isinstance`` check would wave it through and residency would
    silently page the whole tree into RAM/HBM.
    """
    while arr is not None:
        if isinstance(arr, np.memmap):
            return True
        arr = getattr(arr, "base", None)
    return False


def device_budget_bytes(fraction: float = BUDGET_FRACTION) -> int:
    """Per-device placement budget: ``fraction`` of free device memory.

    ``memory_stats()`` is backend-dependent (absent on CPU and some
    platforms); without it the budget falls back to a fixed conservative
    default rather than guessing at hardware.
    """
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:  # noqa: BLE001 — backend-dependent API
        stats = {}
    limit = stats.get("bytes_limit")
    if not limit:
        return DEFAULT_BUDGET_BYTES
    free = int(limit) - int(stats.get("bytes_in_use", 0))
    return max(0, int(free * fraction))


def resident_bytes_per_device(
    images: np.ndarray, labels: np.ndarray, global_batch_size: int,
    data_parallel: int,
) -> int:
    """Per-device HBM the store will claim: the replicated dataset plus the
    double-buffered epoch buffer shard.

    The epoch buffer holds the drop_last-truncated epoch
    (``steps * global_batch`` rows) sharded ``data_parallel`` ways; 2x
    covers the transient overlap while epoch e+1's gather output coexists
    with epoch e's buffer (and matches the ISSUE's stated bound).
    """
    n = len(images)
    used_rows = (n // global_batch_size) * global_batch_size
    row_bytes = (
        int(images.nbytes // max(1, n))
        + int(np.asarray(labels).nbytes // max(1, n))
    )
    buffer_shard = -(-used_rows * row_bytes // max(1, data_parallel))  # ceil
    return dataset_nbytes(images, labels) + 2 * buffer_shard


def windowed_bytes_per_device(
    images: np.ndarray, labels: np.ndarray, global_batch_size: int,
    data_parallel: int, window_batches: int,
) -> int:
    """Per-device HBM the WINDOW store will claim: 2x one window shard
    (the resident window the device trains from plus the shadow buffer the
    prefetch thread stages the next window into). Unlike residency, the
    dataset itself never lands on device, so this bound is independent of
    dataset size — the whole point of the ladder's middle rung.
    """
    n = len(images)
    row_bytes = (
        int(images.nbytes // max(1, n))
        + int(np.asarray(labels).nbytes // max(1, n))
    )
    steps = max(1, n // global_batch_size)
    w = min(max(1, window_batches), steps)  # the store clamps identically
    shard = -(-w * global_batch_size * row_bytes // max(1, data_parallel))
    return 2 * shard


def _agree_across_processes(local_ok: bool) -> bool:
    """Collective AND of the per-process placement verdicts.

    The budget reads LOCAL ``memory_stats``, which can differ across hosts
    (fragmentation, co-resident allocations) — but placement selects which
    COLLECTIVE programs a process runs (the sharded per-epoch gather vs
    window uploads vs per-step puts), so a split verdict would deadlock
    the pod at the first epoch. The invariant is that the CALL COUNT is
    identical on every process during one resolution (the
    ``requested_global`` pattern, utils/preempt.py): explicit placements
    call it once, the 'auto' ladder once per rung it walks — which
    matches because each rung's allgathered outcome is identical
    everywhere, so all processes decide together whether the next rung's
    collective runs. All act on the AND: one over-budget host sends the
    whole job down the ladder. Single process short-circuits — no
    collective in the common case.
    """
    if jax.process_count() == 1:
        tracing.clock_anchor("placement")
        return local_ok
    from jax.experimental import multihost_utils

    # a split placement verdict is the canonical silent-deadlock seed — the
    # flight recorder keeps each host's local vote and the agreed outcome so
    # a wedged pod's dumps show who voted what at which rung
    with tracing.span(
        "placement_decision", track="main:collective", local=bool(local_ok)
    ):
        flags = multihost_utils.process_allgather(
            np.asarray([local_ok], np.int32)
        )
    # the startup alignment ruler: every process just left the same
    # collective, so this stamp is the same physical instant on each
    # host's clock (trace_report --fleet; identical call count per
    # resolution is this function's documented invariant)
    tracing.clock_anchor("placement")
    return bool(np.asarray(flags).all())


def resolve_data_placement(
    placement: str,
    images: np.ndarray,
    labels: np.ndarray,
    global_batch_size: int,
    mesh,
    budget_bytes: Optional[int] = None,
    window_batches: Optional[int] = None,
) -> str:
    """The ``--data_placement`` decision, logged. Returns 'host', 'device',
    or 'window'.

    - ``host``: always honored (the pre-existing per-step H2D loop).
    - ``device``/``window``: honored or a loud ``ValueError`` at startup —
      an explicit request that cannot be satisfied must fail before the
      first step, not OOM mid-run or silently degrade. On a multi-host job
      ANY process's rejection raises on EVERY process (collective verdict):
      one host erroring out while its peers build the store would strand
      the peers in the store's collectives.
    - ``auto``: the three-way ladder, each rung a collective verdict —
      'device' when the dataset is a plain in-RAM array within the budget
      ON EVERY PROCESS, else 'window' when the double-buffered window
      (``2 x window_bytes``; memmap-backed datasets qualify — each window's
      host gather reads only that window's rows) fits everywhere, else
      'host' with a one-line startup banner naming the reason.
    """
    if placement == "host":
        return "host"
    if placement not in ("device", "window", "auto"):
        raise ValueError(f"unknown data_placement {placement!r}")

    def reject(reason: str) -> str:
        if placement != "auto":
            raise ValueError(
                f"--data_placement {placement} cannot be satisfied: {reason}"
                f" — use 'auto' (walks the device->window->host ladder with "
                f"a banner) or 'host'"
            )
        logger.warning("data_placement auto -> host: %s", reason)
        return "host"

    data_parallel = mesh.shape.get(DATA_AXIS, 1)
    budget = device_budget_bytes() if budget_bytes is None else budget_bytes
    w = window_batches or DEFAULT_WINDOW_BATCHES

    # rung 1: full residency (the dataset itself on device)
    if _is_memmap_backed(images) or _is_memmap_backed(labels):
        resident_reason = (
            "dataset is memmap-backed (data/folder.py on-disk cache); "
            "device residency would page the whole tree into RAM/HBM"
        )
        need = None
    else:
        need = resident_bytes_per_device(
            images, labels, global_batch_size, data_parallel
        )
        resident_reason = None if need <= budget else (
            f"dataset needs {need / 1e6:.1f} MB/device (replicated data + "
            f"2x epoch-buffer shard) > budget {budget / 1e6:.1f} MB"
        )
    # rung 2: the double-buffered window (dataset stays on host)
    window_need = windowed_bytes_per_device(
        images, labels, global_batch_size, data_parallel, w
    )
    window_reason = None if window_need <= budget else (
        f"double-buffered {w}-batch window needs {window_need / 1e6:.1f} "
        f"MB/device > budget {budget / 1e6:.1f} MB"
    )

    def log_device() -> str:
        logger.info(
            "data_placement: device (%.1f MB/device resident: %.1f MB "
            "dataset + double-buffered epoch shard; budget %.1f MB)",
            need / 1e6, dataset_nbytes(images, labels) / 1e6, budget / 1e6,
        )
        return "device"

    def log_window(why_not_resident: str) -> str:
        logger.info(
            "data_placement: window (%d batches/window, %.1f MB/device "
            "double-buffered; budget %.1f MB; not fully resident: %s)",
            w, window_need / 1e6, budget / 1e6, why_not_resident,
        )
        return "window"

    peer = (
        "a peer process rejected {0} placement (per-host free-memory "
        "budgets differ); placement selects collective programs, so it "
        "must agree across hosts"
    )
    if placement == "device":
        # every process reaches this exact point once, whatever its local
        # verdict — the allgather schedules must match
        ok_everywhere = _agree_across_processes(resident_reason is None)
        if resident_reason is not None:
            return reject(resident_reason)
        if not ok_everywhere:
            return reject(peer.format("device"))
        return log_device()
    if placement == "window":
        ok_everywhere = _agree_across_processes(window_reason is None)
        if window_reason is not None:
            return reject(window_reason)
        if not ok_everywhere:
            return reject(peer.format("window"))
        return log_window(resident_reason or "explicit window request")
    # auto: walk the ladder. Each rung is one matched collective point; the
    # rung-1 result is identical on every process, so all processes agree
    # on whether rung 2's collective runs at all.
    if _agree_across_processes(resident_reason is None):
        return log_device()
    if _agree_across_processes(window_reason is None):
        return log_window(resident_reason or peer.format("device"))
    return reject(window_reason or peer.format("window"))


def make_store(
    placement: str,
    loader,
    mesh,
    budget_bytes: Optional[int] = None,
    window_batches: Optional[int] = None,
):
    """The drivers' one-call entry point: resolve ``--data_placement``
    against the LOADER'S OWN arrays and geometry, build the matching store
    — :class:`DeviceStore` ('device'), :class:`WindowStore` ('window') —
    or return ``None`` (the host loop).

    Resolving from ``loader.images``/``loader.labels`` (not the raw
    ``load_dataset`` arrays) matters: the loader may have copied a
    non-contiguous input via ``ascontiguousarray``, and what resolution
    inspects must be exactly what the store would upload — two sources
    could drift on the memmap check.
    """
    placement = resolve_data_placement(
        placement, loader.images, loader.labels, loader.global_batch_size,
        mesh, budget_bytes=budget_bytes, window_batches=window_batches,
    )
    if placement == "device":
        return DeviceStore(loader, mesh)
    if placement == "window":
        return WindowStore(
            loader, mesh, window_batches or DEFAULT_WINDOW_BATCHES
        )
    return None


def _validate_loader_geometry(loader, mesh, kind: str) -> None:
    """The shared store-construction contract (DeviceStore and WindowStore
    alike): a drop_last loader whose global batch shards evenly over the
    mesh's data axis."""
    if not loader.drop_last:
        raise ValueError(
            f"{kind} requires drop_last loaders (the training path);"
            " ragged tails have no static step shape"
        )
    data_parallel = mesh.shape.get(DATA_AXIS, 1)
    if loader.global_batch_size % data_parallel != 0:
        raise ValueError(
            f"global batch {loader.global_batch_size} not divisible by "
            f"the mesh's {data_parallel}-way data axis"
        )


def epoch_index_matrix(loader, epoch: int) -> np.ndarray:
    """The epoch's global batch composition as a ``[steps, batch]`` int32
    matrix — EXACTLY ``EpochLoader``'s permutation, drop_last-truncated and
    reshaped. Row ``s`` column range ``[p*per_proc, (p+1)*per_proc)`` is
    process ``p``'s slice of step ``s``'s global batch (pipeline.py
    ``_batches``), which is why sharding the matrix column-wise over the
    'data' axis reproduces the multi-host layout."""
    order = loader._epoch_order(epoch)
    steps, batch = loader.steps_per_epoch, loader.global_batch_size
    return np.ascontiguousarray(
        order[: steps * batch].reshape(steps, batch).astype(np.int32)
    )


def slice_epoch_step(epoch_images, epoch_labels, position):
    """One step's batch out of the resident ``[steps, batch, ...]`` buffers:
    a contiguous leading-axis dynamic slice (each device slices its own
    batch shard locally — no communication, no gather)."""
    images = jax.lax.dynamic_index_in_dim(
        epoch_images, position, axis=0, keepdims=False
    )
    labels = jax.lax.dynamic_index_in_dim(
        epoch_labels, position, axis=0, keepdims=False
    )
    return images, labels


class DeviceStore:
    """HBM-resident dataset + per-epoch shuffled buffer for one loader.

    Wraps the driver's ``EpochLoader`` — the store never computes its own
    permutation or geometry, so host and device placement cannot drift.

    ``index_put`` is the injectable per-epoch index upload (tests assert the
    one-transfer-per-epoch contract through it, the MetricRing pattern).
    """

    # the in-program slice axis is the whole epoch (drivers pass this to the
    # update builders; WindowStore overrides with its window length)
    window_batches: Optional[int] = None

    def __init__(
        self,
        loader,
        mesh,
        *,
        index_put: Optional[Callable[[np.ndarray], jax.Array]] = None,
    ):
        _validate_loader_geometry(loader, mesh, "DeviceStore")
        self.loader = loader
        self.mesh = mesh
        self.steps_per_epoch = loader.steps_per_epoch
        self.global_batch_size = loader.global_batch_size

        repl = replicated_sharding(mesh)
        img_ndim = loader.images.ndim
        # same [S, B] layout as the labels epoch buffer — the index columns
        # must stay aligned with the buffer slices they produce
        self._idx_sharding = epoch_buffer_sharding(mesh, 2)
        self._index_put = index_put or (
            lambda idx: jax.make_array_from_callback(
                idx.shape, self._idx_sharding, lambda i: idx[i]
            )
        )
        # the one-time upload: full dataset replicated per device (each
        # process feeds its own local devices from its own in-RAM copy)
        labels32 = np.ascontiguousarray(np.asarray(loader.labels, np.int32))
        images = np.ascontiguousarray(loader.images)
        self.images = jax.make_array_from_callback(
            images.shape, repl, lambda i: images[i]
        )
        self.labels = jax.make_array_from_callback(
            labels32.shape, repl, lambda i: labels32[i]
        )

        def gather(ds_images, ds_labels, idx):
            # [S, B] indices into the replicated [N, ...] dataset -> the
            # shuffled [S, B, ...] epoch buffer; indices are host-validated
            # by construction (a permutation of range(N))
            return (
                jnp.take(ds_images, idx, axis=0, mode="clip"),
                jnp.take(ds_labels, idx, axis=0, mode="clip"),
            )

        self._gather = jax.jit(
            gather,
            in_shardings=(repl, repl, self._idx_sharding),
            out_shardings=(
                epoch_buffer_sharding(mesh, img_ndim + 1),
                epoch_buffer_sharding(mesh, 2),
            ),
        )
        self._cached_epoch: Optional[int] = None
        self._buffers: Optional[Tuple[jax.Array, jax.Array]] = None

    def epoch_buffers(self, epoch: int) -> Tuple[jax.Array, jax.Array]:
        """The epoch's shuffled resident ``(images[S,B,H,W,C], labels[S,B])``.

        One int32 index upload + one compiled gather per epoch; repeated
        calls for the same epoch return the cached buffers. The previous
        epoch's buffers are dropped as the new ones land (the 2x
        double-buffer bound in :func:`resident_bytes_per_device`).
        """
        if self._cached_epoch != epoch:
            # host-visible boundary (the ONE per-epoch upload + gather
            # dispatch); the span records dispatch-side time only — no sync
            with tracing.span("epoch_gather", track="main:data", epoch=epoch):
                idx = self._index_put(epoch_index_matrix(self.loader, epoch))
                self._buffers = self._gather(self.images, self.labels, idx)
            self._cached_epoch = epoch
        return self._buffers

    def batch_buffers(self, epoch: int, idx: int) -> Tuple[jax.Array, jax.Array]:
        """The store API the driver loops consume (shared with
        :class:`WindowStore`): the device buffers step ``idx`` of ``epoch``
        slices its batch from. Here that is the whole cached epoch buffer —
        the per-step position is derived on device from ``state.step``."""
        del idx  # every step of the epoch reads the same resident buffers
        return self.epoch_buffers(epoch)

    def close(self) -> None:
        """Release driver-owned resources (shared API with WindowStore);
        the resident store holds no threads — nothing to do."""


class WindowStore:
    """Double-buffered streaming window: the dispatch-only hot loop for
    datasets that don't fit in HBM.

    The device trains from a resident ``[window_batches, batch, ...]``
    window of epoch-permutation-ordered batches while the host prefetch
    thread stages the NEXT window into the shadow buffer, so the hot loop
    pays ONE H2D per window instead of one per step — and between window
    boundaries it is exactly PR 5's dispatch-only loop (no host work, no
    transfer, no sync). The swap at a boundary is a handle exchange: the
    prefetched upload was dispatched asynchronously while the previous
    window trained, so the caller never blocks on a landed transfer.

    One permutation source: window ``w`` of epoch ``e`` is rows
    ``[w*W, (w+1)*W)`` of :func:`epoch_index_matrix` — EXACTLY the driver's
    ``EpochLoader`` permutation, drop_last-truncated, with process ``p``'s
    column block of every row being that process's loader slice (the same
    multi-host layout as the resident store, ``epoch_buffer_sharding``).
    The short last window of an epoch is padded back to ``W`` batches with
    rows the step never slices (the in-program position
    ``epoch_position(step) % W`` stays below the tail length), so every
    window shares ONE compiled step program. Mid-epoch resume is a window +
    slice offset shift: the driver asks for ``batch_buffers(epoch,
    start_step)``, which lands in window ``start_step // W``, and the
    restored ``state.step`` positions the in-window slice.

    The host gather for one window reads only that window's rows — and on
    a pod, only THIS process's column block of them (``_stage``) — so on a
    memmap-backed dataset (``data/folder.py``) the epoch streams through
    the page cache window by window instead of paging the whole tree into
    RAM, which is why the placement ladder marks memmap trees *windowable*
    rather than host-degraded.

    ``window_put`` is the injectable per-window upload, receiving the
    process-local ``[W, B/process_count, ...]`` blocks (tests assert the
    one-upload-per-window, window-sized transfer contract through it — the
    ``index_put`` pattern). ``prefetch=False`` stages every window in the
    caller's thread: deterministic upload ordering for tests and for the
    serialized-link A/B proxy (``scripts/window_ab.py``), where overlap
    would hide the modeled transfer.
    """

    def __init__(
        self,
        loader,
        mesh,
        window_batches: int = DEFAULT_WINDOW_BATCHES,
        *,
        window_put: Optional[Callable] = None,
        prefetch: bool = True,
    ):
        _validate_loader_geometry(loader, mesh, "WindowStore")
        if window_batches < 1:
            raise ValueError(
                f"window_batches must be >= 1, got {window_batches}"
            )
        self.loader = loader
        self.mesh = mesh
        self.steps_per_epoch = loader.steps_per_epoch
        self.global_batch_size = loader.global_batch_size
        self.window_batches = min(window_batches, loader.steps_per_epoch)
        self.n_windows = -(-loader.steps_per_epoch // self.window_batches)
        self._img_sharding = epoch_buffer_sharding(mesh, loader.images.ndim + 1)
        self._lab_sharding = epoch_buffer_sharding(mesh, 2)
        self._window_put = window_put or self._default_put
        self._executor = (
            ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="WindowStore-prefetch"
            )
            if prefetch else None
        )
        self._epoch_idx: Optional[Tuple[int, np.ndarray]] = None
        self._current = None  # (epoch, window, (images, labels))
        self._next = None  # (epoch, window, Future)

    def _default_put(self, images: np.ndarray, labels: np.ndarray):
        """Async H2D of one PROCESS-LOCAL window block under the
        epoch-buffer layout (the ``shard_host_batch`` convention: plain
        ``device_put`` single-process, global-array assembly from
        process-local column blocks on a pod)."""
        if jax.process_count() == 1:
            return (
                jax.device_put(images, self._img_sharding),
                jax.device_put(labels, self._lab_sharding),
            )
        w = images.shape[0]
        return (
            jax.make_array_from_process_local_data(
                self._img_sharding, images,
                (w, self.global_batch_size) + images.shape[2:],
            ),
            jax.make_array_from_process_local_data(
                self._lab_sharding, labels, (w, self.global_batch_size),
            ),
        )

    def _index_rows(self, epoch: int, window: int) -> np.ndarray:
        cached = self._epoch_idx
        if cached is None or cached[0] != epoch:
            # benign race with a stale prefetch job: worst case one
            # recompute — the tuple swap below is atomic
            cached = (epoch, epoch_index_matrix(self.loader, epoch))
            self._epoch_idx = cached
        w = self.window_batches
        rows = cached[1][window * w:(window + 1) * w]
        if rows.shape[0] < w:
            # short epoch tail: pad back to the static [W, B] shape with
            # rows the step never slices (epoch_position % W < tail length)
            pad = np.repeat(rows[:1], w - rows.shape[0], axis=0)
            rows = np.concatenate([rows, pad], axis=0)
        return rows

    def _stage(self, epoch: int, window: int):
        """Host-gather one window's rows and start its (async) upload.

        Only THIS process's column block of the window is gathered — on a
        pod each process reads/copies exactly the 1/P of the window its
        own devices will hold (a memmap-backed tree pages only those
        rows), instead of materializing all peers' slices too."""
        # runs on the prefetch thread normally, on the training thread for
        # the first window of an epoch / a resume jump — its own non-main
        # track either way (the main-thread blocking part is what
        # window_swap measures in batch_buffers)
        with tracing.span(
            "window_stage", track="store:stage", epoch=epoch, window=window
        ):
            rows = self._index_rows(epoch, window)
            per_proc = self.global_batch_size // self.loader.process_count
            lo = self.loader.process_index * per_proc
            local_rows = rows[:, lo:lo + per_proc]
            images = np.ascontiguousarray(self.loader.images[local_rows])
            labels = np.ascontiguousarray(
                np.asarray(self.loader.labels)[local_rows].astype(np.int32)
            )
            return self._window_put(images, labels)

    def batch_buffers(self, epoch: int, idx: int) -> Tuple[jax.Array, jax.Array]:
        """The device buffers step ``idx`` of ``epoch`` slices its batch
        from: the window containing ``idx``. Within a window this is the
        cached handle pair (no host work); at a boundary the prefetched
        shadow buffers are swapped in and the NEXT window's staging is
        handed to the prefetch thread. A prefetch exception re-raises here,
        on the training thread, where it can abort the step with a real
        traceback (the EpochLoader worker convention)."""
        window = idx // self.window_batches
        cur = self._current
        if cur is not None and cur[0] == epoch and cur[1] == window:
            return cur[2]
        nxt, self._next = self._next, None
        # window_swap is the main-thread BLOCKING part of the boundary —
        # near-zero when the prefetch won the race, a full synchronous
        # stage when it didn't (the number trace_report attributes to
        # window staging)
        with tracing.span(
            "window_swap", track="main:data", epoch=epoch, window=window,
            prefetched=bool(
                nxt is not None and nxt[0] == epoch and nxt[1] == window
            ),
        ):
            if nxt is not None and nxt[0] == epoch and nxt[1] == window:
                buffers = nxt[2].result()
            else:
                if nxt is not None and not nxt[2].cancel():
                    # a resume/rollback jump abandoned a staged window and
                    # cancel() cannot stop a RUNNING stage: wait it out
                    # (bounded — one window) and free its shard NOW, before
                    # staging the replacement. Letting it drain in the
                    # background would transiently hold a THIRD window shard
                    # on a device the ladder admitted at exactly 2x.
                    try:
                        for arr in nxt[2].result():
                            arr.delete()
                    except Exception:  # noqa: BLE001 — the stale stage itself
                        pass  # failed: nothing landed, nothing to free
                buffers = self._stage(epoch, window)
        self._current = (epoch, window, buffers)
        # Prefetch stays WITHIN the epoch: the first window of each epoch is
        # staged in the caller's thread. That boundary is never hot — every
        # driver drains telemetry collectively (and saves/validates) there —
        # and within-epoch-only staging keeps the upload count per epoch
        # exactly n_windows, which the transfer-count proofs pin.
        if self._executor is not None and window + 1 < self.n_windows:
            self._next = (
                epoch, window + 1,
                self._executor.submit(self._stage, epoch, window + 1),
            )
        return buffers

    def close(self) -> None:
        """Stop the prefetch worker and drop the staged shadow buffers.

        Drivers call this on the way out (their ``finally``, next to the
        EpochLoader ``batches.close()`` hygiene): without it a preemption
        early-exit leaves a live non-daemon prefetch thread whose pending
        window upload — which nothing will ever read — gets joined at
        interpreter exit, stalling the exit-75 path. Queued-but-unstarted
        jobs are cancelled; at most one in-flight stage finishes in the
        background. The store degrades to synchronous staging if used
        again after close (the prefetch=False path)."""
        nxt, self._next = self._next, None
        if nxt is not None:
            nxt[2].cancel()
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None
