"""Device-resident data placement: the HBM-resident epoch buffer.

``docs/PERF.md`` round-5 measured the last unfixed gap between the production
driver loop and the pure compiled step: the per-step uint8 H2D transfer
(``shard_host_batch`` -> ``device_put``) costs a volatile 0-10 ms/step on the
tunneled link, while a device-resident batch sits at a stable 64.6-65.2
ms/step floor (``docs/evidence/h2d_overlap_ab_r5.json``). For datasets that
fit an HBM budget (CIFAR-10/100 train is ~150 MB uint8), this module removes
the per-step transfer entirely:

- the full uint8 dataset is uploaded ONCE at startup, replicated per device
  (replication is what keeps the per-epoch shuffle gather collective-free:
  every device gathers its own rows from its own full copy; the cost is
  bounded and pre-checked against the budget);
- per epoch the host computes the SAME numpy permutation ``EpochLoader``
  already uses (``data/pipeline.py`` ``_epoch_order`` — this class holds the
  loader and calls it, so there is exactly one permutation source) and ships
  only the int32 index matrix (~200 KB for CIFAR: ONE transfer per epoch,
  asserted mechanically via the injectable ``index_put`` hook);
- one compiled program gathers the permuted epoch into a ``[steps, batch,
  ...]`` buffer sharded batch-wise over the mesh's ``data`` axis (each
  process's devices hold only that process's slice of every global batch —
  the multi-host layout of ``EpochLoader``'s per-process slicing); the
  per-epoch gather is the ONLY row gather, so the TPU gather-lowering trap
  (the 227x crop lesson, docs/PERF.md) never applies per-step;
- each train step slices its batch with a contiguous leading-axis
  ``lax.dynamic_slice`` at ``state.step % steps_per_epoch``
  (:func:`slice_epoch_step`; the buffer is a NON-donated jit argument), so
  the hot loop is dispatch-only: no host work, no transfer, no sync.

Batch composition is bit-identical to the host loader by construction (same
permutation, same drop_last truncation, same per-process slicing), so
accuracy ratchets carry over; mid-epoch resume is a slice-offset shift
(``state.step`` restores from the checkpoint and the in-program position
follows). Proven byte-for-byte by ``tests/test_device_store.py``.

``resolve_data_placement`` implements the ``--data_placement`` contract:
``auto`` degrades gracefully to host placement (one startup banner naming
the reason) when the dataset is memmap-backed (``data/folder.py`` trees —
resident placement would silently page the whole memmap into RAM) or
exceeds the HBM budget; it never OOMs.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from simclr_pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    epoch_buffer_sharding,
    replicated_sharding,
)

logger = logging.getLogger(__name__)

# Budget used when the backend reports no memory stats (CPU, some drivers):
# conservative vs any real accelerator HBM, far above CIFAR-scale data.
DEFAULT_BUDGET_BYTES = 4 << 30
# Fraction of the reported free per-device memory the store may claim — the
# model, optimizer state, activations, and the XLA allocator's slack own the
# rest. Deliberately conservative: 'auto' must degrade, never OOM.
BUDGET_FRACTION = 0.4


def dataset_nbytes(images: np.ndarray, labels: np.ndarray) -> int:
    return int(images.nbytes) + int(np.asarray(labels).nbytes)


def _is_memmap_backed(arr) -> bool:
    """True if ``arr`` is an ``np.memmap`` or a view over one.

    Wrappers strip the subclass without copying: ``np.ascontiguousarray`` on
    a C-contiguous memmap (``EpochLoader.__init__``) returns a plain
    ``ndarray`` VIEW whose ``base`` chain still ends at the on-disk file —
    a bare ``isinstance`` check would wave it through and residency would
    silently page the whole tree into RAM/HBM.
    """
    while arr is not None:
        if isinstance(arr, np.memmap):
            return True
        arr = getattr(arr, "base", None)
    return False


def device_budget_bytes(fraction: float = BUDGET_FRACTION) -> int:
    """Per-device placement budget: ``fraction`` of free device memory.

    ``memory_stats()`` is backend-dependent (absent on CPU and some
    platforms); without it the budget falls back to a fixed conservative
    default rather than guessing at hardware.
    """
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:  # noqa: BLE001 — backend-dependent API
        stats = {}
    limit = stats.get("bytes_limit")
    if not limit:
        return DEFAULT_BUDGET_BYTES
    free = int(limit) - int(stats.get("bytes_in_use", 0))
    return max(0, int(free * fraction))


def resident_bytes_per_device(
    images: np.ndarray, labels: np.ndarray, global_batch_size: int,
    data_parallel: int,
) -> int:
    """Per-device HBM the store will claim: the replicated dataset plus the
    double-buffered epoch buffer shard.

    The epoch buffer holds the drop_last-truncated epoch
    (``steps * global_batch`` rows) sharded ``data_parallel`` ways; 2x
    covers the transient overlap while epoch e+1's gather output coexists
    with epoch e's buffer (and matches the ISSUE's stated bound).
    """
    n = len(images)
    used_rows = (n // global_batch_size) * global_batch_size
    row_bytes = (
        int(images.nbytes // max(1, n))
        + int(np.asarray(labels).nbytes // max(1, n))
    )
    buffer_shard = -(-used_rows * row_bytes // max(1, data_parallel))  # ceil
    return dataset_nbytes(images, labels) + 2 * buffer_shard


def _agree_across_processes(local_ok: bool) -> bool:
    """Collective AND of the per-process placement verdicts.

    The budget reads LOCAL ``memory_stats``, which can differ across hosts
    (fragmentation, co-resident allocations) — but placement selects which
    COLLECTIVE programs a process runs (the sharded per-epoch gather vs
    per-step puts), so a split verdict would deadlock the pod at the first
    epoch. Every process calls this exactly once during resolution (the
    ``requested_global`` pattern, utils/preempt.py) and all act on the AND:
    one over-budget host sends the whole job to host placement. Single
    process short-circuits — no collective in the common case.
    """
    if jax.process_count() == 1:
        return local_ok
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([local_ok], np.int32)
    )
    return bool(np.asarray(flags).all())


def resolve_data_placement(
    placement: str,
    images: np.ndarray,
    labels: np.ndarray,
    global_batch_size: int,
    mesh,
    budget_bytes: Optional[int] = None,
) -> str:
    """The ``--data_placement`` decision, logged. Returns 'host' or 'device'.

    - ``host``: always honored (the pre-existing per-step H2D loop).
    - ``device``: honored or a loud ``ValueError`` at startup — an explicit
      request that cannot be satisfied must fail before the first step, not
      OOM mid-run or silently degrade. On a multi-host job ANY process's
      rejection raises on EVERY process (collective verdict): one host
      erroring out while its peers build the store would strand the peers
      in the store's collectives.
    - ``auto``: 'device' when the dataset is a plain in-RAM array within the
      budget ON EVERY PROCESS, else 'host' with a one-line startup banner
      naming the reason (memmap-backed, the computed bytes vs budget, or a
      peer's rejection).
    """
    if placement == "host":
        return "host"
    if placement not in ("device", "auto"):
        raise ValueError(f"unknown data_placement {placement!r}")

    def reject(reason: str) -> str:
        if placement == "device":
            raise ValueError(
                f"--data_placement device cannot be satisfied: {reason} — "
                f"use 'auto' (falls back to host with a banner) or 'host'"
            )
        logger.warning("data_placement auto -> host: %s", reason)
        return "host"

    if _is_memmap_backed(images) or _is_memmap_backed(labels):
        local_reason = (
            "dataset is memmap-backed (data/folder.py on-disk cache); "
            "device residency would page the whole tree into RAM/HBM"
        )
        need = budget = None
    else:
        data_parallel = mesh.shape.get(DATA_AXIS, 1)
        need = resident_bytes_per_device(
            images, labels, global_batch_size, data_parallel
        )
        budget = device_budget_bytes() if budget_bytes is None else budget_bytes
        local_reason = None if need <= budget else (
            f"dataset needs {need / 1e6:.1f} MB/device (replicated data + "
            f"2x epoch-buffer shard) > budget {budget / 1e6:.1f} MB"
        )
    # every process reaches this exact point once, whatever its local
    # verdict — the allgather schedules must match
    ok_everywhere = _agree_across_processes(local_reason is None)
    if local_reason is not None:
        return reject(local_reason)
    if not ok_everywhere:
        return reject(
            "a peer process rejected device placement (per-host free-memory "
            "budgets differ); placement selects collective programs, so it "
            "must agree across hosts"
        )
    logger.info(
        "data_placement: device (%.1f MB/device resident: %.1f MB dataset "
        "+ double-buffered epoch shard; budget %.1f MB)",
        need / 1e6, dataset_nbytes(images, labels) / 1e6, budget / 1e6,
    )
    return "device"


def make_store(
    placement: str, loader, mesh, budget_bytes: Optional[int] = None,
) -> Optional["DeviceStore"]:
    """The drivers' one-call entry point: resolve ``--data_placement``
    against the LOADER'S OWN arrays and geometry, build the store if the
    verdict is 'device', else return ``None`` (the host loop).

    Resolving from ``loader.images``/``loader.labels`` (not the raw
    ``load_dataset`` arrays) matters: the loader may have copied a
    non-contiguous input via ``ascontiguousarray``, and what resolution
    inspects must be exactly what the store would upload — two sources
    could drift on the memmap check.
    """
    placement = resolve_data_placement(
        placement, loader.images, loader.labels, loader.global_batch_size,
        mesh, budget_bytes=budget_bytes,
    )
    return DeviceStore(loader, mesh) if placement == "device" else None


def epoch_index_matrix(loader, epoch: int) -> np.ndarray:
    """The epoch's global batch composition as a ``[steps, batch]`` int32
    matrix — EXACTLY ``EpochLoader``'s permutation, drop_last-truncated and
    reshaped. Row ``s`` column range ``[p*per_proc, (p+1)*per_proc)`` is
    process ``p``'s slice of step ``s``'s global batch (pipeline.py
    ``_batches``), which is why sharding the matrix column-wise over the
    'data' axis reproduces the multi-host layout."""
    order = loader._epoch_order(epoch)
    steps, batch = loader.steps_per_epoch, loader.global_batch_size
    return np.ascontiguousarray(
        order[: steps * batch].reshape(steps, batch).astype(np.int32)
    )


def slice_epoch_step(epoch_images, epoch_labels, position):
    """One step's batch out of the resident ``[steps, batch, ...]`` buffers:
    a contiguous leading-axis dynamic slice (each device slices its own
    batch shard locally — no communication, no gather)."""
    images = jax.lax.dynamic_index_in_dim(
        epoch_images, position, axis=0, keepdims=False
    )
    labels = jax.lax.dynamic_index_in_dim(
        epoch_labels, position, axis=0, keepdims=False
    )
    return images, labels


class DeviceStore:
    """HBM-resident dataset + per-epoch shuffled buffer for one loader.

    Wraps the driver's ``EpochLoader`` — the store never computes its own
    permutation or geometry, so host and device placement cannot drift.

    ``index_put`` is the injectable per-epoch index upload (tests assert the
    one-transfer-per-epoch contract through it, the MetricRing pattern).
    """

    def __init__(
        self,
        loader,
        mesh,
        *,
        index_put: Optional[Callable[[np.ndarray], jax.Array]] = None,
    ):
        if not loader.drop_last:
            raise ValueError(
                "DeviceStore requires drop_last loaders (the training path);"
                " ragged tails have no static step shape"
            )
        data_parallel = mesh.shape.get(DATA_AXIS, 1)
        if loader.global_batch_size % data_parallel != 0:
            raise ValueError(
                f"global batch {loader.global_batch_size} not divisible by "
                f"the mesh's {data_parallel}-way data axis"
            )
        self.loader = loader
        self.mesh = mesh
        self.steps_per_epoch = loader.steps_per_epoch
        self.global_batch_size = loader.global_batch_size

        repl = replicated_sharding(mesh)
        img_ndim = loader.images.ndim
        # same [S, B] layout as the labels epoch buffer — the index columns
        # must stay aligned with the buffer slices they produce
        self._idx_sharding = epoch_buffer_sharding(mesh, 2)
        self._index_put = index_put or (
            lambda idx: jax.make_array_from_callback(
                idx.shape, self._idx_sharding, lambda i: idx[i]
            )
        )
        # the one-time upload: full dataset replicated per device (each
        # process feeds its own local devices from its own in-RAM copy)
        labels32 = np.ascontiguousarray(np.asarray(loader.labels, np.int32))
        images = np.ascontiguousarray(loader.images)
        self.images = jax.make_array_from_callback(
            images.shape, repl, lambda i: images[i]
        )
        self.labels = jax.make_array_from_callback(
            labels32.shape, repl, lambda i: labels32[i]
        )

        def gather(ds_images, ds_labels, idx):
            # [S, B] indices into the replicated [N, ...] dataset -> the
            # shuffled [S, B, ...] epoch buffer; indices are host-validated
            # by construction (a permutation of range(N))
            return (
                jnp.take(ds_images, idx, axis=0, mode="clip"),
                jnp.take(ds_labels, idx, axis=0, mode="clip"),
            )

        self._gather = jax.jit(
            gather,
            in_shardings=(repl, repl, self._idx_sharding),
            out_shardings=(
                epoch_buffer_sharding(mesh, img_ndim + 1),
                epoch_buffer_sharding(mesh, 2),
            ),
        )
        self._cached_epoch: Optional[int] = None
        self._buffers: Optional[Tuple[jax.Array, jax.Array]] = None

    def epoch_buffers(self, epoch: int) -> Tuple[jax.Array, jax.Array]:
        """The epoch's shuffled resident ``(images[S,B,H,W,C], labels[S,B])``.

        One int32 index upload + one compiled gather per epoch; repeated
        calls for the same epoch return the cached buffers. The previous
        epoch's buffers are dropped as the new ones land (the 2x
        double-buffer bound in :func:`resident_bytes_per_device`).
        """
        if self._cached_epoch != epoch:
            idx = self._index_put(epoch_index_matrix(self.loader, epoch))
            self._buffers = self._gather(self.images, self.labels, idx)
            self._cached_epoch = epoch
        return self._buffers
