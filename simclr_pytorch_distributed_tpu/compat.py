"""Version-compat shims for the small jax API surface whose spelling moved.

The repo targets the current jax API (``jax.shard_map`` with ``check_vma``,
``jax.lax.pvary``), but deployment images pin older releases where
``shard_map`` still lives in ``jax.experimental.shard_map`` with the
``check_rep`` keyword and the vma/pvary typing system does not exist yet.
Everything funnels through here so a version bump is a one-file change and an
old runtime degrades gracefully instead of dying at import time (the
pre-compat failure mode: ``from jax import shard_map`` ImportError'd the
whole train package, taking every driver — and the preemption/resume
machinery — down with it).
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6: public API, vma typing
    from jax import shard_map as _shard_map

    _NEW_API = True
except ImportError:  # older jax: experimental module, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    _NEW_API = False


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern keyword spelling on every version.

    ``check_vma`` maps onto the old API's ``check_rep`` — same meaning
    (replication/varying-axes type checking), renamed upstream.
    """
    if _NEW_API:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def pvary(x, axis_names):
    """Mark ``x`` device-varying over ``axis_names`` for shard_map's vma
    typing; identity where the vma system doesn't exist (pre-pvary jax has no
    replication types to satisfy, so there is nothing to mark)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, tuple(axis_names), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, tuple(axis_names))
    return x


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside shard_map.

    ``jax.lax.axis_size`` on current jax; on older releases the frame lookup
    returns the size directly (an int) from the axis environment.
    """
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax._src import core as _core

    frame = _core.axis_frame(axis_name)
    return int(frame) if isinstance(frame, int) else int(frame.size)


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` whose ``vma`` keyword only exists on jax
    versions with the vma typing system; ``vma=None`` (always the case on
    older jax — see :func:`pvary`) needs no keyword at all."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # pre-vma jax given a non-None vma: nothing to type
        return jax.ShapeDtypeStruct(shape, dtype)
