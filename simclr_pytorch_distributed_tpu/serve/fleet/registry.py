"""ModelRegistry — N named checkpoint versions behind one server.

One registry hosts many NAMED models; each name hosts a chain of VERSIONS
(v1, v2, ... — one per promote). The pieces compose, they are not rebuilt:

- every version is an ordinary :class:`~serve.engine.EmbeddingEngine` with
  its own bucketed jit cache, all sharing the one mesh (params are
  replicated per engine; the compiled programs coexist in jax's executable
  cache keyed by the engine's functions);
- every NAME has exactly ONE :class:`~serve.batcher.DynamicBatcher` whose
  queue survives promotes — requests coalesced before a swap and dispatched
  after it simply route to the new serving version, which is what makes
  FIFO ordering across a swap free (the completer was already strictly
  FIFO in dispatch order);
- routing: ``submit(images, model=...)`` picks the name (default = the
  newest promoted name), per-tenant admission quotas layer on top of the
  batcher's own QueueFull/row-bounded backpressure.

**Hot-swap drain (the dispatch/completion split as the swap seam).** A
dispatch pins the CURRENT serving version — its in-flight counter is
incremented under the registry lock BEFORE the engine call, so a promote
landing one instruction later can only mark it ``draining``, never retire
it. Completion (:class:`_TrackedBatch.result`) releases the pin; the last
release of a draining version retires it: the engine reference is dropped
(device buffers freed), the ``drained`` event fires, and a
``model_retired`` tracing event lands in the flight recorder. No request
is ever failed or rerouted by a promote: everything dispatched before the
swap completes on the old engine, everything after dispatches on the new
one. tests/test_serve_fleet.py holds a gated batch in flight ACROSS a
promote to pin exactly this.

Cache identity: the registry stamps ``"<name>@v<version>"`` into each
engine's cache-key prefix (``EmbeddingEngine.set_identity``) before the
version becomes visible, so a shared EmbeddingCache can never serve a
retired version's rows — even byte-identical weights miss after a swap.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from simclr_pytorch_distributed_tpu.serve.batcher import DynamicBatcher, QueueFull
from simclr_pytorch_distributed_tpu.serve.fleet.retrieval import NeighborIndex
from simclr_pytorch_distributed_tpu.utils import tracing

SERVING = "serving"
DRAINING = "draining"
RETIRED = "retired"


class ModelVersion:
    """One hosted checkpoint version: the engine plus its drain state."""

    def __init__(self, name: str, version: int, engine, source: str = ""):
        self.name = name
        self.version = version
        self.engine = engine
        self.source = source
        self.state = SERVING
        self.inflight = 0  # dispatched-but-uncompleted batches pinning us
        self.drained = threading.Event()

    @property
    def identity(self) -> str:
        return f"{self.name}@v{self.version}"

    def info(self) -> dict:
        return {
            "version": self.version,
            "state": self.state,
            "inflight": self.inflight,
            "source": self.source,
        }


class _TrackedBatch:
    """An engine ``InflightBatch`` that releases its version pin on
    completion. ``result()`` stays idempotent, and the release happens
    exactly once whether the completion succeeds or raises (a failed D2H
    still ends the engine's involvement — holding the pin would wedge the
    drain forever)."""

    def __init__(self, registry: "ModelRegistry", mv: ModelVersion, handle):
        self._registry = registry
        self._mv = mv
        self._handle = handle
        self._released = False
        self._lock = threading.Lock()

    @property
    def n_rows(self) -> int:
        return self._handle.n_rows

    def done(self) -> bool:
        return self._handle.done()

    def result(self) -> np.ndarray:
        try:
            return self._handle.result()
        finally:
            with self._lock:
                release, self._released = not self._released, True
            if release:
                self._registry._release(self._mv)


class AdmissionController:
    """Per-(model, tenant) outstanding-row quotas over the shared queue.

    The batcher's QueueFull bounds TOTAL queue memory; it cannot stop one
    tenant from filling it and starving the rest. ``admit`` charges the
    request's rows against its (model, tenant) bucket and raises
    :class:`~serve.batcher.QueueFull` over quota — same exception, same 503
    + Retry-After on the wire — and the returned release callable (hung on
    the request future's done-callback) refunds the rows whichever way the
    request ends. ``max_tenant_rows <= 0`` disables the layer."""

    def __init__(self, max_tenant_rows: int = 0):
        self.max_tenant_rows = int(max_tenant_rows)
        self._outstanding: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._admitted = 0
        self._rejected = 0

    def admit(self, model: str, tenant: str, n: int) -> Callable[[], None]:
        if self.max_tenant_rows <= 0:
            return lambda: None
        key = (model, tenant)
        with self._lock:
            held = self._outstanding.get(key, 0)
            if held + n > self.max_tenant_rows:
                self._rejected += 1
                raise QueueFull(
                    f"tenant {tenant!r} over quota on model {model!r} "
                    f"({held} rows outstanding, quota {self.max_tenant_rows})"
                )
            self._outstanding[key] = held + n
            self._admitted += 1
        released = threading.Event()

        def release() -> None:
            if released.is_set():
                return
            released.set()
            with self._lock:
                left = self._outstanding.get(key, 0) - n
                if left > 0:
                    self._outstanding[key] = left
                else:
                    self._outstanding.pop(key, None)

        return release

    def stats(self) -> dict:
        with self._lock:
            return {
                "max_tenant_rows": self.max_tenant_rows,
                "admitted": self._admitted,
                "rejected": self._rejected,
                "active_buckets": len(self._outstanding),
                "outstanding_rows": sum(self._outstanding.values()),
            }


class _ModelState:
    """Everything one NAME owns: its batcher (queue survives promotes),
    its version chain, and its retrieval index."""

    def __init__(self, name: str, batcher: DynamicBatcher,
                 serving: ModelVersion, index: Optional[NeighborIndex]):
        self.name = name
        self.batcher = batcher
        self.versions: List[ModelVersion] = [serving]
        self.serving = serving
        self.index = index


class ModelRegistry:
    def __init__(
        self,
        *,
        batcher_kwargs: Optional[dict] = None,
        admission: Optional[AdmissionController] = None,
        index_capacity: int = 4096,
        index_factory: Optional[Callable[[int], object]] = None,
    ):
        # one lock orders every routing/promote/drain transition; engine
        # dispatches run OUTSIDE it (they take the engine's own lock and
        # block on host work — serializing models against each other here
        # would defeat multi-model hosting)
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelState] = {}
        self._default: Optional[str] = None
        self._batcher_kwargs = dict(batcher_kwargs or {})
        self.admission = admission if admission is not None else AdmissionController()
        self._index_capacity = int(index_capacity)
        # feat_dim -> index; the registry is impl-blind — the frontend's
        # --retrieval_impl ladder decides brute vs IVF (serve/fleet/ivf.py)
        # and hands the constructor down here
        self._index_factory = index_factory
        self._closed = False

    # ----------------------------------------------------------- lifecycle

    def add_model(self, name: str, engine, source: str = "") -> ModelVersion:
        """Host a new NAME at version 1 and make it the default route."""
        mv = ModelVersion(name, 1, engine, source)
        engine.set_identity(mv.identity)
        if self._index_capacity <= 0:
            index = None
        elif self._index_factory is not None:
            index = self._index_factory(engine.feat_dim)
        else:
            index = NeighborIndex(engine.feat_dim, capacity=self._index_capacity)
        batcher = DynamicBatcher(
            dispatch_fn=lambda images, _n=name: self._dispatch(_n, images),
            # both closures track the CURRENT serving version: a promote
            # retargets queued-but-undispatched requests automatically
            validate=lambda images, _n=name: self._serving(_n).validate_images(images),
            bucket_fn=lambda n, _n=name: self._serving(_n).bucket_for(n),
            **self._batcher_kwargs,
        )
        with self._lock:
            if self._closed:
                batcher.close(drain=False)
                raise RuntimeError("ModelRegistry is closed")
            if name in self._models:
                batcher.close(drain=False)
                raise ValueError(f"model {name!r} already hosted")
            self._models[name] = _ModelState(name, batcher, mv, index)
            self._default = name
        tracing.event(
            "model_added", track="serve:fleet", model=name, version=1,
            source=source,
        )
        return mv

    def promote(self, name: str, engine, source: str = "") -> ModelVersion:
        """Install ``engine`` as ``name``'s next version; the old version
        drains (completes everything already dispatched on it) and retires
        on its last completion. Returns the new version. The swap is
        atomic under the registry lock: no request observes a moment with
        no serving version."""
        with self._lock:
            st = self._models.get(name)
            if st is None:
                raise KeyError(f"unknown model {name!r}")
            old = st.serving
            mv = ModelVersion(name, old.version + 1, engine, source)
            # stamp the cache identity BEFORE the version is visible: the
            # first post-swap request must already key on name@vN+1
            engine.set_identity(mv.identity)
            st.versions.append(mv)
            st.serving = mv
            old.state = DRAINING
            self._default = name
            if st.index is not None:
                # a new version is a new embedding space: neighbors
                # computed by v_old are not comparable to v_new queries
                st.index.clear()
        tracing.event(
            "model_promote", track="serve:fleet", model=name,
            version=mv.version, draining=old.version, source=source,
        )
        with self._lock:
            self._maybe_retire_locked(old)
        return mv

    def close(self) -> None:
        """Drain every model's batcher and retire every version."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            states = list(self._models.values())
        for st in states:
            st.batcher.close()  # drains: completions release every pin
        with self._lock:
            for st in states:
                for mv in st.versions:
                    if mv.state != RETIRED:
                        mv.state = DRAINING
                        self._maybe_retire_locked(mv)

    # ------------------------------------------------------------- routing

    def resolve(self, model: Optional[str]) -> str:
        """The name a request routes to (explicit, else newest promoted)."""
        with self._lock:
            name = model or self._default
            if name is None:
                raise KeyError("no models hosted")
            if name not in self._models:
                raise KeyError(f"unknown model {name!r}")
            return name

    def _serving(self, name: str):
        with self._lock:
            st = self._models.get(name)
            if st is None:
                raise RuntimeError(f"unknown model {name!r}")
            return st.serving.engine

    def _dispatch(self, name: str, images: np.ndarray) -> _TrackedBatch:
        """The batcher's dispatch_fn: pin the current serving version, then
        run the engine's dispatch stage outside the registry lock."""
        with self._lock:
            st = self._models.get(name)
            if st is None:
                raise RuntimeError(f"unknown model {name!r}")
            mv = st.serving
            mv.inflight += 1
        try:
            handle = mv.engine.dispatch(images)
        except BaseException:
            # the pin protects work the engine OWNS; a dispatch that never
            # started owns nothing — release, or the drain never finishes
            self._release(mv)
            raise
        return _TrackedBatch(self, mv, handle)

    def _release(self, mv: ModelVersion) -> None:
        with self._lock:
            mv.inflight -= 1
            self._maybe_retire_locked(mv)

    def _maybe_retire_locked(self, mv: ModelVersion) -> None:
        if mv.state == DRAINING and mv.inflight == 0:
            mv.state = RETIRED
            mv.engine = None  # drop params/jit refs: device buffers free
            mv.drained.set()
            tracing.event(
                "model_retired", track="serve:fleet", model=mv.name,
                version=mv.version,
            )

    def submit(
        self,
        images: np.ndarray,
        *,
        model: Optional[str] = None,
        tenant: str = "",
        timeout_ms: Optional[float] = None,
    ):
        """Route one request: ``(name, future)``. Raises ``KeyError`` for an
        unknown model (HTTP 400), :class:`QueueFull` for backpressure or an
        exhausted tenant quota (503)."""
        name = self.resolve(model)
        images = np.asarray(images)
        n = int(images.shape[0]) if images.ndim == 4 else 0
        release = self.admission.admit(name, tenant, n)
        with self._lock:
            st = self._models[name]
        try:
            future = st.batcher.submit(images, timeout_ms=timeout_ms)
        except BaseException:
            release()
            raise
        future.add_done_callback(lambda _f: release())
        return name, future

    # ----------------------------------------------------------- retrieval

    @staticmethod
    def content_id(image_u8: np.ndarray) -> str:
        """The wire-visible neighbor id: content hash of the raw image
        (shape-qualified like the embedding cache key, but with NO model
        fingerprint — the per-model index already scopes it)."""
        h = hashlib.sha1(str(image_u8.shape).encode())
        h.update(np.ascontiguousarray(image_u8).tobytes())
        return h.hexdigest()[:20]

    def index_add(self, name: str, images: np.ndarray, embeddings: np.ndarray) -> None:
        """Feed served rows into ``name``'s retrieval index (the /embed
        response path; /neighbors queries are NOT inserted, so retrieval
        reads don't mutate the corpus)."""
        with self._lock:
            st = self._models.get(name)
            index = st.index if st is not None else None
        if index is None:
            return
        keys = [self.content_id(images[i]) for i in range(images.shape[0])]
        index.add(keys, embeddings)

    def neighbors_lookup(self, name: str, embeddings: np.ndarray, k: int):
        with self._lock:
            st = self._models.get(name)
            if st is None:
                raise KeyError(f"unknown model {name!r}")
            index = st.index
        if index is None:
            raise RuntimeError("retrieval index disabled (index_capacity=0)")
        return index.query(embeddings, k)

    # --------------------------------------------------------------- views

    def default_model(self) -> Optional[str]:
        with self._lock:
            return self._default

    def batcher(self, name: str) -> DynamicBatcher:
        with self._lock:
            return self._models[name].batcher

    def wait_drained(
        self, name: str, version: int, timeout: Optional[float] = None
    ) -> bool:
        with self._lock:
            st = self._models.get(name)
            mv = None
            if st is not None:
                for v in st.versions:
                    if v.version == version:
                        mv = v
                        break
        if mv is None:
            raise KeyError(f"unknown version {name}@v{version}")
        return mv.drained.wait(timeout)

    def models_payload(self) -> dict:
        """GET /models: the routing table as clients see it."""
        with self._lock:
            return {
                "default": self._default,
                "models": {
                    name: {
                        "serving": st.serving.version,
                        "versions": [mv.info() for mv in st.versions],
                    }
                    for name, st in self._models.items()
                },
            }

    def stats(self) -> dict:
        with self._lock:
            states = list(self._models.items())
            default = self._default
        out = {"default": default, "admission": self.admission.stats(), "models": {}}
        for name, st in states:
            entry = {
                "serving": st.serving.version,
                "versions": [mv.info() for mv in st.versions],
                "batcher": st.batcher.stats(),
            }
            engine = st.serving.engine
            if engine is not None:
                entry["engine"] = engine.stats()
            if st.index is not None:
                entry["index"] = st.index.stats()
            out["models"][name] = entry
        return out
