"""Multi-model HTTP frontend: one server, N models, hot-swap, retrieval.

The wire contract extends serve/server.py (payloads are byte-compatible —
``decode_images`` is shared) with routing and fleet control:

- ``POST /embed`` — as the single-model server, plus optional ``"model"``
  (default = newest promoted) and ``"tenant"`` (admission-quota key).
  Replies carry ``"model"`` so clients see where they routed. Served rows
  feed the model's retrieval index.
- ``POST /models/promote`` — ``{"model": name, "ckpt": path}``: load the
  checkpoint, install it as the model's next version, let the old version
  drain on its own engine (zero failed/dropped requests — the registry
  proves it). Replies the new version and which version is draining.
- ``POST /neighbors`` — ``{"images": ..., "k": 5, "model": ...}``: embed
  the query images through the SAME batcher/admission path as /embed, then
  return top-k ``{"id", "score"}`` neighbors from the model's index
  (brute or IVF per the ``--retrieval_impl`` ladder; ``k`` above
  ``--neighbors_max_k`` is 400 — the index answers ``min(k, entries)``,
  so an unbounded ``k`` would dump the whole index).
- ``GET /models`` — the routing table (names, versions, drain states).
- ``GET /healthz``, ``/stats``, ``/metrics`` — as the single-model server;
  /metrics aggregates the per-model batchers into the UNLABELED gauges the
  replica-fleet supervisor scrapes (supervise/observe.py parses only plain
  ``name value`` lines) and adds per-model labeled series beside them.

Status mapping is identical to serve/server.py: QueueFull (including a
tenant over admission quota) -> 503 + Retry-After, timeouts -> 504,
malformed/unknown-model -> 400, closed -> 503.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from simclr_pytorch_distributed_tpu.serve.batcher import QueueFull, RequestTimeout
from simclr_pytorch_distributed_tpu.serve.fleet import ivf
from simclr_pytorch_distributed_tpu.serve.fleet.registry import (
    AdmissionController,
    ModelRegistry,
)
from simclr_pytorch_distributed_tpu.serve.server import (
    MAX_BODY_BYTES,
    decode_images,
    start_in_thread,
)

logger = logging.getLogger(__name__)


DEFAULT_NEIGHBORS_MAX_K = 100


def make_fleet_handler(
    registry: ModelRegistry,
    *,
    result_timeout_s: float = 30.0,
    promote_loader=None,
    metrics_fn=None,
    neighbors_max_k: int = DEFAULT_NEIGHBORS_MAX_K,
):
    """Request-handler class over one registry.

    ``promote_loader`` is ``(name, ckpt) -> engine`` — injectable so tests
    promote fake engines without checkpoints on disk; absent, /models/promote
    answers 503 (a frontend that cannot load has no business swapping).

    ``neighbors_max_k`` bounds the client-chosen ``k`` on /neighbors
    (0 disables the bound): the index answers ``min(k, entries)``, so an
    unbounded ``k`` lets any client dump the ENTIRE index contents — and
    pay an index-sized response — with one request.
    """

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _reply(self, code: int, obj: dict, extra_headers=()) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra_headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/healthz":
                self._reply(200, {"status": "ok"})
            elif self.path == "/stats":
                self._reply(200, registry.stats())
            elif self.path == "/models":
                self._reply(200, registry.models_payload())
            elif self.path == "/metrics" and metrics_fn is not None:
                body = metrics_fn().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _read_payload(self):
            length = int(self.headers.get("Content-Length", 0) or 0)
            if length <= 0 or length > MAX_BODY_BYTES:
                # close so unread body bytes cannot desync keep-alive
                self._reply(400, {"error": f"bad Content-Length {length}"},
                            [("Connection", "close")])
                return None
            return json.loads(self.rfile.read(length))

        def _submit_and_wait(self, payload):
            """The shared /embed and /neighbors ingress: decode, route,
            admit, wait. Returns ``(name, images, embeddings)`` or replies
            the mapped error itself and returns None."""
            images = decode_images(payload)
            timeout_ms = payload.get("timeout_ms")
            if timeout_ms is not None and (
                not isinstance(timeout_ms, (int, float))
                or isinstance(timeout_ms, bool) or timeout_ms <= 0
            ):
                raise ValueError(
                    f"timeout_ms must be a positive number, got {timeout_ms!r}"
                )
            model = payload.get("model")
            tenant = payload.get("tenant", "")
            if model is not None and not isinstance(model, str):
                raise ValueError(f"model must be a string, got {model!r}")
            if not isinstance(tenant, str):
                raise ValueError(f"tenant must be a string, got {tenant!r}")
            try:
                name, future = registry.submit(
                    images, model=model, tenant=tenant, timeout_ms=timeout_ms
                )
            except QueueFull as e:
                self._reply(503, {"error": str(e)}, [("Retry-After", "1")])
                return None
            except (KeyError, ValueError) as e:
                self._reply(400, {"error": str(e).strip("'\"")})
                return None
            except RuntimeError as e:
                self._reply(503, {"error": str(e)})
                return None
            try:
                emb = future.result(
                    timeout=(timeout_ms / 1e3) if timeout_ms is not None
                    else result_timeout_s
                )
            except (RequestTimeout, FutureTimeout) as e:
                future.cancel()
                self._reply(504, {"error": f"embedding timed out: {e}"})
                return None
            except Exception as e:  # noqa: BLE001 — engine failure -> 500
                self._reply(500, {"error": str(e)})
                return None
            return name, images, emb

        def do_POST(self):  # noqa: N802
            try:
                if self.path in ("/embed", "/neighbors"):
                    payload = self._read_payload()
                    if payload is None:
                        return
                    served = self._submit_and_wait(payload)
                    if served is None:
                        return
                    name, images, emb = served
                    if self.path == "/embed":
                        registry.index_add(name, images, emb)
                        self._reply(200, {
                            "embeddings": [row.tolist() for row in emb],
                            "dim": int(emb.shape[1]),
                            "n": int(emb.shape[0]),
                            "model": name,
                        })
                        return
                    k = payload.get("k", 5)
                    if not isinstance(k, int) or isinstance(k, bool) or k < 1:
                        raise ValueError(f"k must be a positive int, got {k!r}")
                    if neighbors_max_k and k > neighbors_max_k:
                        raise ValueError(
                            f"k={k} exceeds the --neighbors_max_k bound "
                            f"({neighbors_max_k})"
                        )
                    hits = registry.neighbors_lookup(name, emb, k)
                    self._reply(200, {
                        "model": name,
                        "k": k,
                        "neighbors": [
                            [{"id": key, "score": score} for key, score in row]
                            for row in hits
                        ],
                    })
                    return
                if self.path == "/models/promote":
                    payload = self._read_payload()
                    if payload is None:
                        return
                    name = payload.get("model")
                    ckpt = payload.get("ckpt")
                    if not isinstance(name, str) or not name:
                        raise ValueError(f"model must be a name, got {name!r}")
                    if not isinstance(ckpt, str) or not ckpt:
                        raise ValueError(f"ckpt must be a path, got {ckpt!r}")
                    if promote_loader is None:
                        self._reply(503, {
                            "error": "this frontend has no checkpoint loader"
                        })
                        return
                    old_serving = registry.models_payload()["models"].get(
                        name, {}
                    ).get("serving")
                    engine = promote_loader(name, ckpt)
                    mv = registry.promote(name, engine, source=ckpt)
                    self._reply(200, {
                        "model": name,
                        "version": mv.version,
                        "draining": old_serving,
                    })
                    return
                self._reply(404, {"error": f"unknown path {self.path}"})
            except QueueFull as e:
                self._reply(503, {"error": str(e)}, [("Retry-After", "1")])
            except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e).strip("'\"")})
            except RuntimeError as e:
                self._reply(503, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — loader/index failure
                logger.exception("fleet frontend failure on %s", self.path)
                self._reply(500, {"error": str(e)})

        def log_message(self, fmt, *args):  # quiet: route through logging
            logger.debug("%s - %s", self.address_string(), fmt % args)

    return Handler


def create_fleet_server(
    registry: ModelRegistry, host: str = "127.0.0.1", port: int = 8000,
    result_timeout_s: float = 30.0, promote_loader=None, metrics_fn=None,
    neighbors_max_k: int = DEFAULT_NEIGHBORS_MAX_K,
) -> ThreadingHTTPServer:
    handler = make_fleet_handler(
        registry, result_timeout_s=result_timeout_s,
        promote_loader=promote_loader, metrics_fn=metrics_fn,
        neighbors_max_k=neighbors_max_k,
    )
    server = ThreadingHTTPServer((host, port), handler)
    server.daemon_threads = True
    return server


def fleet_metrics_fn(registry: ModelRegistry, latency=None):
    """Prometheus exposition for the fleet frontend.

    Two layers: (1) UNLABELED ``serve_batcher_*`` gauges aggregated across
    models — sums for queue/inflight/throughput counters, max for
    occupancy, min for ``last_completion_age_s`` (any model completing is
    fleet progress) — because the replica supervisor's parser
    (supervise.observe.parse_prometheus_text) reads only plain lines; and
    (2) per-model LABELED series for operators."""
    from simclr_pytorch_distributed_tpu.utils import prom

    SUM_KEYS = (
        "submitted", "rejected", "timeouts", "batches", "batched_images",
        "dispatched_batches", "errors", "queue_depth", "queued_images",
        "inflight_batches", "inflight_rows",
    )

    def metrics() -> str:
        stats = registry.stats()
        models = stats["models"]
        agg = {key: 0.0 for key in SUM_KEYS}
        occ = 0.0
        age = None
        samples = []
        for name, entry in sorted(models.items()):
            bs = entry["batcher"]
            for key in SUM_KEYS:
                agg[key] += bs.get(key, 0)
            occ = max(occ, bs.get("pipeline_occupancy", 0.0))
            a = bs.get("last_completion_age_s")
            if a is not None:
                age = a if age is None else min(age, a)
            samples.append((
                "serve_fleet_model_queue_depth", {"model": name},
                bs.get("queue_depth", 0),
            ))
            samples.append((
                "serve_fleet_model_serving_version", {"model": name},
                entry["serving"],
            ))
            if "index" in entry:
                idx = entry["index"]
                # the full retrieval surface, per model: corpus size, LRU
                # churn, query volume, and the IVF probe/retrain counters
                # (0 on the brute rung — a recall degradation with the
                # retrain counter STUCK is the quantizer-drift failure
                # trail, docs/OBSERVABILITY.md)
                for gauge, key in (
                    ("serve_fleet_index_entries", "entries"),
                    ("serve_fleet_index_inserts_total", "inserts"),
                    ("serve_fleet_index_evictions_total", "evictions"),
                    ("serve_fleet_index_queries_total", "queries"),
                    ("serve_fleet_index_probes_total", "probes"),
                    ("serve_fleet_index_retrains_total", "retrains"),
                ):
                    samples.append((gauge, {"model": name}, idx.get(key, 0)))
        for key in SUM_KEYS:
            samples.append((f"serve_batcher_{key}", None, agg[key]))
        samples.append(("serve_batcher_pipeline_occupancy", None, occ))
        if age is not None:
            samples.append(("serve_batcher_last_completion_age_s", None, age))
        samples.append(("serve_fleet_models", None, len(models)))
        adm = stats["admission"]
        samples.append(("serve_fleet_admission_rejected_total", None,
                        adm["rejected"]))
        samples.append(("serve_fleet_admission_outstanding_rows", None,
                        adm["outstanding_rows"]))
        if latency is not None:
            samples.extend(latency.samples("serve_request_latency_ms"))
        return prom.render_prometheus(samples)

    return metrics


def build_parser():
    from simclr_pytorch_distributed_tpu.serve.server import (
        build_parser as build_serve_parser,
    )

    p = build_serve_parser()
    p.description = (
        "multi-model embedding fleet frontend (POST /embed with routing, "
        "POST /models/promote hot-swap, POST /neighbors retrieval)"
    )
    p.add_argument("--name", default="default",
                   help="name the initial model is hosted under "
                        "(/embed routes here by default)")
    p.add_argument("--index_capacity", type=int, default=4096,
                   help="per-model retrieval index rows (LRU-evicted); "
                        "0 disables /neighbors")
    p.add_argument("--retrieval_impl", default="auto",
                   choices=("brute", "ivf", "auto"),
                   help="/neighbors index implementation (the --loss_impl "
                        "ladder): brute = exact cosine over every row, "
                        "ivf = k-means inverted lists scanning only "
                        "--ivf_nprobe of them, auto = ivf above a "
                        "corpus-size threshold")
    p.add_argument("--ivf_nlist", type=int, default=0,
                   help="IVF coarse-quantizer centroids; 0 = "
                        "sqrt(index_capacity), clamped")
    p.add_argument("--ivf_nprobe", type=int, default=ivf.DEFAULT_NPROBE,
                   help="IVF lists scanned per query: the recall/latency "
                        "dial (docs/SERVING.md)")
    p.add_argument("--neighbors_max_k", type=int,
                   default=DEFAULT_NEIGHBORS_MAX_K,
                   help="reject /neighbors k above this with 400 (the "
                        "index answers min(k, entries), so an unbounded k "
                        "dumps the whole index); 0 disables the bound")
    p.add_argument("--tenant_quota_rows", type=int, default=0,
                   help="admission control: max outstanding rows per "
                        "(model, tenant); 0 disables the layer")
    return p


def build_fleet_stack(args):
    """Registry + initial model + HTTP server from parsed args — the fleet
    analogue of serve.server.build_stack, split out so tests and the bench
    drive the exact CLI stack without serve_forever."""
    from simclr_pytorch_distributed_tpu import config
    from simclr_pytorch_distributed_tpu.serve.cache import EmbeddingCache
    from simclr_pytorch_distributed_tpu.serve.engine import EmbeddingEngine
    from simclr_pytorch_distributed_tpu.utils import prom

    buckets = tuple(int(b) for b in args.buckets.split(","))
    # one cache per model NAME, shared across its versions: the identity
    # stamped into the key prefix is what keeps post-swap hits correct
    caches = {}

    def engine_kwargs(name):
        if args.cache_capacity and name not in caches:
            caches[name] = EmbeddingCache(args.cache_capacity)
        kwargs = dict(buckets=buckets, normalize=args.normalize,
                      output=args.output, cache=caches.get(name),
                      dtype=args.dtype)
        if args.img_size is not None:
            kwargs["img_size"] = args.img_size
        return kwargs

    def loader(name, ckpt):
        return EmbeddingEngine.from_checkpoint(ckpt, **engine_kwargs(name))

    # the --retrieval_impl ladder (the --loss_impl/--conv_impl convention):
    # resolve ONCE at startup, honored-or-raise for explicit asks, and say
    # why in the banner — the impl decides every /neighbors latency number
    impl, reason = ivf.resolve_retrieval_impl(
        args.retrieval_impl, args.index_capacity, args.ivf_nlist
    )
    logging.info(config.impl_resolution_banner(
        "retrieval_impl", args.retrieval_impl, impl, reason
    ))
    index_factory = None
    if impl == "ivf":
        index_factory = lambda dim: ivf.IVFIndex(  # noqa: E731
            dim, capacity=args.index_capacity, nlist=args.ivf_nlist,
            nprobe=args.ivf_nprobe,
        )

    latency = prom.LatencyHistogram()
    registry = ModelRegistry(
        batcher_kwargs=dict(
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            max_queue=args.max_queue, max_inflight=args.max_inflight,
            max_inflight_images=args.max_inflight_images, latency=latency,
        ),
        admission=AdmissionController(args.tenant_quota_rows),
        index_capacity=args.index_capacity,
        index_factory=index_factory,
    )
    if args.ckpt:
        engine = loader(args.name, args.ckpt)
    else:
        logging.warning("--ckpt not given: serving a RANDOM %s", args.model)
        kwargs = engine_kwargs(args.name)
        engine = EmbeddingEngine.random_init(
            model_name=args.model, size=kwargs.get("img_size", 32), **kwargs
        )
    registry.add_model(args.name, engine, source=args.ckpt or "random")
    server = create_fleet_server(
        registry, host=args.host, port=args.port, promote_loader=loader,
        metrics_fn=fleet_metrics_fn(registry, latency),
        neighbors_max_k=args.neighbors_max_k,
    )
    return registry, server


def main(argv=None):
    from simclr_pytorch_distributed_tpu.utils import tracing

    args = build_parser().parse_args(argv)
    recorder = None
    if args.events_jsonl:
        trace_path = os.path.splitext(args.events_jsonl)[0] + ".trace.json"
        recorder = tracing.FlightRecorder(
            args.events_jsonl, trace_path=trace_path
        )
        tracing.install(recorder)
    registry, server = build_fleet_stack(args)
    logging.info("fleet frontend: model %r on http://%s:%d",
                 args.name, args.host, args.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        registry.close()
        tracing.uninstall()
        if recorder is not None:
            recorder.close()


# re-exported so embedders have one import site for "run a fleet frontend"
__all__ = [
    "make_fleet_handler", "create_fleet_server", "fleet_metrics_fn",
    "build_parser", "build_fleet_stack", "main", "start_in_thread",
]
