"""IVFIndex — two-tier inverted-file cosine retrieval (the sublinear rung).

Brute force (`retrieval.NeighborIndex`) scores every stored row per query:
O(capacity * dim) compute and a full-buffer H2D re-upload per mutation
burst. Right at the 4096-row default, hostile at the 10^5-10^6-row corpus
the north star implies. The IVF rung (Jegou et al.'s coarse-quantizer
design; Johnson et al.'s billion-scale Faiss) makes query cost
O(nlist * dim + nprobe * avg_list_len * dim):

- **coarse quantizer** — ``nlist`` k-means centroids trained from the
  index's OWN stored rows (spherical mini-batch Lloyd's, seeded: same
  seed + same insert order -> identical centroids, lists, and answers).
  Training triggers itself: first when the corpus reaches
  ``train_min_rows``, then again whenever rows inserted since the last
  train exceed ``retrain_drift`` of the corpus that trained it — served
  embeddings drift with traffic, and a quantizer trained on last week's
  corpus probes the wrong lists;
- **inverted lists** — every unit row lives in exactly one per-centroid
  list; a query scores the ``[nlist, dim]`` centroid matrix, picks the
  ``nprobe`` nearest lists, and runs EXACT cosine over only those rows.
  Recall@k against the brute oracle is the measured, gateable price
  (scripts/retrieval_ab.py -> docs/evidence/retrieval_ab_r18.json).

Before the first train every row sits in one provisional list and a query
scans it exactly — the untrained index IS brute force, so small corpora
never pay approximation error (and `--retrieval_impl auto` only picks IVF
above a capacity threshold anyway: ``resolve_retrieval_impl``).

Contracts carried over from the brute rung, unchanged on the wire:
content-keyed idempotent ``add`` (re-adding a key overwrites its row and
refreshes recency), ``clear()`` on promote (new version = new embedding
space — centroids are dropped too, they were trained on the old space's
rows), and queries NEVER touch recency. Eviction becomes **per-list with
a global budget**: the ``capacity`` bound is global, but when it is hit
the arriving row's TARGET list evicts its own least-recently-inserted
entry (falling back to the globally oldest row only when the target list
is empty) — a hot list cannot silently consume the cold lists' corpus,
and eviction stays O(1) instead of rescanning ``nlist`` structures.

Everything here is numpy on host, deliberately: per-query candidate sets
have data-dependent lengths, which is exactly the shape-hostile regime
the engine's bucketed-jit discipline exists to avoid, and the win at
large corpus is algorithmic (scan 1/30th of the rows), not kernel-level.
The brute rung keeps its jitted fixed-shape scorer bit-for-bit.
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from simclr_pytorch_distributed_tpu.serve.fleet.retrieval import _normalize

DEFAULT_NPROBE = 8
# `auto` picks IVF only when the configured corpus bound crosses this:
# below it the brute matmul is already one small fused device program and
# IVF would only add approximation error (docs/SERVING.md ladder table)
AUTO_IVF_MIN_CAPACITY = 32768


def auto_nlist(capacity: int) -> int:
    """The sqrt(N) rule of thumb, clamped: balances centroid-scan cost
    (nlist * dim) against per-list scan cost (N/nlist * dim per probe)."""
    return max(8, min(1024, int(round(math.sqrt(max(1, capacity))))))


def resolve_retrieval_impl(
    impl: str, capacity: int, nlist: int = 0
) -> Tuple[str, str]:
    """``(resolved_impl, reason)`` for the ``--retrieval_impl`` ladder —
    the ``resolve_loss_impl``/``resolve_conv_impl`` convention: ``auto``
    picks by corpus bound, an explicit choice is honored or raises (a
    silently ignored flag would misreport every latency number built on
    it), and the reason feeds ``config.impl_resolution_banner``."""
    if impl not in ("brute", "ivf", "auto"):
        raise ValueError(
            f"--retrieval_impl must be brute/ivf/auto, got {impl!r}"
        )
    if capacity <= 0:
        # no index at all: nothing to resolve, but an explicit ivf ask is
        # a config contradiction, not a preference to drop silently
        if impl == "ivf":
            raise ValueError(
                "--retrieval_impl ivf needs a retrieval index: "
                "--index_capacity is 0 (/neighbors disabled)"
            )
        return "brute", "retrieval index disabled (--index_capacity 0)"
    nlist_eff = nlist or auto_nlist(capacity)
    if impl == "ivf":
        if capacity < nlist_eff:
            raise ValueError(
                f"--retrieval_impl ivf needs index_capacity >= nlist "
                f"({capacity} < {nlist_eff}): every centroid needs a row "
                "to own — raise --index_capacity or lower --ivf_nlist"
            )
        return "ivf", (
            f"explicit request ({nlist_eff} lists over "
            f"{capacity}-row budget)"
        )
    if impl == "brute":
        return "brute", "explicit request (exact cosine over every row)"
    if capacity >= AUTO_IVF_MIN_CAPACITY:
        return "ivf", (
            f"index_capacity {capacity} >= {AUTO_IVF_MIN_CAPACITY}: "
            f"brute is O(capacity*dim) per query at this corpus bound "
            f"({nlist_eff} lists)"
        )
    return "brute", (
        f"index_capacity {capacity} < {AUTO_IVF_MIN_CAPACITY}: "
        "exact brute scan is cheap and recall-free at this bound"
    )


class IVFIndex:
    """Bounded content-keyed store of unit rows behind a k-means coarse
    quantizer. Same surface as :class:`~retrieval.NeighborIndex` —
    ``add``/``query``/``clear``/``stats``/``len`` — so the registry and
    frontend are impl-blind."""

    def __init__(
        self,
        dim: int,
        capacity: int = 4096,
        *,
        nlist: int = 0,
        nprobe: int = DEFAULT_NPROBE,
        seed: int = 0,
        train_min_rows: Optional[int] = None,
        retrain_drift: float = 0.5,
        kmeans_iters: int = 10,
        kmeans_batch: int = 4096,
    ):
        if dim < 1 or capacity < 1:
            raise ValueError(f"need dim, capacity >= 1, got {dim}/{capacity}")
        self.dim = int(dim)
        self.capacity = int(capacity)
        self.nlist = int(nlist) or auto_nlist(capacity)
        if self.nlist < 1 or self.nlist > capacity:
            raise ValueError(
                f"need 1 <= nlist <= capacity, got {self.nlist}/{capacity}"
            )
        self.nprobe = max(1, min(int(nprobe), self.nlist))
        self.seed = int(seed)
        # enough rows that every centroid can own a few before we commit
        # to a partition; below it the single provisional list is exact
        self.train_min_rows = int(
            train_min_rows if train_min_rows is not None
            else min(capacity, max(256, 4 * self.nlist))
        )
        self.retrain_drift = float(retrain_drift)
        self.kmeans_iters = int(kmeans_iters)
        self.kmeans_batch = int(kmeans_batch)

        self._lock = threading.Lock()
        self._buf = np.zeros((capacity, dim), np.float32)  # slot -> unit row
        self._free = list(range(capacity - 1, -1, -1))  # pop() -> slot 0 first
        self._order: "OrderedDict[str, int]" = OrderedDict()  # global recency
        self._key_list: Dict[str, int] = {}  # key -> owning list id
        # list id -> (key -> slot), insertion-recency ordered; one
        # provisional list 0 until the first train
        self._lists: List["OrderedDict[str, int]"] = [OrderedDict()]
        self._centroids: Optional[np.ndarray] = None  # [n_lists, dim]
        # per-list cached [m, dim] matrix + key tuple; invalidated per
        # mutated list (the brute index's one-upload-per-burst discipline,
        # per list)
        self._cache: Dict[int, Tuple[np.ndarray, Tuple[str, ...]]] = {}
        self._rows_at_train = 0
        self._inserts_since_train = 0
        self._stats = {
            "inserts": 0, "updates": 0, "evictions": 0, "queries": 0,
            "probes": 0, "retrains": 0,
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._order)

    # ------------------------------------------------------------ mutation

    def add(self, keys: Sequence[str], rows: np.ndarray) -> None:
        """Insert/update ``(key, row)`` pairs; idempotent on key (same
        content under one model version embeds identically) and
        recency-refreshing, exactly like the brute rung."""
        rows = _normalize(rows)
        if len(keys) != rows.shape[0] or rows.shape[1] != self.dim:
            raise ValueError(
                f"{len(keys)} keys vs rows {rows.shape}, index dim {self.dim}"
            )
        with self._lock:
            for key, row in zip(keys, rows):
                self._add_one_locked(key, row)
            if self._should_train_locked():
                self._train_locked()

    def _assign_locked(self, row: np.ndarray) -> int:
        if self._centroids is None:
            return 0
        return int(np.argmax(self._centroids @ row))

    def _add_one_locked(self, key: str, row: np.ndarray) -> None:
        old_list = self._key_list.get(key)
        if old_list is not None:
            # update: the row may move lists (the content hash is the
            # identity; the ROW decides the list)
            slot = self._lists[old_list][key]
            new_list = self._assign_locked(row)
            self._buf[slot] = row
            if new_list != old_list:
                del self._lists[old_list][key]
                self._cache.pop(old_list, None)
                self._lists[new_list][key] = slot
                self._key_list[key] = new_list
            else:
                self._lists[old_list].move_to_end(key)
            self._cache.pop(new_list, None)
            self._order[key] = slot
            self._order.move_to_end(key)
            self._stats["updates"] += 1
            return
        list_id = self._assign_locked(row)
        if self._free:
            slot = self._free.pop()
        else:
            slot = self._evict_locked(list_id)
        self._buf[slot] = row
        self._lists[list_id][key] = slot
        self._key_list[key] = list_id
        self._order[key] = slot
        self._cache.pop(list_id, None)
        self._stats["inserts"] += 1
        self._inserts_since_train += 1

    def _evict_locked(self, target_list: int) -> int:
        """Per-list LRU under the global budget: the arriving row's own
        list gives up its least-recently-inserted entry; an empty target
        list falls back to the globally oldest row (some list must pay —
        the budget is global)."""
        if self._lists[target_list]:
            old_key, slot = self._lists[target_list].popitem(last=False)
            del self._order[old_key]
            victim_list = target_list
        else:
            old_key, slot = self._order.popitem(last=False)
            victim_list = self._key_list[old_key]
            del self._lists[victim_list][old_key]
        del self._key_list[old_key]
        self._cache.pop(victim_list, None)
        self._stats["evictions"] += 1
        return slot

    def clear(self) -> None:
        """Promote seam: a new model version is a new embedding space, so
        the rows AND the centroids trained on them are both invalid."""
        with self._lock:
            self._buf[:] = 0.0
            self._free = list(range(self.capacity - 1, -1, -1))
            self._order.clear()
            self._key_list.clear()
            self._lists = [OrderedDict()]
            self._centroids = None
            self._cache.clear()
            self._rows_at_train = 0
            self._inserts_since_train = 0

    # ------------------------------------------------------------ training

    def _should_train_locked(self) -> bool:
        n = len(self._order)
        if self._centroids is None:
            return n >= self.train_min_rows
        return self._inserts_since_train >= max(
            1, int(self.retrain_drift * self._rows_at_train)
        )

    def _train_locked(self) -> None:
        """Seeded spherical mini-batch Lloyd's over the stored rows, then
        a full reassignment. Deterministic: the rng is seeded from
        ``(seed, retrain ordinal)`` and rows are visited in global
        insertion-recency order, so same seed + same insert order means
        identical centroids and identical lists."""
        keys = list(self._order)
        slots = np.fromiter(
            (self._order[k] for k in keys), np.int64, len(keys)
        )
        rows = self._buf[slots]  # [n, dim], recency-ordered
        n = rows.shape[0]
        k = min(self.nlist, n)
        rng = np.random.default_rng((self.seed, self._stats["retrains"]))
        centroids = rows[rng.choice(n, size=k, replace=False)].copy()
        counts = np.ones(k, np.float64)  # Sculley-style per-center rates
        for _ in range(self.kmeans_iters):
            batch = rows[rng.choice(n, size=min(self.kmeans_batch, n),
                                    replace=False)]
            assign = np.argmax(batch @ centroids.T, axis=1)
            for c in np.unique(assign):
                members = batch[assign == c]
                lr = members.shape[0] / (counts[c] + members.shape[0])
                centroids[c] = (1.0 - lr) * centroids[c] + lr * members.mean(0)
                counts[c] += members.shape[0]
            # spherical k-means: cosine assignment needs unit centroids
            centroids /= np.maximum(
                np.linalg.norm(centroids, axis=1, keepdims=True), 1e-12
            )
        self._centroids = centroids.astype(np.float32)
        # full reassignment, chunked to bound the [chunk, k] similarity
        assign = np.empty(n, np.int64)
        for lo in range(0, n, 65536):
            assign[lo:lo + 65536] = np.argmax(
                rows[lo:lo + 65536] @ centroids.T, axis=1
            )
        self._lists = [OrderedDict() for _ in range(k)]
        self._key_list.clear()
        self._cache.clear()
        # recency-ordered visit: each rebuilt list inherits the relative
        # insertion order its entries had before the retrain
        for key, slot, list_id in zip(keys, slots, assign):
            self._lists[int(list_id)][key] = int(slot)
            self._key_list[key] = int(list_id)
        self._rows_at_train = n
        self._inserts_since_train = 0
        self._stats["retrains"] += 1

    # --------------------------------------------------------------- query

    def _list_matrix_locked(self, list_id: int):
        cached = self._cache.get(list_id)
        if cached is None:
            entries = self._lists[list_id]
            keys = tuple(entries)
            slots = np.fromiter(entries.values(), np.int64, len(entries))
            cached = (self._buf[slots], keys)
            self._cache[list_id] = cached
        return cached

    def query(
        self, rows: np.ndarray, k: int
    ) -> List[List[Tuple[str, float]]]:
        """Top-``k`` ``(key, cosine)`` per query row, best first — exact
        cosine over the union of the ``nprobe`` nearest lists' rows."""
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        rows = _normalize(np.atleast_2d(rows))
        out: List[List[Tuple[str, float]]] = []
        with self._lock:
            self._stats["queries"] += rows.shape[0]
            if not self._order:
                return [[] for _ in range(rows.shape[0])]
            if self._centroids is None:
                probe_plan = [[0]] * rows.shape[0]
            else:
                sims = rows @ self._centroids.T  # [n, n_lists]
                nprobe = min(self.nprobe, sims.shape[1])
                top = np.argpartition(-sims, nprobe - 1, axis=1)[:, :nprobe]
                probe_plan = [
                    lists[np.argsort(-sims[i, lists], kind="stable")]
                    for i, lists in enumerate(top)
                ]
            for row, lists in zip(rows, probe_plan):
                mats, key_sets = [], []
                for list_id in lists:
                    if not self._lists[int(list_id)]:
                        continue
                    mat, keys = self._list_matrix_locked(int(list_id))
                    mats.append(mat)
                    key_sets.append(keys)
                self._stats["probes"] += len(lists)
                if not mats:
                    out.append([])
                    continue
                scores = np.concatenate([m @ row for m in mats])
                keys = [key for keys in key_sets for key in keys]
                k_eff = min(int(k), scores.shape[0])
                top = np.argpartition(-scores, k_eff - 1)[:k_eff]
                top = top[np.argsort(-scores[top], kind="stable")]
                out.append([(keys[i], float(scores[i])) for i in top])
        return out

    # --------------------------------------------------------------- views

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "entries": len(self._order),
                "capacity": self.capacity,
                "dim": self.dim,
                "nlist": self.nlist,
                "nprobe": self.nprobe,
                "trained_lists": (
                    0 if self._centroids is None else len(self._lists)
                ),
                **self._stats,
            }
