"""NeighborIndex — on-device brute-force cosine retrieval over served rows.

`/neighbors` turns the embedding service into a retrieval service: every
row served through `/embed` is inserted (content-keyed, like the embedding
cache) into a bounded per-model index, and a query image's nearest
neighbors are the stored rows with the highest cosine similarity to its
embedding.

Brute force is the right first rung at this scale: the index is bounded
(LRU eviction at ``capacity``), so scoring is one ``[capacity, dim] @
[dim, q]`` matmul — exactly the shape accelerators are best at, and small
enough (4096 x 128 default) that an IVF/graph structure would only add
approximation error. The scoring matmul runs as a jitted device program
over a FIXED-shape buffer: the host keeps the canonical ``[capacity, dim]``
array plus a validity mask, uploads lazily (one H2D per mutation burst, not
per query — the ``dirty`` flag), and queries are padded to a small set of
query buckets so compiles stay bounded, the same discipline as the engine's
batch buckets. Free/evicted slots score ``-inf`` via the mask, so they can
never outrank a real row.

Embedding spaces are per (model, version): the registry clears the index on
promote — v_old's stored rows are not comparable to v_new's queries.

Determinism contract for the exact-recall test: rows are L2-normalized on
insert AND query (cosine = dot of unit rows) and eviction order is
insert/update recency only (queries never touch LRU order) — both
reproducible by the numpy oracle in tests/test_serve_fleet.py.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

QUERY_BUCKETS = (1, 8, 32)


def _normalize(rows: np.ndarray) -> np.ndarray:
    rows = np.asarray(rows, np.float32)
    norms = np.linalg.norm(rows, axis=-1, keepdims=True)
    return rows / np.maximum(norms, 1e-12)


def _score_fn(db, valid, q):
    # [capacity, dim], [capacity], [qb, dim] -> [qb, capacity]
    scores = q @ db.T
    return jnp.where(valid[None, :], scores, -jnp.inf)


class NeighborIndex:
    """Bounded content-keyed store of unit embedding rows + device scorer."""

    def __init__(self, dim: int, capacity: int = 4096):
        if dim < 1 or capacity < 1:
            raise ValueError(f"need dim, capacity >= 1, got {dim}/{capacity}")
        self.dim = int(dim)
        self.capacity = int(capacity)
        self._buf = np.zeros((capacity, dim), np.float32)
        self._valid = np.zeros((capacity,), bool)
        self._slot_key: List = [None] * capacity
        self._slots: "OrderedDict[str, int]" = OrderedDict()  # key -> slot, LRU
        self._free = list(range(capacity - 1, -1, -1))  # pop() yields slot 0 first
        self._lock = threading.Lock()
        self._dirty = True
        self._dev = None  # (device db, device mask) snapshot
        self._jit = jax.jit(_score_fn)
        self._stats = {"inserts": 0, "updates": 0, "evictions": 0, "queries": 0}

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    def add(self, keys: Sequence[str], rows: np.ndarray) -> None:
        """Insert/update ``(key, row)`` pairs; refreshes LRU recency for
        keys already present (their row is overwritten — same content under
        one model version embeds identically, so this is idempotence, not
        drift)."""
        rows = _normalize(rows)
        if len(keys) != rows.shape[0] or rows.shape[1] != self.dim:
            raise ValueError(
                f"{len(keys)} keys vs rows {rows.shape}, index dim {self.dim}"
            )
        with self._lock:
            for key, row in zip(keys, rows):
                slot = self._slots.get(key)
                if slot is not None:
                    self._stats["updates"] += 1
                elif self._free:
                    slot = self._free.pop()
                    self._stats["inserts"] += 1
                else:
                    # full: reuse the least-recently-inserted key's slot
                    _, slot = self._slots.popitem(last=False)
                    self._slot_key[slot] = None
                    self._stats["evictions"] += 1
                    self._stats["inserts"] += 1
                self._buf[slot] = row
                self._valid[slot] = True
                self._slot_key[slot] = key
                self._slots[key] = slot
                self._slots.move_to_end(key)
            self._dirty = True

    def clear(self) -> None:
        with self._lock:
            self._buf[:] = 0.0
            self._valid[:] = False
            self._slot_key = [None] * self.capacity
            self._slots.clear()
            self._free = list(range(self.capacity - 1, -1, -1))
            self._dirty = True
            self._dev = None

    def _device_snapshot(self):
        """Upload the buffer once per mutation burst (under the lock: the
        first query after a write pays the H2D, its peers reuse it)."""
        if self._dirty or self._dev is None:
            self._dev = (jnp.asarray(self._buf), jnp.asarray(self._valid))
            self._dirty = False
        return self._dev

    @staticmethod
    def _bucket(n: int) -> int:
        for b in QUERY_BUCKETS:
            if n <= b:
                return b
        return QUERY_BUCKETS[-1]

    def query(
        self, rows: np.ndarray, k: int
    ) -> List[List[Tuple[str, float]]]:
        """Top-``k`` ``(key, cosine)`` per query row, best first.

        The O(capacity * dim) scoring runs on device against the resident
        snapshot; top-k selection over ``capacity`` scalars runs on host
        (argpartition beats shipping a static-k program per distinct k)."""
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        rows = _normalize(np.atleast_2d(rows))
        n = rows.shape[0]
        with self._lock:
            self._stats["queries"] += n
            entries = len(self._slots)
            if entries == 0:
                return [[] for _ in range(n)]
            db, valid = self._device_snapshot()
            slot_key = list(self._slot_key)
        k_eff = min(int(k), entries)
        out: List[List[Tuple[str, float]]] = []
        step = QUERY_BUCKETS[-1]
        for lo in range(0, n, step):
            chunk = rows[lo:lo + step]
            bucket = self._bucket(chunk.shape[0])
            padded = chunk
            if chunk.shape[0] < bucket:
                padded = np.zeros((bucket, self.dim), np.float32)
                padded[: chunk.shape[0]] = chunk
            scores = np.asarray(self._jit(db, valid, jnp.asarray(padded)))
            for row_scores in scores[: chunk.shape[0]]:
                top = np.argpartition(-row_scores, k_eff - 1)[:k_eff]
                top = top[np.argsort(-row_scores[top], kind="stable")]
                out.append([
                    (slot_key[slot], float(row_scores[slot])) for slot in top
                ])
        return out

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "entries": len(self._slots),
                "capacity": self.capacity,
                "dim": self.dim,
                **self._stats,
            }
