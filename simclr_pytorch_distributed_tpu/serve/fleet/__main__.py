"""CLI: ``python -m simclr_pytorch_distributed_tpu.serve.fleet [flags]`` —
the multi-model frontend (serve/fleet/frontend.py). The replica-fleet
supervisor spawns exactly this as its replica process."""

from simclr_pytorch_distributed_tpu.serve.fleet.frontend import main

if __name__ == "__main__":
    main()
