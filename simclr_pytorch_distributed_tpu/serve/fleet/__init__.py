"""serve/fleet/ — multi-model hosting, hot-swap promotion, retrieval.

The single-model stack (engine -> batcher -> server) scales one checkpoint;
a production fleet serves MANY — several model versions live at once, new
checkpoints promoted under load, and the service answers similarity
queries, not just embeddings. This package composes the existing pieces
into that:

- :mod:`registry` — :class:`ModelRegistry`: N named checkpoint versions
  behind one server; per-model batchers whose queues survive promotes;
  hot-swap ``promote()`` draining in-flight work on the old engine through
  the dispatch/completion split (zero failed requests across a swap);
  per-(model, tenant) :class:`AdmissionController` quotas over the
  batcher's QueueFull backpressure; per-version cache identity
  (``EmbeddingEngine.set_identity``) so a shared cache never serves a
  retired version's rows;
- :mod:`retrieval` — :class:`NeighborIndex`: bounded, content-keyed,
  LRU-evicted store of served embeddings with an on-device brute-force
  cosine scorer — the ``/neighbors`` endpoint's exact small-corpus rung
  and the recall oracle for everything above it;
- :mod:`ivf` — :class:`IVFIndex`: the sublinear rung — self-trained
  k-means coarse quantizer over the stored rows, per-centroid inverted
  lists (per-list LRU under the global budget), exact cosine over only
  the ``nprobe`` nearest lists. ``resolve_retrieval_impl`` is the
  ``--retrieval_impl {brute,ivf,auto}`` ladder both the frontend CLI and
  the bench resolve through;
- :mod:`frontend` — the HTTP surface: ``/embed`` with model routing,
  ``/models/promote``, ``/neighbors`` (``k`` bounded by
  ``--neighbors_max_k``), ``/models``, and a ``/metrics`` exposition
  whose unlabeled gauges the replica-fleet supervisor
  (supervise/replica_fleet.py) scrapes, plus per-model labeled retrieval
  gauges (entries/inserts/evictions/queries/probes/retrains). ``python -m
  simclr_pytorch_distributed_tpu.serve.fleet`` serves it.

Evidence: the end-to-end multi-process scenario (spawn -> saturate ->
restart a killed replica -> promote under load -> drain) is
``scripts/serve_fleet_scenario.py``, committed as
``docs/evidence/serve_fleet_r17.json``; the brute-vs-IVF latency/recall
A/B is ``scripts/retrieval_ab.py``, committed as
``docs/evidence/retrieval_ab_r18.json``. Both gate in ``scripts/ratchet.py``.
"""

from simclr_pytorch_distributed_tpu.serve.fleet.ivf import (  # noqa: F401
    IVFIndex,
    resolve_retrieval_impl,
)
from simclr_pytorch_distributed_tpu.serve.fleet.registry import (  # noqa: F401
    AdmissionController,
    ModelRegistry,
    ModelVersion,
)
from simclr_pytorch_distributed_tpu.serve.fleet.retrieval import (  # noqa: F401
    NeighborIndex,
)
