"""Content-keyed LRU result cache for computed embedding rows.

Serving traffic is heavy-tailed: the same images (popular items, retry
storms, deduplicated uploads) recur far more often than a uniform draw would
suggest, and a ResNet forward is ~10^8 FLOPs per image while a hash of the
raw uint8 bytes is ~10^3 — so a small LRU in front of the engine converts
repeat traffic into O(hash) lookups. Keys are content hashes of the raw
image bytes (plus the engine's preprocessing fingerprint, see
``EmbeddingEngine._cache_key``), so two byte-identical images always share an
entry regardless of which request they arrived in.

The key must carry MODEL IDENTITY, not just content: the engine's
fingerprint prefix includes the served ``identity`` (``"<name>@v<version>"``,
stamped by the fleet registry at promote time) on top of the weights probe,
so a hot-swap promotion can never serve a stale hit computed by the retired
version — even when the new checkpoint's weights are byte-identical
(``EmbeddingEngine.set_identity``; pinned by tests/test_serve_fleet.py).

Thread-safe: the batcher worker writes while HTTP stats readers poll
counters. Stored rows are frozen (``writeable=False``) so a caller mutating a
returned row cannot poison later hits.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

import numpy as np


class EmbeddingCache:
    """Bounded LRU of ``key -> embedding row`` with hit/miss/eviction counters."""

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._data: "OrderedDict[bytes, np.ndarray]" = OrderedDict()
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def get(self, key: bytes) -> Optional[np.ndarray]:
        with self._lock:
            row = self._data.get(key)
            if row is None:
                self._misses += 1
                return None
            self._data.move_to_end(key)
            self._hits += 1
            return row

    def put(self, key: bytes, row: np.ndarray) -> None:
        self.put_many([(key, row)])

    def put_many(self, items) -> None:
        """Insert ``[(key, row), ...]`` under ONE lock acquisition.

        The pipelined completion stage lands a whole engine batch at once
        (serve/engine.py ``InflightBatch.result``); taking the lock per row
        would interleave lock traffic with the HTTP stats readers for every
        row of every batch. :meth:`put` is the single-row spelling.
        """
        frozen_items = []
        for key, row in items:
            frozen = np.array(row, copy=True)
            frozen.setflags(write=False)
            frozen_items.append((key, frozen))
        if not frozen_items:
            return
        with self._lock:
            for key, frozen in frozen_items:
                self._data[key] = frozen
                self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                self._evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def stats(self) -> dict:
        with self._lock:
            total = self._hits + self._misses
            return {
                "entries": len(self._data),
                "capacity": self.capacity,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "hit_rate": (self._hits / total) if total else 0.0,
            }
