"""Stdlib HTTP JSON endpoint over the engine + batcher — no new runtime deps.

Endpoints:

- ``POST /embed`` — body ``{"images": [[...]]}`` (nested uint8 lists) or
  ``{"images_b64": "<base64 raw bytes>", "shape": [n, h, w, 3]}``; optional
  ``"timeout_ms"``. Replies ``{"embeddings": [[...]], "dim": D, "n": N}``.
- ``GET /healthz`` — liveness: ``{"status": "ok"}``.
- ``GET /stats``  — engine/batcher/cache counters plus per-bucket request
  latency quantiles (p50/p95/p99 — the observability the bench and
  operators read).
- ``GET /metrics`` — Prometheus text exposition of the same counters and
  latency histograms (utils/prom.py), so external scrapers see liveness
  and saturation without parsing ``/stats`` JSON. The quantiles and the
  histogram series are computed from the SAME clock-injectable
  ``LatencyHistogram`` — the two views cannot drift.

Status mapping makes the backpressure contract visible on the wire:
``QueueFull`` -> **503** (+ ``Retry-After``), a request/future timeout ->
**504**, malformed input -> **400**. ``ThreadingHTTPServer`` gives one
thread per connection, which is exactly what the DynamicBatcher wants:
concurrent handlers all block on their own futures while the worker thread
coalesces their requests into shared engine batches.
"""

from __future__ import annotations

import base64
import binascii
import json
import logging
import os
import threading
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from simclr_pytorch_distributed_tpu.serve.batcher import (
    DynamicBatcher,
    QueueFull,
    RequestTimeout,
)

logger = logging.getLogger(__name__)

MAX_BODY_BYTES = 64 * 1024 * 1024  # one request can't OOM the server


def decode_images(payload: dict) -> np.ndarray:
    """Images from a request body: ``"images"`` (nested uint8 lists) or
    ``"images_b64"`` + ``"shape"`` (base64 raw bytes). Shared with the
    multi-model frontend (serve/fleet/frontend.py) so both servers accept
    byte-identical payloads."""
    if "images_b64" in payload:
        shape = payload.get("shape")
        if not isinstance(shape, (list, tuple)) or len(shape) != 4:
            raise ValueError("images_b64 requires 'shape': [n, h, w, c]")
        try:
            raw = base64.b64decode(payload["images_b64"], validate=True)
        except (binascii.Error, TypeError) as e:
            raise ValueError(f"invalid base64 image payload: {e}")
        shape = tuple(int(s) for s in shape)
        expect = int(np.prod(shape))
        if len(raw) != expect:
            raise ValueError(
                f"payload is {len(raw)} bytes but shape {shape} needs {expect}"
            )
        return np.frombuffer(raw, np.uint8).reshape(shape)
    if "images" in payload:
        arr = np.asarray(payload["images"])
        if arr.dtype.kind not in "iuf":
            raise ValueError(f"non-numeric image payload ({arr.dtype})")
        if arr.ndim != 4:
            raise ValueError(f"expected [n, h, w, c] images, got shape {arr.shape}")
        if arr.min() < 0 or arr.max() > 255:
            raise ValueError("pixel values must be uint8 (0..255)")
        return arr.astype(np.uint8)
    raise ValueError("body must carry 'images' or 'images_b64'+'shape'")


def make_handler(
    batcher: DynamicBatcher, stats_fn, *, result_timeout_s: float = 30.0,
    metrics_fn=None,
):
    """Build the request-handler class bound to one batcher.

    ``stats_fn`` is any ``() -> dict`` (the engine's ``stats``, wrapped to
    merge batcher/cache views); keeping it a callable means the handler —
    and its tests — need no engine at all. ``metrics_fn`` is an optional
    ``() -> str`` Prometheus text renderer behind ``GET /metrics`` (absent
    = 404, the pre-observability surface).
    """

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _reply(self, code: int, obj: dict, extra_headers=()) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in extra_headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/healthz":
                self._reply(200, {"status": "ok"})
            elif self.path == "/stats":
                self._reply(200, stats_fn())
            elif self.path == "/metrics" and metrics_fn is not None:
                body = metrics_fn().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path != "/embed":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            length = int(self.headers.get("Content-Length", 0) or 0)
            if length <= 0 or length > MAX_BODY_BYTES:
                # replying WITHOUT reading the body would leave its bytes in
                # the keep-alive stream to be parsed as the next request —
                # advertise and perform a connection close so the protocol
                # can't desync (send_header('Connection','close') also sets
                # self.close_connection)
                self._reply(400, {"error": f"bad Content-Length {length}"},
                            [("Connection", "close")])
                return
            try:
                payload = json.loads(self.rfile.read(length))
                images = decode_images(payload)
                timeout_ms = payload.get("timeout_ms")
                if timeout_ms is not None and (
                    not isinstance(timeout_ms, (int, float))
                    or isinstance(timeout_ms, bool) or timeout_ms <= 0
                ):
                    raise ValueError(
                        f"timeout_ms must be a positive number, "
                        f"got {timeout_ms!r}"
                    )
            except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e)})
                return
            try:
                future = batcher.submit(images, timeout_ms=timeout_ms)
            except QueueFull as e:
                # the explicit backpressure signal: better a retryable 503
                # now than an unbounded queue later
                self._reply(503, {"error": str(e)}, [("Retry-After", "1")])
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)})
                return
            except RuntimeError as e:
                # batcher closed (shutdown race): the request was VALID —
                # tell the client to retry elsewhere, not that it's malformed
                self._reply(503, {"error": str(e)})
                return
            try:
                emb = future.result(
                    timeout=(timeout_ms / 1e3) if timeout_ms is not None
                    else result_timeout_s
                )
            except (RequestTimeout, FutureTimeout) as e:
                future.cancel()
                self._reply(504, {"error": f"embedding timed out: {e}"})
                return
            except Exception as e:  # noqa: BLE001 — engine failure -> 500
                self._reply(500, {"error": str(e)})
                return
            self._reply(
                200,
                {
                    "embeddings": [row.tolist() for row in emb],
                    "dim": int(emb.shape[1]),
                    "n": int(emb.shape[0]),
                },
            )

        def log_message(self, fmt, *args):  # quiet: route through logging
            logger.debug("%s - %s", self.address_string(), fmt % args)

    return Handler


def create_server(
    batcher: DynamicBatcher, stats_fn, host: str = "127.0.0.1", port: int = 8000,
    result_timeout_s: float = 30.0, metrics_fn=None,
) -> ThreadingHTTPServer:
    handler = make_handler(
        batcher, stats_fn, result_timeout_s=result_timeout_s,
        metrics_fn=metrics_fn,
    )
    server = ThreadingHTTPServer((host, port), handler)
    server.daemon_threads = True
    return server


def start_in_thread(server: ThreadingHTTPServer) -> threading.Thread:
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


def combined_stats_fn(engine, batcher: DynamicBatcher, latency=None):
    """The ``/stats`` payload: engine + batcher counters, and — when the
    stack carries a ``LatencyHistogram`` — per-bucket p50/p95/p99 request
    latency (the same histogram ``/metrics`` exposes, so the JSON and
    Prometheus views agree by construction). The batcher section already
    carries the time-weighted ``pipeline_occupancy``/``avg_inflight_depth``
    gauges."""

    def stats():
        out = {"engine": engine.stats(), "batcher": batcher.stats()}
        if latency is not None:
            out["latency"] = latency.summary()
        return out

    return stats


def serve_metrics_fn(engine, batcher: DynamicBatcher, latency=None):
    """Prometheus exposition for ``GET /metrics``: flat counters/gauges
    from the engine and batcher stats (numeric leaves only — the nested
    trace/bucket dicts become labeled series) plus the native cumulative
    latency histograms."""
    from simclr_pytorch_distributed_tpu.utils import prom

    def metrics() -> str:
        samples = []
        es = engine.stats()
        for key in ("requests", "images", "padded_rows", "cache_hit_rows"):
            if key in es:
                samples.append((f"serve_engine_{key}_total", None, es[key]))
        for bucket, count in sorted(es.get("bucket_dispatches", {}).items()):
            samples.append((
                "serve_engine_bucket_dispatches_total",
                {"bucket": bucket}, count,
            ))
        cache = es.get("cache") or {}
        for key, value in sorted(cache.items()):
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                samples.append((f"serve_cache_{key}", None, value))
        bs = batcher.stats()
        for key, value in sorted(bs.items()):
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                samples.append((f"serve_batcher_{key}", None, value))
        if latency is not None:
            samples.extend(latency.samples("serve_request_latency_ms"))
        return prom.render_prometheus(samples)

    return metrics


def build_parser():
    import argparse

    from simclr_pytorch_distributed_tpu.serve.engine import (
        DEFAULT_BUCKETS,
        SERVE_DTYPES,
    )

    p = argparse.ArgumentParser(
        description="batched embedding-inference HTTP server "
                    "(POST /embed, GET /healthz, GET /stats)"
    )
    p.add_argument("--ckpt", default="",
                   help="checkpoint/run dir or reference .pth; empty = "
                        "random-init --model (smoke/bench)")
    p.add_argument("--model", default="resnet10",
                   help="architecture for random init when --ckpt is empty")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--buckets", default=",".join(map(str, DEFAULT_BUCKETS)),
                   help="comma-separated jit batch buckets")
    p.add_argument("--max_batch", type=int, default=128)
    p.add_argument("--max_wait_ms", type=float, default=5.0)
    p.add_argument("--max_queue", type=int, default=256)
    p.add_argument("--max_inflight", type=int, default=2,
                   help="pipeline window: batches dispatched to the device "
                        "but not yet materialized (1 = the unpipelined "
                        "serial path)")
    p.add_argument("--max_inflight_images", type=int, default=4096,
                   help="row bound on the pipeline window (caps in-flight "
                        "HBM; batch count alone would not)")
    p.add_argument("--dtype", default="fp32", choices=list(SERVE_DTYPES),
                   help="serving compute dtype: bf16 casts params + "
                        "activations at load (BN stats stay fp32, head "
                        "output is returned fp32)")
    p.add_argument("--img_size", type=int, default=None,
                   help="pinned request H=W (default: the checkpoint "
                        "config's --size, else 32); mismatched requests "
                        "get 400 instead of a fresh compile")
    p.add_argument("--normalize", action="store_true",
                   help="L2-normalize embeddings (ops/losses.py contract)")
    p.add_argument("--output", default="features",
                   choices=["features", "projection"])
    p.add_argument("--cache_capacity", type=int, default=4096,
                   help="content-keyed LRU rows; 0 disables the cache")
    p.add_argument("--watchdog_secs", type=float, default=0.0,
                   help="stall watchdog: dump all thread stacks when a "
                        "dispatched batch goes this long without a "
                        "completion (armed only while batches are in "
                        "flight); 0 = off")
    p.add_argument("--events_jsonl", default="",
                   help="flight-recorder output path: per-request spans "
                        "(queue->dispatch->completion), cache events, and "
                        "a Chrome-trace export beside it on shutdown "
                        "(utils/tracing.py); empty = off")
    return p


def build_stack(args):
    """Engine + pipelined batcher + HTTP server from parsed args.

    Split from :func:`main` so tests (and embedders) can build the exact
    stack the CLI serves — including ``--dtype bf16`` and the pipeline
    knobs — without entering ``serve_forever``.
    """
    from simclr_pytorch_distributed_tpu.serve.cache import EmbeddingCache
    from simclr_pytorch_distributed_tpu.serve.engine import EmbeddingEngine
    from simclr_pytorch_distributed_tpu.utils import prom, tracing

    buckets = tuple(int(b) for b in args.buckets.split(","))
    cache = EmbeddingCache(args.cache_capacity) if args.cache_capacity else None
    kwargs = dict(buckets=buckets, normalize=args.normalize,
                  output=args.output, cache=cache, dtype=args.dtype)
    if args.img_size is not None:
        kwargs["img_size"] = args.img_size
    if args.ckpt:
        engine = EmbeddingEngine.from_checkpoint(args.ckpt, **kwargs)
    else:
        logging.warning("--ckpt not given: serving a RANDOM %s", args.model)
        engine = EmbeddingEngine.random_init(
            model_name=args.model, size=kwargs.get("img_size", 32), **kwargs
        )
    watchdog = None
    if getattr(args, "watchdog_secs", 0) and args.watchdog_secs > 0:
        dump_dir = (
            os.path.dirname(os.path.abspath(args.events_jsonl))
            if getattr(args, "events_jsonl", "") else os.getcwd()
        )
        logging.info("serve stall watchdog: %.0fs deadline, dumps to %s",
                     args.watchdog_secs, dump_dir)
        watchdog = tracing.StallWatchdog(
            args.watchdog_secs, dump_dir,
            recorder=tracing.current(), name="serve",
        )
    latency = prom.LatencyHistogram()
    batcher = DynamicBatcher(
        # async dispatch: the assembler pipelines batches onto the device
        # while the completer materializes earlier ones
        dispatch_fn=engine.dispatch,
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        max_inflight=args.max_inflight,
        max_inflight_images=args.max_inflight_images,
        # geometry mismatches fail the submit (-> 400), never a worker batch
        validate=engine.validate_images,
        # per-bucket request latency, keyed by the engine's jit bucket —
        # feeds BOTH the /stats quantiles and the /metrics histograms
        latency=latency, bucket_fn=engine.bucket_for, watchdog=watchdog,
    )
    server = create_server(
        batcher, combined_stats_fn(engine, batcher, latency),
        host=args.host, port=args.port,
        metrics_fn=serve_metrics_fn(engine, batcher, latency),
    )
    # the watchdog thread outlives build_stack: hang it on the server so
    # main()'s finally (and embedders reusing build_stack) can close it
    server.stall_watchdog = watchdog
    return engine, batcher, server


def main(argv=None):
    from simclr_pytorch_distributed_tpu.utils import tracing

    args = build_parser().parse_args(argv)
    recorder = None
    if args.events_jsonl:
        trace_path = os.path.splitext(args.events_jsonl)[0] + ".trace.json"
        recorder = tracing.FlightRecorder(
            args.events_jsonl, trace_path=trace_path
        )
        tracing.install(recorder)
    engine, batcher, server = build_stack(args)
    logging.info("serving %s embeddings (%s) on http://%s:%d",
                 engine.model.model_name, engine.dtype, args.host, args.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        batcher.close()
        if server.stall_watchdog is not None:
            server.stall_watchdog.close()
        tracing.uninstall()
        if recorder is not None:
            recorder.close()


if __name__ == "__main__":
    main()
