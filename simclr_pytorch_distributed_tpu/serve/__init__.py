"""serve/ — batched embedding-inference subsystem.

Turns any checkpoint this repo produces (or imports from the reference
``.pth`` format) into a high-throughput embedding service:

- :mod:`engine` — ``EmbeddingEngine``: checkpoint -> eval-mode encoder behind
  a shape-bucketed jit cache (arbitrary request sizes never recompile), with
  a split async API (``dispatch() -> InflightBatch``, ``result()``) and an
  optional bf16 serving dtype;
- :mod:`batcher` — ``DynamicBatcher``: async request queue coalescing
  concurrent submits into micro-batches under ``max_batch``/``max_wait_ms``,
  pipelined (up to ``max_inflight`` batches on device while the assembler
  keeps dispatching), with bounded-queue backpressure (``QueueFull``) and
  per-request timeouts;
- :mod:`cache` — ``EmbeddingCache``: content-keyed LRU over computed rows
  (keys carry model identity + weights, so shared caches survive hot-swaps);
- :mod:`server` — stdlib ``http.server`` JSON endpoint
  (``/embed``, ``/healthz``, ``/stats``) — no new runtime dependency;
- :mod:`fleet` — the multi-model layer: ``ModelRegistry`` hosting N named
  checkpoint versions with hot-swap promotion (in-flight work drains on the
  old engine), per-tenant admission control, a ``/neighbors`` retrieval
  index over served embeddings, and the fleet HTTP frontend the
  replica-fleet supervisor (supervise/) manages.

See ``docs/SERVING.md`` for the API contract and bench methodology
(``scripts/serve_bench.py``).
"""

from simclr_pytorch_distributed_tpu.serve.batcher import (  # noqa: F401
    DynamicBatcher,
    QueueFull,
    RequestTimeout,
)
from simclr_pytorch_distributed_tpu.serve.cache import EmbeddingCache  # noqa: F401
from simclr_pytorch_distributed_tpu.serve.engine import (  # noqa: F401
    EmbeddingEngine,
    InflightBatch,
)
