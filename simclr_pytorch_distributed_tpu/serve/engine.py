"""EmbeddingEngine — checkpoint -> eval-mode encoder behind a shape-bucketed
jit cache.

The serving problem with jit is shape polymorphism: every distinct batch
shape is a fresh trace + XLA compile (seconds on CPU, tens of seconds for
big models on TPU), so letting arbitrary request sizes reach the compiled
function turns the first request of every new size into a multi-second
outlier. The fix is a small set of power-of-two batch **buckets**
(default 1/8/32/128): a request of n images is padded up to the smallest
bucket >= n, the compiled program for that bucket runs, and the pad rows are
sliced off the result. Requests larger than the top bucket are chunked
through it.

Why padding is sound: in eval mode (``train=False``) every per-example path
is batch-independent — BN reads running statistics, convs/pools/matmuls are
per-row — so row i's embedding does not depend on rows != i. Within one
compiled program this holds **bitwise** (pad rows, real rows, their count:
irrelevant); across different bucket programs XLA may schedule reductions
differently, so two buckets agree only to float tolerance (~1 ulp observed
on CPU). Both halves of that contract are pinned by
``tests/test_eval_determinism.py`` / ``tests/test_serve_engine.py``.

Device placement goes through ``parallel/mesh.py``: params are replicated,
and a bucket whose size divides the mesh's data axis is sharded across it
(the same data-parallel layout the trainers use — more chips means bigger
buckets at the same latency); smaller buckets run replicated.

The optional ``cache`` (serve/cache.py) sits in FRONT of the compiled call:
rows whose content hash hits skip engine execution entirely, and a request
made entirely of hits never touches the device.

**Dispatch/completion split.** jax dispatches jitted calls asynchronously:
the call returns a device array the moment the work is ENQUEUED, and only
``np.asarray`` (D2H) blocks on it. The training loop already exploits this
(docs/PERF.md: a per-step sync cost 2.4x wall clock); serving gets the same
split here. ``dispatch(images) -> InflightBatch`` runs the host stages —
validation, cache probe, bucket padding, H2D via
``parallel.mesh.put_batch_if_divisible`` — and enqueues the compiled call
for EVERY bucket chunk without materializing anything;
``InflightBatch.result()`` is the completion stage: it blocks on D2H,
slices pad rows, and populates the cache. ``embed`` is now literally
``dispatch(...).result()``, so a miss set spanning several bucket chunks
overlaps chunk k+1's dispatch with chunk k's compute instead of
round-tripping each chunk, and the DynamicBatcher keeps several whole
batches in flight by holding their ``InflightBatch`` handles
(serve/batcher.py).

**bf16 serving** (``dtype="bf16"``): params and activations are cast to
bfloat16 at load — the same bf16-on-MXU win the trainers take with
``--bf16`` — while BN statistics stay fp32 (models/norm.py normalizes in
fp32 regardless of compute dtype) and the head output is cast back to fp32,
so the wire contract is unchanged. Parity with fp32 serving is pinned by
``tests/test_serve_engine.py`` the same way ``tests/test_eval_determinism.py``
pins the fp32 contract.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from simclr_pytorch_distributed_tpu.models import (
    MODEL_DICT,
    SupConResNet,
    infer_architecture_from_variables,
)
from simclr_pytorch_distributed_tpu.ops.augment import (
    DATASET_STATS,
    AugmentConfig,
    eval_batch,
)
from simclr_pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding_if_divisible,
    create_mesh,
    put_batch_if_divisible,
    replicated_sharding,
)
from simclr_pytorch_distributed_tpu.utils import tracing

DEFAULT_BUCKETS = (1, 8, 32, 128)
SERVE_DTYPES = ("fp32", "bf16")


class InflightBatch:
    """Handle to dispatched-but-unmaterialized engine work.

    Created by :meth:`EmbeddingEngine.dispatch` after every bucket chunk's
    compiled call has been ENQUEUED on the device; ``result()`` is the
    completion stage — it blocks on the D2H transfers, slices the pad rows
    off, writes computed rows into the content cache, and returns the
    float32 ``[n, dim]`` array. Idempotent: repeat calls return the same
    array without touching the device again. The handle owns device buffers
    until completed, which is exactly what the batcher's in-flight row
    bound counts (serve/batcher.py ``max_inflight_images``).
    """

    def __init__(self, engine, out, n, chunks, keys):
        self._engine = engine
        self._out = out
        self._n = n
        self._chunks = chunks  # [(miss row indices, device array)]
        self._keys = keys
        self._done = False
        self._lock = threading.Lock()

    @property
    def n_rows(self) -> int:
        """Total request rows (the batcher's HBM-bound accounting unit)."""
        return self._n

    def done(self) -> bool:
        with self._lock:
            return self._done

    def result(self) -> np.ndarray:
        with self._lock:
            if not self._done:
                cache = self._engine.cache
                for rows, dev in self._chunks:
                    emb = np.asarray(dev)[: len(rows)]  # blocks on D2H
                    self._out[rows] = emb
                    if self._keys is not None:
                        cache.put_many(
                            [(self._keys[i], emb[j]) for j, i in enumerate(rows)]
                        )
                self._chunks = ()  # release device buffers
                self._done = True
            return self._out


class EmbeddingEngine:
    """Batched eval-mode embedding inference over a frozen encoder.

    ``embed(images) -> np.ndarray``: uint8 NHWC images in, float32
    ``[n, dim]`` embeddings out. ``output='features'`` serves the encoder's
    pooled features (the probe/kNN/retrieval representation,
    ``SupConResNet.encode``); ``output='projection'`` serves the projection
    head's output. ``normalize=True`` L2-normalizes rows to match the
    post-gather contract the contrastive loss consumes (``ops/losses.py``
    expects unit rows; the reference normalizes at ``main_supcon.py:283``).
    """

    def __init__(
        self,
        model: SupConResNet,
        variables: dict,
        *,
        mesh=None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        normalize: bool = False,
        output: str = "features",
        mean: Optional[Tuple[float, ...]] = None,
        std: Optional[Tuple[float, ...]] = None,
        img_size: int = 32,
        cache=None,
        dtype: str = "fp32",
        identity: str = "",
    ):
        if output not in ("features", "projection"):
            raise ValueError(f"output must be features|projection, got {output!r}")
        if dtype not in SERVE_DTYPES:
            raise ValueError(f"dtype must be one of {SERVE_DTYPES}, got {dtype!r}")
        self.dtype = dtype
        if dtype == "bf16":
            # params + activations cast to bf16 at load (halved param HBM,
            # MXU-native compute — the trainers' --bf16 win); BN statistics
            # stay fp32 (models/norm.py normalizes in fp32 regardless of
            # compute dtype) and _apply casts the head output back to fp32
            model = model.clone(dtype=jnp.bfloat16)
            variables = dict(variables)
            variables["params"] = jax.tree.map(
                lambda x: jnp.asarray(x, jnp.bfloat16)
                if jnp.issubdtype(x.dtype, np.floating) else x,
                variables["params"],
            )
        buckets = tuple(sorted(int(b) for b in buckets))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets}")
        if len(set(buckets)) != len(buckets):
            raise ValueError(f"duplicate buckets: {buckets}")
        self.model = model
        self.buckets = buckets
        self.normalize = bool(normalize)
        self.output = output
        # pinned request geometry: the bucket scheme bounds compiles only if
        # the SPATIAL shape is fixed too — an open endpoint accepting
        # arbitrary (H, W) would compile per size (multi-second outliers,
        # unbounded executable cache: a trivial DoS). Mismatches are
        # rejected in validate_images (HTTP 400, never a compile).
        self.img_size = int(img_size)
        self.cache = cache
        stats = DATASET_STATS["cifar10"]
        self._aug_cfg = AugmentConfig(
            mean=tuple(mean) if mean else stats[0],
            std=tuple(std) if std else stats[1],
            color_ops=False,
        )
        self.mesh = mesh if mesh is not None else create_mesh()
        self._repl = replicated_sharding(self.mesh)
        self._variables = jax.device_put(variables, self._repl)
        if output == "features":
            self.feat_dim = MODEL_DICT[model.model_name][1]
        else:
            self.feat_dim = model.feat_dim
        self._jit_fns: dict = {}  # sharded vs replicated jit objects
        self._lock = threading.Lock()
        self._stats = {
            "requests": 0,
            "images": 0,
            "padded_rows": 0,
            "bucket_dispatches": {b: 0 for b in buckets},
            "traces": {},  # bucket -> trace count (compile-count witness)
            "cache_hit_rows": 0,
        }
        # cache-key fingerprint: byte-identical images served under a
        # different contract — another normalization/output, OR another
        # model/checkpoint (EmbeddingCache is injectable, so one cache may
        # back several engines) — must never share a cache row. The weights
        # probe hashes EVERY leaf: a single canonical leaf won't do (tree
        # order puts BN statistics first, which are identical zeros/ones
        # across fresh checkpoints). One-time cost at construction.
        probe = hashlib.sha1()
        for leaf in jax.tree.leaves(variables):
            probe.update(np.asarray(leaf).tobytes())
        self._weights_probe = probe.hexdigest()[:16]
        self.identity = ""
        self.set_identity(identity)

    def set_identity(self, identity: str) -> None:
        """Stamp the engine's served identity (``"<model name>@v<version>"``)
        into its cache-key fingerprint.

        The weights probe already separates engines whose *weights* differ,
        but a hot-swap promotion must invalidate cached rows even when the
        new version's weights happen to be byte-identical (a re-exported or
        rolled-back checkpoint): after ``POST /models/promote`` every hit
        must come from the version that is actually serving. The registry
        (serve/fleet/registry.py) stamps ``name@vN`` BEFORE the version
        becomes visible to traffic — this is not safe to call with requests
        in flight (``_cache_key`` reads the prefix without a lock)."""
        self.identity = str(identity)
        self._key_prefix = (
            f"{self.identity}|{self.model.model_name}|{self._weights_probe}|"
            f"{self.output}|{int(self.normalize)}|{self.dtype}|"
            f"{self._aug_cfg.mean}|{self._aug_cfg.std}|".encode()
        )

    # ------------------------------------------------------------ loading

    @classmethod
    def from_checkpoint(cls, path: str, **kwargs) -> "EmbeddingEngine":
        """Build from any ``--ckpt`` spelling: an orbax checkpoint dir, a run
        dir (latest complete checkpoint wins), or a reference ``.pth``
        (converted in place on first use). The architecture is inferred from
        the restored tree itself — no ``--model`` flag needed."""
        from simclr_pytorch_distributed_tpu.utils.checkpoint import (
            load_model_payload,
        )

        variables, meta = load_model_payload(path)
        name, head, feat_dim = infer_architecture_from_variables(variables)
        model = SupConResNet(model_name=name, head=head, feat_dim=feat_dim)
        config = meta.get("config") or {}
        dataset = config.get("dataset")
        if (dataset in DATASET_STATS and "mean" not in kwargs
                and "std" not in kwargs):
            kwargs["mean"], kwargs["std"] = DATASET_STATS[dataset]
        # pin the geometry the encoder was trained at (checkpoint meta
        # records the training config's --size) unless the caller overrides
        if "img_size" not in kwargs and config.get("size"):
            kwargs["img_size"] = int(config["size"])
        return cls(model, dict(variables), **kwargs)

    @classmethod
    def random_init(
        cls, model_name: str = "resnet10", size: int = 32, seed: int = 0, **kwargs
    ) -> "EmbeddingEngine":
        """Randomly initialized engine — benchmarking and tests (the serving
        stack's behavior is weight-independent)."""
        model = SupConResNet(model_name=model_name)
        variables = model.init(
            jax.random.key(seed), jnp.zeros((2, size, size, 3)), train=False
        )
        kwargs.setdefault("img_size", size)
        return cls(
            model,
            {"params": variables["params"], "batch_stats": variables["batch_stats"]},
            **kwargs,
        )

    # ------------------------------------------------------------ compute

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (requests above the top bucket are chunked
        through it)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _apply(self, variables, images_u8):
        # NOTE: body executes at TRACE time only — the counter bump below is
        # the compile witness the no-recompile tests assert on. It runs in
        # ordinary Python (tracing, not compiled code), so it takes the lock:
        # an unlocked insert racing a /stats dict copy can crash the poll.
        bucket = int(images_u8.shape[0])
        with self._lock:
            self._stats["traces"][bucket] = (
                self._stats["traces"].get(bucket, 0) + 1
            )
        x = eval_batch(images_u8, self._aug_cfg)
        if self.output == "features":
            feats = self.model.apply(
                variables, x, train=False, method=SupConResNet.encode
            )
        else:
            feats = self.model.apply(variables, x, train=False)
        feats = feats.astype(jnp.float32)
        if self.normalize:
            norms = jnp.linalg.norm(feats, axis=-1, keepdims=True)
            feats = feats / jnp.maximum(norms, 1e-12)
        return feats

    def _fn_for(self, bucket: int):
        # Two jit objects, picked by whether the bucket shards evenly over
        # the data axis; each caches one executable per bucket shape.
        sharded = bucket % self.mesh.shape.get(DATA_AXIS, 1) == 0
        with self._lock:
            fn = self._jit_fns.get(sharded)
            if fn is None:
                fn = jax.jit(
                    self._apply,
                    in_shardings=(
                        self._repl,
                        batch_sharding_if_divisible(self.mesh, bucket, 4),
                    ),
                    out_shardings=self._repl,
                )
                self._jit_fns[sharded] = fn
        return fn

    def _dispatch_chunk(self, images_u8: np.ndarray) -> jax.Array:
        """Pad to the bucket, start the H2D transfer, enqueue the compiled
        call — and return the UNmaterialized device array. Everything here
        is the dispatch stage; the only blocking step (D2H) belongs to
        ``InflightBatch.result``."""
        n = images_u8.shape[0]
        bucket = self.bucket_for(n)
        padded = images_u8
        if n < bucket:
            padded = np.zeros((bucket,) + images_u8.shape[1:], np.uint8)
            padded[:n] = images_u8
        with self._lock:
            self._stats["bucket_dispatches"][bucket] += 1
            self._stats["padded_rows"] += bucket - n
        x = put_batch_if_divisible(self.mesh, padded)
        return self._fn_for(bucket)(self._variables, x)

    def _cache_key(self, image_u8: np.ndarray) -> bytes:
        h = hashlib.sha1(self._key_prefix)
        h.update(str(image_u8.shape).encode())
        h.update(image_u8.tobytes())
        return h.digest()

    def validate_images(self, images: np.ndarray) -> np.ndarray:
        """Raise ``ValueError`` unless ``images`` matches the engine's pinned
        request geometry. Exposed separately so ingress layers (the
        batcher's ``validate=``, hence the HTTP 400 path) can reject bad
        requests synchronously instead of poisoning a coalesced batch."""
        images = np.asarray(images)
        if images.ndim != 4 or images.shape[-1] != 3:
            raise ValueError(
                f"expected [n, H, W, 3] images, got shape {images.shape}"
            )
        if images.shape[1:3] != (self.img_size, self.img_size):
            raise ValueError(
                f"this engine serves {self.img_size}x{self.img_size} images "
                f"(pinned at construction; arbitrary sizes would compile per "
                f"shape), got {images.shape[1]}x{images.shape[2]}"
            )
        if images.dtype != np.uint8:
            raise ValueError(
                f"expected uint8 images (raw pixels; the engine normalizes), "
                f"got {images.dtype}"
            )
        return images

    def dispatch(self, images: np.ndarray) -> InflightBatch:
        """Start one request's device work without waiting for it.

        Runs every host-side stage — validation, stats, cache probe, bucket
        padding, H2D — and enqueues the compiled call for ALL bucket chunks
        of the miss set (a multi-bucket request overlaps chunk k+1's
        dispatch with chunk k's compute instead of round-tripping each).
        The returned :class:`InflightBatch` completes with ``result()``;
        until then the device computes while the caller assembles the next
        batch (serve/batcher.py keeps ``max_inflight`` of these on device).
        """
        images = self.validate_images(images)
        n = images.shape[0]
        out = np.empty((n, self.feat_dim), np.float32)
        if n == 0:
            return InflightBatch(self, out, 0, [], None)
        with self._lock:
            self._stats["requests"] += 1
            self._stats["images"] += n

        if self.cache is None:
            miss_rows = list(range(n))
            keys = None
        else:
            keys = [self._cache_key(images[i]) for i in range(n)]
            miss_rows = []
            for i, key in enumerate(keys):
                row = self.cache.get(key)
                if row is None:
                    miss_rows.append(i)
                else:
                    out[i] = row
            hit_rows = n - len(miss_rows)
            if hit_rows:
                with self._lock:
                    self._stats["cache_hit_rows"] += hit_rows
                # the cache leg of the request path: rows that never reach
                # the device (a full-hit request has an empty miss set and
                # dispatches nothing)
                tracing.event(
                    "cache_hits", track="serve:cache", rows=hit_rows, n=n
                )

        chunks = []
        max_bucket = self.buckets[-1]
        for lo in range(0, len(miss_rows), max_bucket):
            rows = miss_rows[lo:lo + max_bucket]
            chunks.append((rows, self._dispatch_chunk(images[rows])))
        return InflightBatch(self, out, n, chunks, keys)

    def embed(self, images: np.ndarray) -> np.ndarray:
        """uint8 ``[n, H, W, 3]`` -> float32 ``[n, feat_dim]``.

        Row i's embedding depends only on image i — never on which request
        peers or pad rows it was batched with — so micro-batching and the
        content cache are transparent to callers. Synchronous spelling of
        ``dispatch(...).result()``.
        """
        return self.dispatch(images).result()

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            s = {
                **{k: v for k, v in self._stats.items()
                   if not isinstance(v, dict)},
                "bucket_dispatches": dict(self._stats["bucket_dispatches"]),
                "traces": dict(self._stats["traces"]),
            }
        s["model"] = self.model.model_name
        s["identity"] = self.identity
        s["output"] = self.output
        s["normalize"] = self.normalize
        s["dtype"] = self.dtype
        s["buckets"] = list(self.buckets)
        s["feat_dim"] = self.feat_dim
        if self.cache is not None:
            s["cache"] = self.cache.stats()
        return s
