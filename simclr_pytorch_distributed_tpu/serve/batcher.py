"""DynamicBatcher — async request queue + micro-batching worker.

Serving traffic arrives as many small concurrent requests, but the engine's
throughput comes from large batches (the per-dispatch overhead and the
padded-bucket waste both amortize with batch size). The batcher bridges the
two: ``submit(images)`` returns a ``concurrent.futures.Future`` immediately,
and a single worker thread coalesces queued requests into one engine call
under two knobs:

- ``max_batch`` — dispatch as soon as the coalesced batch would exceed it;
- ``max_wait_ms`` — never hold the FIRST request of a batch longer than this
  (the latency the batcher is allowed to add hunting for batch-mates).

Backpressure is explicit: the queue is bounded BOTH in requests
(``max_queue``) and in total queued image rows (``max_queue_images`` —
request count alone would let a burst of large batches hold gigabytes of
pixels), and a full queue REJECTS new submits with :class:`QueueFull`
instead of growing without bound — an overloaded server answers 503 now rather than OOMing
later (serve/server.py maps it). Per-request timeouts (``timeout_ms``)
expire stale work at dequeue time with :class:`RequestTimeout` so a deep
queue cannot burn engine cycles on answers nobody is waiting for.

Time is read through an injectable ``clock`` (default ``time.monotonic``);
deadline logic never touches the wall clock directly, so tests drive
``max_wait_ms``/timeout expiry with a fake clock instead of sleeping
(tests/test_serve_batcher.py). ``close()`` drains in-flight work by default.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np


class QueueFull(RuntimeError):
    """Bounded-queue backpressure: the submit was rejected, not queued."""


class RequestTimeout(TimeoutError):
    """The request's ``timeout_ms`` expired before the worker reached it."""


@dataclass
class _Request:
    images: np.ndarray
    n: int
    future: Future = field(default_factory=Future)
    deadline: Optional[float] = None  # clock() value; None = no timeout


class DynamicBatcher:
    def __init__(
        self,
        embed_fn: Callable[[np.ndarray], np.ndarray],
        *,
        max_batch: int = 128,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        max_queue_images: int = 8192,
        default_timeout_ms: Optional[float] = None,
        validate: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        clock: Callable[[], float] = time.monotonic,
        poll_interval: float = 0.002,
        start: bool = True,
    ):
        if max_batch < 1 or max_queue < 1 or max_queue_images < 1:
            raise ValueError(
                "max_batch, max_queue, and max_queue_images must be >= 1"
            )
        self._embed_fn = embed_fn
        self._max_batch = int(max_batch)
        self._max_wait_s = float(max_wait_ms) / 1e3
        self._max_queue = int(max_queue)
        # request COUNT alone doesn't bound memory — 256 pending requests of
        # large batches is gigabytes of pixels; cap total queued rows too
        self._max_queue_images = int(max_queue_images)
        self._pending_images = 0
        # optional synchronous request gate (e.g. the engine's geometry
        # check): bad requests fail at submit() instead of poisoning a
        # coalesced batch in the worker
        self._validate = validate
        self._default_timeout_ms = default_timeout_ms
        self._clock = clock
        # real-time condition-wait granularity inside the coalescing window;
        # deadlines themselves are computed from ``clock`` so a fake clock
        # controls WHEN the window closes, polling only bounds how fast the
        # worker notices
        self._poll = float(poll_interval)
        self._cond = threading.Condition()
        self._pending: "deque[_Request]" = deque()
        self._closed = False
        self._stats = {
            "submitted": 0,
            "rejected": 0,
            "timeouts": 0,
            "batches": 0,
            "batched_images": 0,
            "errors": 0,
            "max_queue_depth": 0,
            "max_batch_observed": 0,
        }
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._worker, name="dynamic-batcher", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------- client

    def submit(
        self, images: np.ndarray, timeout_ms: Optional[float] = None
    ) -> Future:
        """Enqueue one request; resolves to float32 ``[n, dim]`` embeddings.

        Raises :class:`QueueFull` when ``max_queue`` requests are already
        waiting (backpressure — retry later) and ``RuntimeError`` after
        ``close()``. The future fails with :class:`RequestTimeout` if the
        worker cannot reach the request within its timeout.
        """
        images = np.asarray(images)
        if images.ndim != 4:
            raise ValueError(f"expected [n, H, W, C] images, got {images.shape}")
        n = images.shape[0]
        if n < 1:
            raise ValueError("empty request")
        if self._validate is not None:
            images = self._validate(images)
        if timeout_ms is None:
            timeout_ms = self._default_timeout_ms
        req = _Request(
            images=images,
            n=n,
            deadline=(self._clock() + timeout_ms / 1e3)
            if timeout_ms is not None else None,
        )
        with self._cond:
            if self._closed:
                raise RuntimeError("DynamicBatcher is closed")
            if len(self._pending) >= self._max_queue:
                self._stats["rejected"] += 1
                raise QueueFull(
                    f"request queue full ({self._max_queue} pending requests)"
                )
            if self._pending_images + n > self._max_queue_images:
                self._stats["rejected"] += 1
                raise QueueFull(
                    f"request queue full ({self._pending_images} images "
                    f"pending, row cap {self._max_queue_images})"
                )
            self._pending.append(req)
            self._pending_images += n
            self._stats["submitted"] += 1
            self._stats["max_queue_depth"] = max(
                self._stats["max_queue_depth"], len(self._pending)
            )
            self._cond.notify_all()
        return req.future

    def close(self, drain: bool = True) -> None:
        """Stop accepting submits; by default the worker finishes everything
        already queued before exiting (``drain=False`` fails queued requests
        immediately). With no worker thread (``start=False``) there is
        nobody to drain — queued requests are failed either way rather than
        leaving their futures hanging forever."""
        with self._cond:
            self._closed = True
            if not drain or self._thread is None:
                while self._pending:
                    req = self._pending.popleft()
                    self._pending_images -= req.n
                    self._fail(req, RuntimeError("DynamicBatcher closed"))
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def stats(self) -> dict:
        with self._cond:
            s = dict(self._stats)
            s["queue_depth"] = len(self._pending)
            s["queued_images"] = self._pending_images
        s["max_batch"] = self._max_batch
        s["max_wait_ms"] = self._max_wait_s * 1e3
        s["max_queue"] = self._max_queue
        s["max_queue_images"] = self._max_queue_images
        if s["batches"]:
            s["avg_batch_images"] = s["batched_images"] / s["batches"]
        return s

    # ------------------------------------------------------------- worker

    def _fail(self, req: _Request, exc: Exception) -> None:
        try:
            req.future.set_exception(exc)
        except InvalidStateError:
            pass  # cancelled by the caller — nothing to deliver

    def _pop_live_locked(self) -> Optional[_Request]:
        """Next unexpired, uncancelled request; expired ones fail in place."""
        while self._pending:
            req = self._pending.popleft()
            self._pending_images -= req.n
            if req.future.cancelled():
                continue
            if req.deadline is not None and self._clock() > req.deadline:
                self._stats["timeouts"] += 1
                self._fail(req, RequestTimeout(
                    "request expired before the batcher reached it"
                ))
                continue
            return req
        return None

    def _next_batch(self):
        """Block for the next micro-batch; ``None`` means closed-and-drained."""
        with self._cond:
            while True:
                req = self._pop_live_locked()
                if req is not None:
                    break
                if self._closed:
                    return None
                self._cond.wait(0.05)
            batch = [req]
            total = req.n
            window_end = self._clock() + self._max_wait_s
            shape = req.images.shape[1:]
            dtype = req.images.dtype
            while total < self._max_batch:
                if self._pending:
                    nxt = self._pending[0]
                    if nxt.future.cancelled():
                        self._pending.popleft()
                        self._pending_images -= nxt.n
                        continue
                    if nxt.deadline is not None and self._clock() > nxt.deadline:
                        self._pending.popleft()
                        self._pending_images -= nxt.n
                        self._stats["timeouts"] += 1
                        self._fail(nxt, RequestTimeout(
                            "request expired before the batcher reached it"
                        ))
                        continue
                    if nxt.images.shape[1:] != shape or nxt.images.dtype != dtype:
                        # incompatible with this batch's geometry: dispatching
                        # together would fail EVERY waiter on the concatenate;
                        # leave it to lead the next (same-shape) batch
                        break
                    if total + nxt.n > self._max_batch:
                        break  # would overflow; leave it for the next batch
                    self._pending.popleft()
                    self._pending_images -= nxt.n
                    batch.append(nxt)
                    total += nxt.n
                    continue
                if self._closed or self._clock() >= window_end:
                    break
                self._cond.wait(self._poll)
        return batch

    def _dispatch(self, batch) -> None:
        total = sum(r.n for r in batch)
        images = (
            batch[0].images if len(batch) == 1
            else np.concatenate([r.images for r in batch], axis=0)
        )
        try:
            emb = self._embed_fn(images)
        except Exception as exc:  # noqa: BLE001 — delivered to every waiter
            with self._cond:
                self._stats["errors"] += 1
            for req in batch:
                self._fail(req, exc)
            return
        with self._cond:
            self._stats["batches"] += 1
            self._stats["batched_images"] += total
            self._stats["max_batch_observed"] = max(
                self._stats["max_batch_observed"], total
            )
        offset = 0
        for req in batch:
            rows = emb[offset:offset + req.n]
            offset += req.n
            try:
                req.future.set_result(rows)
            except InvalidStateError:
                pass  # cancelled mid-flight

    def _worker(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            self._dispatch(batch)
