"""DynamicBatcher — async request queue + pipelined micro-batching.

Serving traffic arrives as many small concurrent requests, but the engine's
throughput comes from large batches (the per-dispatch overhead and the
padded-bucket waste both amortize with batch size). The batcher bridges the
two: ``submit(images)`` returns a ``concurrent.futures.Future`` immediately,
and an assembler thread coalesces queued requests into one engine call
under two knobs:

- ``max_batch`` — dispatch as soon as the coalesced batch would exceed it;
- ``max_wait_ms`` — never hold the FIRST request of a batch longer than this
  (the latency the batcher is allowed to add hunting for batch-mates).

**Pipelined execution.** With a ``dispatch_fn`` (the engine's async API,
``EmbeddingEngine.dispatch``) the data path splits into two overlapped
stages: the assembler keeps coalescing and DISPATCHING — host padding, H2D,
enqueueing the compiled call — while up to ``max_inflight`` earlier batches
are still computing on device, and a completer thread resolves each batch's
futures as its transfer lands. Without the split, batch k's host phases and
batch k+1's device phases serialize (the device idles during every host
phase and vice versa — the serving analogue of the per-iter sync that cost
the training loop 2.4x wall clock, docs/PERF.md). The window is bounded in
BOTH batches (``max_inflight``) and total in-flight rows
(``max_inflight_images``) so pipelining cannot hold unbounded HBM; a batch
larger than the row bound is still admitted alone (the engine chunks it).
Completion is strictly FIFO in dispatch order, so per-request ordering and
the existing QueueFull/timeout/close-drain semantics are unchanged. With
only a synchronous ``embed_fn`` the same code path runs with the compute
folded into the dispatch stage (the pre-pipeline behavior).

Backpressure is explicit: the queue is bounded BOTH in requests
(``max_queue``) and in total queued image rows (``max_queue_images`` —
request count alone would let a burst of large batches hold gigabytes of
pixels), and a full queue REJECTS new submits with :class:`QueueFull`
instead of growing without bound — an overloaded server answers 503 now rather than OOMing
later (serve/server.py maps it). Per-request timeouts (``timeout_ms``)
expire stale work at dequeue time with :class:`RequestTimeout` so a deep
queue cannot burn engine cycles on answers nobody is waiting for.

Time is read through an injectable ``clock`` (default ``time.monotonic``);
deadline logic never touches the wall clock directly, so tests drive
``max_wait_ms``/timeout expiry with a fake clock instead of sleeping
(tests/test_serve_batcher.py). ``close()`` drains in-flight work by default.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from simclr_pytorch_distributed_tpu.utils import tracing


class QueueFull(RuntimeError):
    """Bounded-queue backpressure: the submit was rejected, not queued."""


class RequestTimeout(TimeoutError):
    """The request's ``timeout_ms`` expired before the worker reached it."""


@dataclass
class _Request:
    images: np.ndarray
    n: int
    future: Future = field(default_factory=Future)
    deadline: Optional[float] = None  # clock() value; None = no timeout
    # lifecycle stamps (batcher ``clock`` domain): submit -> dispatch ->
    # completion; the per-bucket latency histogram and the flight
    # recorder's per-request spans both read them
    t_submit: float = 0.0
    t_dispatch: float = 0.0


class _EagerHandle:
    """Adapter giving a synchronous ``embed_fn`` the handle shape of
    ``EmbeddingEngine.dispatch``: the compute already happened at dispatch,
    ``result()`` just hands it back. Keeps one code path through the
    pipeline for both engine spellings."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value


@dataclass
class _Inflight:
    """One dispatched-but-uncompleted batch in the pipeline window."""

    batch: list  # [_Request]
    total: int  # rows (the max_inflight_images accounting unit)
    handle: object  # .result() -> [total, dim]


class DynamicBatcher:
    def __init__(
        self,
        embed_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        *,
        dispatch_fn: Optional[Callable[[np.ndarray], object]] = None,
        max_batch: int = 128,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        max_queue_images: int = 8192,
        max_inflight: int = 2,
        max_inflight_images: int = 4096,
        default_timeout_ms: Optional[float] = None,
        validate: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        clock: Callable[[], float] = time.monotonic,
        poll_interval: float = 0.002,
        start: bool = True,
        latency=None,
        bucket_fn: Optional[Callable[[int], int]] = None,
        watchdog=None,
    ):
        if max_batch < 1 or max_queue < 1 or max_queue_images < 1:
            raise ValueError(
                "max_batch, max_queue, and max_queue_images must be >= 1"
            )
        if max_inflight < 1 or max_inflight_images < 1:
            raise ValueError(
                "max_inflight and max_inflight_images must be >= 1"
            )
        if embed_fn is None and dispatch_fn is None:
            raise ValueError("need embed_fn or dispatch_fn")
        if embed_fn is not None and dispatch_fn is not None:
            # silently preferring one would serve requests through a
            # different function than the caller supplied
            raise ValueError("pass embed_fn OR dispatch_fn, not both")
        if dispatch_fn is None:
            # synchronous engine: compute runs inside the dispatch stage and
            # completion is a no-op — the pre-pipeline behavior, and what
            # the policy unit tests' fake embed functions exercise
            dispatch_fn = lambda images: _EagerHandle(embed_fn(images))  # noqa: E731
        self._dispatch_fn = dispatch_fn
        self._max_batch = int(max_batch)
        self._max_wait_s = float(max_wait_ms) / 1e3
        self._max_queue = int(max_queue)
        # request COUNT alone doesn't bound memory — 256 pending requests of
        # large batches is gigabytes of pixels; cap total queued rows too
        self._max_queue_images = int(max_queue_images)
        self._pending_images = 0
        # optional synchronous request gate (e.g. the engine's geometry
        # check): bad requests fail at submit() instead of poisoning a
        # coalesced batch in the worker
        self._validate = validate
        self._default_timeout_ms = default_timeout_ms
        self._clock = clock
        # real-time condition-wait granularity inside the coalescing window;
        # deadlines themselves are computed from ``clock`` so a fake clock
        # controls WHEN the window closes, polling only bounds how fast the
        # worker notices
        self._poll = float(poll_interval)
        self._cond = threading.Condition()
        self._pending: "deque[_Request]" = deque()
        self._closed = False
        # pipeline window: batches dispatched to the device but not yet
        # materialized. Only the assembler appends, only the completer pops
        # — so the completer may peek [0] unlocked-result() safely.
        self._max_inflight = int(max_inflight)
        self._max_inflight_images = int(max_inflight_images)
        self._inflight: "deque[_Inflight]" = deque()
        self._inflight_rows = 0
        self._assembler_done = False
        # time-weighted pipeline occupancy (∫depth·dt), read via ``clock`` so
        # the gauges are as fake-clock-testable as the deadlines
        self._occ_start = self._clock()
        self._occ_last = self._occ_start
        self._occ_area = 0.0  # ∫ inflight_depth dt
        self._occ_busy = 0.0  # time with >= 1 batch in flight
        # liveness anchor: when the pipeline last delivered a completion
        # (success OR failure — both are forward progress; a replica that
        # only ever errors is unhealthy on `errors`, not on liveness).
        # Initialized to construction time so `last_completion_age_s` reads
        # "seconds since the batcher last proved it can finish work" from
        # the very first scrape — the replica-fleet supervisor's stall
        # signal (supervise/replica.py), meaningful only alongside
        # queue_depth/inflight_batches > 0 (an idle server ages too).
        self._last_completion = self._occ_start
        self._stats = {
            "submitted": 0,
            "rejected": 0,
            "timeouts": 0,
            "batches": 0,
            "batched_images": 0,
            "dispatched_batches": 0,
            "errors": 0,
            "max_queue_depth": 0,
            "max_batch_observed": 0,
            "max_inflight_observed": 0,
        }
        # observability (utils/prom.py, utils/tracing.py; all optional):
        # ``latency`` is a LatencyHistogram observed per REQUEST at
        # completion, keyed by ``bucket_fn(n)`` (the engine's jit bucket,
        # serve/server.py wires ``engine.bucket_for``) — timed with the same
        # injectable ``clock`` as the deadlines, so /stats quantiles and the
        # /metrics exposition are fake-clock-testable. ``watchdog`` is a
        # tracing.StallWatchdog armed only while batches are in flight: the
        # stall it detects is "the device owes us a completion and isn't
        # delivering", never an idle server.
        self._latency = latency
        self._bucket_fn = bucket_fn
        self._watchdog = watchdog
        if watchdog is not None:
            watchdog.disarm()  # idle until the first dispatch
        self._thread: Optional[threading.Thread] = None
        self._completer: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._worker, name="batcher-assembler", daemon=True
            )
            self._completer = threading.Thread(
                target=self._completer_loop, name="batcher-completer",
                daemon=True,
            )
            self._thread.start()
            self._completer.start()

    # ------------------------------------------------------------- client

    def submit(
        self, images: np.ndarray, timeout_ms: Optional[float] = None
    ) -> Future:
        """Enqueue one request; resolves to float32 ``[n, dim]`` embeddings.

        Raises :class:`QueueFull` when ``max_queue`` requests are already
        waiting (backpressure — retry later) and ``RuntimeError`` after
        ``close()``. The future fails with :class:`RequestTimeout` if the
        worker cannot reach the request within its timeout.
        """
        images = np.asarray(images)
        if images.ndim != 4:
            raise ValueError(f"expected [n, H, W, C] images, got {images.shape}")
        n = images.shape[0]
        if n < 1:
            raise ValueError("empty request")
        if self._validate is not None:
            images = self._validate(images)
        if timeout_ms is None:
            timeout_ms = self._default_timeout_ms
        now = self._clock()
        req = _Request(
            images=images,
            n=n,
            deadline=(now + timeout_ms / 1e3)
            if timeout_ms is not None else None,
            t_submit=now,
        )
        with self._cond:
            if self._closed:
                raise RuntimeError("DynamicBatcher is closed")
            if len(self._pending) >= self._max_queue:
                self._stats["rejected"] += 1
                raise QueueFull(
                    f"request queue full ({self._max_queue} pending requests)"
                )
            if self._pending_images + n > self._max_queue_images:
                self._stats["rejected"] += 1
                raise QueueFull(
                    f"request queue full ({self._pending_images} images "
                    f"pending, row cap {self._max_queue_images})"
                )
            self._pending.append(req)
            self._pending_images += n
            self._stats["submitted"] += 1
            self._stats["max_queue_depth"] = max(
                self._stats["max_queue_depth"], len(self._pending)
            )
            self._cond.notify_all()
        return req.future

    def close(self, drain: bool = True) -> None:
        """Stop accepting submits; by default the pipeline finishes
        everything already queued before exiting (``drain=False`` fails
        QUEUED requests immediately — batches already dispatched to the
        device are completed either way: their compute is spent and their
        waiters are blocked on real futures). With no worker thread
        (``start=False``) there is nobody to drain — queued requests are
        failed either way rather than leaving their futures hanging
        forever."""
        if self._watchdog is not None:
            # closing is expected silence: whatever is left in flight is
            # about to be drained or failed, not stalled
            self._watchdog.disarm()
        with self._cond:
            self._closed = True
            if not drain or self._thread is None:
                while self._pending:
                    req = self._pending.popleft()
                    self._pending_images -= req.n
                    self._fail(req, RuntimeError("DynamicBatcher closed"))
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._completer is not None:
            self._completer.join()
            self._completer = None

    def stats(self) -> dict:
        with self._cond:
            s = dict(self._stats)
            s["queue_depth"] = len(self._pending)
            s["queued_images"] = self._pending_images
            s["inflight_batches"] = len(self._inflight)
            s["inflight_rows"] = self._inflight_rows
            self._occ_tick_locked()  # bring the integrals up to now
            elapsed = self._occ_last - self._occ_start
            s["pipeline_occupancy"] = (
                self._occ_busy / elapsed if elapsed > 0 else 0.0
            )
            s["avg_inflight_depth"] = (
                self._occ_area / elapsed if elapsed > 0 else 0.0
            )
            # numeric leaf -> auto-exported as serve_batcher_last_completion_
            # age_s by serve_metrics_fn: the replica-fleet liveness gauge
            s["last_completion_age_s"] = max(
                0.0, self._occ_last - self._last_completion
            )
        s["max_batch"] = self._max_batch
        s["max_wait_ms"] = self._max_wait_s * 1e3
        s["max_queue"] = self._max_queue
        s["max_queue_images"] = self._max_queue_images
        s["max_inflight"] = self._max_inflight
        s["max_inflight_images"] = self._max_inflight_images
        if s["batches"]:
            s["avg_batch_images"] = s["batched_images"] / s["batches"]
        return s

    # ------------------------------------------------------------- worker

    def _fail(self, req: _Request, exc: Exception) -> None:
        try:
            req.future.set_exception(exc)
        except InvalidStateError:
            pass  # cancelled by the caller — nothing to deliver

    def _pop_live_locked(self) -> Optional[_Request]:
        """Next unexpired, uncancelled request; expired ones fail in place."""
        while self._pending:
            req = self._pending.popleft()
            self._pending_images -= req.n
            if req.future.cancelled():
                continue
            if req.deadline is not None and self._clock() > req.deadline:
                self._stats["timeouts"] += 1
                self._fail(req, RequestTimeout(
                    "request expired before the batcher reached it"
                ))
                continue
            return req
        return None

    def _next_batch(self):
        """Block for the next micro-batch; ``None`` means closed-and-drained."""
        with self._cond:
            while True:
                req = self._pop_live_locked()
                if req is not None:
                    break
                if self._closed:
                    return None
                self._cond.wait(0.05)
            batch = [req]
            total = req.n
            window_end = self._clock() + self._max_wait_s
            shape = req.images.shape[1:]
            dtype = req.images.dtype
            while total < self._max_batch:
                if self._pending:
                    nxt = self._pending[0]
                    if nxt.future.cancelled():
                        self._pending.popleft()
                        self._pending_images -= nxt.n
                        continue
                    if nxt.deadline is not None and self._clock() > nxt.deadline:
                        self._pending.popleft()
                        self._pending_images -= nxt.n
                        self._stats["timeouts"] += 1
                        self._fail(nxt, RequestTimeout(
                            "request expired before the batcher reached it"
                        ))
                        continue
                    if nxt.images.shape[1:] != shape or nxt.images.dtype != dtype:
                        # incompatible with this batch's geometry: dispatching
                        # together would fail EVERY waiter on the concatenate;
                        # leave it to lead the next (same-shape) batch
                        break
                    if total + nxt.n > self._max_batch:
                        break  # would overflow; leave it for the next batch
                    self._pending.popleft()
                    self._pending_images -= nxt.n
                    batch.append(nxt)
                    total += nxt.n
                    continue
                if self._closed or self._clock() >= window_end:
                    break
                self._cond.wait(self._poll)
        return batch

    # ---------------------------------------------- dispatch & completion

    def _occ_tick_locked(self) -> None:
        """Advance the occupancy integrals to now at the CURRENT depth.

        Must be called (under the lock) immediately before any change to
        ``len(self._inflight)`` so ∫depth·dt charges each interval to the
        depth that actually held during it.
        """
        now = self._clock()
        dt = now - self._occ_last
        if dt > 0:
            depth = len(self._inflight)
            self._occ_area += depth * dt
            if depth:
                self._occ_busy += dt
            self._occ_last = now

    def _start_dispatch(self, batch) -> Optional[_Inflight]:
        """Dispatch stage: concatenate and hand the batch to the engine.

        With the engine's async API this runs only the host phases (padding,
        H2D, enqueueing the compiled call); the device is computing when it
        returns. A dispatch-time failure fails every waiter here — there is
        nothing in flight to complete."""
        total = sum(r.n for r in batch)
        images = (
            batch[0].images if len(batch) == 1
            else np.concatenate([r.images for r in batch], axis=0)
        )
        now = self._clock()
        for req in batch:
            req.t_dispatch = now
        try:
            with tracing.span("dispatch", track="serve:dispatch", rows=total):
                handle = self._dispatch_fn(images)
        except Exception as exc:  # noqa: BLE001 — delivered to every waiter
            with self._cond:
                self._stats["errors"] += 1
            for req in batch:
                self._fail(req, exc)
            return None
        with self._cond:
            self._stats["dispatched_batches"] += 1
        return _Inflight(batch=batch, total=total, handle=handle)

    def _finish(self, inflight: _Inflight) -> None:
        """Completion stage: block on the result and resolve the futures."""
        try:
            with tracing.span(
                "complete", track="serve:complete", rows=inflight.total
            ):
                emb = inflight.handle.result()
        except Exception as exc:  # noqa: BLE001 — delivered to every waiter
            with self._cond:
                self._stats["errors"] += 1
                self._last_completion = self._clock()
            for req in inflight.batch:
                self._fail(req, exc)
            return
        with self._cond:
            self._last_completion = self._clock()
            self._stats["batches"] += 1
            self._stats["batched_images"] += inflight.total
            self._stats["max_batch_observed"] = max(
                self._stats["max_batch_observed"], inflight.total
            )
        now = self._clock()
        offset = 0
        for req in inflight.batch:
            rows = emb[offset:offset + req.n]
            offset += req.n
            try:
                req.future.set_result(rows)
            except InvalidStateError:
                pass  # cancelled mid-flight
            # per-request observability at the moment the answer exists:
            # the histogram keys on the jit bucket the request padded into
            # (the same axis the bench reports), the recorder span covers
            # queue -> dispatch -> completion in the batcher's clock domain
            key = self._bucket_fn(req.n) if self._bucket_fn else req.n
            if self._latency is not None:
                self._latency.observe(key, (now - req.t_submit) * 1e3)
            tracing.record_span(
                "request", "serve:request", req.t_submit, now,
                n=req.n, bucket=int(key),
                queue_ms=round((req.t_dispatch - req.t_submit) * 1e3, 3),
            )

    def _dispatch(self, batch) -> None:
        """Synchronous dispatch+complete — the no-worker (``start=False``)
        path the policy unit tests drive batch by batch."""
        inflight = self._start_dispatch(batch)
        if inflight is not None:
            self._finish(inflight)

    def _worker(self) -> None:
        """Assembler: coalesce -> wait for window room -> dispatch.

        Window admission happens BEFORE the dispatch call: the window
        bounds HBM, and the dispatch stage is what allocates device buffers
        (H2D + the enqueued program's outputs). Room only grows between the
        check and the dispatch — the completer is the sole remover and this
        thread the sole adder — so the post-dispatch append needs no
        re-check. The row bound admits an oversized batch alone
        (``self._inflight`` empty) rather than deadlocking on it.
        """
        while True:
            batch = self._next_batch()
            if batch is None:
                break
            total = sum(r.n for r in batch)
            with self._cond:
                while len(self._inflight) >= self._max_inflight or (
                    self._inflight
                    and self._inflight_rows + total > self._max_inflight_images
                ):
                    self._cond.wait(self._poll)
            inflight = self._start_dispatch(batch)
            if inflight is None:
                continue
            with self._cond:
                self._occ_tick_locked()
                self._inflight.append(inflight)
                self._inflight_rows += inflight.total
                self._stats["max_inflight_observed"] = max(
                    self._stats["max_inflight_observed"], len(self._inflight)
                )
                # arm only on the idle->busy edge: re-arming on every
                # dispatch would keep pushing the deadline out while an
                # earlier batch sits stuck — completion, not dispatch, is
                # the progress the watchdog certifies
                if self._watchdog is not None and len(self._inflight) == 1:
                    self._watchdog.arm()
                self._cond.notify_all()
        with self._cond:
            self._assembler_done = True
            self._cond.notify_all()

    def _completer_loop(self) -> None:
        """Completer: resolve in-flight batches strictly FIFO in dispatch
        order (per-request ordering is preserved end to end)."""
        while True:
            with self._cond:
                while not self._inflight and not self._assembler_done:
                    self._cond.wait(self._poll)
                if not self._inflight:
                    return  # assembler exited and the window is drained
                inflight = self._inflight[0]  # peek: stays visible in gauges
            # blocking D2H happens OUTSIDE the lock — submits, stats polls,
            # and the assembler's window wait all proceed meanwhile
            self._finish(inflight)
            with self._cond:
                self._occ_tick_locked()
                self._inflight.popleft()
                self._inflight_rows -= inflight.total
                if self._watchdog is not None:
                    # a completed batch is progress; an emptied window is
                    # expected silence, not a stall
                    if self._inflight:
                        self._watchdog.beat()
                    else:
                        self._watchdog.disarm()
                self._cond.notify_all()
