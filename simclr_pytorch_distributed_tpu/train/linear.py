"""Linear-probe evaluation driver — main_linear.py, TPU-native.

Semantics from the reference (SURVEY.md §3.4):

- the pretrained encoder is FROZEN and in eval mode: BN uses running statistics
  and nothing updates (``model.eval()`` + ``torch.no_grad()`` + ``.detach()``,
  ``main_linear.py:149,170-172``) — here the encoder runs ``train=False`` under
  ``stop_gradient`` and only classifier params are in the optimizer;
- train aug is RRC(0.2-1)+flip only, val is normalize only
  (``main_ce.py:31-41`` via ``main_linear.py:12,253``);
- SGD on the classifier with step decay 60/75/90 x0.2 by default, 100 epochs;
  top-1/top-5 tracked, best val acc reported at the end
  (``main_linear.py:284-288``) — the number the README tables quote.

The probe runs data-parallel over the mesh (the reference is single-GPU; here
extra chips just shard the batch — the math is identical because the encoder is
frozen and CE is a per-example mean).
"""

from __future__ import annotations


import contextlib
import logging
import time
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu.data.cifar import (
    ensure_dataset_available,
    load_dataset,
)
from simclr_pytorch_distributed_tpu.data import device_store
from simclr_pytorch_distributed_tpu.data.device_store import slice_epoch_step
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader
from simclr_pytorch_distributed_tpu.models import (
    MODEL_DICT,
    LinearClassifier,
    SupConResNet,
)
from simclr_pytorch_distributed_tpu.ops.augment import (
    DATASET_STATS,
    AugmentConfig,
    augment_batch,
    eval_batch,
)
from simclr_pytorch_distributed_tpu.ops.losses import cross_entropy_loss
from simclr_pytorch_distributed_tpu.ops.metrics import AverageMeter, topk_correct
from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
from simclr_pytorch_distributed_tpu.parallel.mesh import (
    batch_sharding,
    broadcast_from_main,
    create_mesh,
    epoch_buffer_sharding,
    is_main_process,
    replicated_sharding,
    setup_distributed,
    shard_host_batch,
    sync_processes,
)
from simclr_pytorch_distributed_tpu.train.state import make_optimizer
from simclr_pytorch_distributed_tpu.train.supcon import enable_compile_cache
from simclr_pytorch_distributed_tpu.train.supcon_step import epoch_position
from simclr_pytorch_distributed_tpu.utils import preempt
from simclr_pytorch_distributed_tpu.utils.guard import (
    exit_code_for,
    exit_with_code,
)
from simclr_pytorch_distributed_tpu.utils.checkpoint import (
    load_pretrained_variables,
    save_classifier,
)
from simclr_pytorch_distributed_tpu.utils.logging_utils import TBLogger, setup_logging
from simclr_pytorch_distributed_tpu.utils import tracing
from simclr_pytorch_distributed_tpu.utils.obs import RunObservability
from simclr_pytorch_distributed_tpu.utils.profiling import StepTracer
from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession

# ring columns for the probe/CE step metrics (ops/metrics.MetricRing)
PROBE_METRIC_KEYS = ("loss", "top1", "top5")


class ProbeState(struct.PyTreeNode):
    step: jax.Array
    params: Any  # classifier params only
    opt_state: Any


def stats_for(dataset: str) -> Tuple[Tuple[float, ...], Tuple[float, ...]]:
    if dataset in DATASET_STATS:
        return DATASET_STATS[dataset]
    return ((0.5, 0.5, 0.5), (0.25, 0.25, 0.25))  # synthetic


def build_probe(cfg: config_lib.LinearConfig, steps_per_epoch: int, encoder_variables):
    dtype = jnp.bfloat16 if cfg.bf16 else jnp.float32
    encoder = SupConResNet(model_name=cfg.model, dtype=dtype)
    classifier = LinearClassifier(model_name=cfg.model, num_classes=cfg.n_cls)
    schedule = make_lr_schedule(
        learning_rate=cfg.learning_rate, epochs=cfg.epochs,
        steps_per_epoch=steps_per_epoch, cosine=cfg.cosine,
        lr_decay_rate=cfg.lr_decay_rate, lr_decay_epochs=cfg.lr_decay_epochs,
        warm=cfg.warm, warm_epochs=cfg.warm_epochs, warmup_from=cfg.warmup_from,
    )
    tx = make_optimizer(schedule, momentum=cfg.momentum, weight_decay=cfg.weight_decay)
    feat_dim = MODEL_DICT[cfg.model][1]
    cls_params = classifier.init(
        jax.random.key(cfg.seed), jnp.zeros((2, feat_dim))
    )["params"]
    state = ProbeState(
        step=jnp.zeros((), jnp.int32), params=cls_params, opt_state=tx.init(cls_params)
    )

    def encode(images):
        feats = encoder.apply(
            {"params": encoder_variables["params"],
             "batch_stats": encoder_variables["batch_stats"]},
            images, train=False, method=SupConResNet.encode,
        )
        return jax.lax.stop_gradient(feats.astype(jnp.float32))

    return encoder, classifier, schedule, tx, state, encode


def jit_scalar_or_ring_step(
    step_fn, metric_ring, mesh, resident_steps=None, window_batches=None
):
    """Jit a ``(state, images_u8, labels, key) -> (state, metrics)`` train
    step for a probe-style driver. With ``metric_ring`` the step is wrapped
    to write its metrics into the donated device ring at ``state.step``
    (``(state, ring, images, labels, key) -> (state, ring)``, see
    train/supcon.make_fused_update); ``None`` keeps the scalar-returning
    signature (bench.py). ``resident_steps`` (the loader's steps_per_epoch)
    switches the data arguments to the device-resident ``[steps, batch, ...]``
    epoch buffers (data/device_store.py): the program slices its own batch
    at ``state.step % resident_steps`` and the buffers are NOT donated;
    ``window_batches`` additionally narrows them to one streaming window
    (a WindowStore) by reducing the position modulo the window length (see
    train/supcon.make_fused_update). Shared by the probe and CE builders so
    the ring/resident wiring (shardings + donation) cannot diverge between
    them."""
    repl = replicated_sharding(mesh)
    if resident_steps is None:
        data = (batch_sharding(mesh, 4), batch_sharding(mesh, 1))
        sliced_step = step_fn
    else:
        data = (epoch_buffer_sharding(mesh, 5), epoch_buffer_sharding(mesh, 2))

        def sliced_step(state, epoch_images, epoch_labels, base_key):
            pos = epoch_position(state.step, resident_steps)
            if window_batches is not None:
                pos = pos % window_batches
            images_u8, labels = slice_epoch_step(
                epoch_images, epoch_labels, pos
            )
            return step_fn(state, images_u8, labels, base_key)

    if metric_ring is None:
        return jax.jit(
            sliced_step,
            in_shardings=(repl, *data, repl),
            out_shardings=(repl, repl),
            donate_argnums=(0,),
        )

    def ring_step(state, ring, images_arg, labels_arg, base_key):
        new_state, metrics = sliced_step(state, images_arg, labels_arg, base_key)
        return new_state, metric_ring.write(ring, metrics, state.step)

    return jax.jit(
        ring_step,
        in_shardings=(repl, repl, *data, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0, 1),
    )


def make_probe_steps(
    classifier, tx, encode, aug_cfg, eval_cfg, mesh, metric_ring=None,
    resident_steps=None, window_batches=None,
):
    """``metric_ring`` switches the train step to ring telemetry —
    ``(state, ring, images, labels, key) -> (state, ring)`` with the metrics
    written on device (see train/supcon.make_fused_update); ``None`` keeps
    the scalar-returning signature (bench.py). ``resident_steps`` switches
    the train step's data args to the device-resident epoch buffers
    (jit_scalar_or_ring_step); validation always streams from the host (it
    runs once per epoch — not a hot path)."""
    repl = replicated_sharding(mesh)

    def train_step(state: ProbeState, images_u8, labels, base_key):
        # fold_in INSIDE the program (state.step == the driver's global
        # step): a host-side per-step fold_in costs an H2D scalar transfer
        # that throttles this small step on a tunneled chip (docs/PERF.md)
        key = jax.random.fold_in(base_key, state.step)
        images = augment_batch(key, images_u8, aug_cfg)

        def loss_fn(params):
            logits = classifier.apply({"params": params}, encode(images))
            return cross_entropy_loss(logits, labels), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_state = ProbeState(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            opt_state=new_opt,
        )
        correct = topk_correct(logits, labels)
        metrics = {"loss": loss, "top1": correct[1], "top5": correct[5]}
        return new_state, metrics

    def eval_step(params, images_u8, labels, valid):
        images = eval_batch(images_u8, eval_cfg)
        logits = classifier.apply({"params": params}, encode(images))
        per_ex = -jax.nn.log_softmax(logits)[jnp.arange(labels.shape[0]), labels]
        loss_sum = jnp.sum(per_ex * valid)
        maxk_hit = jax.lax.top_k(logits, 5)[1] == labels[:, None]
        top1 = jnp.sum(jnp.any(maxk_hit[:, :1], axis=1) * valid)
        top5 = jnp.sum(jnp.any(maxk_hit, axis=1) * valid)
        return {"loss_sum": loss_sum, "top1": top1, "top5": top5, "n": jnp.sum(valid)}

    train_jit = jit_scalar_or_ring_step(
        train_step, metric_ring, mesh, resident_steps=resident_steps,
        window_batches=window_batches,
    )
    eval_jit = jax.jit(
        eval_step,
        in_shardings=(repl, batch_sharding(mesh, 4), batch_sharding(mesh, 1),
                      batch_sharding(mesh, 1)),
        out_shardings=repl,
    )
    return train_jit, eval_jit


def run_validation(eval_jit, params, val_images, val_labels, batch_size, mesh):
    """Full-val top-1/top-5 (reference validate(), main_linear.py:204-244).

    The tail batch is padded to a static shape and masked so the jit never
    recompiles; every example counts exactly once.
    """
    n = len(val_images)
    totals = None
    for lo in range(0, n, batch_size):
        chunk_img = val_images[lo:lo + batch_size]
        chunk_lab = val_labels[lo:lo + batch_size]
        valid = np.ones(len(chunk_img), np.float32)
        pad = batch_size - len(chunk_img)
        if pad:
            chunk_img = np.concatenate([chunk_img, np.repeat(chunk_img[:1], pad, 0)])
            chunk_lab = np.concatenate([chunk_lab, np.repeat(chunk_lab[:1], pad)])
            valid = np.concatenate([valid, np.zeros(pad, np.float32)])
        batch = shard_host_batch((chunk_img, chunk_lab, valid), mesh)
        m = eval_jit(params, *batch)
        # accumulate ON DEVICE: a float() here would sync every batch and
        # stall the async dispatch pipeline (round-3 weak #5); the single
        # readback below is the only host sync of the validation pass
        totals = m if totals is None else jax.tree.map(jnp.add, totals, m)
    totals = {k: float(v) for k, v in totals.items()}
    return {
        "loss": totals["loss_sum"] / totals["n"],
        "top1": 100.0 * totals["top1"] / totals["n"],
        "top5": 100.0 * totals["top5"] / totals["n"],
    }


def run(cfg: config_lib.LinearConfig):
    setup_distributed()
    # the collective classifier save needs every process writing into
    # process 0's timestamped run folder (ce.py/supcon.py do the same)
    cfg.save_folder = broadcast_from_main(cfg.save_folder)
    cfg.tb_folder = broadcast_from_main(cfg.tb_folder)
    enable_compile_cache(cfg.compile_cache, cfg.workdir)
    setup_logging(cfg.save_folder, is_main_process())
    mesh = create_mesh()

    ensure_dataset_available(cfg.dataset, cfg.data_folder, cfg.download)
    train_data, test_data, n_cls = load_dataset(
        cfg.dataset, cfg.data_folder,
        allow_synthetic_fallback=(cfg.dataset == "synthetic"),
    )
    cfg.n_cls = n_cls
    loader = EpochLoader(
        train_data["images"], train_data["labels"], cfg.batch_size,
        base_seed=cfg.seed, process_index=jax.process_index(),
        process_count=jax.process_count(),
    )
    steps_per_epoch = len(loader)
    # observability stack (docs/OBSERVABILITY.md, utils/obs.py): flight
    # recorder -> <save_folder>/events.jsonl (+ trace.json), stall
    # watchdog on the flush boundary, optional Prometheus sidecar. Built
    # BEFORE the store: placement resolution is the run's first
    # collective, and its span + startup clock anchor (the fleet report's
    # alignment ruler) must land on the record.
    obs = RunObservability(cfg, name="linear")
    # --data_placement (data/device_store.py): 'device' keeps the train set
    # HBM-resident, 'window' streams a double-buffered window — the probe
    # step is SMALL, so the per-step H2D was a proportionally bigger slice
    # of its loop than the pretrain driver's
    try:
        store = device_store.make_store(
            cfg.data_placement, loader, mesh,
            budget_bytes=device_store.budget_override_bytes(cfg.device_budget_mb),
            window_batches=cfg.data_window_batches,
        )
    except BaseException as e:
        # the placement rejection (an explicit --data_placement the
        # budget/ladder refuses) is a DESIGNED raise path that sits
        # before the driver's main try/finally: close the stack here
        # so the recorder still exports and the terminal exit code
        # stamps (the startup-failure post-mortem the stack exists for)
        obs.close(exit_code=exit_code_for(e))
        raise
    obs.staged()  # staging done: reset the watchdog deadline (utils/obs.py)

    # encoder variables from the pretrain checkpoint (main_linear.py:125-142)
    dtype = jnp.bfloat16 if cfg.bf16 else jnp.float32
    enc_model = SupConResNet(model_name=cfg.model, dtype=dtype)
    abstract = enc_model.init(
        jax.random.key(0), jnp.zeros((2, cfg.size, cfg.size, 3)), train=False
    )
    if cfg.ckpt:
        encoder_variables = load_pretrained_variables(
            cfg.ckpt, {"params": abstract["params"], "batch_stats": abstract["batch_stats"]}
        )
        logging.info("loaded encoder from %s", cfg.ckpt)
    else:
        logging.warning("--ckpt not given: probing a RANDOM encoder")
        encoder_variables = {
            "params": abstract["params"], "batch_stats": abstract["batch_stats"]
        }

    _, classifier, schedule, tx, state, encode = build_probe(
        cfg, steps_per_epoch, encoder_variables
    )
    mean, std = stats_for(cfg.dataset)
    aug_cfg = AugmentConfig(size=cfg.size, mean=mean, std=std, color_ops=False)
    # device-side metric ring + background flush (utils/telemetry.py): the
    # probe step is SMALL, so the per-window sync flush was a proportionally
    # bigger slice of its loop than the pretrain driver's
    telemetry = TelemetrySession(
        cfg.print_freq, PROBE_METRIC_KEYS, cfg.telemetry,
        watchdog=obs.watchdog, gauges=obs.gauges,
    )
    train_jit, eval_jit = make_probe_steps(
        classifier, tx, encode, aug_cfg, aug_cfg, mesh,
        metric_ring=telemetry.ring,
        resident_steps=steps_per_epoch if store is not None else None,
        window_batches=None if store is None else store.window_batches,
    )

    tb = TBLogger(cfg.tb_folder, enabled=is_main_process())
    base_key = jax.random.key(cfg.seed + 1)
    # windowed jax.profiler capture (utils/profiling.py) — previously
    # reachable only from the supcon driver, so the probe stage could not
    # capture an xplane window
    tracer = StepTracer(
        cfg.trace_dir, cfg.trace_start_step, cfg.trace_steps,
        enabled=is_main_process(),
    )
    best_acc, best_acc5 = 0.0, 0.0
    best_params = None

    # The probe has no full-state checkpoints to resume (epochs are seconds,
    # not hours), but it still honors the fleet's SIGTERM contract: finish
    # the flush window, persist the best classifier so far, exit with the
    # preemption code so the launcher knows no re-run bookkeeping is lost.
    # The launcher's blanket "re-run with --resume" relaunch is accepted
    # (config.linear_parser) and means: retrain from scratch.
    if getattr(cfg, "resume", ""):
        logging.warning(
            "--resume %s: the probe keeps no full-state checkpoints; "
            "retraining from scratch", cfg.resume,
        )
    preempt.install()
    preempted = False
    # explicit capture for the exit-code gauge (see the pretrain driver's
    # note: sys.exc_info() in a finally also sees enclosing-frame handlers)
    exit_exc = None
    try:
        for epoch in range(1, cfg.epochs + 1):
            t1 = time.time()
            obs.set_epoch(epoch)
            losses, top1, top5 = AverageMeter(), AverageMeter(), AverageMeter()
            bt = AverageMeter()
            bsz = cfg.batch_size
            ring_buf = telemetry.init_buffer(replicated_sharding(mesh))
            telemetry.start_window_clock()

            def submit_window(boundary_idx, ring_buf, step_hint):
                # one flush_boundary (utils/telemetry.py): meter the window
                # on the main thread, snapshot + queue the one-transfer
                # flush, observe failures collectively
                def consume(fetched, bt):
                    # ``bt`` shadows the meter with the (val, avg) tuple
                    # flush_boundary snapshotted on the main thread — the
                    # live meter keeps mutating while this job runs
                    for _, m in fetched:
                        losses.update(m["loss"], bsz)
                        top1.update(100.0 * m["top1"] / bsz, bsz)
                        top5.update(100.0 * m["top5"] / bsz, bsz)
                    logging.info(
                        "Train: [%d][%d/%d]\tBT %.3f (%.3f)\tloss %.3f (%.3f)\t"
                        "Acc@1 %.3f (%.3f)",
                        epoch, boundary_idx + 1, steps_per_epoch, bt[0], bt[1],
                        losses.val, losses.avg, top1.val, top1.avg,
                    )

                telemetry.flush_boundary(ring_buf, consume, batch_meter=bt,
                                         step_hint=step_hint)

            batches = None if store is not None else loader.epoch(epoch)
            try:
                with tracing.span("epoch", track="main:epoch", epoch=epoch):
                    for idx in range(steps_per_epoch):
                        gstep = (epoch - 1) * steps_per_epoch + idx  # == state.step
                        # first dispatch of the run carries trace+compile
                        # (main:compile phase; see train/supcon.py) — every
                        # later step takes the nullcontext arm
                        span = (
                            tracing.span("first_step", track="main:compile",
                                         step=gstep)
                            if epoch == 1 and idx == 0
                            else contextlib.nullcontext()
                        )
                        if batches is None:
                            epoch_images, epoch_labels = store.batch_buffers(
                                epoch, idx
                            )
                            with span:
                                state, ring_buf = train_jit(
                                    state, ring_buf, epoch_images,
                                    epoch_labels, base_key
                                )
                        else:
                            images_u8, labels = next(batches)
                            batch = shard_host_batch((images_u8, labels), mesh)
                            with span:
                                state, ring_buf = train_jit(
                                    state, ring_buf, batch[0], batch[1],
                                    base_key
                                )
                        telemetry.append(idx, gstep)
                        if tracer is not None:
                            tracer.step(gstep)
                        if (idx + 1) % cfg.print_freq == 0 or idx + 1 == steps_per_epoch:
                            submit_window(idx, ring_buf, gstep)
                            if preempt.requested_global():
                                # collective decision (see train/supcon.py),
                                # on the MAIN thread — independent of any
                                # in-flight flush: all hosts leave the loop
                                # at the same boundary, keeping the
                                # end-of-run barriers matched
                                preempted = True
                                break
            finally:
                if batches is not None:
                    batches.close()  # stop the prefetch worker on early exit
            # flush any short-epoch tail, then drain COLLECTIVELY ahead of
            # the end-of-run save (the ordering contract lives on the session)
            telemetry.finish_epoch(
                lambda hint: submit_window(steps_per_epoch - 1, ring_buf, hint),
                epoch * steps_per_epoch - 1,
            )
            if preempted:
                tracing.event("preempt_exit", track="main:guard", epoch=epoch)
                logging.warning(
                    "preempted (%s) during epoch %d: stopping the probe",
                    preempt.signal_name(), epoch,
                )
                break
            logging.info(
                "Train epoch %d, total time %.2f, accuracy:%.2f",
                epoch, time.time() - t1, top1.avg,
            )
            if is_main_process():
                tb.log_value("classifier/train_loss", losses.avg, epoch)
                tb.log_value("classifier/train_acc1", top1.avg, epoch)
                tb.log_value("classifier/train_acc5", top5.avg, epoch)

            with tracing.span("validation", track="main:eval", epoch=epoch):
                val = run_validation(
                    eval_jit, state.params, test_data["images"],
                    test_data["labels"], cfg.val_batch_size, mesh,
                )
            logging.info(" * Acc@1 %.3f, Acc@5 %.3f", val["top1"], val["top5"])
            if is_main_process():
                tb.log_value("classifier/val_loss", val["loss"], epoch)
                tb.log_value("classifier/val_acc1", val["top1"], epoch)
                tb.log_value("classifier/val_acc5", val["top5"], epoch)
            if val["top1"] > best_acc:
                best_acc, best_acc5 = val["top1"], val["top5"]
                best_params = jax.device_get(state.params)
    except BaseException as e:
        exit_exc = e
        raise
    finally:
        preempt.uninstall()
        telemetry.close()
        if store is not None:
            store.close()  # stop the window prefetch worker on any exit
        tracer.close()
        # no async saves in the probe (save_classifier is blocking), so
        # the observability teardown has nothing to wait for. The probe's
        # preemption exit (SystemExit(75)) is raised AFTER this finally —
        # unlike the pretrain driver's in-try raise — so the terminal
        # exit-code gauge reads the `preempted` flag, not exc_info.
        obs.close(exit_code=(
            preempt.EXIT_PREEMPTED if preempted
            else exit_code_for(exit_exc)
        ))

    if best_params is not None:
        # beyond parity: persist the best probe head (the reference only
        # reports best_acc, main_linear.py:284-288); collective orbax save
        path = save_classifier(cfg.save_folder, best_params, best_acc)
        logging.info("saved best classifier to %s", path)
    logging.info("best accuracy: %.2f, accuracy5: %.2f", best_acc, best_acc5)
    tb.close()
    if preempted:
        sync_processes("linear_run_preempted")
        raise SystemExit(preempt.EXIT_PREEMPTED)
    sync_processes("linear_run_end")
    return best_acc, best_acc5


def main(argv=None):
    cfg = config_lib.parse_linear(argv)
    # typed exit codes (docs/RESILIENCE.md): NaN/flush aborts exit 1/2,
    # preemption 75 via SystemExit — the supervisor's classification input
    exit_with_code(lambda: run(cfg))


if __name__ == "__main__":
    main()
