"""Supervised cross-entropy baseline trainer — the trainer main_ce.py LOST.

The reference fork kept only ``set_loader`` of main_ce.py (``main_ce.py:19-68``);
``SupCEResNet`` is imported but never trained (SURVEY.md §2.1 #14). BASELINE.json
still lists the CE-baseline config, so this rebuilds the complete trainer:
SupCEResNet end-to-end with the probe stage's aug stack (RRC+flip, main_ce.py:
31-36), SGD + the shared schedule machinery, top-1/5 validation, best-acc
tracking — distributed over the mesh like the contrastive stage.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu.data.cifar import (
    ensure_dataset_available,
    load_dataset,
)
from simclr_pytorch_distributed_tpu.data import device_store
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader
from simclr_pytorch_distributed_tpu.models import SupCEResNet
from simclr_pytorch_distributed_tpu.ops.augment import (
    AugmentConfig,
    augment_batch,
    eval_batch,
)
from simclr_pytorch_distributed_tpu.ops.losses import cross_entropy_loss
from simclr_pytorch_distributed_tpu.ops.metrics import AverageMeter, topk_correct
from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
from simclr_pytorch_distributed_tpu.parallel.mesh import (
    batch_sharding,
    broadcast_from_main,
    create_mesh,
    is_main_process,
    replicated_sharding,
    setup_distributed,
    shard_host_batch,
    sync_processes,
)
from simclr_pytorch_distributed_tpu.train.linear import (
    PROBE_METRIC_KEYS,
    jit_scalar_or_ring_step,
    run_validation,
    stats_for,
)
from simclr_pytorch_distributed_tpu.train.supcon import enable_compile_cache
from simclr_pytorch_distributed_tpu.utils import preempt
from simclr_pytorch_distributed_tpu.utils.guard import (
    exit_code_for,
    exit_with_code,
)
from simclr_pytorch_distributed_tpu.utils import tracing
from simclr_pytorch_distributed_tpu.utils.checkpoint import (
    resolve_resume_path,
    restore_checkpoint,
    resume_position,
    save_checkpoint,
    wait_for_saves,
)
from simclr_pytorch_distributed_tpu.utils.logging_utils import TBLogger, setup_logging
from simclr_pytorch_distributed_tpu.utils.obs import RunObservability
from simclr_pytorch_distributed_tpu.utils.profiling import StepTracer
from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession


class CEState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any


def make_ce_steps(
    model, tx, aug_cfg, mesh, metric_ring=None, resident_steps=None,
    window_batches=None,
):
    """``metric_ring`` switches the train step to ring telemetry (see
    train/supcon.make_fused_update); ``None`` keeps the scalar-returning
    signature (bench.py). ``resident_steps`` switches the train step's data
    args to the device-resident epoch buffers, ``window_batches`` narrows
    them to one streaming window (jit_scalar_or_ring_step)."""
    repl = replicated_sharding(mesh)

    def train_step(state: CEState, images_u8, labels, base_key):
        # fold_in INSIDE the program (state.step == the driver's global step;
        # host-side per-step fold_in = an H2D transfer per step, docs/PERF.md)
        key = jax.random.fold_in(base_key, state.step)
        images = augment_batch(key, images_u8, aug_cfg)

        def loss_fn(params):
            logits, mutated = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images, train=True, mutable=["batch_stats"],
            )
            return cross_entropy_loss(logits.astype(jnp.float32), labels), (logits, mutated)

        (loss, (logits, mutated)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_state = CEState(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            batch_stats=mutated["batch_stats"],
            opt_state=new_opt,
        )
        correct = topk_correct(logits, labels)
        return new_state, {"loss": loss, "top1": correct[1], "top5": correct[5]}

    def eval_step(state_vars, images_u8, labels, valid):
        images = eval_batch(images_u8, aug_cfg)
        logits = model.apply(
            {"params": state_vars["params"], "batch_stats": state_vars["batch_stats"]},
            images, train=False,
        ).astype(jnp.float32)
        per_ex = -jax.nn.log_softmax(logits)[jnp.arange(labels.shape[0]), labels]
        hit = jax.lax.top_k(logits, 5)[1] == labels[:, None]
        return {
            "loss_sum": jnp.sum(per_ex * valid),
            "top1": jnp.sum(jnp.any(hit[:, :1], axis=1) * valid),
            "top5": jnp.sum(jnp.any(hit, axis=1) * valid),
            "n": jnp.sum(valid),
        }

    train_jit = jit_scalar_or_ring_step(
        train_step, metric_ring, mesh, resident_steps=resident_steps,
        window_batches=window_batches,
    )
    eval_jit = jax.jit(
        eval_step,
        in_shardings=(repl, batch_sharding(mesh, 4), batch_sharding(mesh, 1),
                      batch_sharding(mesh, 1)),
        out_shardings=repl,
    )
    return train_jit, eval_jit


def run(cfg: config_lib.LinearConfig):
    setup_distributed()
    cfg.save_folder = broadcast_from_main(cfg.save_folder)
    cfg.tb_folder = broadcast_from_main(cfg.tb_folder)
    enable_compile_cache(cfg.compile_cache, cfg.workdir)
    setup_logging(cfg.save_folder, is_main_process())
    mesh = create_mesh()

    ensure_dataset_available(cfg.dataset, cfg.data_folder, cfg.download)
    train_data, test_data, n_cls = load_dataset(
        cfg.dataset, cfg.data_folder,
        allow_synthetic_fallback=(cfg.dataset == "synthetic"),
    )
    cfg.n_cls = n_cls
    loader = EpochLoader(
        train_data["images"], train_data["labels"], cfg.batch_size,
        base_seed=cfg.seed, process_index=jax.process_index(),
        process_count=jax.process_count(),
    )
    steps_per_epoch = len(loader)

    dtype = jnp.bfloat16 if cfg.bf16 else jnp.float32
    # --syncBN off (default) = the reference's per-GPU BatchNorm2d semantics:
    # BN statistics scoped to the data-parallel device slices (models/norm.py
    # grouped mode; conversion is conditional upstream, main_supcon.py:223-224)
    model = SupCEResNet(
        model_name=cfg.model, num_classes=n_cls, dtype=dtype,
        sync_bn=cfg.syncBN,
        bn_local_groups=1 if cfg.syncBN else mesh.shape["data"],
    )
    schedule = make_lr_schedule(
        learning_rate=cfg.learning_rate, epochs=cfg.epochs,
        steps_per_epoch=steps_per_epoch, cosine=cfg.cosine,
        lr_decay_rate=cfg.lr_decay_rate, lr_decay_epochs=cfg.lr_decay_epochs,
        warm=cfg.warm, warm_epochs=cfg.warm_epochs, warmup_from=cfg.warmup_from,
    )
    from simclr_pytorch_distributed_tpu.train.state import make_optimizer

    tx = make_optimizer(schedule, momentum=cfg.momentum, weight_decay=cfg.weight_decay)
    variables = model.init(
        jax.random.key(cfg.seed), jnp.zeros((2, cfg.size, cfg.size, 3)), train=True
    )
    state = CEState(
        step=jnp.zeros((), jnp.int32),
        params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=tx.init(variables["params"]),
    )

    mean, std = stats_for(cfg.dataset)
    aug_cfg = AugmentConfig(size=cfg.size, mean=mean, std=std, color_ops=False)
    # observability stack (docs/OBSERVABILITY.md, utils/obs.py): flight
    # recorder -> <save_folder>/events.jsonl (+ trace.json), stall
    # watchdog on the flush boundary, optional Prometheus sidecar. Built
    # BEFORE the store: placement resolution is the run's first
    # collective, and its span + startup clock anchor (the fleet report's
    # alignment ruler) must land on the record.
    obs = RunObservability(cfg, name="ce")
    # --data_placement (data/device_store.py): HBM-resident train set or a
    # double-buffered streaming window, dispatch-only hot loop either way;
    # 'auto' walks the device->window->host ladder with a banner
    try:
        store = device_store.make_store(
            cfg.data_placement, loader, mesh,
            budget_bytes=device_store.budget_override_bytes(cfg.device_budget_mb),
            window_batches=cfg.data_window_batches,
        )
    except BaseException as e:
        # the placement rejection (an explicit --data_placement the
        # budget/ladder refuses) is a DESIGNED raise path that sits
        # before the driver's main try/finally: close the stack here
        # so the recorder still exports and the terminal exit code
        # stamps (the startup-failure post-mortem the stack exists for)
        obs.close(exit_code=exit_code_for(e))
        raise
    obs.staged()  # staging done: reset the watchdog deadline (utils/obs.py)
    # device-side metric ring + background flush (utils/telemetry.py)
    telemetry = TelemetrySession(
        cfg.print_freq, PROBE_METRIC_KEYS, cfg.telemetry,
        watchdog=obs.watchdog, gauges=obs.gauges,
    )
    train_jit, eval_jit = make_ce_steps(
        model, tx, aug_cfg, mesh, metric_ring=telemetry.ring,
        resident_steps=steps_per_epoch if store is not None else None,
        window_batches=None if store is None else store.window_batches,
    )

    start_epoch, start_step = 1, 0
    meta = {}
    if getattr(cfg, "resume", ""):
        # full-state resume, step-granular like the pretrain driver's: the
        # restore goes through the TrainState facade state_for_save already
        # defines for the saver, then maps back onto CEState.
        resume_path = resolve_resume_path(cfg.resume)
        # mesh= -> elastic restore (orbax reshards onto this run's mesh;
        # see the pretrain driver's note and utils/checkpoint.py)
        restored, meta = restore_checkpoint(
            resume_path, state_for_save(state), mesh=mesh
        )
        state = CEState(
            step=restored.step, params=restored.params,
            batch_stats=restored.batch_stats, opt_state=restored.opt_state,
        )
        start_epoch, start_step = resume_position(meta, steps_per_epoch)
        logging.info(
            "resumed from %s at epoch %d step %d",
            resume_path, start_epoch, start_step,
        )

    tb = TBLogger(cfg.tb_folder, enabled=is_main_process())
    base_key = jax.random.key(cfg.seed + 1)
    # windowed jax.profiler capture (utils/profiling.py) — previously
    # reachable only from the supcon driver, so the CE stage could not
    # capture an xplane window
    tracer = StepTracer(
        cfg.trace_dir, cfg.trace_start_step, cfg.trace_steps,
        enabled=is_main_process(),
    )
    # the best-accuracy watermark is RUN state: a resumed run that never
    # re-beats the pre-preemption peak must still report it (checkpoint
    # meta carries it, like the pretrain driver's rollback damping)
    best_acc = float(meta.get("best_acc") or 0.0)
    best_acc5 = float(meta.get("best_acc5") or 0.0)

    def run_meta():
        return {"best_acc": best_acc, "best_acc5": best_acc5}

    def eval_variables(state):
        return {"params": state.params, "batch_stats": state.batch_stats}

    preempt.install()
    # explicit capture for the exit-code gauge (see the pretrain driver's
    # note: sys.exc_info() in a finally also sees enclosing-frame handlers)
    exit_exc = None
    try:
        for epoch in range(start_epoch, cfg.epochs + 1):
            t1 = time.time()
            obs.set_epoch(epoch)
            losses, top1 = AverageMeter(), AverageMeter()
            ring_buf = telemetry.init_buffer(replicated_sharding(mesh))

            def submit_window(boundary_idx, ring_buf, step_hint):
                # one flush_boundary (utils/telemetry.py): snapshot + queue
                # the one-transfer flush (meters/log run on the telemetry
                # thread, FIFO), observe failures collectively
                def consume(fetched):
                    for _, m in fetched:
                        losses.update(m["loss"], cfg.batch_size)
                        top1.update(100.0 * m["top1"] / cfg.batch_size, cfg.batch_size)
                    logging.info(
                        "Train: [%d][%d/%d]\tloss %.3f (%.3f)\tAcc@1 %.3f (%.3f)",
                        epoch, boundary_idx + 1, steps_per_epoch,
                        losses.val, losses.avg, top1.val, top1.avg,
                    )

                telemetry.flush_boundary(ring_buf, consume,
                                         step_hint=step_hint)

            ss = start_step if epoch == start_epoch else 0
            # both loop shapes iterate range(ss, steps_per_epoch) — an
            # oversized resume offset (changed geometry) must raise, not
            # silently complete a zero-step epoch
            loader.check_start_step(ss)
            batches = None if store is not None else loader.epoch(
                epoch, start_step=ss
            )
            try:
                epoch_span = tracing.span("epoch", track="main:epoch",
                                          epoch=epoch)
                epoch_span.__enter__()
                for idx in range(ss, steps_per_epoch):
                    gstep = (epoch - 1) * steps_per_epoch + idx  # == state.step
                    # first dispatch of the run carries trace+compile
                    # (main:compile phase; see train/supcon.py)
                    span = (
                        tracing.span("first_step", track="main:compile",
                                     step=gstep)
                        if epoch == start_epoch and idx == ss
                        else contextlib.nullcontext()
                    )
                    if batches is None:
                        epoch_images, epoch_labels = store.batch_buffers(
                            epoch, idx
                        )
                        with span:
                            state, ring_buf = train_jit(
                                state, ring_buf, epoch_images, epoch_labels,
                                base_key
                            )
                    else:
                        images_u8, labels = next(batches)
                        batch = shard_host_batch((images_u8, labels), mesh)
                        with span:
                            state, ring_buf = train_jit(
                                state, ring_buf, batch[0], batch[1], base_key
                            )
                    telemetry.append(idx, gstep)
                    if tracer is not None:
                        tracer.step(gstep)
                    if (idx + 1) % cfg.print_freq == 0 or idx + 1 == steps_per_epoch:
                        submit_window(idx, ring_buf, gstep)
                        if idx + 1 < steps_per_epoch and preempt.requested_global():
                            # SIGTERM/SIGINT at a flush boundary, decided
                            # collectively on the MAIN thread (see
                            # train/supcon.py — independent of any in-flight
                            # flush). Drain COLLECTIVELY (a host-local raise
                            # here would skip the collective emergency save
                            # while peers enter it) so the mid-epoch save —
                            # collective, same semantics as the pretrain driver
                            # — sees complete metrics; the distinct exit code
                            # tells the launcher to re-run with --resume.
                            telemetry.drain_global(gstep)
                            tracing.event(
                                "preempt_exit", track="main:guard",
                                epoch=epoch, step_in_epoch=idx + 1,
                            )
                            preempt.emergency_save_and_exit(
                                cfg.save_folder,
                                f"preempt_epoch_{epoch}_step_{idx + 1}",
                                state_for_save(state),
                                config_lib.config_dict(cfg), epoch - 1,
                                step_in_epoch=idx + 1, extra_meta=run_meta(),
                                cleanup=(tb.close, telemetry.close),
                            )
            finally:
                epoch_span.__exit__(None, None, None)
                if batches is not None:
                    batches.close()  # stop the prefetch worker on early exit
            # flush any short-epoch tail, then drain COLLECTIVELY ahead of
            # the scheduled save (the ordering contract lives on the session)
            telemetry.finish_epoch(
                lambda hint: submit_window(steps_per_epoch - 1, ring_buf, hint),
                epoch * steps_per_epoch - 1,
            )
            logging.info("Train epoch %d, total time %.2f, accuracy:%.2f",
                         epoch, time.time() - t1, top1.avg)

            with tracing.span("validation", track="main:eval", epoch=epoch):
                val = run_validation(
                    eval_jit, eval_variables(state), test_data["images"],
                    test_data["labels"], cfg.val_batch_size, mesh,
                )
            logging.info(" * Acc@1 %.3f, Acc@5 %.3f", val["top1"], val["top5"])
            if is_main_process():
                tb.log_value("ce/train_loss", losses.avg, epoch)
                tb.log_value("ce/train_acc1", top1.avg, epoch)
                tb.log_value("ce/val_loss", val["loss"], epoch)
                tb.log_value("ce/val_acc1", val["top1"], epoch)
                tb.log_value("ce/val_acc5", val["top5"], epoch)
            if val["top1"] > best_acc:
                best_acc, best_acc5 = val["top1"], val["top5"]
            if epoch % cfg.save_freq == 0:
                # collective on all processes (orbax coordinates writers;
                # meta.json stays process-0-gated inside save_checkpoint)
                save_checkpoint(
                    cfg.save_folder, f"ckpt_epoch_{epoch}",
                    # CEState quacks enough like TrainState for the saver
                    state_for_save(state), config=config_lib.config_dict(cfg),
                    epoch=epoch, block=False, extra_meta=run_meta(),
                )
            if preempt.requested_global():
                # boundary preemption (collective decision): this epoch is
                # persisted (by the scheduled save above, or a preempt_*
                # save now), then the distinct exit
                tracing.event(
                    "preempt_exit", track="main:guard", epoch=epoch,
                )
                preempt.emergency_save_and_exit(
                    cfg.save_folder,
                    None if epoch % cfg.save_freq == 0
                    else f"preempt_epoch_{epoch}",
                    state_for_save(state), config_lib.config_dict(cfg),
                    epoch, extra_meta=run_meta(),
                    cleanup=(tb.close, telemetry.close),
                )

    except BaseException as e:
        exit_exc = e
        raise
    finally:
        preempt.uninstall()
        telemetry.close()
        if store is not None:
            store.close()  # stop the window prefetch worker on any exit
        tracer.close()
        # drain in-flight async saves BEFORE the observability teardown
        # (utils/obs.py ordering contract: the final checkpoint_commit span
        # must land in the record, and the watchdog must still be watching
        # if that drain wedges); the post-loop wait below is then a no-op
        wait_for_saves()
        obs.close(exit_code=exit_code_for(exit_exc))
    wait_for_saves()
    logging.info("best accuracy: %.2f, accuracy5: %.2f", best_acc, best_acc5)
    tb.close()
    sync_processes("ce_run_end")
    return best_acc, best_acc5


def state_for_save(state: CEState):
    from simclr_pytorch_distributed_tpu.train.state import TrainState

    # The placeholder scalar must inherit the step's mesh-replicated global
    # sharding: a fresh jnp.zeros(()) is a host-local single-device array and
    # orbax REFUSES to serialize those in a multi-process job (found by
    # tests/test_multiprocess.py::test_two_process_ce_driver).
    return TrainState(
        step=state.step, params=state.params, batch_stats=state.batch_stats,
        opt_state=state.opt_state,
        record_norm_mean=(state.step * 0).astype(jnp.float32),
    )


def main(argv=None):
    cfg = config_lib.parse_linear(argv, ce=True)
    # typed exit codes (docs/RESILIENCE.md): NaN/flush aborts exit 1/2,
    # preemption 75 via SystemExit — the supervisor's classification input
    exit_with_code(lambda: run(cfg))


if __name__ == "__main__":
    main()
