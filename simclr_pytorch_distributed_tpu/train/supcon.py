"""Distributed contrastive pretraining driver — main_supcon.py, TPU-native.

One process per host drives the SPMD program: build mesh -> data -> model/state
-> jit(augment+step over the mesh) -> epoch loop with meters/TB/checkpoints.
The reference call stack being replaced is SURVEY.md §3.1/§3.2.

Perf notes vs the reference hot loop:
- augmentation + forward + loss + update is ONE compiled program per step; the
  host only permutes uint8 indices (no worker pool, no PIL, no pinned-memory
  staging);
- per-step metrics are written into a device-side ring INSIDE the jitted
  update and flushed as ONE contiguous D2H per ``print_freq`` window on a
  background telemetry thread (utils/telemetry.py), so the hot loop never
  blocks on observability (the reference's per-iter ``loss.item()`` is a sync
  point, ``main_supcon.py:320``) while still metering/TB-logging EVERY step at
  reference cadence;
- checkpoint RESUME is supported (``--resume``), which the reference lacks.
"""

from __future__ import annotations

import contextlib
import logging
import math
import os
import time
from typing import List

import jax
import jax.numpy as jnp

from simclr_pytorch_distributed_tpu import config as config_lib
from simclr_pytorch_distributed_tpu import recipes as recipes_lib
from simclr_pytorch_distributed_tpu.data.cifar import (
    ensure_dataset_available,
    load_dataset,
)
from simclr_pytorch_distributed_tpu.data import device_store
from simclr_pytorch_distributed_tpu.data.device_store import slice_epoch_step
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader
from simclr_pytorch_distributed_tpu.models import MODEL_DICT, SupConResNet
from simclr_pytorch_distributed_tpu.ops.augment import (
    DATASET_STATS,
    AugmentConfig,
    two_crop_batch,
)
from simclr_pytorch_distributed_tpu.ops import pallas_loss
from simclr_pytorch_distributed_tpu.ops.metrics import AverageMeter
from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
from simclr_pytorch_distributed_tpu.parallel.mesh import (
    batch_sharding,
    broadcast_from_main,
    create_mesh,
    epoch_buffer_sharding,
    is_main_process,
    replicated_sharding,
    setup_distributed,
    shard_host_batch,
    state_sharding,
    sync_processes,
)
from simclr_pytorch_distributed_tpu.train.state import (
    TrainState,
    create_train_state,
    make_optimizer,
    realign_schedule_count,
)
from simclr_pytorch_distributed_tpu.train.supcon_step import (
    HEALTH_METRIC_KEYS,
    METRIC_KEYS,
    ONLINE_PROBE_METRIC_KEYS,
    SupConStepConfig,
    build_online_probe,
    epoch_position,
    make_train_step,
    metric_keys,
)
from simclr_pytorch_distributed_tpu.utils.checkpoint import (
    jit_copy_tree,
    load_pretrained_variables,
    resolve_resume_path,
    restore_checkpoint,
    resume_position,
    save_checkpoint,
    wait_for_saves,
)
from simclr_pytorch_distributed_tpu.utils import preempt
from simclr_pytorch_distributed_tpu.utils import tracing
from simclr_pytorch_distributed_tpu.utils.obs import RunObservability
from simclr_pytorch_distributed_tpu.utils.guard import (
    FailurePolicy,
    NonFiniteLossError,
    check_finite_loss,
    exit_code_for,
    exit_with_code,
)
from simclr_pytorch_distributed_tpu.utils.logging_utils import TBLogger, setup_logging
from simclr_pytorch_distributed_tpu.utils.profiling import StepTracer
from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetrySession


def make_augment_config(cfg: config_lib.SupConConfig, color_ops: bool = True) -> AugmentConfig:
    if cfg.dataset in DATASET_STATS:
        mean, std = DATASET_STATS[cfg.dataset]
    elif cfg.dataset.startswith("synthetic"):
        mean, std = ((0.5, 0.5, 0.5), (0.25, 0.25, 0.25))
    else:  # 'path' datasets: user-supplied strings (reference main_supcon.py:163-165,
        # minus its std=eval(mean) bug)
        mean = tuple(float(x) for x in cfg.mean.strip("()").split(","))
        std = tuple(float(x) for x in cfg.std.strip("()").split(","))
    return AugmentConfig(size=cfg.size, mean=mean, std=std, color_ops=color_ops)


def resolve_loss_impl_reasoned(
    loss_impl: str, batch_size: int, n_devices: int, model_parallel: int = 1,
    moco_queue: int = 0,
) -> tuple:
    """``(resolved_impl, reason)`` — the ``resolve_loss_impl`` ladder with
    the WHY attached, so the driver's startup banner
    (config.impl_resolution_banner) can name a silent degradation
    (unsupported geometry, non-TPU backend) instead of leaving it
    discoverable only by reading this function."""
    if moco_queue and loss_impl == "auto":
        return "dense", (
            f"--moco_queue {moco_queue} extends the contrast side past the "
            "fixed 2B geometry the fused/ring kernels tile"
        )
    if loss_impl != "auto":
        return loss_impl, "explicit request"
    if jax.default_backend() != "tpu":
        return "dense", (
            f"non-TPU backend ({jax.default_backend()}): the fused Pallas "
            "kernel compiles on TPU only"
        )
    data_parallel = max(1, n_devices // max(1, model_parallel))
    if data_parallel == 1:
        if pallas_loss.supports(batch_size, 2):
            return "fused", "TPU single-chip, geometry tiles (+6.6% e2e)"
        return "dense", (
            f"2B={2 * batch_size} does not tile the fused kernel's blocks "
            "(ops/pallas_loss.supports)"
        )
    if pallas_loss.supports_sharded(batch_size, 2, data_parallel):
        return "fused", (
            f"TPU mesh (data={data_parallel}): shard_map-sharded fused "
            "kernel, anchors stay sharded"
        )
    return "dense", (
        f"2B={2 * batch_size} over data={data_parallel} does not tile the "
        "sharded fused kernel (ops/pallas_loss.supports_sharded)"
    )


def resolve_loss_impl(
    loss_impl: str, batch_size: int, n_devices: int, model_parallel: int = 1,
    moco_queue: int = 0,
) -> str:
    """'auto' -> the fused Pallas kernel on TPU, dense otherwise.

    Single chip: the plain fused kernel (+6.6% end-to-end, docs/PERF.md).
    Multi-device mesh: the shard_map-sharded fused kernel — anchors stay
    sharded over 'data', contrast all-gathered, logits tiles VMEM-only
    (ops/pallas_loss.py fused_sharded_supcon_loss) — so 'auto' no longer
    silently downgrades to the O((2B)^2)-materializing dense path on the
    v5e-8 target. Shapes the kernels can't tile fall back to dense, which
    GSPMD partitions as plain HLO.

    ``moco_queue > 0`` forces dense: the queue extends the contrast side to
    ``2B + K``, which the fixed-geometry fused/ring kernels don't tile
    (explicit fused/ring with a queue is rejected at parse,
    config.validate_recipe).
    """
    impl, _ = resolve_loss_impl_reasoned(
        loss_impl, batch_size, n_devices, model_parallel, moco_queue
    )
    return impl


def conv_fused_sites(
    model: str, rows: int, size: int, dtype=jnp.float32
) -> List[str]:
    """The encoder sites ``--conv_impl pallas`` would fuse at this
    geometry and compute dtype: the admitted subset of
    ``models.resnet.fused_site_plan`` — the single-sourced walk the block
    modules' own gates mirror, so banner and runtime dispatch can never
    disagree. ``rows`` is the encoder's view-major batch (``2*batch_size``
    for the two-crop step)."""
    from simclr_pytorch_distributed_tpu.models.resnet import fused_site_plan

    return [
        site["desc"]
        for site in fused_site_plan(model, rows, size, dtype=dtype)
        if site["admitted"]
    ]


def resolve_conv_impl(
    conv_impl: str, model: str, batch_size: int, size: int,
    n_devices: int, bf16: bool = False,
) -> tuple:
    """``(resolved_impl, reason)`` for ``--conv_impl`` — the
    ``resolve_loss_impl`` ladder convention applied to the encoder's conv
    path (ops/pallas_conv.py).

    'auto' picks the fused Pallas stem/BasicBlock/Bottleneck kernels only
    on a single-device TPU mesh, fp32 OR bf16 compute, at geometries the
    per-site ``supports_*`` gates admit (the model applies them site by
    site; the reason names the admitted sites and the compute dtype).
    Explicit 'pallas' is honored on any backend (interpret mode off-TPU —
    tests and the checkpoint round-trip smoke, not throughput), with
    ``--bf16`` admitted site-by-site exactly like fp32 (the kernels carry
    bf16 variants with fp32 accumulation; config.validate_conv_impl no
    longer rejects the pairing at parse), but raises loudly where it
    could only be a silent no-op (multi-device mesh, zero admitted
    sites) — the placement ladder's honored-or-raise rule.
    """
    if conv_impl == "xla":
        return "xla", "explicit request: bitwise-pinned XLA conv path"
    rows = 2 * batch_size
    dtype = jnp.bfloat16 if bf16 else jnp.float32
    dtype_tag = "bf16" if bf16 else "fp32"
    if conv_impl == "pallas":
        if n_devices > 1:
            raise ValueError(
                f"--conv_impl pallas requires a single-device mesh, got "
                f"{n_devices} devices: the fused kernels compute whole-"
                "batch BN statistics inside one program (per-device BN "
                "groups / GSPMD partitioning of the pallas_call are the "
                "recorded open edge, docs/PERF.md round 15)"
            )
        sites = conv_fused_sites(model, rows, size, dtype=dtype)
        if not sites:
            raise ValueError(
                f"--conv_impl pallas admits no site for {model} at "
                f"[{rows},{size},{size}] {dtype_tag} (see "
                "ops/pallas_conv.supports_*) — use auto, which degrades "
                "to xla with a banner"
            )
        backend = jax.default_backend()
        mode = (
            "compiled" if backend == "tpu"
            else f"INTERPRET mode on {backend} (correctness only, slow)"
        )
        return "pallas", (
            f"explicit request, {mode}, compute dtype {dtype_tag}; "
            f"fused sites: {', '.join(sites)}"
        )
    # auto
    if jax.default_backend() != "tpu":
        return "xla", (
            f"non-TPU backend ({jax.default_backend()}): fused kernels "
            "compile on TPU only"
        )
    if n_devices > 1:
        return "xla", (
            f"multi-device mesh ({n_devices}): fused kernels are "
            "single-chip (whole-batch BN inside one program)"
        )
    sites = conv_fused_sites(model, rows, size, dtype=dtype)
    if not sites:
        return "xla", (
            f"no admitted geometry for {model} at [{rows},{size},{size}] "
            f"{dtype_tag} (ops/pallas_conv.supports_*)"
        )
    return "pallas", (
        f"TPU single-chip, compute dtype {dtype_tag}, "
        f"fused sites: {', '.join(sites)}"
    )


def build(cfg: config_lib.SupConConfig, steps_per_epoch: int, n_devices: int = 1):
    """Model, schedule, optimizer, initial state, and the fused jitted update."""
    dtype = jnp.bfloat16 if cfg.bf16 else jnp.float32
    # --syncBN off = the reference's default per-GPU BatchNorm2d
    # (main_supcon.py:223-224 converts to SyncBN only when the flag is given):
    # BN statistics are scoped to the data-parallel device slices, not the
    # global batch (models/norm.py grouped mode).
    data_parallel = max(1, n_devices // max(1, cfg.model_parallel))
    # --conv_impl: the encoder's conv-block path (ops/pallas_conv.py).
    # Resolved HERE, with the startup banner naming the resolution and the
    # reason (the data_placement ladder convention) — a silent degradation
    # must be discoverable from the log
    conv_impl, conv_reason = resolve_conv_impl(
        cfg.conv_impl, cfg.model, cfg.batch_size, cfg.size, n_devices,
        bf16=cfg.bf16,
    )
    logging.info(
        "%s",
        config_lib.impl_resolution_banner(
            "conv_impl", cfg.conv_impl, conv_impl, conv_reason
        ),
    )
    model = SupConResNet(
        model_name=cfg.model, head=cfg.head, feat_dim=cfg.feat_dim,
        dtype=dtype, sync_bn=cfg.syncBN, remat=cfg.remat,
        bn_local_groups=1 if cfg.syncBN else data_parallel,
        conv_impl=conv_impl,
    )
    # --ngpu auto -> the mesh's data-parallel size; an explicit mismatch is
    # promoted from a log-only warning to a startup banner naming the
    # effective-LR consequence (config.ngpu_mismatch_banner)
    grad_div = config_lib.resolve_ngpu(cfg.ngpu, data_parallel)
    if grad_div != data_parallel:
        logging.warning(
            "%s",
            config_lib.ngpu_mismatch_banner(
                grad_div, data_parallel, cfg.learning_rate
            ),
        )
    schedule = make_lr_schedule(
        learning_rate=cfg.learning_rate, epochs=cfg.epochs,
        steps_per_epoch=steps_per_epoch, cosine=cfg.cosine,
        lr_decay_rate=cfg.lr_decay_rate, lr_decay_epochs=cfg.lr_decay_epochs,
        warm=cfg.warm, warm_epochs=cfg.warm_epochs, warmup_from=cfg.warmup_from,
    )
    tx = make_optimizer(
        schedule, momentum=cfg.momentum, weight_decay=cfg.weight_decay,
        optimizer=cfg.optimizer,
    )
    state = create_train_state(
        model, tx, jax.random.key(cfg.seed),
        jnp.zeros((2, cfg.size, cfg.size, 3), jnp.float32),
    )
    loss_impl, loss_reason = resolve_loss_impl_reasoned(
        cfg.loss_impl, cfg.batch_size, n_devices, cfg.model_parallel,
        moco_queue=cfg.moco_queue,
    )
    logging.info(
        "%s",
        config_lib.impl_resolution_banner(
            "loss_impl", cfg.loss_impl, loss_impl, loss_reason
        ),
    )
    step_cfg = SupConStepConfig(
        method=cfg.method, temperature=cfg.temp,
        sec=cfg.sec, sec_wei=cfg.sec_wei, l2reg=cfg.l2reg, l2reg_wei=cfg.l2reg_wei,
        norm_momentum=cfg.norm_momentum, epochs=cfg.epochs,
        steps_per_epoch=steps_per_epoch, grad_div=float(grad_div),
        loss_impl=loss_impl,
        health=cfg.health_freq > 0,
        health_freq=max(1, cfg.health_freq),
        online_probe=cfg.online_probe == "on",
    )
    return model, schedule, tx, state, step_cfg


def attach_online_probe(cfg: config_lib.SupConConfig, state, n_cls: int):
    """``(state_with_probe_slots, OnlineProbe)`` for a ``--online_probe on``
    run: the classifier head + its optimizer (train/supcon_step.py), with
    the trainable probe state attached to the TrainState so it rides the
    jitted update, the donation discipline, and the checkpoint ``probe``
    payload. ``n_cls`` comes from the dataset's own labels, so 'path' trees
    need no extra flag."""
    spec, params, opt_state = build_online_probe(
        cfg.model, MODEL_DICT[cfg.model][1], n_cls, cfg.probe_lr,
        seed=cfg.seed,
    )
    return state.replace(probe_params=params, probe_opt_state=opt_state), spec


def make_fused_update(
    model, tx, schedule, step_cfg, aug_cfg, mesh, state_example,
    metric_ring=None, resident=False, window_batches=None, probe=None,
    recipe=None,
):
    """augment(two crops) + train step as one GSPMD program.

    ``base_key`` is the run's base PRNG key, passed UNCHANGED every step: the
    per-step key is ``fold_in(base_key, state.step)`` INSIDE the program.
    Deriving it on the host (`fold_in` per step) costs a host->device scalar
    transfer per call — ~5 ms/step on a tunneled chip, where it throttled the
    small probe/CE steps (docs/PERF.md); ``state.step`` equals the driver's
    global step, so the key stream (and therefore training) is bit-identical.

    ``metric_ring`` (an ops/metrics.MetricRing) switches the program to ring
    telemetry: ``update(state, ring, images, labels, key) -> (state, ring)``
    with the step's metrics written into row ``state.step % window`` of the
    donated ring instead of being returned as ~7 live device scalars — the
    flush then needs ONE contiguous D2H per window (docs/PERF.md zero-sync
    telemetry). ``None`` keeps the scalar-returning signature (bench.py, the
    dryrun modes, and the distributed-equivalence tests).

    ``resident`` switches the data arguments from one host-fed batch to the
    device-resident ``[steps, batch, ...]`` epoch buffers
    (data/device_store.py): the program slices its own batch at
    ``state.step % steps_per_epoch`` (train/supcon_step.epoch_position) so
    the hot loop carries NO per-step host work or transfer. The buffers are
    deliberately NOT donated — every step of the epoch reads them.
    ``window_batches`` (with ``resident=True``) narrows the buffers to one
    streaming ``[window_batches, batch, ...]`` window (a WindowStore): the
    in-program position becomes ``epoch_position % window_batches``, valid
    because windows are aligned to multiples of the window length.

    ``probe`` (an OnlineProbe, required iff ``step_cfg.online_probe``) adds
    the detached online-probe update to the same compiled program
    (train/supcon_step.py) — its metrics ride the ring like everything else.

    ``recipe`` (a recipes/ Recipe) swaps the loss head inside the same
    compiled program — predictor update, EMA transition, and queue rotation
    all ride the one dispatch (train/supcon_step.make_train_step). ``None``
    keeps the pre-recipe inline contrastive step.
    """
    train_step = make_train_step(
        model, tx, schedule, step_cfg, mesh=mesh, probe=probe, recipe=recipe
    )
    repl = replicated_sharding(mesh)
    state_sh = state_sharding(mesh, state_example)
    if resident:
        data_sh = (
            epoch_buffer_sharding(mesh, 5), epoch_buffer_sharding(mesh, 2),
        )
    else:
        data_sh = (batch_sharding(mesh, 4), batch_sharding(mesh, 1))

    def core(state: TrainState, images_arg, labels_arg, base_key):
        if resident:
            pos = epoch_position(state.step, step_cfg.steps_per_epoch)
            if window_batches is not None:
                pos = pos % window_batches
            images_u8, labels = slice_epoch_step(images_arg, labels_arg, pos)
        else:
            images_u8, labels = images_arg, labels_arg
        key = jax.random.fold_in(base_key, state.step)
        views = two_crop_batch(key, images_u8, aug_cfg)
        return train_step(state, views, labels)

    if metric_ring is None:
        return jax.jit(
            core,
            in_shardings=(state_sh, *data_sh, repl),
            out_shardings=(state_sh, repl),
            donate_argnums=(0,),
        )

    def ring_update(state: TrainState, ring, images_arg, labels_arg, base_key):
        new_state, metrics = core(state, images_arg, labels_arg, base_key)
        return new_state, metric_ring.write(ring, metrics, state.step)

    return jax.jit(
        ring_update,
        in_shardings=(state_sh, repl, *data_sh, repl),
        out_shardings=(state_sh, repl),
        donate_argnums=(0, 1),
    )


TB_ITER_SCALARS = (  # reference per-iter scalars, main_supcon.py:327-333
    "norm_mean", "norm_var", "record_norm_mean", "loss_sec", "loss_l2reg",
)

# training-health TB tags (docs/OBSERVABILITY.md "Training health"): the
# ring's health/probe columns, logged at the TRUE global step like info/*
# so a collapse correlates directly against the loss curves. NaN sentinel
# rows (non-health steps) are skipped host-side. Recipe metric columns
# (recipes/: the VICReg term breakdown) land under recipe/* — the static
# map covers every recipe's keys; runs without them simply never match.
EXTRA_TB_TAGS = {
    **{k: "health/" + k[len("health_"):] for k in HEALTH_METRIC_KEYS},
    **{k: "probe/" + k[len("probe_"):] for k in ONLINE_PROBE_METRIC_KEYS},
    **{k: "recipe/" + k for k in recipes_lib.ALL_RECIPE_METRIC_KEYS},
}


def train_one_epoch(
    epoch, loader, update_fn, state, mesh, base_key, cfg, tb, steps_per_epoch,
    tracer=None, start_step=0, telemetry=None, store=None, compile_span=False,
    health_monitor=None, gauges=None,
):
    """One epoch (reference train(), main_supcon.py:242-351).

    Metric handling: the jitted update writes every step's metrics into a
    device-side ring (``update_fn(state, ring, images, labels, key)``); at
    each ``print_freq`` boundary the ring is SNAPSHOTTED (device-side copy —
    later steps donate the ring buffer) and the window job — ONE contiguous
    D2H, NaN check, meters, TB, the progress log line — runs on the
    telemetry executor. With ``--telemetry async`` (default) the main thread
    never blocks on observability; ``sync`` runs the same job inline (the
    pre-ring semantics). Either way the reference's observability contract
    holds — ``info/*`` TB scalars every iteration (main_supcon.py:327-333)
    and a loss meter averaging ALL steps (main_supcon.py:320) — without the
    reference's per-iter ``.item()`` sync point.

    ``start_step > 0`` is the mid-epoch resume path: the loader skips the
    already-consumed prefix of the epoch's deterministic permutation and the
    step indices continue from where the preempted run stopped (``state.step``
    was restored from the checkpoint, so the in-program per-step PRNG keys
    line up with the uninterrupted run). The ring is transient (never
    checkpointed); a fresh one is created here each epoch.

    ``store`` (a data/device_store DeviceStore or WindowStore) switches the
    epoch to the device-resident data path: every step dispatches against
    the resident buffers ``store.batch_buffers(epoch, idx)`` returns — the
    whole cached epoch for a DeviceStore (one index upload + compiled
    shuffle-gather at epoch start), or the streaming window containing
    ``idx`` for a WindowStore (one H2D per window, the next window staged
    by its prefetch thread) — while ``update_fn`` (built with
    ``resident=True``) slices its own batch from them on device. No host
    gather, no per-step H2D either way. The permutation source is the same
    ``loader``, so batch composition is bit-identical in every placement;
    under resume the slice position follows the restored step counter, so
    ``start_step`` only sets where this host loop begins (and which window
    is fetched first).

    Each flush boundary also checks the preemption flag (utils/preempt.py)
    ON THE MAIN THREAD — the collective decision never depended on the D2H
    completing; the executor is drained before returning so the emergency
    checkpoint in :func:`run` sees complete meters. A non-finite loss
    detected by a background flush re-raises here at the next boundary (at
    most one window late; docs/RESILIENCE.md).

    Returns ``(state, loss_avg, last_metrics, preempted_at)`` where
    ``preempted_at`` is the number of epoch steps completed when preemption
    was observed, or ``None`` for a full epoch.
    """
    owns_telemetry = telemetry is None
    if owns_telemetry:
        telemetry = TelemetrySession(
            cfg.print_freq,
            metric_keys(health=cfg.health_freq > 0,
                        online_probe=cfg.online_probe == "on",
                        extra=recipes_lib.recipe_metric_keys(
                            getattr(cfg, "recipe", "simclr"))),
            cfg.telemetry,
        )
    batch_time, data_time, losses = AverageMeter(), AverageMeter(), AverageMeter()
    end = time.time()
    last_host = {}  # most recently flushed metrics, as python floats
    bsz = cfg.batch_size
    telemetry.start_window_clock()
    ring_buf = telemetry.init_buffer(replicated_sharding(mesh))

    def submit_window(boundary_idx, step_hint):
        """One ``flush_boundary`` (utils/telemetry.py: meter the window on
        the main thread — same aggregate semantics as the reference's
        per-iter meter, main_supcon.py:336-337, amortized over print_freq
        steps — snapshot + queue the one-transfer flush, observe failures
        collectively). The job NaN-checks, meters, TB-logs every step, and
        emits the progress line. ``bt`` arrives snapshotted from the main
        thread (flush_boundary), and ``dt`` is snapshotted here at the
        boundary: the main thread keeps mutating both meters while the
        async job runs, so a worker-side read would log a later window's
        (possibly torn) numbers."""
        dt = (data_time.val, data_time.avg)

        def consume(fetched, bt):
            for (idx_f, gstep_f), m in fetched:
                check_finite_loss(m["loss"], gstep_f, cfg.nan_guard)
                losses.update(m["loss"], bsz)
                if is_main_process() and tb is not None:
                    # the TRUE global step — same coordinate as the tracer,
                    # the checkpoint meta, and the preemption/rollback log
                    # lines, so a failure event correlates directly against
                    # the curves
                    it = (epoch - 1) * steps_per_epoch + idx_f
                    for name in TB_ITER_SCALARS:
                        tb.log_value(f"info/{name}", m[name], it)
                    for name, tag in EXTRA_TB_TAGS.items():
                        # NaN = the lax.cond sentinel for a non-health step
                        if name in m and math.isfinite(m[name]):
                            tb.log_value(tag, m[name], it)
                last_host.clear()
                last_host.update(m)
            if health_monitor is not None:
                # windowed collapse/divergence evaluation (utils/guard.py):
                # emits health_window/health_alarm recorder events, stamps
                # the sidecar gauges, and under --health_policy abort raises
                # here on the telemetry thread — surfaced COLLECTIVELY at
                # the next boundary as failure code 3, like the NaN check
                health_monitor.ingest(
                    [(gstep_f, m) for (_, gstep_f), m in fetched],
                    gauges=gauges,
                )
            logging.info(
                "Train: [%d][%d/%d]\tBT %.3f (%.3f)\tDT %.3f (%.3f)\t"
                "loss %.3f (%.3f)\tnorm_mean %.3f (record: %.3f) var %.3f",
                epoch, boundary_idx + 1, steps_per_epoch,
                bt[0], bt[1], dt[0], dt[1], losses.val, losses.avg,
                last_host["norm_mean"], last_host["record_norm_mean"],
                last_host["norm_var"],
            )

        telemetry.flush_boundary(ring_buf, consume, batch_meter=batch_time,
                                 step_hint=step_hint)

    def epoch_loss_avg():
        return losses.avg if losses.count else last_host.get("loss", 0.0)

    # both loop shapes iterate range(start_step, steps_per_epoch) — an
    # oversized resume offset (changed geometry) must raise, not silently
    # complete a zero-step epoch
    loader.check_start_step(start_step)
    batches = None if store is not None else loader.epoch(
        epoch, start_step=start_step
    )
    try:
        for idx in range(start_step, steps_per_epoch):
            if batches is not None:
                images_u8, labels = next(batches)
            data_time.update(time.time() - end)  # resident: nothing staged
            global_step = (epoch - 1) * steps_per_epoch + idx
            # the ONE per-run instrumented dispatch: the first call's
            # duration is dominated by trace+XLA compile (dispatch is async,
            # so steady-state calls return in microseconds) — the flight
            # recorder's main:compile phase, wrapped around ONLY the update
            # call (the store's epoch_gather/window_swap record on main:data,
            # and main:* phase spans never nest across tracks). Every later
            # step takes the nullcontext arm: no span records in the hot
            # loop.
            span = (
                tracing.span("first_step", track="main:compile",
                             step=global_step)
                if compile_span and idx == start_step
                else contextlib.nullcontext()
            )
            # per-step key = fold_in(base_key, state.step) INSIDE the program
            # (state.step == global_step); see make_fused_update
            if batches is None:
                epoch_images, epoch_labels = store.batch_buffers(epoch, idx)
                with span:
                    state, ring_buf = update_fn(
                        state, ring_buf, epoch_images, epoch_labels, base_key
                    )
            else:
                batch = shard_host_batch((images_u8, labels), mesh)
                with span:
                    state, ring_buf = update_fn(
                        state, ring_buf, batch[0], batch[1], base_key
                    )
            telemetry.append((idx, global_step), global_step)
            if tracer is not None:
                tracer.step(global_step)

            if (idx + 1) % cfg.print_freq == 0 or idx + 1 == steps_per_epoch:
                submit_window(idx, global_step)
                if idx + 1 < steps_per_epoch and preempt.requested_global():
                    # collective decision — every process calls
                    # requested_global at this same deterministic boundary
                    # (main thread; independent of any in-flight flush), so
                    # all hosts commit to the same preemption step (a
                    # lone-host observation would deadlock the collective
                    # save against peers' train steps). Drain COLLECTIVELY
                    # (drain_global — a host-local raise here would skip the
                    # collective emergency save in run() while peers enter
                    # it) so the meters and that checkpoint see complete
                    # metrics. The last-step boundary falls through instead —
                    # that preemption is an ordinary epoch-boundary save.
                    telemetry.drain_global(global_step)
                    return state, epoch_loss_avg(), dict(last_host), idx + 1
            end = time.time()

        # flush any short-epoch tail, then drain COLLECTIVELY — the
        # epoch-boundary save that follows is collective too (the ordering
        # contract lives on the session)
        telemetry.finish_epoch(
            lambda hint: submit_window(steps_per_epoch - 1, hint),
            epoch * steps_per_epoch - 1,
        )
        return state, epoch_loss_avg(), dict(last_host), None
    finally:
        if batches is not None:
            # an early return (preemption) or a raise abandons the loader's
            # generator mid-epoch; close() stops its prefetch worker
            # (data/pipeline.py handles GeneratorExit) instead of leaving it
            # blocked in q.put()
            batches.close()
        if owns_telemetry:
            telemetry.close()


def enable_compile_cache(compile_cache: str, workdir: str) -> None:
    """Persistent XLA compile cache: restarts/resumes skip the cold compile.

    A cache dir already configured (tests' shared ``.jax_cache``, or a user's
    own setting) wins — overriding it with a per-workdir path would throw the
    warm cache away.
    """
    if not compile_cache or jax.config.jax_compilation_cache_dir:
        return
    path = (
        os.path.join(workdir, ".jax_cache") if compile_cache == "auto"
        else compile_cache
    )
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def run(cfg: config_lib.SupConConfig) -> TrainState:
    setup_distributed()
    # collective saves need every process writing into process 0's run folder
    # (the timestamped name is derived per-process, mesh.broadcast_from_main)
    cfg.save_folder = broadcast_from_main(cfg.save_folder)
    cfg.tb_folder = broadcast_from_main(cfg.tb_folder)
    enable_compile_cache(cfg.compile_cache, cfg.workdir)
    setup_logging(cfg.save_folder, is_main_process())
    mesh = create_mesh(model_parallel=cfg.model_parallel)
    logging.info("mesh: %s over %d devices", dict(mesh.shape), mesh.size)

    ensure_dataset_available(cfg.dataset, cfg.data_folder, cfg.download)
    train_data, _, _ = load_dataset(
        cfg.dataset, cfg.data_folder,
        allow_synthetic_fallback=(cfg.dataset == "synthetic"), size=cfg.size,
        store_size=cfg.store_size, mmap_threshold_mb=cfg.mmap_threshold_mb,
    )
    loader = EpochLoader(
        train_data["images"], train_data["labels"], cfg.batch_size,
        base_seed=cfg.seed, process_index=jax.process_index(),
        process_count=jax.process_count(),
    )
    steps_per_epoch = len(loader)
    # Observability stack (docs/OBSERVABILITY.md, utils/obs.py): the flight
    # recorder writes host-boundary spans to <save_folder>/events.jsonl
    # (+ a Chrome-trace export on close), the stall watchdog turns a
    # non-advancing flush boundary into stack-dump artifacts, and the
    # optional Prometheus sidecar exposes liveness gauges. All host-only:
    # the dispatch-only hot loop gains zero device syncs or transfers
    # (asserted mechanically in tests/test_tracing.py). Built BEFORE the
    # store: placement resolution is the run's FIRST collective, and its
    # placement_decision span + startup clock anchor (the fleet report's
    # alignment ruler, trace_report --fleet) must land on the record.
    obs = RunObservability(cfg, name="supcon")
    # --data_placement: 'device' keeps the uint8 dataset HBM-resident,
    # 'window' streams a double-buffered window (one H2D per window), and
    # 'auto' walks the device->window->host ladder against the budget
    # (--device_budget_mb overrides it) with a startup banner naming any
    # degradation (data/device_store.py)
    try:
        store = device_store.make_store(
            cfg.data_placement, loader, mesh,
            budget_bytes=device_store.budget_override_bytes(cfg.device_budget_mb),
            window_batches=cfg.data_window_batches,
        )
    except BaseException as e:
        # the placement rejection (an explicit --data_placement the
        # budget/ladder refuses) is a DESIGNED raise path that sits
        # before the driver's main try/finally: close the stack here
        # so the recorder still exports and the terminal exit code
        # stamps (the startup-failure post-mortem the stack exists for)
        obs.close(exit_code=exit_code_for(e))
        raise
    obs.staged()  # staging done: reset the watchdog deadline (utils/obs.py)
    # build() emits the loss_impl/conv_impl resolution banners
    model, schedule, tx, state, step_cfg = build(cfg, steps_per_epoch, mesh.size)
    # --recipe: the SSL loss head + its TrainState slots (recipes/). Attach
    # BEFORE any resume restore so the abstract state carries the recipe
    # slots (the probe convention below); slot-free recipes leave the state
    # untouched. The recorded run_recipe event is what offline readers
    # (scripts/health_report.py) key their per-recipe thresholds on.
    state, recipe = recipes_lib.attach_for_config(
        cfg, model, state, schedule=schedule
    )
    logging.info(
        "recipe: %s%s", recipe.name,
        f" (moco_queue={cfg.moco_queue})" if cfg.moco_queue else "",
    )
    probe = None
    if cfg.online_probe == "on":
        # attach BEFORE any resume restore: the abstract state then carries
        # the probe slots, so restore_checkpoint brings the probe payload
        # back (or degrades to the fresh init with a warning)
        state, probe = attach_online_probe(
            cfg, state, int(train_data["labels"].max()) + 1
        )
        logging.info(
            "online probe: %d-class linear head on stop_gradient encoder "
            "features (lr %g)", int(train_data["labels"].max()) + 1,
            cfg.probe_lr,
        )

    start_epoch, start_step = 1, 0
    if cfg.ckpt:
        # warm start: model variables only (main_supcon.py:216-220)
        variables = load_pretrained_variables(
            cfg.ckpt, {"params": state.params, "batch_stats": state.batch_stats}
        )
        state = state.replace(
            params=variables["params"], batch_stats=variables["batch_stats"]
        )
        logging.info("load model from %s ...", cfg.ckpt)
    meta = {}
    if cfg.resume:
        resume_path = resolve_resume_path(cfg.resume)
        # mesh= makes the restore ELASTIC: orbax reshards onto THIS run's
        # mesh on load, so a checkpoint saved under a different device
        # count resumes here (the supervisor's restart-resized decision;
        # _warn_mesh_change names the BN/ngpu consequences). recipe= is the
        # cross-recipe hygiene key: a checkpoint whose recorded recipe
        # differs restores the encoder trajectory but degrades the recipe
        # slots to fresh init, loudly (utils/checkpoint.py).
        state, meta = restore_checkpoint(
            resume_path, state, mesh=mesh, recipe=recipe.name,
            moco_queue=cfg.moco_queue,
        )
        # mid-epoch emergency save (utils/preempt.py): re-enter the epoch at
        # the first unconsumed batch of its deterministic permutation
        start_epoch, start_step = resume_position(meta, steps_per_epoch)
        logging.info(
            "resumed from %s at epoch %d step %d",
            resume_path, start_epoch, start_step,
        )

    aug_cfg = make_augment_config(cfg)
    # One telemetry session per run: the device-side metric ring (written
    # inside the jitted update) + the background flush executor the epoch
    # loop hands each print_freq window to (utils/telemetry.py). The
    # watchdog/gauges ride its flush boundaries.
    telemetry = TelemetrySession(
        cfg.print_freq,
        metric_keys(health=step_cfg.health, online_probe=step_cfg.online_probe,
                    extra=recipe.metric_keys),
        cfg.telemetry,
        watchdog=obs.watchdog, gauges=obs.gauges,
    )
    # durable recipe marker on the recorder stream: offline readers
    # (scripts/health_report.py) pick their per-recipe collapse signatures
    # off this event instead of guessing from the metric columns
    tracing.event(
        "run_recipe", track="main:guard", recipe=recipe.name,
        moco_queue=cfg.moco_queue,
    )

    def build_update(lr_scale: float):
        """The fused jitted update; ``lr_scale != 1`` (the NaN-rollback
        damping) rescales the whole schedule — optimizer chain structure is
        unchanged, so existing opt_states restore into it directly."""
        store_kwargs = dict(
            resident=store is not None,
            window_batches=None if store is None else store.window_batches,
            probe=probe, recipe=recipe,
        )
        if lr_scale == 1.0:
            return make_fused_update(
                model, tx, schedule, step_cfg, aug_cfg, mesh, state,
                metric_ring=telemetry.ring, **store_kwargs,
            )
        scaled = lambda s, sc=lr_scale: schedule(s) * sc  # noqa: E731
        return make_fused_update(
            model,
            make_optimizer(
                scaled, momentum=cfg.momentum,
                weight_decay=cfg.weight_decay, optimizer=cfg.optimizer,
            ),
            scaled, step_cfg, aug_cfg, mesh, state,
            metric_ring=telemetry.ring, **store_kwargs,
        )

    # failure policy (utils/guard.py): what a NonFiniteLossError does to the
    # run. Rollback damping is RUN state, not config — it rides checkpoint
    # meta (extra_meta below) so a preempted/crashed run resumes at the
    # damped LR with its rollback budget intact, instead of silently
    # reverting to the LR that NaN'd in the first place.
    policy = FailurePolicy(cfg.nan_policy)
    try:
        policy.lr_scale = float(meta.get("lr_scale") or 1.0)
        policy.rollbacks = int(meta.get("rollbacks") or 0)
    except (TypeError, ValueError):
        pass  # hand-edited meta: keep the fresh policy
    if policy.lr_scale != 1.0:
        logging.warning(
            "resumed with rollback damping: lr_scale %.3g after %d "
            "rollbacks", policy.lr_scale, policy.rollbacks,
        )

    def policy_meta():
        # the recipe name/queue geometry ride checkpoint meta so a resume
        # under a DIFFERENT recipe is detectable (utils/checkpoint.py
        # cross-recipe hygiene) without probing payload tree structure
        return {"lr_scale": policy.lr_scale, "rollbacks": policy.rollbacks,
                "recipe": recipe.name, "moco_queue": cfg.moco_queue}

    update_fn = build_update(policy.lr_scale)
    tb = TBLogger(cfg.tb_folder, enabled=is_main_process())
    base_key = jax.random.key(cfg.seed + 1)
    tracer = StepTracer(
        cfg.trace_dir, cfg.trace_start_step, cfg.trace_steps,
        enabled=is_main_process(),
    )

    # The per-epoch crash backup as ONE jitted program: mapping bare
    # ``jnp.copy`` over the tree dispatches ~30 op-by-op ``jit(copy)``
    # programs whose caches all miss AGAIN at epoch 2 (the post-update state
    # carries mesh shardings the fresh epoch-1 state lacked), costing ~20 s
    # of sub-second compiles that the persistent cache never keeps. One
    # program = one compile per sharding layout, persisted across runs —
    # shared with the restore path's buffer re-owning copy.
    copy_state = jit_copy_tree

    # NOTE on preemption in multi-process jobs: the decision to stop is
    # collective (preempt.requested_global), so the emergency save below
    # sees all processes arrive (docs/RESILIENCE.md).
    preempt.install()
    # captured explicitly for the terminal exit-code gauge: sys.exc_info()
    # inside the finally would also see an exception being HANDLED in an
    # enclosing frame (a caller's retry wrapper), misclassifying a clean
    # run as that outer failure
    exit_exc = None
    try:
        for epoch in range(start_epoch, cfg.epochs + 1):
            t1 = time.time()
            ss = start_step if epoch == start_epoch else 0
            # The update donates the incoming state's buffers, so the pre-epoch
            # `state` object is DELETED after the first step — an un-donated
            # on-device copy (one HBM->HBM copy per epoch) is what the crash
            # handler can still save.
            backup = copy_state(state) if cfg.nan_guard else None
            obs.set_epoch(epoch)
            try:
                with tracing.span("epoch", track="main:epoch", epoch=epoch):
                    state, loss_avg, metrics, preempted_at = train_one_epoch(
                        epoch, loader, update_fn, state, mesh, base_key, cfg,
                        tb, steps_per_epoch, tracer=tracer, start_step=ss,
                        telemetry=telemetry, store=store,
                        compile_span=(epoch == start_epoch),
                        health_monitor=obs.health, gauges=obs.gauges,
                    )
            except NonFiniteLossError:
                # emergency save of the epoch-top state so --resume can
                # restart after the root cause is addressed (failure
                # detection, SURVEY.md §5 — absent upstream). step_in_epoch
                # = ss: after a mid-epoch resume the backup sits mid-epoch,
                # and a resume from this save must not replay consumed
                # batches. NOTE: orbax multi-process saves are collective —
                # EVERY process calls save_checkpoint (orbax coordinates who
                # writes; meta.json is process-0-gated inside); only logging
                # stays process-0.
                save_checkpoint(
                    cfg.save_folder, f"crash_epoch_{epoch}", backup,
                    config=config_lib.config_dict(cfg), epoch=epoch - 1,
                    step_in_epoch=ss, extra_meta=policy_meta(),
                )
                if is_main_process():
                    logging.error("non-finite loss: saved crash_epoch_%d", epoch)
                if not policy.should_rollback():
                    raise
                # --nan_policy rollback: restore the epoch-boundary backup,
                # SKIP the poisoned epoch (the step counter jumps to this
                # epoch's end so the LR schedule position and the per-step
                # PRNG stream stay aligned with the epoch number), damp the
                # LR, and keep training. The applied LR reads the
                # optimizer's OWN ScaleByScheduleState counter, so the jump
                # must realign that too — not just state.step — or the
                # schedule silently lags the skip.
                target = epoch * steps_per_epoch
                state = backup.replace(
                    step=backup.step + (target - int(backup.step)),
                    opt_state=realign_schedule_count(backup.opt_state, target),
                )
                tracing.event(
                    "nan_rollback", track="main:guard", epoch=epoch,
                    rollbacks=policy.rollbacks, lr_scale=policy.lr_scale,
                )
                update_fn = build_update(policy.lr_scale)
                logging.warning(
                    "nan_policy=rollback (%d/%d): epoch %d skipped from its "
                    "boundary backup, lr scaled to %.3g",
                    policy.rollbacks, policy.max_rollbacks, epoch,
                    policy.lr_scale,
                )
                continue
            if preempted_at is not None:
                # SIGTERM/SIGINT observed (collectively) at a flush boundary
                # mid-epoch: blocking emergency save carrying the intra-epoch
                # position, then the distinct exit code. run()'s finally
                # still drains/uninstalls/closes on the way out.
                tracing.event(
                    "preempt_exit", track="main:guard", epoch=epoch,
                    step_in_epoch=preempted_at,
                )
                preempt.emergency_save_and_exit(
                    cfg.save_folder,
                    f"preempt_epoch_{epoch}_step_{preempted_at}", state,
                    config_lib.config_dict(cfg), epoch - 1,
                    step_in_epoch=preempted_at, extra_meta=policy_meta(),
                )
            t2 = time.time()
            logging.info("epoch %d, total time %.2f", epoch, t2 - t1)
            if is_main_process():
                tb.log_value("loss", loss_avg, epoch)
                tb.log_value(
                    "learning_rate",
                    float(schedule((epoch - 1) * steps_per_epoch)) * policy.lr_scale,
                    epoch,
                )
            if epoch % cfg.save_freq == 0:
                # collective on all processes (see crash handler note); async
                # write: D2H serialization is synchronous (safe with buffer
                # donation), the disk write overlaps the next epochs
                save_checkpoint(
                    cfg.save_folder, f"ckpt_epoch_{epoch}", state,
                    config=config_lib.config_dict(cfg), epoch=epoch, block=False,
                    extra_meta=policy_meta(),
                )
            if preempt.requested_global():
                # epoch-boundary preemption (the signal landed in the last
                # flush window), decided collectively like the mid-epoch
                # check: persist this epoch unless the scheduled save above
                # already did (name=None skips the write but still drains
                # the async save so its meta stamps), then exit.
                tracing.event(
                    "preempt_exit", track="main:guard", epoch=epoch,
                )
                preempt.emergency_save_and_exit(
                    cfg.save_folder,
                    None if epoch % cfg.save_freq == 0
                    else f"preempt_epoch_{epoch}",
                    state, config_lib.config_dict(cfg), epoch,
                    extra_meta=policy_meta(),
                )
        wait_for_saves()
        save_checkpoint(
            cfg.save_folder, "last", state,
            config=config_lib.config_dict(cfg), epoch=cfg.epochs,
            extra_meta=policy_meta(),
        )
    except BaseException as e:
        exit_exc = e
        raise
    finally:
        # On failure too: stop/flush an active profiler trace (it is most
        # valuable exactly when the epoch loop died), stop the telemetry
        # worker (close never raises — a pending flush error must not mask
        # the real failure), stop the window store's prefetch worker (a
        # pending shadow-buffer upload nobody will read must not stall the
        # exit-75 path), and drain in-flight async checkpoint writes so
        # finished payloads get their meta stamp.
        preempt.uninstall()
        telemetry.close()
        if store is not None:
            store.close()
        tracer.close()
        tb.close()
        wait_for_saves()
        # observability teardown LAST (after the final wait_for_saves so
        # the checkpoint_commit span lands in the record and the watchdog
        # still watches a wedging drain) — the ordering lives on obs.close.
        # The in-flight exception (if any) classifies the exit for the
        # terminal gauge + run_exit event (utils/guard.py exit-code surface).
        obs.close(exit_code=exit_code_for(exit_exc))
    sync_processes("supcon_run_end")
    return state


def main(argv=None):
    cfg = config_lib.parse_supcon(argv)
    # typed exit codes (docs/RESILIENCE.md): health 3 > flush 2 > NaN 1,
    # preemption 75 via SystemExit — the supervisor's classification input
    exit_with_code(lambda: run(cfg))


if __name__ == "__main__":
    main()
