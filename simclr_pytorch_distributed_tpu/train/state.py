"""Functional train state + the torch-matching SGD optimizer chain.

The reference's mutable training state is scattered across the DDP module, the
torch SGD optimizer, and fields smuggled into the argparse namespace (the SEC
EMA ``opt.record_norm_mean``, ``main_supcon.py:150,304-307``). Here it is one
immutable pytree carried through the jitted step.

``make_optimizer`` reproduces ``torch.optim.SGD(lr, momentum, weight_decay)``
over ALL parameters (reference ``util.py:79-84`` — note BN scale/bias are weight-
decayed too, which matters for the published recipe):
``d_p = g + wd*p; buf = mu*buf + d_p; p -= lr*buf`` maps onto
``add_decayed_weights -> trace(momentum) -> scale_by_learning_rate(schedule)``.
"""

from __future__ import annotations

from typing import Any, Callable, Union

import jax
import jax.numpy as jnp
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array  # 0-based global iteration
    params: Any
    batch_stats: Any
    opt_state: Any
    # SEC feature-norm EMA (reference opt.record_norm_mean, main_supcon.py:304-307).
    record_norm_mean: jax.Array
    # Online linear probe (--online_probe, train/supcon_step.py): a detached
    # classifier head trained by the same compiled update on stop_gradient
    # encoder features, so probe top-1 streams through the metric ring live
    # instead of waiting for the post-hoc main_linear.py pass. ``None`` (an
    # empty pytree node) when the probe is off — the state tree, checkpoint
    # layout, and jit cache keys are then exactly the pre-probe ones. When
    # present the pair is checkpointed as its own ``probe`` payload
    # (utils/checkpoint.py), so resume restores the probe mid-trajectory and
    # probe-off consumers (warm start, serving) never see it.
    probe_params: Any = None
    probe_opt_state: Any = None
    # SSL-recipe slots (--recipe, simclr_pytorch_distributed_tpu/recipes/):
    # ``recipe_params`` holds a recipe's extra TRAINABLE tree (the BYOL/
    # SimSiam predictor head) updated by its own optimizer chain
    # (``recipe_opt_state``) inside the same compiled step, and
    # ``recipe_state`` holds non-trainable recipe state transitioned
    # post-step (the BYOL EMA target network, the MoCo-style negative-queue
    # ring). All ``None`` for the contrastive recipes without a queue — the
    # state tree, checkpoint layout, and jit cache keys are then exactly the
    # pre-recipe ones (the probe-slot contract). When present the triple is
    # checkpointed as its own ``recipe`` payload (utils/checkpoint.py), so
    # cross-recipe resumes degrade loudly to fresh recipe-slot init instead
    # of restoring a mismatched tree.
    recipe_params: Any = None
    recipe_opt_state: Any = None
    recipe_state: Any = None


def make_optimizer(
    learning_rate: Union[float, Callable],
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    optimizer: str = "sgd",
) -> optax.GradientTransformation:
    """``sgd`` reproduces the reference recipe (module docstring), including
    its weight-decay-everything semantics. ``lars`` (layer-wise adaptive rate
    scaling) is the standard choice for the large-global-batch configs the
    reference never reached (SimCLR ImageNet bs=4096, BASELINE.json
    configs[4]); unlike the sgd path it follows the LARS-paper convention of
    applying BOTH weight decay and trust-ratio adaptation to kernels only
    (1-D params — biases, BN scale/bias — get plain SGD+momentum)."""
    if optimizer == "lars":
        # Standard LARS recipe (SimCLR/LARS papers): biases and BN
        # scale/bias (all 1-D tensors) are EXCLUDED from both weight decay
        # and trust-ratio adaptation — otherwise zero-init offsets freeze
        # near zero and BN scales train with a ~1000x smaller effective lr.
        def kernels_only(params):
            return jax.tree.map(lambda p: jnp.ndim(p) > 1, params)

        return optax.lars(
            learning_rate=learning_rate,
            weight_decay=weight_decay,
            weight_decay_mask=kernels_only,
            trust_ratio_mask=kernels_only,
            momentum=momentum,
            nesterov=False,
        )
    if optimizer != "sgd":
        raise ValueError(f"optimizer not supported: {optimizer}")
    parts = []
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    if momentum:
        parts.append(optax.trace(decay=momentum, nesterov=False))
    parts.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*parts)


def realign_schedule_count(opt_state, step: int):
    """Set every ``ScaleByScheduleState.count`` inside ``opt_state`` to
    ``step``.

    The applied LR is ``schedule(opt_state.count)``, NOT ``schedule
    (state.step)`` — the two advance in lockstep normally, but any manual
    step jump (the NaN-rollback epoch skip, train/supcon.py) must move BOTH,
    or training silently runs the schedule an epoch behind the position the
    logs report. Works for both optimizer chains (sgd and lars place the
    state at different chain indexes) and is a no-op for constant-LR chains
    (no schedule state to find).
    """
    is_sched = lambda s: isinstance(s, optax.ScaleByScheduleState)  # noqa: E731

    def fix(s):
        if is_sched(s):
            # derive from the existing count: keeps dtype AND the
            # mesh-replicated sharding a fresh scalar would lack
            return s._replace(count=(s.count * 0 + step).astype(s.count.dtype))
        return s

    return jax.tree.map(fix, opt_state, is_leaf=is_sched)


def create_train_state(
    model,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    example_input: jax.Array,
) -> TrainState:
    variables = model.init(rng, example_input, train=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        record_norm_mean=jnp.zeros((), jnp.float32),
    )
