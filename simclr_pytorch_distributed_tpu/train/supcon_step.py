"""The distributed SimCLR/SupCon train step, as one jitted SPMD program.

TPU-native redesign of the reference hot loop (``main_supcon.py:242-351``):

- the reference runs per-GPU processes that forward a LOCAL half-batch, then
  ``all_gather`` the projection features, re-insert the local grad-carrying
  tensor (hardcoded to ranks 0/1, ``main_supcon.py:268-279``), and rely on DDP to
  mean-reduce gradients. Here the step is written over the logically GLOBAL
  batch; with the batch sharded over the ``data`` mesh axis, XLA materializes the
  feature gather for the O((2B)^2) loss matmul and the gradient reductions as ICI
  collectives — ``lax.all_gather`` is differentiable by construction, so no
  re-insertion trick exists, and it generalizes past 2 devices (fixing reference
  bug: hardcoded world=2);
- SupCon actually works distributed: labels live in the same global program as
  the features (the reference crashes — local labels vs gathered features,
  ``main_supcon.py:287-288`` -> ``losses.py:46-47``);
- feature ordering, normalize-after-gather, the SEC EMA, and the aux-loss linear
  ramps all match the reference step (see inline cites).

Gradient-scale fidelity: in the reference, each rank's backward flows only
through its own feature rows and DDP MEANS gradients over ``ngpu`` ranks, so the
applied gradient is (1/ngpu) of the true global-batch gradient. JAX computes the
exact global gradient, so the loss is multiplied by ``1/grad_div`` (default 2 =
the recipe's ``--ngpu``) before differentiation; weight decay is applied by the
optimizer and is correctly NOT scaled. ``tests/test_distributed.py`` verifies
this equivalence against a simulated per-rank-backward + mean.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from simclr_pytorch_distributed_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from simclr_pytorch_distributed_tpu.ops.losses import supcon_loss
from simclr_pytorch_distributed_tpu.ops.pallas_loss import (
    fused_sharded_supcon_loss,
    fused_supcon_loss,
)
from simclr_pytorch_distributed_tpu.parallel.collectives import ring_supcon_loss
from simclr_pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    replicated_sharding,
    state_sharding,
)
from simclr_pytorch_distributed_tpu.train.state import TrainState


# The step's full metric-dict key set (aux + learning_rate), sorted — the
# column order of the device-side metric ring (ops/metrics.MetricRing): the
# jitted writer and the host reader both derive columns from this one tuple,
# so a metric added to ``train_step`` without extending it fails loudly at
# trace time instead of silently shifting columns.
METRIC_KEYS = (
    "learning_rate", "loss", "loss_l2reg", "loss_sec",
    "norm_mean", "norm_var", "record_norm_mean",
)


def epoch_position(step, steps_per_epoch: int):
    """A step's position within its epoch, derived ON DEVICE from the state's
    global step counter — the resident-data slice index
    (``data/device_store.py``: the step takes the epoch buffer as a
    non-donated arg and slices row ``position`` out of it).

    Valid because every driver maintains ``state.step == (epoch-1) *
    steps_per_epoch + idx`` through ALL control flow: mid-epoch resume
    restores the counter from checkpoint meta, and the NaN-rollback path
    realigns it to the skipped epoch's boundary (train/supcon.py) — so the
    remainder is always the in-epoch index and no extra per-step host scalar
    (which would be an H2D transfer, docs/PERF.md) is needed.
    """
    return jax.lax.rem(step, jnp.int32(steps_per_epoch))


@dataclasses.dataclass(frozen=True)
class SupConStepConfig:
    """Static step configuration (mirrors the reference argparse flags)."""

    method: str = "SimCLR"  # --method {SimCLR, SupCon}
    temperature: float = 0.5  # --temp
    base_temperature: float = 0.07  # fixed, losses.py:90
    contrast_mode: str = "all"
    # aux losses (main_supcon.py:76-82, 295-317)
    sec: bool = False
    sec_wei: float = 0.0
    l2reg: bool = False
    l2reg_wei: float = 0.0
    norm_momentum: float = 1.0
    # ramp denominator: epochs * steps_per_epoch (main_supcon.py:311-317)
    epochs: int = 1000
    steps_per_epoch: int = 1
    # DDP gradient-mean fidelity (see module docstring); the recipe's --ngpu.
    grad_div: float = 2.0
    # 'dense' = XLA O(N^2)-materializing path; 'fused' = flash-style Pallas
    # kernel (ops/pallas_loss.py); 'ring' = ppermute-sharded streaming loss
    # (parallel/collectives.py) that keeps anchors sharded over the 'data'
    # axis — O((2B/P)^2) per-device memory for large global batches.
    # Resolved from the config's 'auto' upstream.
    loss_impl: str = "dense"


def two_view_forward(model, params, batch_stats, images: jax.Array, *, train: bool = True):
    """Forward both views through the encoder+head as ONE batch.

    ``images`` is ``[B, 2, H, W, C]``. Views are flattened view-major —
    rows ``[v1 of all samples; v2 of all samples]`` — the same global layout the
    reference assembles post-gather (``main_supcon.py:276-279``). Both views
    share one BN batch, matching the reference's ``cat([v1, v2])`` forward
    (``main_supcon.py:256,266``).
    """
    B = images.shape[0]
    flat = jnp.transpose(images, (1, 0, 2, 3, 4)).reshape((2 * B,) + images.shape[2:])
    if train:
        feats, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            flat, train=True, mutable=["batch_stats"],
        )
        return feats, mutated["batch_stats"]
    feats = model.apply(
        {"params": params, "batch_stats": batch_stats}, flat, train=False
    )
    return feats, batch_stats


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    schedule: Callable,
    cfg: SupConStepConfig,
    mesh=None,
) -> Callable:
    """Build the pure train step: (state, images[B,2,H,W,C], labels[B]) -> (state, metrics).

    ``mesh`` is required only for ``loss_impl='ring'`` (the shard_map needs an
    explicit mesh; dense/fused run as plain HLO that GSPMD partitions).
    """
    if cfg.loss_impl == "ring" and mesh is None:
        raise ValueError("loss_impl='ring' needs the mesh passed to make_train_step")
    # 'fused' on a multi-device mesh routes through the shard_map-sharded
    # kernel (ops/pallas_loss.py fused_sharded_supcon_loss): anchors stay
    # sharded over 'data', the contrast side is all-gathered, and the logits
    # tiles never leave VMEM. A bare pallas_call has no GSPMD partitioning
    # rule, so without this the kernel would run fully replicated.
    fused_on_mesh = (
        cfg.loss_impl == "fused" and mesh is not None and mesh.size > 1
    )

    def loss_fn(params, state: TrainState, images, labels):
        feats, new_batch_stats = two_view_forward(
            model, params, state.batch_stats, images, train=True
        )
        feats = feats.astype(jnp.float32)
        B = images.shape[0]

        # feature-norm statistics on UNNORMALIZED embeddings (main_supcon.py:298-301)
        norms = jnp.linalg.norm(feats, axis=1)
        norm_mean = jnp.mean(norms)
        norm_var = jnp.mean(jnp.square(norms - norm_mean))

        # SEC EMA: update-then-use, seeded with the first batch's mean
        # (main_supcon.py:304-307; momentum 1.0 degenerates to the batch mean)
        norm_mean_sg = jax.lax.stop_gradient(norm_mean)
        record = jnp.where(
            state.step == 0,
            norm_mean_sg,
            (1.0 - cfg.norm_momentum) * state.record_norm_mean
            + cfg.norm_momentum * norm_mean_sg,
        )
        loss_sec = jnp.mean(jnp.square(norms - record))
        loss_l2reg = jnp.mean(jnp.square(norms))

        # normalize AFTER the (logical) gather (main_supcon.py:283), stack views
        # back to [B_global, 2, D] with f1 = all view-1 rows (:285-286)
        n_fea = feats / jnp.linalg.norm(feats, axis=1, keepdims=True)
        n_features = jnp.stack([n_fea[:B], n_fea[B:]], axis=1)

        if cfg.method not in ("SupCon", "SimCLR"):
            raise ValueError(f"contrastive method not supported: {cfg.method}")
        loss_labels = labels if cfg.method == "SupCon" else None
        if cfg.loss_impl in ("fused", "ring") and cfg.contrast_mode != "all":
            raise ValueError(
                f"loss_impl={cfg.loss_impl!r} implements contrast_mode='all' "
                f"only; got {cfg.contrast_mode!r} — use loss_impl='dense'"
            )
        if cfg.loss_impl == "ring":
            # anchors stay sharded over 'data'; n_fea is already the view-major
            # global row layout the ring expects ([v1 rows; v2 rows]).
            def _ring(rows, lab):
                return ring_supcon_loss(
                    rows, lab, axis_name=DATA_AXIS,
                    temperature=cfg.temperature,
                    base_temperature=cfg.base_temperature, n_views=2,
                )

            if loss_labels is None:
                contrastive = shard_map(
                    lambda r: _ring(r, None),
                    mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(),
                )(n_fea)
            else:
                contrastive = shard_map(
                    _ring, mesh=mesh,
                    in_specs=(P(DATA_AXIS), P()), out_specs=P(),
                )(n_fea, loss_labels)
        elif fused_on_mesh:
            # same row layout and shard_map plumbing as the ring path; the
            # kernel needs check_vma=False (interpret-mode Pallas cannot type
            # kernel-internal constants) — its custom VJP compensates for the
            # per-shard cotangent shares (ops/pallas_loss.py).
            def _fs(rows, lab):
                return fused_sharded_supcon_loss(
                    rows, lab, axis_name=DATA_AXIS,
                    temperature=cfg.temperature,
                    base_temperature=cfg.base_temperature, n_views=2,
                    interpret=jax.default_backend() != "tpu",
                )

            if loss_labels is None:
                contrastive = shard_map(
                    lambda r: _fs(r, None), mesh=mesh,
                    in_specs=P(DATA_AXIS), out_specs=P(), check_vma=False,
                )(n_fea)
            else:
                contrastive = shard_map(
                    _fs, mesh=mesh,
                    in_specs=(P(DATA_AXIS), P()), out_specs=P(),
                    check_vma=False,
                )(n_fea, loss_labels)
        elif cfg.loss_impl == "fused":
            contrastive = fused_supcon_loss(
                n_features, labels=loss_labels,
                temperature=cfg.temperature, base_temperature=cfg.base_temperature,
                # Mosaic compiles only on TPU; anywhere else (CPU tests) the
                # kernel runs under the Pallas interpreter.
                interpret=jax.default_backend() != "tpu",
            )
        else:
            contrastive = supcon_loss(
                n_features, labels=loss_labels,
                temperature=cfg.temperature, base_temperature=cfg.base_temperature,
                contrast_mode=cfg.contrast_mode,
            )

        # linear-ramped aux terms (main_supcon.py:311-317)
        ramp = state.step / (cfg.epochs * cfg.steps_per_epoch)
        loss = contrastive
        if cfg.sec:
            loss = loss + cfg.sec_wei * ramp * loss_sec
        if cfg.l2reg:
            loss = loss + cfg.l2reg_wei * ramp * loss_l2reg

        aux = {
            "loss": loss,  # the reported (unscaled) loss, main_supcon.py:320
            "norm_mean": norm_mean,
            "norm_var": norm_var,
            "record_norm_mean": record,
            "loss_sec": loss_sec,
            "loss_l2reg": loss_l2reg,
        }
        # grad-scale fidelity: DDP means over ngpu ranks (module docstring)
        return loss / cfg.grad_div, (aux, new_batch_stats)

    def train_step(
        state: TrainState, images: jax.Array, labels: jax.Array
    ) -> Tuple[TrainState, dict]:
        grads, (aux, new_batch_stats) = jax.grad(loss_fn, has_aux=True)(
            state.params, state, images, labels
        )
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = dict(aux, learning_rate=jnp.asarray(schedule(state.step)))
        assert tuple(sorted(metrics)) == METRIC_KEYS, sorted(metrics)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_batch_stats,
            opt_state=new_opt_state,
            record_norm_mean=aux["record_norm_mean"],
        )
        return new_state, metrics

    return train_step


def make_sharded_train_step(
    model,
    tx: optax.GradientTransformation,
    schedule: Callable,
    cfg: SupConStepConfig,
    mesh,
    state_shape: Optional[Any] = None,
    donate: bool = True,
) -> Callable:
    """jit the train step over the mesh: state replicated, batch data-sharded.

    Under GSPMD this single program IS the distributed algorithm: XLA inserts the
    feature all-gather for the loss matmul and a gradient reduce over ICI —
    the TPU-native replacement for NCCL all_gather + DDP bucketed all-reduce.
    """
    step = make_train_step(model, tx, schedule, cfg, mesh=mesh)
    repl = replicated_sharding(mesh)

    state_sh = (
        state_sharding(mesh, state_shape) if state_shape is not None else repl
    )
    in_shardings = (
        state_sh,
        batch_sharding(mesh, 5),  # images [B, 2, H, W, C]
        batch_sharding(mesh, 1),  # labels [B]
    )
    return jax.jit(
        step,
        in_shardings=in_shardings,
        out_shardings=(state_sh, repl),
        donate_argnums=(0,) if donate else (),
    )
