"""The distributed SimCLR/SupCon train step, as one jitted SPMD program.

TPU-native redesign of the reference hot loop (``main_supcon.py:242-351``):

- the reference runs per-GPU processes that forward a LOCAL half-batch, then
  ``all_gather`` the projection features, re-insert the local grad-carrying
  tensor (hardcoded to ranks 0/1, ``main_supcon.py:268-279``), and rely on DDP to
  mean-reduce gradients. Here the step is written over the logically GLOBAL
  batch; with the batch sharded over the ``data`` mesh axis, XLA materializes the
  feature gather for the O((2B)^2) loss matmul and the gradient reductions as ICI
  collectives — ``lax.all_gather`` is differentiable by construction, so no
  re-insertion trick exists, and it generalizes past 2 devices (fixing reference
  bug: hardcoded world=2);
- SupCon actually works distributed: labels live in the same global program as
  the features (the reference crashes — local labels vs gathered features,
  ``main_supcon.py:287-288`` -> ``losses.py:46-47``);
- feature ordering, normalize-after-gather, the SEC EMA, and the aux-loss linear
  ramps all match the reference step (see inline cites).

Gradient-scale fidelity: in the reference, each rank's backward flows only
through its own feature rows and DDP MEANS gradients over ``ngpu`` ranks, so the
applied gradient is (1/ngpu) of the true global-batch gradient. JAX computes the
exact global gradient, so the loss is multiplied by ``1/grad_div`` (default 2 =
the recipe's ``--ngpu``) before differentiation; weight decay is applied by the
optimizer and is correctly NOT scaled. ``tests/test_distributed.py`` verifies
this equivalence against a simulated per-rank-backward + mean.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from simclr_pytorch_distributed_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from simclr_pytorch_distributed_tpu.models import LinearClassifier
from simclr_pytorch_distributed_tpu.ops.losses import (
    cross_entropy_loss,
    supcon_loss,
)
from simclr_pytorch_distributed_tpu.ops.metrics import (
    embedding_covariance,
    topk_correct,
)
from simclr_pytorch_distributed_tpu.ops.pallas_loss import (
    fused_sharded_supcon_loss,
    fused_supcon_loss,
)
from simclr_pytorch_distributed_tpu.parallel.collectives import ring_supcon_loss
from simclr_pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    replicated_sharding,
    state_sharding,
)
from simclr_pytorch_distributed_tpu.train.state import TrainState


# The step's base metric-dict key set (aux + learning_rate), sorted — the
# column order of the device-side metric ring (ops/metrics.MetricRing): the
# jitted writer and the host reader both derive columns from this one tuple,
# so a metric added to ``train_step`` without extending it fails loudly at
# trace time instead of silently shifting columns. Health/probe extensions
# below are opt-in per run; :func:`metric_keys` derives the run's full set,
# and the SAME derivation feeds the writer's trace-time assertion and the
# host reader, so the two sides cannot diverge.
METRIC_KEYS = (
    "learning_rate", "loss", "loss_l2reg", "loss_sec",
    "norm_mean", "norm_var", "record_norm_mean",
)

# Representation-health diagnostics (--health_freq > 0): computed INSIDE the
# jitted update from the loss's own normalized embeddings and the step's
# gradients, written into the same donated ring — zero new per-step D2H. On
# non-health steps (step % health_freq != 0) the columns carry an all-NaN
# sentinel row; host consumers (TB tags, the HealthMonitor, gauges) skip it.
HEALTH_METRIC_KEYS = (
    "health_align",      # mean positive-pair cosine (collapse -> 1.0)
    "health_con_top1",   # contrastive top-1: positive is the argmax contrast
    "health_eff_rank",   # exp-entropy of the d x d embedding covariance
    "health_grad_norm",  # global gradient norm (divergence signal)
    "health_neg_max",    # max negative-pair cosine
    "health_neg_mean",   # mean negative-pair cosine (collapse -> 1.0)
    "health_unif",       # Wang-Isola uniformity, log E exp(-2||z_i-z_j||^2)
)

# Online linear probe (--online_probe on): a detached classifier head trained
# by the same compiled update on stop_gradient encoder features; its loss and
# top-1 (percent, over both views) stream through the ring every step.
ONLINE_PROBE_METRIC_KEYS = ("probe_loss", "probe_top1")


def metric_keys(health: bool = False, online_probe: bool = False, extra=()):
    """The run's full sorted ring-key tuple. The drivers and the step builder
    both call this with the SAME config bits, so a flag mismatch between the
    writer and the TelemetrySession reader fails loudly at trace time
    (MetricRing.write's key check) instead of silently shifting columns.
    ``extra`` is the active recipe's own metric-key tuple
    (``recipe.metric_keys``, e.g. the VICReg term breakdown) — same
    derivation on both sides, same loud-failure contract."""
    keys = METRIC_KEYS + tuple(extra)
    if health:
        keys = keys + HEALTH_METRIC_KEYS
    if online_probe:
        keys = keys + ONLINE_PROBE_METRIC_KEYS
    return tuple(sorted(keys))


def epoch_position(step, steps_per_epoch: int):
    """A step's position within its epoch, derived ON DEVICE from the state's
    global step counter — the resident-data slice index
    (``data/device_store.py``: the step takes the epoch buffer as a
    non-donated arg and slices row ``position`` out of it).

    Valid because every driver maintains ``state.step == (epoch-1) *
    steps_per_epoch + idx`` through ALL control flow: mid-epoch resume
    restores the counter from checkpoint meta, and the NaN-rollback path
    realigns it to the skipped epoch's boundary (train/supcon.py) — so the
    remainder is always the in-epoch index and no extra per-step host scalar
    (which would be an H2D transfer, docs/PERF.md) is needed.
    """
    return jax.lax.rem(step, jnp.int32(steps_per_epoch))


def contrastive_health_metrics(emb: jax.Array, grads) -> dict:
    """The :data:`HEALTH_METRIC_KEYS` diagnostics from one batch.

    ``emb`` is the loss's OWN L2-normalized embedding matrix ``[2B, D]`` in
    the view-major global row layout (rows ``[v1 of all samples; v2 of all
    samples]``, so row ``i``'s positive sits at ``(i + B) % 2B``), passed out
    of ``loss_fn``'s aux under ``stop_gradient`` — nothing here is a second
    forward, and on the dense loss path the similarity matmul is the same
    ``dot(emb, emb^T)`` HLO the loss already builds (XLA CSE-able). Runs only
    on health steps: the caller gates it behind ``lax.cond`` on
    ``step % health_freq``, so non-health steps pay neither the ``O((2B)^2)``
    matmul nor the ``d x d`` eigendecomposition.

    A collapsed representation (all embeddings equal) reads as: align -> 1,
    neg_mean/neg_max -> 1, eff_rank -> 1, unif -> 0 (its maximum), con_top1
    -> chance. A diverging one shows up first in ``grad_norm``.
    """
    n = emb.shape[0]
    b = n // 2
    sim = emb @ emb.T  # [2B, 2B] cosine (rows are unit-norm)
    idx = jnp.arange(n)
    pos_idx = (idx + b) % n
    eye = idx[:, None] == idx[None, :]
    pos = pos_idx[:, None] == idx[None, :]
    neg = ~(eye | pos)
    align = jnp.mean(jnp.sum(emb[:b] * emb[b:], axis=1))
    neg_count = jnp.maximum(jnp.sum(neg.astype(jnp.float32)), 1.0)
    neg_mean = jnp.sum(jnp.where(neg, sim, 0.0)) / neg_count
    neg_max = jnp.max(jnp.where(neg, sim, -jnp.inf))
    # contrastive top-1: is the positive the highest-similarity non-self row?
    top1 = 100.0 * jnp.mean(
        (jnp.argmax(jnp.where(eye, -jnp.inf, sim), axis=1) == pos_idx)
        .astype(jnp.float32)
    )
    # Wang-Isola uniformity with t=2 over non-self pairs; ||z_i - z_j||^2 =
    # 2 - 2*cos for unit rows, so the exponent is bounded in [-8, 0].
    unif = jnp.log(
        jnp.sum(jnp.where(eye, 0.0, jnp.exp(4.0 * sim - 4.0)))
        / (n * (n - 1))
    )
    # effective rank = exp(entropy) of the normalized covariance spectrum
    # (uncentered second moment — ops/metrics.embedding_covariance, the
    # construction the VICReg covariance penalty shares in centered form)
    cov = embedding_covariance(emb)
    eig = jnp.clip(jnp.linalg.eigvalsh(cov), 0.0, None)
    p = eig / jnp.maximum(jnp.sum(eig), 1e-12)
    entropy = -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-12)), 0.0))
    return {
        "health_align": align,
        "health_con_top1": top1,
        "health_eff_rank": jnp.exp(entropy),
        "health_grad_norm": optax.global_norm(grads),
        "health_neg_max": neg_max,
        "health_neg_mean": neg_mean,
        "health_unif": unif,
    }


@dataclasses.dataclass(frozen=True)
class OnlineProbe:
    """The online linear probe's static pieces: a ``LinearClassifier`` head
    and its (schedule-free SGD) optimizer. Built once per run by
    :func:`build_online_probe`; the trainable state lives on
    ``TrainState.probe_params`` / ``probe_opt_state``."""

    classifier: Any
    tx: optax.GradientTransformation


def build_online_probe(model_name: str, feat_dim: int, n_cls: int,
                       lr: float, momentum: float = 0.9, seed: int = 0):
    """``(OnlineProbe, probe_params, probe_opt_state)`` for a run.

    The head and recipe mirror the post-hoc probe (train/linear.py:
    ``LinearClassifier`` + SGD momentum, zero weight decay) so the live
    curve estimates the same quantity ``main_linear.py`` measures hours
    later — the documented tolerance between the two is in
    docs/OBSERVABILITY.md. Constant LR: the probe chases a moving encoder,
    so the post-hoc step schedule has nothing to anneal against.
    """
    from simclr_pytorch_distributed_tpu.train.state import make_optimizer

    classifier = LinearClassifier(model_name=model_name, num_classes=n_cls)
    tx = make_optimizer(lr, momentum=momentum, weight_decay=0.0)
    params = classifier.init(
        jax.random.key(seed), jnp.zeros((2, feat_dim))
    )["params"]
    return OnlineProbe(classifier=classifier, tx=tx), params, tx.init(params)


@dataclasses.dataclass(frozen=True)
class RecipeContext:
    """Everything one train step hands a recipe's ``loss`` (recipes/base.py):
    the step's OWN forward products — no recipe re-runs the backbone for the
    online branch — plus the recipe slots. ``feats`` is the unnormalized
    fp32 projection matrix ``[2B, D]`` in the view-major row layout
    (``[v1 of all samples; v2 of all samples]``), ``n_fea`` its L2-normalized
    form (the contrastive/health layout). ``model``/``params``/
    ``batch_stats``/``images`` are for recipes that need a SECOND forward
    through different weights (the BYOL EMA target network)."""

    model: Any
    params: Any
    batch_stats: Any
    images: jax.Array
    labels: jax.Array
    feats: jax.Array
    n_fea: jax.Array
    recipe_params: Any
    recipe_state: Any


@dataclasses.dataclass(frozen=True)
class SupConStepConfig:
    """Static step configuration (mirrors the reference argparse flags)."""

    method: str = "SimCLR"  # --method {SimCLR, SupCon}
    temperature: float = 0.5  # --temp
    base_temperature: float = 0.07  # fixed, losses.py:90
    contrast_mode: str = "all"
    # aux losses (main_supcon.py:76-82, 295-317)
    sec: bool = False
    sec_wei: float = 0.0
    l2reg: bool = False
    l2reg_wei: float = 0.0
    norm_momentum: float = 1.0
    # ramp denominator: epochs * steps_per_epoch (main_supcon.py:311-317)
    epochs: int = 1000
    steps_per_epoch: int = 1
    # DDP gradient-mean fidelity (see module docstring); the recipe's --ngpu.
    grad_div: float = 2.0
    # 'dense' = XLA O(N^2)-materializing path; 'fused' = flash-style Pallas
    # kernel (ops/pallas_loss.py); 'ring' = ppermute-sharded streaming loss
    # (parallel/collectives.py) that keeps anchors sharded over the 'data'
    # axis — O((2B/P)^2) per-device memory for large global batches.
    # Resolved from the config's 'auto' upstream.
    loss_impl: str = "dense"
    # representation-health diagnostics (HEALTH_METRIC_KEYS): computed every
    # health_freq-th step inside a lax.cond (NaN sentinel rows otherwise),
    # written into the same donated metric ring — zero new per-step D2H
    health: bool = False
    health_freq: int = 10
    # online linear probe (ONLINE_PROBE_METRIC_KEYS): make_train_step must
    # then be given the matching OnlineProbe spec, and the state must carry
    # probe_params/probe_opt_state
    online_probe: bool = False


def two_view_forward(
    model, params, batch_stats, images: jax.Array, *,
    train: bool = True, with_features: bool = False,
):
    """Forward both views through the encoder+head as ONE batch.

    ``images`` is ``[B, 2, H, W, C]``. Views are flattened view-major —
    rows ``[v1 of all samples; v2 of all samples]`` — the same global layout the
    reference assembles post-gather (``main_supcon.py:276-279``). Both views
    share one BN batch, matching the reference's ``cat([v1, v2])`` forward
    (``main_supcon.py:256,266``).

    ``with_features=True`` routes through ``forward_with_features`` (one
    backbone pass, models/heads.py) and the FIRST return element becomes the
    ``(projection, encoder_features)`` pair — the online probe's input
    without a second encoder forward. Default callers see the unchanged
    2-tuple.
    """
    B = images.shape[0]
    flat = jnp.transpose(images, (1, 0, 2, 3, 4)).reshape((2 * B,) + images.shape[2:])
    method = type(model).forward_with_features if with_features else None
    if train:
        feats, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            flat, train=True, mutable=["batch_stats"], method=method,
        )
        return feats, mutated["batch_stats"]
    feats = model.apply(
        {"params": params, "batch_stats": batch_stats}, flat, train=False,
        method=method,
    )
    return feats, batch_stats


def contrastive_loss_terms(
    cfg: SupConStepConfig, mesh, fused_on_mesh: bool, n_fea: jax.Array, labels
):
    """The contrastive loss term over the normalized view-major ``[2B, D]``
    embedding rows — the pre-recipe step's loss head, extracted VERBATIM so
    the inline (``recipe=None``) control path and the supcon/simclr recipe
    (recipes/supcon.py) share one implementation; the recipe dispatch around
    it is proven bitwise-neutral driver-level (tests/test_recipes.py,
    docs/PARITY.md). ``labels`` is the SupCon label vector or ``None`` for
    SimCLR (the caller resolves ``cfg.method``)."""
    B = n_fea.shape[0] // 2
    # stack views back to [B_global, 2, D] with f1 = all view-1 rows
    # (main_supcon.py:285-286)
    n_features = jnp.stack([n_fea[:B], n_fea[B:]], axis=1)
    loss_labels = labels
    if cfg.loss_impl in ("fused", "ring") and cfg.contrast_mode != "all":
        raise ValueError(
            f"loss_impl={cfg.loss_impl!r} implements contrast_mode='all' "
            f"only; got {cfg.contrast_mode!r} — use loss_impl='dense'"
        )
    if cfg.loss_impl == "ring":
        # anchors stay sharded over 'data'; n_fea is already the view-major
        # global row layout the ring expects ([v1 rows; v2 rows]).
        def _ring(rows, lab):
            return ring_supcon_loss(
                rows, lab, axis_name=DATA_AXIS,
                temperature=cfg.temperature,
                base_temperature=cfg.base_temperature, n_views=2,
            )

        if loss_labels is None:
            contrastive = shard_map(
                lambda r: _ring(r, None),
                mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(),
            )(n_fea)
        else:
            contrastive = shard_map(
                _ring, mesh=mesh,
                in_specs=(P(DATA_AXIS), P()), out_specs=P(),
            )(n_fea, loss_labels)
    elif fused_on_mesh:
        # same row layout and shard_map plumbing as the ring path; the
        # kernel needs check_vma=False (interpret-mode Pallas cannot type
        # kernel-internal constants) — its custom VJP compensates for the
        # per-shard cotangent shares (ops/pallas_loss.py).
        def _fs(rows, lab):
            return fused_sharded_supcon_loss(
                rows, lab, axis_name=DATA_AXIS,
                temperature=cfg.temperature,
                base_temperature=cfg.base_temperature, n_views=2,
                interpret=jax.default_backend() != "tpu",
            )

        if loss_labels is None:
            contrastive = shard_map(
                lambda r: _fs(r, None), mesh=mesh,
                in_specs=P(DATA_AXIS), out_specs=P(), check_vma=False,
            )(n_fea)
        else:
            contrastive = shard_map(
                _fs, mesh=mesh,
                in_specs=(P(DATA_AXIS), P()), out_specs=P(),
                check_vma=False,
            )(n_fea, loss_labels)
    elif cfg.loss_impl == "fused":
        contrastive = fused_supcon_loss(
            n_features, labels=loss_labels,
            temperature=cfg.temperature, base_temperature=cfg.base_temperature,
            # Mosaic compiles only on TPU; anywhere else (CPU tests) the
            # kernel runs under the Pallas interpreter.
            interpret=jax.default_backend() != "tpu",
        )
    else:
        contrastive = supcon_loss(
            n_features, labels=loss_labels,
            temperature=cfg.temperature, base_temperature=cfg.base_temperature,
            contrast_mode=cfg.contrast_mode,
        )
    return contrastive


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    schedule: Callable,
    cfg: SupConStepConfig,
    mesh=None,
    probe: Optional[OnlineProbe] = None,
    recipe=None,
) -> Callable:
    """Build the pure train step: (state, images[B,2,H,W,C], labels[B]) -> (state, metrics).

    ``mesh`` is required only for ``loss_impl='ring'`` (the shard_map needs an
    explicit mesh; dense/fused run as plain HLO that GSPMD partitions).

    ``probe`` (an :class:`OnlineProbe`, required iff ``cfg.online_probe``)
    adds the detached online-probe update: the classifier trains on
    ``stop_gradient`` encoder features from the SAME backbone forward, so the
    encoder/head/optimizer math is bit-identical probe-on vs probe-off
    (tests/test_health.py proves it bitwise) and the probe costs one
    ``[2B, feat_dim] x [feat_dim, n_cls]`` matmul pair per step.

    ``recipe`` (a recipes/ Recipe) swaps the loss head and its extra slots:
    the recipe's ``loss`` runs inside this same jitted update on the step's
    own forward (``RecipeContext``), a trainable recipe's predictor rides
    ``state.recipe_params`` under its own optimizer chain, and its post-step
    transition (BYOL EMA, queue rotation) lands in ``state.recipe_state`` —
    all in ONE compiled program, so every recipe inherits the dispatch-only
    hot loop. ``None`` keeps the pre-recipe inline contrastive step (bench,
    the dryrun modes, and the bitwise-neutrality control arm — the
    contrastive term itself is shared via :func:`contrastive_loss_terms`).
    """
    if cfg.loss_impl == "ring" and mesh is None:
        raise ValueError("loss_impl='ring' needs the mesh passed to make_train_step")
    if (probe is not None) != cfg.online_probe:
        raise ValueError(
            f"online_probe={cfg.online_probe} but probe spec "
            f"{'missing' if probe is None else 'given'} — the step config "
            "and the OnlineProbe must be built together"
        )
    recipe_extra = () if recipe is None else tuple(recipe.metric_keys)
    recipe_trainable = recipe is not None and recipe.trainable
    expected_keys = metric_keys(
        health=cfg.health, online_probe=cfg.online_probe, extra=recipe_extra
    )
    if cfg.health and cfg.health_freq < 1:
        raise ValueError(f"health_freq must be >= 1, got {cfg.health_freq}")
    # 'fused' on a multi-device mesh routes through the shard_map-sharded
    # kernel (ops/pallas_loss.py fused_sharded_supcon_loss): anchors stay
    # sharded over 'data', the contrast side is all-gathered, and the logits
    # tiles never leave VMEM. A bare pallas_call has no GSPMD partitioning
    # rule, so without this the kernel would run fully replicated.
    fused_on_mesh = (
        cfg.loss_impl == "fused" and mesh is not None and mesh.size > 1
    )

    def loss_fn(params, recipe_params, state: TrainState, images, labels):
        probe_feats = None
        if probe is not None:
            (feats, enc_feats), new_batch_stats = two_view_forward(
                model, params, state.batch_stats, images, train=True,
                with_features=True,
            )
            # the probe's whole detachment contract: gradients CANNOT flow
            # from the classifier back into the encoder
            probe_feats = jax.lax.stop_gradient(enc_feats.astype(jnp.float32))
        else:
            feats, new_batch_stats = two_view_forward(
                model, params, state.batch_stats, images, train=True
            )
        feats = feats.astype(jnp.float32)

        # feature-norm statistics on UNNORMALIZED embeddings (main_supcon.py:298-301)
        norms = jnp.linalg.norm(feats, axis=1)
        norm_mean = jnp.mean(norms)
        norm_var = jnp.mean(jnp.square(norms - norm_mean))

        # SEC EMA: update-then-use, seeded with the first batch's mean
        # (main_supcon.py:304-307; momentum 1.0 degenerates to the batch mean)
        norm_mean_sg = jax.lax.stop_gradient(norm_mean)
        record = jnp.where(
            state.step == 0,
            norm_mean_sg,
            (1.0 - cfg.norm_momentum) * state.record_norm_mean
            + cfg.norm_momentum * norm_mean_sg,
        )
        loss_sec = jnp.mean(jnp.square(norms - record))
        loss_l2reg = jnp.mean(jnp.square(norms))

        # normalize AFTER the (logical) gather (main_supcon.py:283)
        n_fea = feats / jnp.linalg.norm(feats, axis=1, keepdims=True)

        recipe_aux = {}
        if recipe is None:
            # the pre-recipe inline path (bitwise control arm; bench/dryruns)
            if cfg.method not in ("SupCon", "SimCLR"):
                raise ValueError(
                    f"contrastive method not supported: {cfg.method}"
                )
            loss_labels = labels if cfg.method == "SupCon" else None
            contrastive = contrastive_loss_terms(
                cfg, mesh, fused_on_mesh, n_fea, loss_labels
            )
        else:
            ctx = RecipeContext(
                model=model, params=params, batch_stats=state.batch_stats,
                images=images, labels=labels, feats=feats, n_fea=n_fea,
                recipe_params=recipe_params, recipe_state=state.recipe_state,
            )
            contrastive, recipe_aux = recipe.loss(cfg, mesh, fused_on_mesh, ctx)

        # linear-ramped aux terms (main_supcon.py:311-317)
        ramp = state.step / (cfg.epochs * cfg.steps_per_epoch)
        loss = contrastive
        if cfg.sec:
            loss = loss + cfg.sec_wei * ramp * loss_sec
        if cfg.l2reg:
            loss = loss + cfg.l2reg_wei * ramp * loss_l2reg

        aux = {
            "loss": loss,  # the reported (unscaled) loss, main_supcon.py:320
            "norm_mean": norm_mean,
            "norm_var": norm_var,
            "record_norm_mean": record,
            "loss_sec": loss_sec,
            "loss_l2reg": loss_l2reg,
        }
        # recipe extras: metric terms (recipe.metric_keys) + the detached
        # rotation payload ("recipe_embeddings", queue recipes)
        aux.update(recipe_aux)
        if cfg.health:
            # the loss's OWN normalized, view-major embedding rows — the
            # health diagnostics' input, detached so aux plumbing cannot
            # perturb the gradient
            aux["embeddings"] = jax.lax.stop_gradient(n_fea)
        if probe is not None:
            aux["probe_feats"] = probe_feats
        # grad-scale fidelity: DDP means over ngpu ranks (module docstring)
        return loss / cfg.grad_div, (aux, new_batch_stats)

    def probe_update(state: TrainState, probe_feats, labels):
        """One detached classifier step on the stop_gradient encoder
        features of BOTH views (labels tiled view-major to match)."""
        labels2 = jnp.concatenate([labels, labels])

        def probe_loss_fn(pp):
            logits = probe.classifier.apply({"params": pp}, probe_feats)
            return cross_entropy_loss(logits, labels2), logits

        (ploss, logits), pgrads = jax.value_and_grad(
            probe_loss_fn, has_aux=True
        )(state.probe_params)
        pupdates, new_popt = probe.tx.update(
            pgrads, state.probe_opt_state, state.probe_params
        )
        new_pparams = optax.apply_updates(state.probe_params, pupdates)
        top1 = topk_correct(logits, labels2, ks=(1,))[1]
        pmetrics = {
            "probe_loss": ploss,
            "probe_top1": 100.0 * top1.astype(jnp.float32) / labels2.shape[0],
        }
        return new_pparams, new_popt, pmetrics

    def train_step(
        state: TrainState, images: jax.Array, labels: jax.Array
    ) -> Tuple[TrainState, dict]:
        if recipe_trainable:
            # joint gradient: the recipe's predictor trains WITH the encoder
            # (BYOL/SimSiam gradients reach the backbone only through the
            # predictor path), each under its own optimizer chain
            (grads, rgrads), (aux, new_batch_stats) = jax.grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(state.params, state.recipe_params, state, images, labels)
        else:
            grads, (aux, new_batch_stats) = jax.grad(loss_fn, has_aux=True)(
                state.params,
                None if recipe is None else state.recipe_params,
                state, images, labels,
            )
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = dict(aux, learning_rate=jnp.asarray(schedule(state.step)))
        metrics.pop("embeddings", None)
        metrics.pop("probe_feats", None)
        metrics.pop("recipe_embeddings", None)
        replace_kwargs = {}
        if recipe_trainable:
            rupdates, new_ropt = recipe.tx.update(
                rgrads, state.recipe_opt_state, state.recipe_params
            )
            replace_kwargs.update(
                recipe_params=optax.apply_updates(
                    state.recipe_params, rupdates
                ),
                recipe_opt_state=new_ropt,
            )
        if recipe is not None and state.recipe_state is not None:
            # the recipe's post-step state transition (BYOL EMA toward the
            # freshly updated online params; queue rotation with the batch's
            # detached embeddings) — still inside this one compiled program
            replace_kwargs["recipe_state"] = recipe.post_step(
                state.recipe_state, new_params=new_params, aux=aux
            )
        if cfg.health:
            # lax.cond, not where: the false branch must SKIP the O((2B)^2)
            # similarity matmul and the d x d eigendecomposition at runtime,
            # not just mask their results — non-health steps pay nothing
            metrics.update(jax.lax.cond(
                state.step % cfg.health_freq == 0,
                lambda ops: contrastive_health_metrics(*ops),
                lambda ops: {
                    k: jnp.full((), jnp.nan, jnp.float32)
                    for k in HEALTH_METRIC_KEYS
                },
                (aux["embeddings"], grads),
            ))
        if probe is not None:
            new_pparams, new_popt, pmetrics = probe_update(
                state, aux["probe_feats"], labels
            )
            metrics.update(pmetrics)
            replace_kwargs.update(
                probe_params=new_pparams, probe_opt_state=new_popt
            )
        assert tuple(sorted(metrics)) == expected_keys, sorted(metrics)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_batch_stats,
            opt_state=new_opt_state,
            record_norm_mean=aux["record_norm_mean"],
            **replace_kwargs,
        )
        return new_state, metrics

    return train_step


def make_sharded_train_step(
    model,
    tx: optax.GradientTransformation,
    schedule: Callable,
    cfg: SupConStepConfig,
    mesh,
    state_shape: Optional[Any] = None,
    donate: bool = True,
    recipe=None,
) -> Callable:
    """jit the train step over the mesh: state replicated, batch data-sharded.

    Under GSPMD this single program IS the distributed algorithm: XLA inserts the
    feature all-gather for the loss matmul and a gradient reduce over ICI —
    the TPU-native replacement for NCCL all_gather + DDP bucketed all-reduce.
    """
    step = make_train_step(model, tx, schedule, cfg, mesh=mesh, recipe=recipe)
    repl = replicated_sharding(mesh)

    state_sh = (
        state_sharding(mesh, state_shape) if state_shape is not None else repl
    )
    in_shardings = (
        state_sh,
        batch_sharding(mesh, 5),  # images [B, 2, H, W, C]
        batch_sharding(mesh, 1),  # labels [B]
    )
    return jax.jit(
        step,
        in_shardings=in_shardings,
        out_shardings=(state_sh, repl),
        donate_argnums=(0,) if donate else (),
    )
