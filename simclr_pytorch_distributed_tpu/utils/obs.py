"""RunObservability — the epoch drivers' one-call observability wiring.

All three drivers want the identical stack (flight recorder + stall
watchdog + Prometheus sidecar) with the identical lifecycle, and the
teardown ORDER is a correctness property: the recorder must outlive the
last ``wait_for_saves()`` (so the final ``checkpoint_commit`` span lands
in the record) and the watchdog must still be watching while that drain
can wedge. Keeping the wiring here — the ``device_store.make_store``
convention — means the order cannot drift between drivers.

Usage (see train/supcon.py)::

    obs = RunObservability(cfg, name="supcon")
    telemetry = TelemetrySession(..., watchdog=obs.watchdog,
                                 gauges=obs.gauges)
    try:
        ...
    finally:
        ...
        wait_for_saves()   # BEFORE obs.close(): the commit span records
        obs.close()
"""

from __future__ import annotations

import logging

from simclr_pytorch_distributed_tpu.utils import prom, tracing
from simclr_pytorch_distributed_tpu.utils.checkpoint import pending_saves

logger = logging.getLogger(__name__)


class RunObservability:
    """Build (and later tear down, in the right order) the per-run
    observability stack from a trainer config:

    - ``recorder`` — installed as the module-level tracing recorder;
      ``None`` under ``--flight_recorder off``;
    - ``watchdog`` — a started :class:`tracing.StallWatchdog` beating on
      the flush boundary (via ``TelemetrySession``); ``None`` unless
      ``--watchdog_secs > 0``;
    - ``gauges`` + the ``--metrics_port`` sidecar server; ``None`` when
      the port is 0;
    - ``health`` — a :class:`guard.HealthMonitor` (the windowed
      collapse/divergence detector fed by the flush-boundary consume jobs)
      when the config carries health flags with ``health_freq > 0``
      (pretrain only); ``None`` otherwise.
    """

    def __init__(self, cfg, name: str):
        self.recorder = tracing.recorder_for_run(
            cfg.save_folder, enabled=(cfg.flight_recorder != "off")
        )
        tracing.install(self.recorder)
        self.watchdog = None
        if cfg.watchdog_secs > 0:
            self.watchdog = tracing.StallWatchdog(
                cfg.watchdog_secs, cfg.save_folder, recorder=self.recorder,
                name=name,
            )
        self.health = None
        if getattr(cfg, "health_freq", 0) > 0:
            from simclr_pytorch_distributed_tpu.utils.guard import (
                HealthMonitor,
                thresholds_for_recipe,
            )

            from simclr_pytorch_distributed_tpu.recipes import (
                recipe_metric_keys,
            )

            # per-recipe bars (guard.RECIPE_HEALTH_THRESHOLDS): the
            # negative-free recipes run under a raised eff-rank bar —
            # there the collapse detector is load-bearing. The recipe's
            # own metric columns ride the same window stream.
            self.health = HealthMonitor(
                policy=getattr(cfg, "health_policy", "warn"),
                thresholds=thresholds_for_recipe(
                    getattr(cfg, "recipe", None)
                ),
                extra_keys=recipe_metric_keys(
                    getattr(cfg, "recipe", None)
                ),
            )
        self.gauges = self.sidecar = None
        if cfg.metrics_port:
            self.gauges = prom.TrainerGauges()
            self.gauges.register("checkpoint_pending_saves", pending_saves)
            if self.recorder is not None:
                # records evicted from the recorder's bounded in-memory
                # ring (trace.json / watchdog snapshots truncated; the
                # jsonl keeps all) — a saturated recorder must be an
                # operator-visible signal, not a silent loss
                rec = self.recorder
                self.gauges.register(
                    "recorder_dropped_records", lambda: rec.dropped
                )
            self.sidecar = prom.start_metrics_server(
                cfg.metrics_port, self.gauges.prometheus_text,
                host=getattr(cfg, "metrics_host", "127.0.0.1"),
            )
            logger.info(
                "metrics sidecar on %s:%d",
                *self.sidecar.server_address[:2],
            )

    def set_epoch(self, epoch: int) -> None:
        if self.gauges is not None:
            self.gauges.set(epoch=epoch)

    def staged(self) -> None:
        """Call right after ``make_store`` returns. The stack is built
        BEFORE placement resolution (so the placement collective — a real
        deadlock candidate — runs under the armed watchdog and its span
        lands on the record), but the store's one-time dataset upload can
        be large: without this beat that staging time would eat into the
        first flush-boundary deadline, which ``--watchdog_secs`` is only
        documented to cover from compile onward (a spurious staging dump
        would be read by the supervisor as a stall)."""
        if self.watchdog is not None:
            self.watchdog.beat()

    def close(self, exit_code: int = None) -> None:
        """Teardown, last in the driver's ``finally`` (after the final
        ``wait_for_saves()``): stop the watchdog/sidecar threads, then
        uninstall and close the recorder — ``close()`` exports trace.json
        and never raises.

        ``exit_code`` (the drivers pass ``guard.exit_code_for`` of the
        in-flight exception) stamps the terminal ``train_exit_code`` gauge
        and records a final ``run_exit`` event before the sidecar stops —
        the supervisor's last scrape and the recorder's last line both
        classify the exit without log parsing."""
        if exit_code is not None:
            if self.gauges is not None:
                self.gauges.set_exit_code(exit_code)
            tracing.event("run_exit", track="main:guard", code=int(exit_code))
        if self.watchdog is not None:
            self.watchdog.close()
        if self.sidecar is not None:
            self.sidecar.shutdown()
        tracing.uninstall()
        if self.recorder is not None:
            self.recorder.close()
