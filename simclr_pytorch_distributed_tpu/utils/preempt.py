"""Preemption tolerance: SIGTERM/SIGINT -> flag -> clean mid-epoch exit.

On real TPU fleets the dominant failure is not NaNs but PREEMPTION
(maintenance events, spot reclamation, OOM-killer sweeps): the runtime sends
SIGTERM and gives the process a grace window before SIGKILL. The reference has
no handling at all — a killed rank hangs NCCL and loses everything since the
last scheduled save (SURVEY.md §5). Here every driver installs these handlers;
the flag is CHECKED (never acted on inside the handler — no I/O or collectives
are signal-safe) at each ``print_freq`` flush boundary, where the drivers
already sync with the device. The driver then drains metrics, writes an
emergency mid-epoch checkpoint carrying ``step_in_epoch`` in its meta, and
exits with :data:`EXIT_PREEMPTED` so the launcher can distinguish "re-run me
with --resume" from a real failure.

The resume is BIT-IDENTICAL to the uninterrupted run (proved by
tests/test_fault_injection.py): the per-step PRNG key is
``fold_in(base_key, state.step)`` and the epoch shuffle is seeded
``base_seed + epoch``, so skipping the already-consumed prefix of the
deterministic permutation (``EpochLoader(..., start_step=...)``) replays the
exact remaining stream.
"""

from __future__ import annotations

import logging
import signal
from typing import Optional

# EX_TEMPFAIL from sysexits.h: "temporary failure, retry later" — the operator
# contract is: exit EXIT_PREEMPTED means state was saved cleanly, re-run the
# same command with --resume <run_dir> (docs/RESILIENCE.md exit-code table).
# The other typed exits live in utils/guard.py (EXIT_HEALTH 3 > EXIT_FLUSH 2 >
# EXIT_NONFINITE 1); together they are the classification surface the fleet
# supervisor (simclr_pytorch_distributed_tpu/supervise/) decides on — 75 is
# the one code that relaunches WITHOUT backoff, and a resize is a legal
# response to it (mesh-shape-agnostic restore, utils/checkpoint.py).
EXIT_PREEMPTED = 75

_SIGNALS = (signal.SIGTERM, signal.SIGINT)

_received: Optional[int] = None
_prev_handlers: dict = {}


def _handler(signum, frame):  # noqa: ARG001 — signal handler signature
    global _received
    if _received is not None and signum == signal.SIGINT:
        # second Ctrl-C while the first is still draining: the user wants out
        # NOW — give them the ordinary KeyboardInterrupt abort.
        raise KeyboardInterrupt
    _received = signum


def install() -> None:
    """Install the flag-setting handlers (idempotent). Must run on the main
    thread; anywhere else (embedded drivers) it degrades to a warning —
    preemption then behaves like the unhandled default."""
    global _received
    if _prev_handlers:
        return
    _received = None
    try:
        for s in _SIGNALS:
            _prev_handlers[s] = signal.signal(s, _handler)
    except ValueError:  # not the main thread
        _prev_handlers.clear()
        logging.warning(
            "preemption handlers need the main thread; running without "
            "SIGTERM-triggered emergency checkpointing"
        )


def uninstall() -> None:
    """Restore the previous handlers (drivers pair this with install() in a
    finally, so a driver run inside pytest leaves the interpreter's own
    SIGINT behavior intact)."""
    global _received
    while _prev_handlers:
        s, prev = _prev_handlers.popitem()
        try:
            signal.signal(s, prev)
        except ValueError:  # pragma: no cover - thread teardown edge
            pass
    _received = None


def requested() -> bool:
    return _received is not None


def requested_global() -> bool:
    """Cross-host agreement on the local flags: True iff ANY process saw a
    signal.

    A multi-host job must commit to ONE preemption step: signal delivery is
    per-host and the flush boundaries are not wall-clock synchronized, so a
    host observing SIGTERM one flush earlier than its peers would return to
    the collective emergency save while the others dispatch the next step's
    cross-host collectives — a distributed deadlock that burns the whole
    grace window and loses the checkpoint. Every process therefore calls
    this at every flush boundary (the call sites are gated on deterministic
    step counts, so the allgather schedules match), and all act on the OR.
    Single-process jobs short-circuit to the local flag — no collective in
    the hot loop.
    """
    import jax

    if jax.process_count() == 1:
        return requested()
    import numpy as np
    from jax.experimental import multihost_utils

    from simclr_pytorch_distributed_tpu.utils import tracing

    # the span that matters most in a pod post-mortem: when this collective
    # deadlocks (a peer left the loop early), every surviving host's
    # recorder shows its last completed preempt_decision and the watchdog's
    # stack dump shows the allgather it is stuck in
    with tracing.span(
        "preempt_decision", track="main:collective",
        local=bool(_received is not None),
    ):
        flags = multihost_utils.process_allgather(
            np.asarray([_received is not None], np.int32)
        )
    return bool(np.asarray(flags).any())


def signal_name() -> str:
    return signal.Signals(_received).name if _received is not None else "none"


def request(signum: int = signal.SIGTERM) -> None:
    """Programmatic preemption (in-process tests simulate the signal without
    OS delivery; the checked-at-flush-boundary path is identical)."""
    global _received
    _received = signum


def emergency_save_and_exit(
    save_folder: str, name: Optional[str], state, config: dict,
    epoch: int, step_in_epoch: int = 0, extra_meta: Optional[dict] = None,
    cleanup=(),
) -> None:
    """The one preemption exit sequence, shared by the epoch drivers.

    Drains in-flight async checkpoint writes, writes the blocking emergency
    save (collective across processes, like every orbax save here) unless
    ``name`` is None (a scheduled save already covers this position), logs on
    the main process, runs ``cleanup`` callables, and raises
    ``SystemExit(EXIT_PREEMPTED)``. Keeping it in one place keeps the
    ordering (drain -> save -> log -> cleanup -> exit) from drifting between
    drivers.
    """
    import logging

    from simclr_pytorch_distributed_tpu.parallel.mesh import is_main_process
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        save_checkpoint,
        wait_for_saves,
    )

    wait_for_saves()
    path = save_folder
    if name is not None:
        path = save_checkpoint(
            save_folder, name, state, config=config, epoch=epoch,
            step_in_epoch=step_in_epoch, extra_meta=extra_meta,
        )
    if is_main_process():
        logging.warning(
            "preempted (%s): state saved at %s; exiting %d (resume with "
            "--resume %s)", signal_name(), path, EXIT_PREEMPTED, save_folder,
        )
    for fn in cleanup:
        fn()
    raise SystemExit(EXIT_PREEMPTED)
