"""Failure detection + policy (SURVEY.md §5: absent upstream — a rank crash
just hangs NCCL until timeout and all progress is lost since there is no
resume).

Detection: a non-finite loss observed at the metrics fetch raises
:class:`NonFiniteLossError` instead of silently training on NaNs for hours.
The check piggybacks on the every-``print_freq`` device sync the meters
already do, so it adds zero extra host<->device round-trips to the hot loop.

Policy (``--nan_policy``): what the driver DOES about it.

- ``abort`` (default, the original behavior): emergency-checkpoint the last
  epoch-boundary state as ``crash_epoch_N`` and die; a human addresses the
  root cause and re-runs with ``--resume``.
- ``rollback``: self-heal. The driver still writes ``crash_epoch_N``
  (forensics), then restores the epoch-boundary backup it already keeps for
  the abort path, SKIPS the poisoned epoch (the step counter advances past it
  so the LR-schedule position and per-step PRNG stream stay aligned with the
  epoch number), multiplies the LR by :data:`ROLLBACK_LR_MULT` to damp
  whatever spiked, and continues. :data:`MAX_ROLLBACKS` consecutive-run
  rollbacks bound the self-healing — a run whose loss keeps exploding at
  1/8th of the recipe LR has a real bug and aborts like before.

Preemption (SIGTERM/SIGINT) is the other half of the failure model and lives
in utils/preempt.py; docs/RESILIENCE.md has the full matrix.
"""

from __future__ import annotations

import math

# Each rollback halves the LR: strong enough that two rollbacks tame a
# warmup/batch-order spike, gentle enough that one spurious NaN doesn't
# flatten the schedule.
ROLLBACK_LR_MULT = 0.5
MAX_ROLLBACKS = 3


class NonFiniteLossError(RuntimeError):
    """Raised when the training loss goes NaN/Inf."""

    def __init__(self, loss: float, step: int):
        super().__init__(
            f"non-finite loss {loss!r} at global step {step}; aborting "
            "(an emergency checkpoint of the last epoch boundary is saved)"
        )
        self.loss = loss
        self.step = step


def check_finite_loss(loss: float, step: int, enabled: bool = True) -> None:
    if enabled and not math.isfinite(loss):
        raise NonFiniteLossError(loss, step)


class FailurePolicy:
    """Driver-side decision state for non-finite-loss failures.

    One instance per run. ``should_rollback()`` is consulted from the
    driver's ``except NonFiniteLossError`` handler AFTER the crash
    checkpoint is written; when it grants a rollback it also advances the
    cumulative ``lr_scale`` the driver applies to the schedule.
    """

    def __init__(
        self,
        policy: str = "abort",
        max_rollbacks: int = MAX_ROLLBACKS,
        lr_mult: float = ROLLBACK_LR_MULT,
    ):
        if policy not in ("abort", "rollback"):
            raise ValueError(f"unknown nan_policy {policy!r}")
        self.policy = policy
        self.max_rollbacks = max_rollbacks
        self.lr_mult = lr_mult
        self.rollbacks = 0
        self.lr_scale = 1.0

    def should_rollback(self) -> bool:
        """True -> the driver restores the backup and continues; also books
        the rollback (count + LR damping). False -> abort (re-raise)."""
        if self.policy != "rollback" or self.rollbacks >= self.max_rollbacks:
            return False
        self.rollbacks += 1
        self.lr_scale *= self.lr_mult
        return True
