"""Failure detection + policy (SURVEY.md §5: absent upstream — a rank crash
just hangs NCCL until timeout and all progress is lost since there is no
resume).

Detection: a non-finite loss observed at the metrics fetch raises
:class:`NonFiniteLossError` instead of silently training on NaNs for hours.
The check piggybacks on the every-``print_freq`` device sync the meters
already do, so it adds zero extra host<->device round-trips to the hot loop.

Policy (``--nan_policy``): what the driver DOES about it.

- ``abort`` (default, the original behavior): emergency-checkpoint the last
  epoch-boundary state as ``crash_epoch_N`` and die; a human addresses the
  root cause and re-runs with ``--resume``.
- ``rollback``: self-heal. The driver still writes ``crash_epoch_N``
  (forensics), then restores the epoch-boundary backup it already keeps for
  the abort path, SKIPS the poisoned epoch (the step counter advances past it
  so the LR-schedule position and per-step PRNG stream stay aligned with the
  epoch number), multiplies the LR by :data:`ROLLBACK_LR_MULT` to damp
  whatever spiked, and continues. :data:`MAX_ROLLBACKS` consecutive-run
  rollbacks bound the self-healing — a run whose loss keeps exploding at
  1/8th of the recipe LR has a real bug and aborts like before.

Representation health (``--health_policy``) is the third leg: the
:class:`HealthMonitor` evaluates the windowed on-device diagnostics
(train/supcon_step.HEALTH_METRIC_KEYS) the metric ring delivers at flush
boundaries and turns a collapsed or diverging representation — which a
finite loss hides completely — into flight-recorder events (``warn``) or a
typed :class:`RepresentationHealthError` abort. Unlike a NaN, a health
abort is NEVER rolled back: collapse lives in the weights, so replaying the
epoch from the boundary backup at half the LR just re-detects it
(docs/RESILIENCE.md, precedence note).

Preemption (SIGTERM/SIGINT) is the other half of the failure model and lives
in utils/preempt.py; docs/RESILIENCE.md has the full matrix.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from collections import deque

from simclr_pytorch_distributed_tpu.utils import tracing

# Each rollback halves the LR: strong enough that two rollbacks tame a
# warmup/batch-order spike, gentle enough that one spurious NaN doesn't
# flatten the schedule.
ROLLBACK_LR_MULT = 0.5
MAX_ROLLBACKS = 3

# ----------------------------------------------------------- typed exit codes
#
# The drivers' process exit codes mirror the collective failure codes the
# flush boundary allgathers (utils/telemetry.py _failure_code), so an external
# operator — the supervisor (simclr_pytorch_distributed_tpu/supervise/), a
# Prometheus alert on the terminal `train_exit_code` gauge, or a shell
# launcher — can classify the last exit without parsing logs. Precedence when
# several failures land in one window is decided by the collective code
# exchange (health 3 > flush 2 > NaN 1); preemption keeps its own sysexits
# code 75 (utils/preempt.EXIT_PREEMPTED). docs/RESILIENCE.md has the table.
EXIT_NONFINITE = 1   # NonFiniteLossError under --nan_policy abort
EXIT_FLUSH = 2       # TelemetryFlushError (non-NaN flush failure: TB IOError, D2H fault)
EXIT_HEALTH = 3      # RepresentationHealthError under --health_policy abort


def exit_code_for(exc: "BaseException | None") -> int:
    """The typed exit code for an exception leaving a driver's run().

    ``None`` (clean return) -> 0; ``SystemExit`` passes its own code through
    (the preemption path raises ``SystemExit(75)``); the three typed failure
    exceptions map to their collective failure codes; anything else is a
    plain crash (1, the interpreter's default for an unhandled exception) so
    launchers keying only on 75-vs-other keep working.
    """
    if exc is None:
        return 0
    if isinstance(exc, SystemExit):
        code = exc.code
        if code is None:
            return 0
        return code if isinstance(code, int) else 1
    # local import not needed: TelemetryFlushError lives in utils/telemetry,
    # which imports nothing from here at module scope
    from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetryFlushError

    if isinstance(exc, RepresentationHealthError):
        return EXIT_HEALTH
    if isinstance(exc, TelemetryFlushError):
        return EXIT_FLUSH
    if isinstance(exc, NonFiniteLossError):
        return EXIT_NONFINITE
    return 1


def exit_with_code(run_fn) -> None:
    """The drivers' shared ``main()`` epilogue: run, convert the typed
    failure exceptions into their exit codes (with the traceback logged —
    the code replaces the interpreter's generic rc 1, not the diagnostics),
    and let everything else (SystemExit 75, real bugs) propagate unchanged.
    """
    import logging

    from simclr_pytorch_distributed_tpu.utils.telemetry import TelemetryFlushError

    try:
        run_fn()
    except (RepresentationHealthError, TelemetryFlushError,
            NonFiniteLossError) as e:
        logging.exception("typed failure abort (exit code %d)", exit_code_for(e))
        raise SystemExit(exit_code_for(e)) from e


class NonFiniteLossError(RuntimeError):
    """Raised when the training loss goes NaN/Inf."""

    def __init__(self, loss: float, step: int):
        super().__init__(
            f"non-finite loss {loss!r} at global step {step}; aborting "
            "(an emergency checkpoint of the last epoch boundary is saved)"
        )
        self.loss = loss
        self.step = step


def check_finite_loss(loss: float, step: int, enabled: bool = True) -> None:
    if enabled and not math.isfinite(loss):
        raise NonFiniteLossError(loss, step)


class FailurePolicy:
    """Driver-side decision state for non-finite-loss failures.

    One instance per run. ``should_rollback()`` is consulted from the
    driver's ``except NonFiniteLossError`` handler AFTER the crash
    checkpoint is written; when it grants a rollback it also advances the
    cumulative ``lr_scale`` the driver applies to the schedule.
    """

    def __init__(
        self,
        policy: str = "abort",
        max_rollbacks: int = MAX_ROLLBACKS,
        lr_mult: float = ROLLBACK_LR_MULT,
    ):
        if policy not in ("abort", "rollback"):
            raise ValueError(f"unknown nan_policy {policy!r}")
        self.policy = policy
        self.max_rollbacks = max_rollbacks
        self.lr_mult = lr_mult
        self.rollbacks = 0
        self.lr_scale = 1.0

    def should_rollback(self) -> bool:
        """True -> the driver restores the backup and continues; also books
        the rollback (count + LR damping). False -> abort (re-raise)."""
        if self.policy != "rollback" or self.rollbacks >= self.max_rollbacks:
            return False
        self.rollbacks += 1
        self.lr_scale *= self.lr_mult
        return True


# ------------------------------------------------------- representation health

# health samples the detector's rolling window holds; at the default
# health_freq=10 this is ~80 steps of history — long enough that one odd
# batch cannot trip a verdict, short enough that a real collapse is caught
# within a few print_freq windows
HEALTH_WINDOW = 8


class RepresentationHealthError(RuntimeError):
    """Raised (under ``--health_policy abort``) when the windowed
    representation diagnostics say the run is collapsed or diverging."""

    def __init__(self, findings, step: int):
        findings = list(findings)
        super().__init__(
            f"representation health alarm at global step {step}: "
            + "; ".join(findings)
        )
        self.findings = findings
        self.step = step


@dataclasses.dataclass(frozen=True)
class HealthThresholds:
    """Detector bars over WINDOWED means (see :class:`HealthMonitor`).

    Scales are absolute properties of unit-norm embeddings, not tuned per
    run: a truly collapsed representation sits at eff_rank == 1.0 and
    align == neg_mean == 1.0 exactly, while even a random-init encoder
    spreads its projector outputs to eff_rank >> 2 with negatives near 0 —
    so the defaults fire only on the degenerate regime, never on the
    (normal) early-training plateau. ``grad_norm_max`` is off by default:
    healthy gradient scales are recipe-specific, and the NaN guard already
    catches the terminal form of divergence.
    """

    eff_rank_min: float = 2.0
    align_max: float = 0.995
    neg_mean_max: float = 0.995
    grad_norm_max: float = 0.0  # 0 = divergence bar disabled
    min_samples: int = 2


# Per-recipe detector bars (recipes/, docs/OBSERVABILITY.md threshold
# table). The contrastive recipes keep the PR-8 defaults: their loss
# actively repels negatives, so only the fully degenerate regime should
# fire. The negative-FREE recipes (BYOL/SimSiam) are exactly the runs where
# collapse is the failure mode the recipe's asymmetry exists to prevent —
# here the detector is load-bearing, not decorative, so the effective-rank
# bar is raised: an ablated predictor (the known-collapsing form,
# recipes/byol.py) must trip it. Healthy negative-free runs legitimately
# drive alignment toward 1, so the align bar stays paired with neg_mean
# (both ~1 = constant embeddings) rather than tightened. VICReg's variance
# hinge fights collapse in the loss itself — defaults apply, and an alarm
# there means the coefficients are broken.
RECIPE_HEALTH_THRESHOLDS = {
    "supcon": HealthThresholds(),
    "simclr": HealthThresholds(),
    "byol": HealthThresholds(eff_rank_min=3.0),
    "simsiam": HealthThresholds(eff_rank_min=3.0),
    "vicreg": HealthThresholds(),
}


def thresholds_for_recipe(recipe: "str | None") -> HealthThresholds:
    """The live detector bars for a recipe name; unknown/None (the probe/CE
    trainers, pre-recipe event streams) get the defaults. Shared by the
    in-run HealthMonitor (utils/obs.py) and the offline reader
    (scripts/health_report.py), so both reach the same verdict."""
    return RECIPE_HEALTH_THRESHOLDS.get(recipe, HealthThresholds())


class HealthMonitor:
    """Windowed collapse/divergence detector over the ring's health samples.

    The drivers' flush-boundary ``consume`` job feeds it every fetched row
    (:meth:`ingest`, running on the telemetry thread): all-NaN health
    columns — the non-health-step sentinel rows ``lax.cond`` writes — are
    skipped, finite samples enter a rolling window, and the window means
    are evaluated against :class:`HealthThresholds`. Each ingest with new
    samples emits one ``health_window`` event (the means — the post-hoc
    metric stream ``scripts/health_report.py`` reads) on the ``health``
    track; a verdict additionally emits a ``health_alarm`` event and, under
    ``policy='abort'``, raises :class:`RepresentationHealthError` — which
    the telemetry executor stores and the boundary's COLLECTIVE
    ``check_failures_global`` re-raises on every host as failure code 3
    (utils/telemetry.py), the same deterministic exit discipline as the NaN
    check. Host-only throughout: no device sync, no transfer.
    """

    def __init__(self, policy: str = "warn", thresholds: HealthThresholds = None,
                 window: int = HEALTH_WINDOW, extra_keys=()):
        if policy not in ("warn", "abort"):
            raise ValueError(f"unknown health_policy {policy!r}")
        self.policy = policy
        self.thresholds = thresholds if thresholds is not None else HealthThresholds()
        # recipe metric columns (recipes/*.metric_keys, e.g. the VICReg term
        # breakdown) ingested alongside the health_/probe_ families so they
        # ride the same window means -> health_window events -> gauges
        self.extra_keys = tuple(extra_keys)
        self._window: "deque[dict]" = deque(maxlen=window)
        self.samples = 0  # real health samples ingested (sentinels excluded)
        self.alarms = 0
        self.last_means: dict = {}
        # non-finite values seen inside REAL samples ({key: count}): an inf
        # gradient norm or a NaN eigen-spectrum is itself a divergence
        # finding — window_means averages finite values only, so these are
        # tracked here rather than through the means. ``_nonfinite_surfaced``
        # is how many have already been reported: the delta surfaces at the
        # next evaluation (independent of min_samples — one inf is a hard
        # signal, not a windowed statistic), and never re-alarms.
        self.nonfinite_keys: dict = {}
        self._nonfinite_surfaced = 0

    def observe(self, metrics: dict, step: int) -> bool:
        """Ingest one fetched ring row; returns True iff it carried a real
        (non-sentinel) health sample."""
        sample = {
            k: float(v) for k, v in metrics.items()
            if k.startswith(("health_", "probe_")) or k in self.extra_keys
        }
        health_vals = [v for k, v in sample.items() if k.startswith("health_")]
        if not health_vals or all(math.isnan(v) for v in health_vals):
            return False  # sentinel row: step % health_freq != 0
        for k, v in sample.items():
            if not math.isfinite(v):
                self.nonfinite_keys[k] = self.nonfinite_keys.get(k, 0) + 1
        sample["step"] = int(step)
        self._window.append(sample)
        self.samples += 1
        return True

    def window_means(self) -> dict:
        """Mean of each finite metric over the rolling window (``step`` is
        the window's LAST step, not averaged)."""
        if not self._window:
            return {}
        keys = set().union(*(s.keys() for s in self._window)) - {"step"}
        means = {}
        for k in sorted(keys):
            vals = [s[k] for s in self._window if k in s and math.isfinite(s[k])]
            if vals:
                means[k] = sum(vals) / len(vals)
        means["step"] = self._window[-1]["step"]
        return means

    def verdicts(self, means: dict):
        """The findings for one window-mean dict (pure; tested directly)."""
        t = self.thresholds
        findings = []
        eff = means.get("health_eff_rank")
        if eff is not None and eff < t.eff_rank_min:
            findings.append(
                f"collapse: embedding effective rank {eff:.3g} < "
                f"{t.eff_rank_min:g}"
            )
        align = means.get("health_align")
        neg = means.get("health_neg_mean")
        if (align is not None and neg is not None
                and align > t.align_max and neg > t.neg_mean_max):
            findings.append(
                f"collapse: positives ({align:.4f}) and negatives "
                f"({neg:.4f}) both ~1 — all embeddings identical"
            )
        gnorm = means.get("health_grad_norm")
        if gnorm is not None and t.grad_norm_max and gnorm > t.grad_norm_max:
            findings.append(
                f"divergence: gradient norm {gnorm:.3g} > {t.grad_norm_max:g}"
            )
        return findings

    def ingest(self, rows, gauges=None) -> list:
        """One flush window's worth of ``(step, metrics)`` rows. Returns the
        findings (empty = healthy); raises under ``policy='abort'``."""
        fresh = 0
        for step, metrics in rows:
            fresh += self.observe(metrics, step)
        if not fresh:
            return []
        means = self.window_means()
        self.last_means = means
        if gauges is not None:
            gauges.set(**{k: v for k, v in means.items() if k != "step"})
        tracing.event(
            "health_window", track="health",
            **{k: round(v, 6) if isinstance(v, float) else v
               for k, v in means.items()},
        )
        # non-finite values surface regardless of min_samples (a single inf
        # gradient norm is a hard signal, not a windowed statistic); the
        # surfaced counter defers — never drops — ones that landed earlier
        findings = []
        nonfinite_total = sum(self.nonfinite_keys.values())
        if nonfinite_total > self._nonfinite_surfaced:
            self._nonfinite_surfaced = nonfinite_total
            findings.append(
                "divergence: non-finite health metrics "
                f"{sorted(self.nonfinite_keys)}"
            )
        if len(self._window) >= self.thresholds.min_samples:
            findings = self.verdicts(means) + findings
        if findings:
            self.alarms += 1
            tracing.event(
                "health_alarm", track="health", step=means["step"],
                policy=self.policy, findings=findings,
            )
            logging.warning(
                "representation health alarm at step %d (policy=%s): %s",
                means["step"], self.policy, "; ".join(findings),
            )
            if self.policy == "abort":
                raise RepresentationHealthError(findings, means["step"])
        return findings
