"""Failure detection (SURVEY.md §5: absent upstream — a rank crash just hangs
NCCL until timeout and all progress is lost since there is no resume).

Here the cheap, high-value guard is numeric: a non-finite loss observed at the
metrics fetch aborts the run with an emergency checkpoint of the last known-good
state instead of silently training on NaNs for hours. Combined with
``--resume``, the run restarts from the crash checkpoint after the root cause
(LR spike, bad batch) is addressed.

The check piggybacks on the every-``print_freq`` device sync the meters already
do, so it adds zero extra host<->device round-trips to the hot loop.
"""

from __future__ import annotations

import math


class NonFiniteLossError(RuntimeError):
    """Raised when the training loss goes NaN/Inf."""

    def __init__(self, loss: float, step: int):
        super().__init__(
            f"non-finite loss {loss!r} at global step {step}; aborting "
            "(an emergency checkpoint of the last epoch boundary is saved)"
        )
        self.loss = loss
        self.step = step


def check_finite_loss(loss: float, step: int, enabled: bool = True) -> None:
    if enabled and not math.isfinite(loss):
        raise NonFiniteLossError(loss, step)
