"""Prometheus text exposition, latency histograms, and trainer liveness
gauges — stdlib only (the repo bakes in no client library).

Three consumers:

- ``serve/server.py`` exposes ``GET /metrics`` (engine/batcher counters +
  per-bucket request-latency histograms) so external scrapers see the
  serving fleet's liveness and saturation without polling ``/stats`` JSON;
- the trainers' optional metrics sidecar (``--metrics_port``) serves the
  :class:`TrainerGauges` — step counter, last-flush-boundary age, in-flight
  telemetry windows, pending checkpoint saves — the minimal signal an
  external watchdog needs to distinguish "training" from "wedged" without
  touching the device;
- ``/stats`` reuses :class:`LatencyHistogram.summary` for its
  p50/p95/p99-per-bucket section, so the JSON and Prometheus views are
  computed from the SAME clock-injectable histogram and cannot drift.

Exposition format: the Prometheus text format (``name{label="v"} value``
lines). Histograms follow the native convention (cumulative ``_bucket``
series with an ``le`` label, plus ``_sum``/``_count``).
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple

Sample = Tuple[str, Optional[dict], float]


def _fmt_label(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_value(value) -> str:
    """Exact rendering: '%g' would quantize to 6 significant digits, which
    corrupts large counters (a step counter past ~1e6, a latency _sum) —
    Prometheus rate()/increase() over quantized counters can even go
    negative. Integers render as integers; floats via repr (shortest
    round-trip)."""
    v = float(value)
    if v.is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(v)


def render_prometheus(samples: Iterable[Sample]) -> str:
    """Prometheus text lines from ``(name, labels_or_None, value)`` samples."""
    lines = []
    for name, labels, value in samples:
        if labels:
            inner = ",".join(
                f'{k}="{_fmt_label(v)}"' for k, v in sorted(labels.items())
            )
            lines.append(f"{name}{{{inner}}} {_fmt_value(value)}")
        else:
            lines.append(f"{name} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


# default request-latency bounds (ms): log-spaced from sub-batch-window to
# the server's 30 s result timeout; an overflow bucket catches the rest
DEFAULT_BOUNDS_MS = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1000.0, 2000.0, 5000.0, 10000.0, 30000.0,
)


class LatencyHistogram:
    """Per-key fixed-bound latency histograms with interpolated quantiles.

    ``observe(key, ms)`` is O(buckets) under one lock — cheap enough for
    the serve completion path. Quantiles interpolate linearly inside the
    bucket that crosses the rank (overflow observations clamp to the top
    bound), which is the standard histogram-quantile tradeoff: bounded
    memory, no reservoir bias, accuracy set by the bound spacing. Values
    come from the CALLER'S clock (the batcher's injectable ``clock``), so
    the whole latency story is fake-clock-testable.
    """

    def __init__(self, bounds_ms: Sequence[float] = DEFAULT_BOUNDS_MS):
        bounds = tuple(float(b) for b in bounds_ms)
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError(f"bounds must be strictly increasing, got {bounds}")
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts: Dict[str, list] = {}  # key -> [len(bounds)+1 counts]
        self._sums: Dict[str, float] = {}

    def observe(self, key, ms: float) -> None:
        key = str(key)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.bounds) + 1)
                self._sums[key] = 0.0
            i = 0
            while i < len(self.bounds) and ms > self.bounds[i]:
                i += 1
            counts[i] += 1
            self._sums[key] += float(ms)

    def _quantile_locked(self, counts, q: float) -> float:
        total = sum(counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c:
                if i >= len(self.bounds):  # overflow: clamp to the top bound
                    return self.bounds[-1]
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = self.bounds[i]
                return lo + (hi - lo) * (rank - prev_cum) / c
        return self.bounds[-1]

    def quantile(self, key, q: float) -> float:
        with self._lock:
            counts = self._counts.get(str(key))
            if counts is None:
                return 0.0
            return self._quantile_locked(counts, q)

    def summary(self) -> dict:
        """``{key: {count, mean_ms, p50_ms, p95_ms, p99_ms}}`` — the
        ``/stats`` latency section."""
        out = {}
        with self._lock:
            for key, counts in self._counts.items():
                n = sum(counts)
                out[key] = {
                    "count": n,
                    "mean_ms": (self._sums[key] / n) if n else 0.0,
                    "p50_ms": self._quantile_locked(counts, 0.50),
                    "p95_ms": self._quantile_locked(counts, 0.95),
                    "p99_ms": self._quantile_locked(counts, 0.99),
                }
        return out

    def samples(self, name: str, key_label: str = "bucket") -> list:
        """Prometheus-native cumulative ``_bucket``/``_sum``/``_count``
        series, one set per key."""
        out = []
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                cum = 0
                for bound, c in zip(self.bounds, counts):
                    cum += c
                    out.append((
                        f"{name}_bucket",
                        {key_label: key, "le": f"{bound:g}"}, cum,
                    ))
                cum += counts[-1]
                out.append((f"{name}_bucket", {key_label: key, "le": "+Inf"}, cum))
                out.append((f"{name}_sum", {key_label: key}, self._sums[key]))
                out.append((f"{name}_count", {key_label: key}, cum))
        return out


class TrainerGauges:
    """The trainer sidecar's liveness surface, updated at flush boundaries.

    ``beat(step)`` stamps the boundary clock (wired through
    ``TelemetrySession.flush_boundary`` — the same host-visible point the
    stall watchdog watches); ``set()`` records auxiliary gauges (epoch,
    in-flight windows, and — on health-enabled pretrain runs — the
    ``health_*``/``probe_*`` window means the HealthMonitor stamps from the
    flush consume job, so a scraper reads representation quality next to
    liveness); ``register()`` attaches lazy callables evaluated at
    scrape time (pending checkpoint saves). ``last_boundary_age_seconds``
    is THE liveness signal: a scraper sees it climb monotonically exactly
    when the run is wedged.

    Two supervisor-facing gauges (docs/RESILIENCE.md supervisor section):
    ``start_time_seconds`` — the unix wall clock at construction, so a
    scraper (the fleet supervisor, a Prometheus uptime alert) computes
    process uptime without /proc access; and the TERMINAL ``exit_code``
    gauge — stamped by :meth:`set_exit_code` on the way out of the driver
    (utils/obs.RunObservability.close), absent until then, so the last
    scrape before the sidecar dies classifies the exit (75 preempt,
    3 health > 2 flush > 1 NaN — utils/guard.py exit-code surface) without
    parsing logs.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        self._clock = clock
        self._lock = threading.Lock()
        self._values: Dict[str, float] = {"start_time_seconds": wall_clock()}
        self._lazy: Dict[str, Callable[[], float]] = {}
        self._last_boundary: Optional[float] = None

    def set_exit_code(self, code: int) -> None:
        """Stamp the terminal exit-code gauge (once, on the exit path)."""
        self.set(exit_code=int(code))

    def beat(self, step: int) -> None:
        with self._lock:
            self._values["step"] = float(step)
            self._last_boundary = self._clock()

    def set(self, **kv) -> None:
        with self._lock:
            for k, v in kv.items():
                self._values[k] = float(v)

    def register(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self._lazy[name] = fn

    def collect(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._values)
            lazy = dict(self._lazy)
            last = self._last_boundary
            out["last_boundary_age_seconds"] = (
                self._clock() - last if last is not None else -1.0
            )
        for name, fn in lazy.items():
            try:
                out[name] = float(fn())
            except Exception:  # noqa: BLE001 — a scrape must never raise
                out[name] = -1.0
        return out

    def prometheus_text(self, prefix: str = "train_") -> str:
        return render_prometheus(
            (prefix + name, None, value)
            for name, value in sorted(self.collect().items())
        )


def start_metrics_server(
    port: int, text_fn: Callable[[], str], host: str = "127.0.0.1"
) -> ThreadingHTTPServer:
    """A daemon-threaded ``GET /metrics`` (+ ``/healthz``) HTTP server —
    the trainer sidecar. ``port=0`` binds an ephemeral port
    (``server.server_address`` reports it); callers ``shutdown()`` it in
    their ``finally``. Loopback by default, like ``serve/server.py`` —
    exposing an unauthenticated endpoint beyond the host is an explicit
    ``host=`` choice (``--metrics_host``)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/metrics":
                body = text_fn().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
            elif self.path == "/healthz":
                body = b'{"status": "ok"}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
            else:
                body = b"not found"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet: scrapes every few secs
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    server.daemon_threads = True
    t = threading.Thread(
        target=server.serve_forever, name="metrics-sidecar", daemon=True
    )
    t.start()
    return server
