"""Zero-sync telemetry: the background flush pipeline behind the metric ring.

docs/PERF.md round 5 measured the last mapped driver overhead: every metric
flush is a synchronous D2H on the dispatch thread (~110 ms/window tunneled;
a real sync barrier even on a TPU VM host), costing ~5.5 ms/step at the
recipe's ``print_freq 20``. This module is the training-loop analogue of the
serve/ pipelined executor (PR 3's assembler/completer split): the main thread
SNAPSHOTS the device-side ring at each ``print_freq`` boundary and keeps
dispatching; the D2H, ``check_finite_loss``, meter math, TB writes, and the
progress log line run on one background telemetry thread, strictly FIFO.

Semantics contract (tested, not assumed — tests/test_telemetry.py):

- TB scalars: same tags, same steps, same float values as the synchronous
  path (jobs are FIFO on one thread; the values are the very same device
  computations, only fetched later);
- preemption: ``preempt.requested_global`` stays on the MAIN thread at the
  same deterministic flush boundaries — the collective decision never
  depended on the D2H completing;
- NaN detection: at most one window late, and COLLECTIVE. The worker's
  ``NonFiniteLossError`` re-raises on the main thread at the next boundary
  via :meth:`TelemetrySession.check_failures_global` (all hosts agree
  before any leaves the loop — async submission itself never raises, since
  flush completion timing is per-host) or at ``drain`` — under
  ``--nan_policy abort`` the run aborts one window later; under
  ``rollback`` the epoch is discarded from its boundary backup regardless,
  so the latency is invisible. Non-NaN flush failures (TB ``IOError`` etc.)
  exit as :class:`TelemetryFlushError` instead — never the NaN policy;
- epoch ends and emergency saves ``drain()`` first, so ``loss_avg``, the
  meters, and crash/preempt checkpoints see complete metrics (the same
  exception-forwarding discipline as ``EpochLoader``'s prefetch thread).

``mode='sync'`` runs every job inline on the calling thread — the control
arm for the A/B (scripts/flush_ab.py) and the reference-semantics fallback
(``--telemetry sync``). Failure handling is the SAME in both modes: job
exceptions are stored and surfaced through ``check_failures_global`` at the
boundary (a sync job raising straight out of ``submit`` would skip the
collective failure-code exchange and exit with the raw, unclassified type).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional, Sequence

from simclr_pytorch_distributed_tpu.ops.metrics import MetricRing
from simclr_pytorch_distributed_tpu.utils import tracing

_STOP = object()


class TelemetryFlushError(RuntimeError):
    """A background window-flush job failed for a reason OTHER than a
    non-finite loss (a TB write ``IOError``, a D2H fault, a bug in a consume
    callback). Deliberately distinct from ``NonFiniteLossError``: the NaN
    policy must not roll back epochs over an I/O error, so this aborts under
    BOTH ``--nan_policy`` modes. The original exception rides as
    ``__cause__`` on the host that saw it (under multi-host only the
    collective failure code crosses hosts)."""


class FlushExecutor:
    """One background worker draining window jobs FIFO; exceptions re-raise
    on the main thread at the next boundary."""

    def __init__(self, mode: str = "async"):
        if mode not in ("async", "sync"):
            raise ValueError(f"telemetry mode must be async|sync, got {mode!r}")
        self.mode = mode
        self._exc: Optional[BaseException] = None
        self._cv = threading.Condition()
        self._unfinished = 0
        self._closed = False
        if mode == "async":
            self._q: "queue.SimpleQueue" = queue.SimpleQueue()
            self._thread = threading.Thread(
                target=self._run, name="telemetry-flush", daemon=True
            )
            self._thread.start()

    # -- worker ----------------------------------------------------------
    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is _STOP:
                return
            try:
                # once poisoned, queued jobs are DISCARDED (their metrics
                # post-date the failure) until the main thread observes the
                # exception via poll(); poll clears the poison only after
                # the queue is drained, so no stale job can slip through.
                if self._exc is None:
                    job()
            except BaseException as e:  # noqa: BLE001 — forwarded, not handled
                self._exc = e
            finally:
                with self._cv:
                    self._unfinished -= 1
                    self._cv.notify_all()

    # -- main-thread API -------------------------------------------------
    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue a window job; in ``sync`` mode it runs inline on the
        calling thread. Submission NEVER raises a job exception itself: whether a flush has completed by a given boundary
        is scheduling-dependent, so an eager raise here would surface on
        different hosts at different boundaries — failures surface through
        ``poll``/``drain``/``TelemetrySession.check_failures_global``, which
        the drivers call at deterministic points (queued jobs after a
        failure are discarded by the worker, so the queue stays bounded)."""
        if self._closed:
            # same lifecycle contract in BOTH modes — a submit-after-close
            # must not silently run under the sync control arm while the
            # async default raises
            raise RuntimeError("FlushExecutor is closed")
        if self.mode == "sync":
            # inline — the D2H stall stays on the caller, which is the whole
            # point of the control arm — but failures follow the SAME
            # deferred protocol as async: stored, then classified and raised
            # by the boundary's ``check_failures_global``/``poll``. A raw
            # raise here would leave the epoch loop BEFORE the failure-code
            # exchange, with the wrong type (a TB ``IOError`` instead of
            # ``TelemetryFlushError``) — the exact multi-host hazard
            # ``check_failures_global`` documents.
            if self._exc is None:
                try:
                    job()
                except BaseException as e:  # noqa: BLE001 — forwarded
                    self._exc = e
            return
        with self._cv:
            self._unfinished += 1
        self._q.put(job)

    def wait_idle(self) -> None:
        if self.mode == "sync":
            return
        with self._cv:
            while self._unfinished:
                self._cv.wait()

    def unfinished(self) -> int:
        """Window jobs submitted but not yet completed (the in-flight-windows
        gauge the trainer metrics sidecar exposes)."""
        with self._cv:
            return self._unfinished

    def poll(self) -> None:
        """Re-raise the first worker exception on the calling thread.

        Drains the queue first (the worker discards poisoned jobs), THEN
        clears the poison — so after the raise the executor is clean and
        reusable (the rollback policy keeps training on the same run).
        """
        if self._exc is None:
            return
        self.wait_idle()
        exc, self._exc = self._exc, None
        raise exc

    def drain(self) -> None:
        """Block until every submitted job completed; then surface errors.
        Call before reading meters and before emergency/epoch-end saves."""
        self.wait_idle()
        self.poll()

    def close(self) -> None:
        """Stop the worker. Never raises pending exceptions (it runs in
        ``finally`` blocks where a raise would mask the real failure)."""
        if self._closed:
            return
        self._closed = True
        if self.mode == "sync":
            return
        self._q.put(_STOP)
        self._thread.join()


class TelemetrySession:
    """The ring + executor pair the epoch drivers share.

    The driver's per-window flow is::

        ring_buf = session.init_buffer()                  # fresh each epoch
        state, ring_buf = update_fn(state, ring_buf, ...) # jitted write
        session.append(info, global_step)                 # host bookkeeping
        ...at each print_freq boundary...
        session.submit_window(ring_buf, consume)          # snapshot + queue

    ``submit_window`` snapshots the ring with a device-side copy (one tiny
    HBM->HBM program) BEFORE handing it to the executor: subsequent steps
    donate ``ring_buf``, so the flush must read a buffer donation can't
    reuse. Dispatch order guarantees the copy sees the window's writes.
    """

    def __init__(
        self,
        window: int,
        keys: Sequence[str],
        mode: str = "async",
        device_get: Optional[Callable] = None,
        watchdog=None,
        gauges=None,
    ):
        self.ring = MetricRing(window, keys, device_get=device_get)
        self.executor = FlushExecutor(mode)
        self.mode = mode
        self._window_start = time.time()
        # observability hooks (both host-only, both optional): the stall
        # watchdog is beaten and the sidecar gauges stamped at the same
        # deterministic flush boundaries the collective decisions use —
        # "the boundary stopped advancing" is exactly the signal that means
        # a wedged collective/device rather than ordinary slowness
        self._watchdog = watchdog
        self._gauges = gauges
        # this host's wait inside the PREVIOUS boundary's failure-code
        # allgather (ms), piggybacked on the next one (fleet skew; -1 =
        # nothing yet): see check_failures_global
        self._last_wait_ms = -1

    # ring pass-throughs used by the drivers
    def init_buffer(self, sharding=None):
        return self.ring.init_buffer(sharding)

    def pending_count(self) -> int:
        return self.ring.pending_count()

    def append(self, info, step: int) -> None:
        self.ring.append(info, step)

    def submit_window(self, ring_buf, consume: Callable) -> None:
        """Snapshot the pending window and hand ``consume(fetched_rows)`` to
        the executor. An empty window is a pure no-op — never a raise point:
        failures surface only through ``check_failures_global``/``drain``
        at the drivers' deterministic boundaries."""
        pending = self.ring.take_window()
        if not pending:
            return
        from simclr_pytorch_distributed_tpu.utils.checkpoint import jit_copy_tree

        snapshot = jit_copy_tree(ring_buf)

        def job():
            # the D2H + consume side of the window, on whichever thread the
            # executor runs it (its own track either way: under sync mode it
            # nests inside the main-thread boundary span, which must not
            # share a track with it — main:* tracks never nest)
            with tracing.span(
                "flush_job", track="telemetry:flush", steps=len(pending)
            ):
                consume(self.ring.resolve(snapshot, pending))

        self.executor.submit(job)

    def drain(self) -> None:
        self.executor.drain()

    def drain_global(self, step_hint: int = 0) -> None:
        """Collective drain for the epoch-loop exits.

        Blocks until every submitted job completed (no raise — completion
        timing is per-host), THEN observes failures collectively. Use ahead
        of COLLECTIVE operations (epoch-end and emergency checkpoint saves):
        a plain ``drain()`` raises host-locally, and a lone host skipping a
        collective save while its peers enter it deadlocks the job.
        Single-process this is ``drain()`` with the failure-type contract
        of :meth:`check_failures_global` applied."""
        self.executor.wait_idle()
        self.check_failures_global(step_hint)
        if self._watchdog is not None:
            # a completed drain is progress: the epoch-end save that often
            # follows must start with the full deadline
            self._watchdog.beat()

    def start_window_clock(self) -> None:
        """Reset the boundary-to-boundary wall clock (call at epoch start)."""
        self._window_start = time.time()
        if self._watchdog is not None:
            # an epoch edge is progress too: the first window of an epoch
            # must get the full deadline even after a long validation/save
            self._watchdog.beat()

    def flush_boundary(
        self,
        ring_buf,
        consume: Callable,
        batch_meter=None,
        step_hint: int = 0,
    ) -> None:
        """The drivers' shared ``print_freq``-boundary protocol, in order:

        1. meter the closing window on the MAIN thread as
           boundary-to-boundary wall time / steps (``batch_meter``, when
           given): windows then partition the loop's wall clock exactly —
           a completion-timed measurement would double-count windows that
           overlap under async telemetry, and under ``sync`` the inline
           flush of window k lands in window k+1's delta (one-window
           shift, aggregate preserved);
        2. snapshot + queue the flush (ONE D2H per window, FIFO on the
           telemetry thread);
        3. observe failures COLLECTIVELY (``check_failures_global`` — the
           allgather schedules must match across hosts).

        The caller then makes its own collective preemption decision at the
        same boundary. The ordering is a multi-host correctness invariant:
        keep it here, not copied per driver. That decision
        (``preempt.requested_global``) is a SECOND single-int32 allgather
        right after this one — kept separate deliberately: folding the
        preempt flag into the failure code would couple this module to the
        signal handler's contract to save one tiny collective per
        ``print_freq`` window (single-process runs short-circuit both).

        When ``batch_meter`` is given, ``consume`` is called as
        ``consume(fetched, (val, avg))`` with the meter SNAPSHOTTED here on
        the main thread: the async job runs while later boundaries keep
        mutating the meter, so a worker-side read would print window k+1's
        (possibly torn) numbers against window k's log line.
        """
        # span covers the main-thread boundary work (meter + snapshot +
        # queue) but NOT the collective failure observation below — that
        # records on its own main:collective track, and main:* phase tracks
        # must never nest across each other (the trace_report attribution
        # invariant, utils/tracing.py)
        with tracing.span(
            "flush_boundary", track="main:flush", step=step_hint,
            steps=self.pending_count(),
        ):
            if batch_meter is not None:
                n_pending = self.pending_count()
                if n_pending:
                    now = time.time()
                    batch_meter.update(
                        (now - self._window_start) / n_pending, n=n_pending
                    )
                    self._window_start = now
                bt = (batch_meter.val, batch_meter.avg)
                self.submit_window(
                    ring_buf, lambda fetched: consume(fetched, bt)
                )
            else:
                self.submit_window(ring_buf, consume)
        self.check_failures_global(step_hint)
        # the boundary ADVANCED: beat the stall watchdog and stamp the
        # sidecar gauges (both host-only; no device sync, no transfer)
        if self._watchdog is not None:
            self._watchdog.beat()
        if self._gauges is not None:
            self._gauges.beat(step_hint)
            self._gauges.set(inflight_windows=self.executor.unfinished())

    def finish_epoch(self, submit_tail: Callable[[int], None], step_hint: int) -> None:
        """The drivers' shared epoch-end epilogue, ordering-critical like
        :meth:`flush_boundary` — keep it here, not copied per driver.

        ``submit_tail(step_hint)`` is the driver's own boundary helper,
        invoked for the final boundary: a no-op unless a short epoch left
        steps pending (the ring bookkeeping is session-lifetime — stale
        pending entries would poison the NEXT epoch's windows). Then a
        COLLECTIVE drain: meters are complete before the driver reads
        them, and the raise point stays matched across hosts ahead of the
        collective epoch-end/final save (a host-local raise here would
        skip a save its peers enter)."""
        submit_tail(step_hint)
        self.drain_global(step_hint)

    def close(self) -> None:
        self.executor.close()

    def _failure_code(self) -> int:
        """0 = clean, 1 = non-finite loss, 2 = any other flush failure,
        3 = representation-health abort (guard.HealthMonitor under
        ``--health_policy abort``)."""
        exc = self.executor._exc
        if exc is None:
            return 0
        from simclr_pytorch_distributed_tpu.utils.guard import (
            NonFiniteLossError,
            RepresentationHealthError,
        )

        if isinstance(exc, RepresentationHealthError):
            return 3
        return 1 if isinstance(exc, NonFiniteLossError) else 2

    def check_failures_global(self, step_hint: int = 0) -> None:
        """Collective failure observation for the epoch-loop boundary.

        Under async telemetry, WHETHER a host's flush (and therefore its
        ``check_finite_loss``) has completed by a given boundary is
        scheduling-dependent — so a lone host raising out of the epoch loop
        while its peers dispatch the next window's cross-host collectives
        would deadlock the job, exactly the hazard ``preempt.requested_global``
        guards on the preemption side. Every process calls this at every
        flush boundary (deterministic schedule); if ANY host has a pending
        worker failure, ALL hosts drain and raise at this same boundary —
        and they must leave through the SAME exception type, or the failure
        POLICY diverges across the job (host 0 rolling back while a peer
        aborts is a collective mismatch). The allgathered failure CODE picks
        that type deterministically, by max over hosts: a
        representation-health abort (code 3, ``--health_policy abort``)
        outranks everything — all three codes end the run, but the health
        verdict carries the actionable finding and is never subject to the
        NaN policy (rolling back a collapsed representation just re-detects
        it); a non-NaN flush failure (code 2: a TB-volume ``IOError``, a D2H
        fault) outranks a non-finite loss and exits as
        :class:`TelemetryFlushError` — it must NOT trigger the NaN policy,
        else ``--nan_policy rollback`` would discard clean epochs for a disk
        error; only a pure non-finite-loss window exits as
        ``NonFiniteLossError``. A host whose own windows were clean raises
        the type the code names (skew guard). Single-process jobs
        short-circuit to the local code — no collective in the hot loop.
        """
        import jax

        code = self._failure_code()
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils

            # The allgather payload carries TWO int32s per host: the
            # failure code, plus this host's wait (ms) inside the PREVIOUS
            # boundary's allgather — widening an EXISTING collective, not
            # adding one (the zero-sync discipline). Every host then knows
            # the whole fleet's last-boundary waits: for a synchronous
            # collective each host's wait ≈ (last arrival − its own
            # arrival) + network, so the spread max(wait) − min(wait) is
            # the fleet's ARRIVAL skew and the host that waited LEAST is
            # the straggler (it arrived last; everyone else was parked on
            # it). One boundary stale by construction — the NaN-detection
            # latency convention. The span's own ts/dur are this host's
            # arrival/wait for the offline fleet report.
            prev_wait = self._last_wait_ms
            t_arrive = time.monotonic()
            with tracing.span(
                "failure_code_allgather", track="main:collective",
                step=step_hint, local_code=code,
            ):
                gathered = multihost_utils.process_allgather(
                    np.asarray([code, prev_wait], np.int32)
                )
            wait_s = time.monotonic() - t_arrive
            self._last_wait_ms = min(int(round(wait_s * 1e3)), 2**31 - 1)
            gathered = np.asarray(gathered).reshape(-1, 2)
            code = int(gathered[:, 0].max())
            if self._gauges is not None:
                self._gauges.set(collective_wait_seconds=wait_s)
            waits = gathered[:, 1]
            if len(waits) > 1 and (waits >= 0).all():
                skew_s = float(waits.max() - waits.min()) / 1e3
                if self._gauges is not None:
                    # skew + the straggler's IDENTITY and the fleet size:
                    # the supervisor's rebalance/exclude ladder needs to
                    # know WHO is slow and what share it holds, not just
                    # that someone is (supervise/observe.StragglerTracker)
                    self._gauges.set(
                        boundary_skew_seconds=skew_s,
                        boundary_straggler=float(waits.argmin()),
                        process_count=float(len(waits)),
                    )
                tracing.event(
                    "boundary_skew", track=tracing.FLEET_TRACK,
                    step=step_hint, skew_s=round(skew_s, 6),
                    straggler=int(waits.argmin()),
                )
        elif self._gauges is not None:
            # single process: no peers to wait on — publish the keys so a
            # scraper's dashboard reads 0, not absent (straggler identity
            # -1 = nobody: the supervisor's tracker treats a one-process
            # "fleet" as always benign)
            self._gauges.set(
                collective_wait_seconds=0.0, boundary_skew_seconds=0.0,
                boundary_straggler=-1.0, process_count=1.0,
            )
        # the matched instant every process just left (or, single-process,
        # a plain deterministic stamp): the fleet report's alignment ruler
        tracing.clock_anchor("flush_boundary", step=step_hint)
        if code == 0:
            return
        # the recorder is exactly for this moment: a post-mortem must show
        # WHICH boundary observed the failure and with what collective code
        tracing.event(
            "flush_failure", track="main:guard", code=code, step=step_hint
        )
        from simclr_pytorch_distributed_tpu.utils.guard import (
            NonFiniteLossError,
            RepresentationHealthError,
        )

        try:
            self.drain()  # re-raises this host's own exception when present
        except BaseException as e:
            # The exit TYPE must be a pure function of the ALLGATHERED code:
            # drain() can surface a failure that landed AFTER the code
            # exchange (this host's window was still in flight at the
            # snapshot), and classifying that locally would diverge the
            # policy across hosts — e.g. a late TB IOError aborting here
            # while the NaN peers roll back and re-enter the epoch loop's
            # collectives without us.
            if code == 3:
                if isinstance(e, RepresentationHealthError):
                    raise
                raise RepresentationHealthError(
                    ["peer reported a representation health alarm"], step_hint
                ) from e
            if code == 2:
                raise TelemetryFlushError(
                    f"telemetry flush failed near global step {step_hint}"
                ) from e
            # code == 1: every host exits through the NaN policy. A late
            # local non-NaN failure rides along as the chained cause (the
            # epoch is lost either way; if it recurs it allgathers as
            # code 2 at the next boundary and aborts collectively).
            if isinstance(e, NonFiniteLossError):
                raise
            raise NonFiniteLossError(float("nan"), step_hint) from e
        # skew guard: this host's own windows were clean but a peer flagged
        if code == 3:
            raise RepresentationHealthError(
                ["peer reported a representation health alarm"], step_hint
            )
        if code == 2:
            raise TelemetryFlushError(
                f"peer telemetry flush failed near global step {step_hint}"
            )
        raise NonFiniteLossError(float("nan"), step_hint)
