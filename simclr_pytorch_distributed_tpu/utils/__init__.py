from simclr_pytorch_distributed_tpu.utils.checkpoint import (  # noqa: F401
    load_pretrained_variables,
    restore_checkpoint,
    save_checkpoint,
)
from simclr_pytorch_distributed_tpu.utils.logging_utils import (  # noqa: F401
    TBLogger,
    setup_logging,
)
