from simclr_pytorch_distributed_tpu.utils import preempt  # noqa: F401
from simclr_pytorch_distributed_tpu.utils.checkpoint import (  # noqa: F401
    load_pretrained_variables,
    resolve_resume_path,
    restore_checkpoint,
    save_checkpoint,
    wait_for_saves,
)
from simclr_pytorch_distributed_tpu.utils.guard import (  # noqa: F401
    FailurePolicy,
    NonFiniteLossError,
    check_finite_loss,
)
from simclr_pytorch_distributed_tpu.utils.profiling import StepTracer  # noqa: F401
from simclr_pytorch_distributed_tpu.utils.telemetry import (  # noqa: F401
    FlushExecutor,
    TelemetryFlushError,
    TelemetrySession,
)
from simclr_pytorch_distributed_tpu.utils.logging_utils import (  # noqa: F401
    TBLogger,
    setup_logging,
)
