"""Logging + TensorBoard, process-0 gated.

Mirrors the reference's three channels (SURVEY.md §5 observability row):

- python ``logging`` with a formatted stream handler (``util.py:98-105``) and a
  rank-0 ``log-ing`` file in the save folder (``util.py:108-114`` — whose
  undefined-``root_path`` fallback bug is fixed here by requiring a work_dir);
- TensorBoard scalars with the reference's exact tag names/cadence
  (``info/*`` per-iter, ``loss``/``learning_rate`` per-epoch,
  ``classifier/*`` for the probe) via torch's SummaryWriter;
- stdout progress lines from the epoch drivers.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

_FMT = "%(asctime)s %(filename)s [line:%(lineno)d] %(levelname)s %(message)s"


def setup_logging(
    work_dir: Optional[str] = None,
    is_main: bool = True,
    level: int = logging.INFO,
) -> None:
    """Stream logger everywhere; file logger ``log-ing`` on the main process."""
    root = logging.getLogger()
    root.setLevel(level)
    if not any(isinstance(h, logging.StreamHandler) and not isinstance(h, logging.FileHandler)
               for h in root.handlers):
        sh = logging.StreamHandler()
        sh.setFormatter(logging.Formatter(_FMT))
        root.addHandler(sh)
    if work_dir and is_main:
        os.makedirs(work_dir, exist_ok=True)
        target = os.path.abspath(os.path.join(work_dir, "log-ing"))
        # dedup by handler TARGET, like the stream guard above: repeated
        # setup_logging calls against the same run dir (launcher resume
        # loops in one process, driver tests) must not stack FileHandlers —
        # each stacked handler writes every line once more
        if not any(
            isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == target
            for h in root.handlers
        ):
            fh = logging.FileHandler(target)
            fh.setFormatter(logging.Formatter(_FMT))
            root.addHandler(fh)


class TBLogger:
    """tb_logger.Logger-compatible facade over SummaryWriter; no-op off-main."""

    def __init__(self, logdir: str, enabled: bool = True):
        self._writer = None
        if enabled:
            os.makedirs(logdir, exist_ok=True)
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._writer = SummaryWriter(log_dir=logdir, flush_secs=2)
            except Exception as e:  # pragma: no cover - environment-dependent
                logging.warning("TensorBoard writer unavailable (%s); disabled", e)

    def log_value(self, tag: str, value, step: int) -> None:
        if self._writer is not None:
            self._writer.add_scalar(tag, float(value), int(step))

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
