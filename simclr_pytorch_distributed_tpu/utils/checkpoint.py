"""Checkpointing via orbax — save AND resume (the reference can only save).

Reference semantics being covered (SURVEY.md §3.5):

- cadence/naming: ``ckpt_epoch_{N}`` every ``save_freq`` epochs plus a final
  ``last`` (``main_supcon.py:397-406``);
- contents: model params + optimizer state + epoch + config
  (``util.py:87-96`` — minus its bug of pickling a live tensor inside the
  argparse namespace; config is stored as a plain JSON dict here);
- consumers: pretrain warm-start restores model variables only
  (``main_supcon.py:216-220``); the linear probe restores the encoder
  (``main_linear.py:125-142`` — no 'module.' prefix surgery needed, there is no
  DDP wrapper to strip).

Layout: ``{name}/model`` holds {params, batch_stats} and ``{name}/train`` holds
{opt_state, step, record_norm_mean}, so model-only consumers (probe, warm-start)
never need the optimizer's tree structure. Runs with the ONLINE probe
(``--online_probe on``, train/supcon_step.py) additionally write
``{name}/probe`` holding {probe_params, probe_opt_state} — its OWN payload so
probe-off consumers (warm start, the post-hoc linear probe, serving) never
see it, and a probe-on resume of a probe-off checkpoint degrades to a fresh
probe init with a warning instead of failing the whole restore.

Improvement over the reference: ``restore_checkpoint`` brings back the FULL
train state so a crashed run resumes instead of restarting (the reference has no
resume path at all).
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from simclr_pytorch_distributed_tpu.parallel.mesh import is_main_process
from simclr_pytorch_distributed_tpu.utils import tracing

META_FILE = "meta.json"

# Stamped into meta.json so weight-incompatible model revisions are LOUD on
# resume. Param trees can have identical shapes across revisions (so orbax
# loads them without complaint) while the program means something different:
# v2 = torch-aligned conv padding (models/resnet.py PAD3 — before this,
# stride-2 convs used XLA SAME's (0,1) padding, shifting every window one
# pixel, so pre-v2 checkpoints silently degrade under the current model).
MODEL_LAYOUT_VERSION = 2

# async saves in flight: each entry is one logical checkpoint —
# (its checkpointers, its directory, its meta). meta.json is the "checkpoint
# complete" marker consumers look at, so it is stamped only after THAT
# checkpoint's own payload writes commit (a crash mid-write must never leave a
# complete-looking but unloadable checkpoint).
_PENDING: List[Tuple[List[ocp.StandardCheckpointer], str, dict]] = []


def _abstract(tree, mesh=None):
    """Shape/dtype targets for an orbax restore.

    With ``mesh`` given, every leaf is annotated with its sharding on the
    CURRENT mesh (``parallel.mesh.state_sharding``'s layout: replicated
    over 'data', channel-split over 'model' where it divides) — orbax then
    RESHARDS on load, so a checkpoint saved under mesh shape A restores
    directly onto mesh shape B (elastic resume, docs/RESILIENCE.md). A
    sharded restore never round-trips the whole state through one device:
    each device reads its own slice of the array file.

    Without ``mesh`` (the default) leaves keep whatever sharding the
    abstract tree's arrays carry — the single-host path, where the jitted
    update's ``in_shardings`` does the placement on first dispatch.
    """
    if mesh is None:
        return jax.tree.map(ocp.utils.to_shape_dtype_struct, tree)
    from simclr_pytorch_distributed_tpu.parallel.mesh import state_sharding

    shardings = state_sharding(mesh, tree)
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(
            getattr(x, "shape", ()), getattr(x, "dtype", None), sharding=s
        ),
        tree, shardings,
    )


# One jitted whole-tree copy, shared by every consumer (restore re-owning
# below, the drivers' per-epoch crash backup): a single jit object means one
# trace cache per tree structure/sharding, and one program per dispatch
# instead of ~30 op-by-op jit(copy) cache misses (see train/supcon.py's
# epoch-backup note for the measured cost of the op-by-op version).
jit_copy_tree = jax.jit(lambda t: jax.tree.map(jnp.copy, t))


def resume_position(meta: dict, steps_per_epoch: int) -> Tuple[int, int]:
    """Decode a checkpoint meta into ``(start_epoch, start_step)``.

    ``epoch`` counts completed epochs; ``step_in_epoch`` counts consumed
    steps of the next one (mid-epoch emergency saves). A recorded offset at
    or past this run's ``steps_per_epoch`` means the config changed across
    the resume (batch size / dataset) — the offset is meaningless, so warn
    and degrade to the next epoch boundary.
    """
    import logging

    start_epoch = int(meta.get("epoch", 0)) + 1
    try:
        start_step = int(meta.get("step_in_epoch") or 0)
    except (TypeError, ValueError):
        # hand-edited meta: resolve_resume_path tolerates this (treats it as
        # an epoch boundary), so the resume must degrade the same way
        # instead of crashing the driver
        logging.warning(
            "unparseable step_in_epoch %r in checkpoint meta; resuming at "
            "the epoch boundary", meta.get("step_in_epoch"),
        )
        start_step = 0
    if start_step >= steps_per_epoch:
        logging.warning(
            "checkpoint step_in_epoch %d >= %d steps/epoch (config changed "
            "across resume?); starting at the next epoch",
            start_step, steps_per_epoch,
        )
        start_epoch += 1
        start_step = 0
    return start_epoch, start_step


def _save_tree(path: str, tree, block: bool = True):
    """Orbax save. The D2H serialization is always synchronous (so donated
    device buffers are safe to reuse immediately), but with ``block=False`` the
    disk write continues in a background thread (the returned checkpointer is
    still open) — call ``wait_for_saves()`` before reading the checkpoint back
    or exiting."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=True)
    if block:
        ckptr.wait_until_finished()
        ckptr.close()
        return None
    return ckptr


def _write_meta(path: str, meta: dict) -> None:
    # process-0-gated: orbax payload saves are collective across processes,
    # but the completeness marker has exactly one writer.
    if not is_main_process():
        return
    # atomic: meta.json is the completeness marker, so it must never exist
    # half-written (a truncated marker would crash resume resolution)
    target = os.path.join(path, META_FILE)
    tmp = target + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, default=str)
    os.replace(tmp, target)


def pending_saves() -> int:
    """In-flight async checkpoint writes (the sidecar's checkpoint gauge)."""
    return len(_PENDING)


def wait_for_saves() -> None:
    """Drain all in-flight background checkpoint writes; each checkpoint's
    meta.json marker is stamped as soon as ITS payloads commit."""
    if not _PENDING:
        return
    # the COMMIT side of an async save: submit (save_checkpoint) and commit
    # are separate spans — the flight recorder distinguishes "the driver
    # stalled serializing a save" from "the driver stalled waiting for an
    # earlier save's disk write"
    with tracing.span(
        "checkpoint_commit", track="main:checkpoint", pending=len(_PENDING)
    ):
        while _PENDING:
            ckptrs, path, meta = _PENDING.pop(0)
            for c in ckptrs:
                c.wait_until_finished()
                c.close()
            _write_meta(path, meta)


def _restore_tree(path: str, abstract_tree):
    ckptr = ocp.StandardCheckpointer()
    tree = ckptr.restore(path, abstract_tree)
    ckptr.close()
    return tree


def save_checkpoint(
    save_folder: str, name: str, state, config: Optional[dict] = None,
    epoch: Optional[int] = None, block: bool = True,
    step_in_epoch: int = 0, extra_meta: Optional[dict] = None,
) -> str:
    """Write ``{save_folder}/{name}`` (ckpt_epoch_N / last naming upstream).

    ``block=False`` overlaps the disk write with subsequent training (the
    reference's ``torch.save`` stalls the epoch loop); the driver drains
    pending writes via ``wait_for_saves()`` before the final save/exit.

    ``epoch`` counts COMPLETED epochs; ``step_in_epoch`` counts steps of the
    NEXT epoch (``epoch + 1``) already consumed — non-zero only for the
    mid-epoch emergency saves a preemption triggers (utils/preempt.py). The
    pair is the full dataset-position coordinate a resume needs: the epoch
    shuffle is deterministic in ``base_seed + epoch`` and the per-step PRNG
    key in ``state.step``, so resuming at (epoch+1, step_in_epoch) replays
    the uninterrupted run bit-identically.

    ``extra_meta`` carries driver-side run state that must survive a resume
    but lives outside the jax state tree (the NaN-rollback LR damping, the
    CE trainer's best-accuracy watermark); keys merge into meta.json beside
    the reserved ones.
    """
    if not block:
        # bound resources to one in-flight save: the previous async write
        # (a save_freq of epochs ago) has long finished, so this is ~free.
        # Deliberately OUTSIDE the submit span below — it records its own
        # checkpoint_commit span, and main:* spans never nest (tracing.py).
        wait_for_saves()
    with tracing.span(
        "checkpoint_save", track="main:checkpoint", ckpt=name, block=block
    ):
        if not block:
            # Snapshot before handing off: the caller's buffers are DONATED
            # to the very next train step while the background write may
            # still be serializing them. On backends where device memory IS
            # host memory (CPU) orbax can read the reused buffer and persist
            # a torn state a few steps AHEAD of the recorded epoch —
            # observed as a kill -9 resume restarting from a
            # mid-later-epoch step (tests/test_fault_injection.py). One
            # on-device copy decouples the save from donation on every
            # backend.
            state = jit_copy_tree(state)
        path = os.path.abspath(os.path.join(save_folder, name))
        ckptrs = [_save_tree(
            os.path.join(path, "model"),
            {"params": state.params, "batch_stats": state.batch_stats},
            block=block,
        )]
        ckptrs.append(_save_tree(
            os.path.join(path, "train"),
            {
                "opt_state": state.opt_state,
                "step": state.step,
                "record_norm_mean": state.record_norm_mean,
            },
            block=block,
        ))
        if getattr(state, "probe_params", None) is not None:
            # the online probe's own payload (module docstring): restored
            # only by probe-on resumes, invisible to model/train consumers
            ckptrs.append(_save_tree(
                os.path.join(path, "probe"),
                {
                    "probe_params": state.probe_params,
                    "probe_opt_state": state.probe_opt_state,
                },
                block=block,
            ))
        recipe_payload = _recipe_payload(state)
        if recipe_payload:
            # SSL-recipe slots (recipes/, the probe-payload convention): the
            # predictor/EMA-target/queue trees live in their own payload so
            # model/train consumers never see them and a cross-recipe resume
            # can skip them cleanly. Which slots exist varies by recipe
            # (SimSiam has no recipe_state, the queue has only it), so only
            # the non-None slots are written — restore mirrors this from the
            # abstract state.
            ckptrs.append(_save_tree(
                os.path.join(path, "recipe"), recipe_payload, block=block,
            ))
        meta = {
            **(extra_meta or {}),
            "epoch": epoch, "step_in_epoch": int(step_in_epoch),
            "config": config or {},
            "model_layout": MODEL_LAYOUT_VERSION,
            # the SAVING topology, for the elastic-resume diagnostics: a
            # restore under a different shape is legal (orbax reshards on
            # load) but worth naming, since per-device BN and an explicit
            # --ngpu have shape-dependent training-math consequences
            # (_warn_mesh_change, docs/RESILIENCE.md)
            "devices": jax.device_count(),
            "process_count": jax.process_count(),
        }
        if block:
            _write_meta(path, meta)
        else:
            _PENDING.append((ckptrs, path, meta))
    return path


RECIPE_SLOTS = ("recipe_params", "recipe_opt_state", "recipe_state")


def _recipe_payload(state) -> dict:
    """The non-None recipe slots of a state, as the ``recipe`` payload dict
    (empty when the recipe contributes no slots)."""
    return {
        slot: value for slot in RECIPE_SLOTS
        if (value := getattr(state, slot, None)) is not None
    }


def _restore_recipe_slots(path: str, state, abstract_state, meta: dict,
                          recipe: "str | None", mesh=None,
                          moco_queue: "int | None" = None):
    """Cross-recipe checkpoint hygiene (the probe-payload convention, made
    generic): restore the ``recipe`` payload ONLY when the checkpoint's
    recorded recipe matches this run's, else degrade LOUDLY to the fresh
    recipe-slot init the abstract state already carries.

    Matching is by the ``recipe`` name and ``moco_queue`` geometry stamped
    into meta.json by the driver — not by tree structure, which can
    coincide across recipes and silently restore a mismatched tree. A
    structural mismatch inside a matching name (hand-edited meta, changed
    predictor geometry) still degrades rather than failing the whole
    restore.
    """
    import logging

    wanted = _recipe_payload(abstract_state)
    saved_recipe = meta.get("recipe")
    if not wanted:
        if os.path.isdir(os.path.join(path, "recipe")):
            # e.g. a BYOL checkpoint resumed with --recipe supcon: the
            # encoder trajectory restores, the predictor/target are dropped
            logging.warning(
                "checkpoint %s carries a %r recipe payload this run's "
                "recipe (%r) does not use; recipe slots ignored",
                path, saved_recipe, recipe,
            )
        return state
    if not os.path.isdir(os.path.join(path, "recipe")):
        logging.warning(
            "checkpoint %s has no recipe payload (saved recipe %r, this "
            "run %r); recipe slots start fresh", path, saved_recipe, recipe,
        )
        return state
    if recipe is not None and saved_recipe is not None and saved_recipe != recipe:
        logging.warning(
            "checkpoint %s was trained with recipe %r but this run uses "
            "%r; the encoder trajectory is restored, recipe slots "
            "(predictor/EMA target/queue) start fresh", path, saved_recipe,
            recipe,
        )
        return state
    saved_queue = meta.get("moco_queue")
    if (moco_queue is not None and saved_queue is not None
            and int(saved_queue) != int(moco_queue)):
        logging.warning(
            "checkpoint %s was trained with --moco_queue %s but this run "
            "uses %s; the queue/key-encoder slots start fresh (the ring "
            "geometry changed)", path, saved_queue, moco_queue,
        )
        return state
    try:
        restored = _restore_tree(
            os.path.join(path, "recipe"),
            _abstract(wanted, mesh),
        )
    except Exception as e:  # orbax raises various types on tree mismatch
        logging.warning(
            "checkpoint %s recipe payload does not match this run's "
            "recipe-slot structure (%s); recipe slots start fresh",
            path, e,
        )
        return state
    return state.replace(**restored)


def resolve_resume_path(path: str) -> str:
    """Accepts either one checkpoint dir or a RUN dir; returns a checkpoint.

    Passing a run folder (the timestamped directory holding ``ckpt_epoch_N``/
    ``crash_epoch_N``/``preempt_*``/``last``) picks the COMPLETE checkpoint
    (meta.json present AND parseable) with the most recorded progress —
    ``(epoch, step_in_epoch)`` lexicographically, so a mid-epoch preemption
    save beats the scheduled save of the epoch before it — and after a
    crash/preemption ``--resume <run_dir>`` does the right thing without the
    user inspecting which save survived. A truncated or corrupt meta.json
    (torn emergency write, kill -9 mid-stamp) never wins: it is skipped in
    favor of older complete saves.
    """
    path = os.path.abspath(path)
    if os.path.exists(os.path.join(path, META_FILE)):
        return path
    if os.path.isdir(os.path.join(path, "model")):
        # it IS a checkpoint dir (payload present) whose completeness marker
        # never got stamped — keep the interrupted-save diagnostic rather
        # than misreporting "contains no checkpoint"
        raise RuntimeError(
            f"{path} has no {META_FILE}: the checkpoint write was interrupted "
            f"before completion. Resume from an earlier checkpoint, or pass "
            f"the run directory to pick the latest complete one."
        )
    candidates = []
    for name in os.listdir(path) if os.path.isdir(path) else []:
        meta_path = os.path.join(path, name, META_FILE)
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
            except ValueError:
                continue  # corrupt marker: skip, fall back to older complete saves
            epoch = meta.get("epoch")
            if epoch is not None:
                try:
                    step = int(meta.get("step_in_epoch") or 0)
                except (TypeError, ValueError):
                    step = 0  # hand-edited meta: treat as an epoch boundary
                # Progress ties ((epoch, step) equal) are broken EXPLICITLY
                # in favour of scheduled saves (ckpt_*/last) over emergency
                # crash_*/preempt_* saves — an emergency save at the same
                # recorded progress holds at best the same state, and may
                # predate the scheduled save's optimizer I/O.
                scheduled = 0 if name.startswith(("crash", "preempt")) else 1
                candidates.append(
                    (int(epoch), step, scheduled, os.path.join(path, name))
                )
    if not candidates:
        raise FileNotFoundError(
            f"{path} contains no complete checkpoint (no */{META_FILE})"
        )
    return max(candidates)[3]


def restore_checkpoint(
    path: str, abstract_state, mesh=None, recipe: "str | None" = None,
    moco_queue: "int | None" = None,
) -> Tuple[Any, dict]:
    """Full-state resume. ``abstract_state`` is a freshly built TrainState with
    the right structure (its values are only used as shape/dtype targets).

    ``recipe`` (the run's resolved ``--recipe`` name) and ``moco_queue``
    gate the ``recipe`` payload: it restores only when both match the
    checkpoint's recorded values — a cross-recipe (or changed-queue-
    geometry) resume keeps the encoder trajectory and loudly re-initializes
    the recipe slots (``_restore_recipe_slots``).

    MESH-SHAPE-AGNOSTIC: ``mesh`` (the run's current mesh) makes the restore
    elastic — orbax reshards every leaf onto the current mesh's layout on
    load (see ``_abstract``), so a checkpoint saved under N devices restores
    under M with the optimizer/TrainState intact. The training-math
    consequences of a shape change are the caller's contract, named loudly
    at restore (``_warn_mesh_change``): batch composition is already
    mesh-shape-independent (the EpochLoader's global permutation depends
    only on ``base_seed + epoch``), ``--ngpu auto`` re-derives the gradient
    divisor (with the effective-LR banner), and per-device BN statistics
    (``--syncBN`` off) are the one documented divergence
    (docs/RESILIENCE.md, elastic-resume section).
    """
    path = os.path.abspath(path)
    model = _restore_tree(
        os.path.join(path, "model"),
        _abstract({"params": abstract_state.params,
                   "batch_stats": abstract_state.batch_stats}, mesh),
    )
    train = _restore_tree(
        os.path.join(path, "train"),
        _abstract({"opt_state": abstract_state.opt_state,
                   "step": abstract_state.step,
                   "record_norm_mean": abstract_state.record_norm_mean}, mesh),
    )
    state = abstract_state.replace(
        step=train["step"],
        params=model["params"],
        batch_stats=model["batch_stats"],
        opt_state=train["opt_state"],
        record_norm_mean=train["record_norm_mean"],
    )
    if getattr(abstract_state, "probe_params", None) is not None:
        probe_dir = os.path.join(path, "probe")
        if os.path.isdir(probe_dir):
            probe = _restore_tree(
                probe_dir,
                _abstract({"probe_params": abstract_state.probe_params,
                           "probe_opt_state": abstract_state.probe_opt_state},
                          mesh),
            )
            state = state.replace(
                probe_params=probe["probe_params"],
                probe_opt_state=probe["probe_opt_state"],
            )
        else:
            # probe turned on across the resume: the encoder trajectory is
            # intact either way, so degrade to the fresh probe init instead
            # of refusing the restore (the probe re-converges in steps)
            import logging

            logging.warning(
                "checkpoint %s has no online-probe payload; the probe "
                "restarts from its fresh init", path,
            )
    meta_path = os.path.join(path, META_FILE)
    if not os.path.exists(meta_path):
        # meta.json is stamped only after the payload writes commit; its
        # absence means the save was interrupted. Resuming anyway would
        # silently restart at epoch 1 (wrong LR-schedule position) on top of
        # trained weights — fail loudly instead.
        raise RuntimeError(
            f"{path} has no {META_FILE}: the checkpoint write was interrupted "
            f"before completion. Resume from an earlier checkpoint (e.g. the "
            f"previous ckpt_epoch_N or 'last')."
        )
    with open(meta_path) as f:
        meta = json.load(f)
    # recipe slots AFTER the meta read: which payload (if any) restores is
    # decided by the recipe name recorded there, not by tree structure
    state = _restore_recipe_slots(
        path, state, abstract_state, meta, recipe, mesh=mesh,
        moco_queue=moco_queue,
    )
    # Re-own every restored buffer through the shared jitted copy: orbax
    # hands back arrays whose host memory the XLA allocator does not own,
    # and the train steps DONATE their input state — donating a
    # not-XLA-owned buffer double-frees and corrupts the heap (segfault
    # within two steps of any resume on the CPU backend; found by
    # tests/test_fault_injection.py).
    state = jit_copy_tree(state)
    _warn_layout_mismatch(path, meta)
    _warn_mesh_change(path, meta)
    return state, meta


def _warn_mesh_change(path: str, meta: dict) -> None:
    """Name an elastic resume loudly: the restore itself is exact (orbax
    reshards on load; batch composition depends only on seed+epoch), but
    per-device BN statistics (``--syncBN`` off) and a fixed ``--ngpu``
    divisor make the TRAINING MATH shape-dependent — the documented
    divergence (docs/RESILIENCE.md elastic-resume section)."""
    saved = meta.get("devices")
    if saved is None:
        return
    try:
        saved = int(saved)
    except (TypeError, ValueError):
        return
    now = jax.device_count()
    if saved != now:
        import logging

        logging.warning(
            "elastic resume: checkpoint %s was saved under %d device(s), "
            "restoring under %d — state resharded on load; batch "
            "composition is unchanged (seed+epoch permutation), but "
            "per-device BN statistics (--syncBN off) and a non-auto "
            "--ngpu divisor do depend on the shape (docs/RESILIENCE.md)",
            path, saved, now,
        )


def _warn_layout_mismatch(path: str, meta: dict) -> None:
    saved_layout = meta.get("model_layout", 1)
    if saved_layout != MODEL_LAYOUT_VERSION:
        import logging

        logging.warning(
            "checkpoint %s was saved at model layout v%s but this build is "
            "v%s (see MODEL_LAYOUT_VERSION in utils/checkpoint.py): the "
            "param shapes load, but the weights were trained under different "
            "conv semantics and accuracy will silently degrade",
            path, saved_layout, MODEL_LAYOUT_VERSION,
        )


def save_classifier(save_folder: str, params, best_acc: float) -> str:
    """Persist the best probe classifier head (beyond parity: the reference
    reports best_acc but never saves the trained classifier,
    main_linear.py:284-288)."""
    path = os.path.abspath(os.path.join(save_folder, "classifier_best"))
    _save_tree(os.path.join(path, "model"), {"params": params})
    _write_meta(path, {"best_acc": best_acc})
    return path


def load_classifier(path: str, abstract_params):
    """Restore a classifier head saved by ``save_classifier``."""
    path = os.path.abspath(path)
    return _restore_tree(
        os.path.join(path, "model"), _abstract({"params": abstract_params})
    )["params"]


def _resolve_model_dir(path: str) -> str:
    """Resolve any accepted ``--ckpt`` spelling to a dir holding a ``model``
    payload: a checkpoint dir, a run dir (latest complete checkpoint, with a
    model-only fallback for payloads whose meta marker never got stamped), or
    a reference ``.pth`` file (converted in place to ``<file>.converted/`` on
    first use, utils/torch_convert.py)."""
    path = os.path.abspath(path)
    if os.path.isfile(path):
        out_dir = path + ".converted"
        if not os.path.isdir(os.path.join(out_dir, "model")):
            # multi-process: exactly one writer (orbax force=True DELETES an
            # existing target, so concurrent converters can clobber each
            # other), and a barrier so nobody restores a half-written payload
            from simclr_pytorch_distributed_tpu.parallel.mesh import (
                sync_processes,
            )
            from simclr_pytorch_distributed_tpu.utils.torch_convert import (
                convert_reference_checkpoint,
            )

            if is_main_process():
                convert_reference_checkpoint(path, out_dir)
            sync_processes("pth_convert")
        path = out_dir
    if not os.path.isdir(os.path.join(path, "model")):
        try:
            path = resolve_resume_path(path)
        except (FileNotFoundError, RuntimeError):
            # model-only policy: a committed payload without its meta marker
            # is still loadable here — prefer 'last', else newest ckpt dir
            subs = [
                os.path.join(path, n) for n in sorted(os.listdir(path))
                if os.path.isdir(os.path.join(path, n, "model"))
            ] if os.path.isdir(path) else []
            last = os.path.join(path, "last")
            if os.path.isdir(os.path.join(last, "model")):
                path = last
            elif subs:
                path = max(subs, key=os.path.getmtime)
            else:
                raise
    return path


def _read_meta_and_warn(path: str) -> dict:
    """Best-effort meta.json read + layout-mismatch warning. Bare payload
    dirs without meta.json (hand-built) are exempt — returns ``{}``."""
    meta_path = os.path.join(path, META_FILE)
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            _warn_layout_mismatch(path, meta)
            return meta
        except ValueError:
            pass
    return {}


def load_model_payload(path: str) -> Tuple[dict, dict]:
    """Restore a ``model`` payload WITHOUT knowing the architecture up front.

    Unlike :func:`load_pretrained_variables` (which needs an abstract tree
    built from an already-chosen model), this restores whatever
    ``{'params', 'batch_stats'}`` tree the checkpoint holds — the serving
    engine then infers the architecture from the tree itself
    (``models.heads.infer_architecture_from_variables``), so ``--ckpt`` needs
    no accompanying ``--model`` flag. Accepts the same path spellings as
    ``--ckpt`` (checkpoint dir / run dir / reference ``.pth``).

    Returns ``(variables, meta)``; ``meta`` is ``{}`` for bare payload dirs.
    OWNERSHIP CAVEAT: the arrays are orbax-restored host buffers, NOT
    re-owned — fine for non-donating consumers (the serving engine
    device_puts them, which yields fresh arrays anyway), but anything that
    feeds them into a donating jit must pass them through ``jit_copy_tree``
    first (see ``restore_checkpoint``'s double-free note).
    """
    path = _resolve_model_dir(path)
    meta = _read_meta_and_warn(path)
    ckptr = ocp.StandardCheckpointer()
    try:
        variables = ckptr.restore(os.path.join(path, "model"))
    finally:
        ckptr.close()
    return variables, meta


def load_pretrained_variables(path: str, abstract_variables: dict) -> dict:
    """Model-variables-only load: pretrain warm-start (main_supcon.py:216-220)
    and the probe's encoder restore (main_linear.py:125-142). Accepts a run
    directory too (resolved to its latest complete checkpoint), so ``--ckpt``
    and ``--resume`` take the same kinds of paths. A dir that directly holds a
    ``model`` payload is used as-is — meta.json completeness only gates FULL
    resume, not model-only loads (e.g. hand-built encoder checkpoints).

    A reference ``.pth`` file (torch.save layout, util.py:87-96) is accepted
    directly: it is converted in place to ``<file>.converted/`` on first use
    (utils/torch_convert.py) and loaded from there — ``--ckpt ref.pth`` just
    works."""
    path = _resolve_model_dir(path)
    # The layout check must cover THIS path too — warm-start/probe loads are
    # the primary way an old encoder gets reused.
    _read_meta_and_warn(path)
    variables = _restore_tree(
        os.path.join(path, "model"),
        _abstract({"params": abstract_variables["params"],
                   "batch_stats": abstract_variables["batch_stats"]}),
    )
    # re-own the buffers (see restore_checkpoint): a warm-started pretrain
    # feeds these into a donating step, and donating orbax-owned host
    # memory corrupts the heap
    return jit_copy_tree(variables)
