"""Windowed jax.profiler trace capture (SURVEY.md §5: the reference has only
wall-clock AverageMeters, no profiler at all — this is the TPU-native upgrade).

A ``StepTracer`` starts a TensorBoard-loadable trace at ``start_step`` and
stops it ``num_steps`` later, skipping the compile-dominated first iterations.
View with ``tensorboard --logdir <trace_dir>`` (Profile tab) or xprof.
"""

from __future__ import annotations

import logging

import jax


class StepTracer:
    def __init__(
        self,
        trace_dir: str,
        start_step: int = 10,
        num_steps: int = 10,
        enabled: bool = True,
    ):
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self.enabled = bool(trace_dir) and enabled
        self._active = False

    def step(self, global_step: int) -> None:
        """Call once per training step with the global step index."""
        if not self.enabled:
            return
        # >= not ==: after a checkpoint resume the first observed step may
        # already be past start_step; still capture a window.
        if not self._active and global_step >= self.start_step:
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
            logging.info("profiler: tracing steps [%d, %d) -> %s",
                         self.start_step, self.stop_step, self.trace_dir)
            self.stop_step = global_step + self.stop_step - self.start_step
        elif self._active and global_step >= self.stop_step:
            self.close()

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self.enabled = False  # one window per run
