"""Reference-checkpoint interoperability, BOTH directions:
torch ``.pth`` <-> orbax payload.

The reference saves ``{'opt', 'model', 'optimizer', 'epoch'}`` via ``torch.save``
(``util.py:87-96``), where ``'model'`` is the DDP-wrapped ``SupConResNet``
state_dict — every key carries a ``'module.'`` prefix that the probe strips on
load (``main_linear.py:125-142``). This module converts that layout into this
framework's orbax ``model`` payload (``{'params', 'batch_stats'}``) so a
reference-pretrained encoder can be probed/warm-started here directly, and
exports this framework's checkpoints back into the reference's exact layout so
encoders pretrained HERE can be consumed by the reference's probe or any torch
tooling built around its checkpoints:

    # import: reference .pth -> orbax dir usable as --ckpt
    python -m simclr_pytorch_distributed_tpu.utils.torch_convert \
        path/to/ckpt_epoch_100.pth out_dir/
    python main_linear.py --ckpt out_dir/ ...

    # export: any checkpoint/run dir -> reference-format .pth
    python -m simclr_pytorch_distributed_tpu.utils.torch_convert \
        --export work_space/..._models/<run>/last out.pth

Layout mapping (torch ``resnet_big.py`` -> ``models/``):

- conv weights OIHW -> HWIO (XLA:TPU's native conv kernel layout);
- linear weights ``[out, in]`` -> ``[in, out]``;
- ``bn.weight/bias`` -> ``params/../scale|bias``; ``running_mean/var`` ->
  ``batch_stats/../mean|var``; ``num_batches_tracked`` dropped (torch keeps it
  for momentum=None mode, never used by the reference's momentum=0.1 BNs);
- ``encoder.layer{L}.{i}.conv{k}`` -> ``encoder/layer{L}_block{i}/Conv_{k-1}``,
  ``shortcut.0/1`` -> ``shortcut_conv``/``shortcut_bn``;
- ``head.0/head.2`` (mlp) -> ``proj_head/fc1|fc2``; ``head`` (linear) ->
  ``proj_head/fc``.

Architecture (resnet18/34/50/101, mlp/linear head) is inferred from the
state_dict itself — no unpickling of the reference's argparse Namespace needed.
torch is imported lazily: only conversion needs it, the framework does not.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Tuple

import numpy as np

# torch layer index -> (stage sizes -> model name); resnet_big.py:121-142
_STAGES_TO_NAME = {
    (2, 2, 2, 2): "resnet18",
    (3, 4, 6, 3): None,  # resnet34 (BasicBlock) or resnet50 (Bottleneck)
    (3, 4, 23, 3): "resnet101",
    (1, 1, 1, 1): "resnet10",  # this framework's smoke-test extension
}


def strip_module_prefix(state_dict: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Remove the DDP ``'module.'`` prefix (main_linear.py:129-133)."""
    out = {}
    for k, v in state_dict.items():
        out[k[len("module."):] if k.startswith("module.") else k] = v
    return out


def infer_architecture(sd: Dict[str, np.ndarray]) -> Tuple[str, str, int]:
    """(model_name, head, feat_dim) from state_dict keys/shapes alone."""
    stages = []
    for layer in (1, 2, 3, 4):
        blocks = {
            int(m.group(1))
            for k in sd
            if (m := re.match(rf"encoder\.layer{layer}\.(\d+)\.", k))
        }
        stages.append(max(blocks) + 1 if blocks else 0)
    bottleneck = any(k.startswith("encoder.layer1.0.conv3") for k in sd)
    stages = tuple(stages)
    name = _STAGES_TO_NAME.get(stages)
    if name is None and stages == (3, 4, 6, 3):
        name = "resnet50" if bottleneck else "resnet34"
    if name is None:
        raise ValueError(f"unrecognized stage sizes {stages}")

    if "head.0.weight" in sd:
        head, feat_dim = "mlp", int(sd["head.2.weight"].shape[0])
    elif "head.weight" in sd:
        head, feat_dim = "linear", int(sd["head.weight"].shape[0])
    else:
        # A headless payload would convert "successfully" but then fail a
        # late, cryptic orbax restore against SupConResNet's proj_head tree —
        # fail loudly here instead.
        raise ValueError(
            "state_dict has no head.* keys (encoder-only checkpoint); the "
            "reference's save_model always includes the projection head "
            "(util.py:87-96), and --ckpt loads expect it"
        )
    return name, head, feat_dim


def _set(tree: dict, path: Tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def _conv(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))  # OIHW -> HWIO


def torch_state_dict_to_variables(state_dict) -> dict:
    """Reference ``SupConResNet`` state_dict -> ``{'params', 'batch_stats'}``.

    Accepts torch tensors or numpy arrays; ``'module.'`` prefixes are stripped.
    Raises on any unconsumed key so a layout drift cannot pass silently.
    """
    sd = {
        k: (v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v))
        for k, v in strip_module_prefix(state_dict).items()
    }
    params: dict = {}
    stats: dict = {}
    consumed = set()

    def take(key: str) -> np.ndarray:
        consumed.add(key)
        return np.asarray(sd[key], np.float32)

    def map_bn(src: str, dst: Tuple[str, ...]) -> None:
        _set(params, dst + ("scale",), take(f"{src}.weight"))
        _set(params, dst + ("bias",), take(f"{src}.bias"))
        _set(stats, dst + ("mean",), take(f"{src}.running_mean"))
        _set(stats, dst + ("var",), take(f"{src}.running_var"))
        if f"{src}.num_batches_tracked" in sd:
            consumed.add(f"{src}.num_batches_tracked")

    def map_linear(src: str, dst: Tuple[str, ...]) -> None:
        _set(params, dst + ("kernel",), take(f"{src}.weight").T.copy())
        _set(params, dst + ("bias",), take(f"{src}.bias"))

    for key in sd:
        if key in consumed:
            continue
        if key == "encoder.conv1.weight":
            _set(params, ("encoder", "conv1", "kernel"), _conv(take(key)))
        elif key.startswith("encoder.bn1."):
            map_bn("encoder.bn1", ("encoder", "bn1"))
        elif m := re.match(r"encoder\.layer(\d)\.(\d+)\.(conv|bn)(\d)\.", key):
            layer, block, kind, idx = m.groups()
            scope = ("encoder", f"layer{layer}_block{block}")
            if kind == "conv":
                _set(
                    params, scope + (f"Conv_{int(idx) - 1}", "kernel"),
                    _conv(take(f"encoder.layer{layer}.{block}.conv{idx}.weight")),
                )
            else:
                map_bn(f"encoder.layer{layer}.{block}.bn{idx}", scope + (f"bn{idx}",))
        elif m := re.match(r"encoder\.layer(\d)\.(\d+)\.shortcut\.(\d)\.", key):
            layer, block, idx = m.groups()
            scope = ("encoder", f"layer{layer}_block{block}")
            src = f"encoder.layer{layer}.{block}.shortcut.{idx}"
            if idx == "0":
                _set(params, scope + ("shortcut_conv", "kernel"), _conv(take(f"{src}.weight")))
            else:
                map_bn(src, scope + ("shortcut_bn",))
        elif key.startswith("head.0."):
            map_linear("head.0", ("proj_head", "fc1"))
        elif key.startswith("head.2."):
            map_linear("head.2", ("proj_head", "fc2"))
        elif key.startswith("head.") and key.split(".")[1] in ("weight", "bias"):
            map_linear("head", ("proj_head", "fc"))

    leftover = set(sd) - consumed
    if leftover:
        raise ValueError(f"unmapped reference keys: {sorted(leftover)[:8]}")
    return {"params": params, "batch_stats": stats}


def _inv_conv(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.transpose(w, (3, 2, 0, 1)))  # HWIO -> OIHW


# models the reference can actually construct (resnet_big.py model_dict);
# exports of framework-only extensions (resnet10) would produce a .pth the
# reference cannot consume, so export refuses them.
_REFERENCE_MODELS = frozenset({"resnet18", "resnet34", "resnet50", "resnet101"})


def _bn_stats(stats: dict, path: Tuple[str, ...]) -> dict:
    """Resolve one BN's ``batch_stats`` node, raising ValueError (this
    module's stated error contract) naming the missing node instead of a bare
    KeyError from deep indexing."""
    node = stats
    for p in path:
        if not isinstance(node, dict) or p not in node:
            raise ValueError(
                "variables tree is missing batch_stats for BN node "
                f"'{'/'.join(path)}' — cannot express it in the reference "
                "layout (was the checkpoint saved without batch_stats?)"
            )
        node = node[p]
    for leaf in ("mean", "var"):
        if leaf not in node:
            raise ValueError(
                f"batch_stats node '{'/'.join(path)}' has no '{leaf}' — "
                "cannot express it in the reference layout"
            )
    return node


def variables_to_torch_state_dict(variables: dict) -> Dict[str, np.ndarray]:
    """Inverse of :func:`torch_state_dict_to_variables`: this framework's
    ``{'params', 'batch_stats'}`` -> the reference ``SupConResNet`` state_dict
    layout (``resnet_big.py:156-183``), as numpy arrays without the DDP
    ``'module.'`` prefix. ``num_batches_tracked`` is emitted as 0 for every BN
    (torch's fresh-module value; the reference's momentum=0.1 BNs never read
    it) so ``load_state_dict(strict=True)`` sees a complete dict. Raises on
    any tree node it cannot represent in the reference layout (e.g. the
    ``--stem s2d`` repacked stem), so a lossy export cannot pass silently."""
    params = variables["params"]
    stats = variables.get("batch_stats", {})
    sd: Dict[str, np.ndarray] = {}

    def put(key: str, arr) -> None:
        sd[key] = np.ascontiguousarray(np.asarray(arr, np.float32))

    def put_bn(dst: str, p: dict, stats_path: Tuple[str, ...]) -> None:
        s = _bn_stats(stats, stats_path)
        put(f"{dst}.weight", p["scale"])
        put(f"{dst}.bias", p["bias"])
        put(f"{dst}.running_mean", s["mean"])
        put(f"{dst}.running_var", s["var"])
        sd[f"{dst}.num_batches_tracked"] = np.asarray(0, np.int64)

    def put_linear(dst: str, p: dict) -> None:
        put(f"{dst}.weight", np.asarray(p["kernel"], np.float32).T)
        put(f"{dst}.bias", p["bias"])

    for name, sub in params["encoder"].items():
        if name == "conv1":
            put("encoder.conv1.weight", _inv_conv(sub["kernel"]))
        elif name == "bn1":
            put_bn("encoder.bn1", sub, ("encoder", "bn1"))
        elif m := re.match(r"layer(\d)_block(\d+)$", name):
            layer, block = m.groups()
            for part, leaf in sub.items():
                dst = f"encoder.layer{layer}.{block}"
                if cm := re.match(r"Conv_(\d)$", part):
                    put(f"{dst}.conv{int(cm.group(1)) + 1}.weight",
                        _inv_conv(leaf["kernel"]))
                elif re.match(r"bn\d$", part):
                    put_bn(f"{dst}.{part}", leaf, ("encoder", name, part))
                elif part == "shortcut_conv":
                    put(f"{dst}.shortcut.0.weight", _inv_conv(leaf["kernel"]))
                elif part == "shortcut_bn":
                    put_bn(f"{dst}.shortcut.1", leaf, ("encoder", name, part))
                else:
                    raise ValueError(
                        f"cannot express {name}/{part} in the reference layout"
                    )
        else:
            raise ValueError(
                f"cannot express encoder/{name} in the reference layout "
                f"(e.g. '--stem s2d' checkpoints are not exportable)"
            )

    head = params["proj_head"]
    if "fc1" in head:
        put_linear("head.0", head["fc1"])
        put_linear("head.2", head["fc2"])
    elif "fc" in head:
        put_linear("head", head["fc"])
    else:
        raise ValueError(f"unrecognized proj_head tree: {sorted(head)}")
    return sd


def export_reference_checkpoint(
    ckpt_path: str, out_pth: str, epoch: "int | None" = None,
    allow_missing_meta: bool = False,
) -> dict:
    """This framework's checkpoint -> a reference-format ``.pth``.

    The exported file matches ``util.py:87-96``'s ``save_model`` layout —
    ``{'opt', 'model' ('module.'-prefixed state_dict), 'optimizer', 'epoch'}``
    — so the reference's own ``main_linear.py:125-142`` load path (and any
    torch tooling built around its checkpoints) consumes it directly.
    ``ckpt_path`` is a dir holding a ``model`` payload (ckpt_epoch_N / last /
    a torch_convert output) or a run dir (resolved to its latest complete
    checkpoint). Returns ``{'model_name', 'head', 'feat_dim', 'epoch',
    'path'}``."""
    import torch  # lazy: only conversion needs torch

    import orbax.checkpoint as ocp

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        MODEL_LAYOUT_VERSION,
        resolve_resume_path,
    )

    ckpt_path = os.path.abspath(ckpt_path)
    if not os.path.isdir(os.path.join(ckpt_path, "model")):
        ckpt_path = resolve_resume_path(ckpt_path)
    meta_path = os.path.join(ckpt_path, "meta.json")
    meta = {}
    if not os.path.exists(meta_path):
        # meta.json is both the save-completeness marker (utils/checkpoint.py
        # stamps it atomically after the payload) and the only carrier of
        # model_layout; exporting without it would skip the layout guard
        # below — the 'lossy export cannot pass silently' contract.
        if not allow_missing_meta:
            raise ValueError(
                f"{ckpt_path} has no meta.json — the checkpoint may be an "
                "incomplete save, and its model layout cannot be verified; "
                "pass --allow-missing-meta to export anyway"
            )
    else:
        with open(meta_path) as f:
            meta = json.load(f)
        saved_layout = meta.get("model_layout", 1)
        if saved_layout != MODEL_LAYOUT_VERSION:
            # torch's padding=1 convs match this build's v2 semantics only; a
            # pre-v2 checkpoint would strict-load into the reference cleanly
            # yet be silently wrong — refuse, per this module's contract.
            raise ValueError(
                f"{ckpt_path} was saved at model layout v{saved_layout} but "
                f"the reference's conv semantics require v{MODEL_LAYOUT_VERSION}"
                f"; re-train or re-save under the current layout before export"
            )
    if epoch is None:
        epoch = meta.get("epoch")

    ckptr = ocp.StandardCheckpointer()
    variables = ckptr.restore(os.path.join(ckpt_path, "model"))
    ckptr.close()
    sd_np = variables_to_torch_state_dict(variables)
    sd = {f"module.{k}": torch.from_numpy(v) for k, v in sd_np.items()}
    model_name, head, feat_dim = infer_architecture(sd_np)
    if model_name not in _REFERENCE_MODELS:
        # e.g. resnet10: opt.model would name an architecture absent from the
        # reference's model_dict (resnet_big.py:121-142) — the .pth would
        # export "successfully" yet be unconsumable upstream.
        raise ValueError(
            f"'{model_name}' is a framework-only extension with no entry in "
            "the reference's model_dict — the exported .pth could not be "
            "loaded by the reference"
        )
    payload = {
        # the reference stores its argparse Namespace here; a plain dict keeps
        # the slot readable without importing anything of ours
        "opt": {
            "model": model_name, "head": head, "feat_dim": feat_dim,
            "exported_from": ckpt_path,
            "config": meta.get("config", {}),
        },
        "model": sd,
        "optimizer": {},  # reference stores SGD state; not transferable
        "epoch": int(epoch) if epoch is not None else 0,
    }
    out_pth = os.path.abspath(out_pth)
    os.makedirs(os.path.dirname(out_pth) or ".", exist_ok=True)
    torch.save(payload, out_pth)
    return {
        "model_name": model_name, "head": head, "feat_dim": feat_dim,
        "epoch": epoch, "path": out_pth,
    }


def convert_reference_checkpoint(pth_path: str, out_dir: str) -> dict:
    """Load a reference ``.pth`` and write this framework's orbax payload.

    Returns ``{'model_name', 'head', 'feat_dim', 'epoch', 'path'}``. The output
    dir is directly consumable by ``--ckpt`` (``load_pretrained_variables``
    accepts a dir holding a ``model`` payload).
    """
    import torch  # lazy: only conversion needs torch

    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        MODEL_LAYOUT_VERSION,
        _save_tree,
        _write_meta,
    )

    ckpt = torch.load(pth_path, map_location="cpu", weights_only=False)
    sd = ckpt["model"] if isinstance(ckpt, dict) and "model" in ckpt else ckpt
    sd = strip_module_prefix({k: v for k, v in sd.items()})
    model_name, head, feat_dim = infer_architecture(sd)
    variables = torch_state_dict_to_variables(sd)

    out_dir = os.path.abspath(out_dir)
    _save_tree(os.path.join(out_dir, "model"), variables)
    epoch = ckpt.get("epoch") if isinstance(ckpt, dict) else None
    _write_meta(out_dir, {
        "epoch": int(epoch) if epoch is not None else None,
        # torch weights are padding=1 semantics == this build's v2 layout
        "model_layout": MODEL_LAYOUT_VERSION,
        "config": {
            "model": model_name, "head": head, "feat_dim": feat_dim,
            "converted_from": os.path.abspath(pth_path),
        },
    })
    info = {
        "model_name": model_name, "head": head, "feat_dim": feat_dim,
        "epoch": epoch, "path": out_dir,
    }
    return info


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="convert checkpoints between the reference's torch .pth "
                    "layout and this framework's orbax payload (both "
                    "directions)"
    )
    p.add_argument("src", help="reference .pth (import) or checkpoint/run dir "
                               "(--export)")
    p.add_argument("dst", help="output dir usable as --ckpt (import) or "
                               "output .pth path (--export)")
    p.add_argument(
        "--export", action="store_true",
        help="reverse direction: orbax checkpoint -> reference-format .pth",
    )
    p.add_argument(
        "--allow-missing-meta", action="store_true",
        help="export even when the checkpoint dir has no meta.json "
             "(completeness marker + model-layout carrier); epoch defaults "
             "to 0 and the layout guard is skipped",
    )
    args = p.parse_args(argv)
    if args.export:
        info = export_reference_checkpoint(
            args.src, args.dst, allow_missing_meta=args.allow_missing_meta
        )
    else:
        info = convert_reference_checkpoint(args.src, args.dst)
    print(json.dumps(info))


if __name__ == "__main__":
    main()
