"""Flight recorder: unified span/event tracing + the stall watchdog.

Every subsystem this repo grew — the zero-sync metric ring, the windowed
device store, the pipelined serve executor, the collective
preemption/placement decisions — observes itself in its own private way,
and the failure class the code works hardest to prevent (a split collective
decision deadlocking the pod) is exactly the one that produces NO
diagnostic output at all. This module is the shared answer:

- :class:`FlightRecorder` — a thread-safe span/event recorder with an
  injectable monotonic clock, appending one JSON line per record to a
  per-run ``events.jsonl`` and exporting a Chrome-trace/Perfetto-loadable
  ``trace.json`` on close. Only HOST-VISIBLE boundaries are instrumented
  (flush boundaries, window swaps, checkpoint submit/commit, collective
  decisions, epoch edges, serve request stages), so the dispatch-only hot
  loop gains zero device syncs or transfers — asserted mechanically in
  tier-1 through the existing injectable ``device_get``/``index_put``
  hooks (tests/test_tracing.py).

- :class:`StallWatchdog` — a background thread that fires when the
  observed progress beat (the drivers' flush boundary; the serve
  completer) hasn't advanced within a deadline, dumping ALL thread stacks
  via ``faulthandler`` plus a recorder snapshot into the run dir. A silent
  collective deadlock becomes an attributable artifact instead of an
  opaque hang that burns the preemption grace window.

Track convention (what ``scripts/trace_report.py`` attributes): spans on
``main:*`` tracks are main-thread phases that never nest ACROSS tracks —
they partition the epoch loop's wall clock, so the report's attribution
table (compile / data / flush / checkpoint / collective / ... /
steady-state) sums to the measured wall time. ``main:epoch`` is the one
exception: an envelope track the report uses for context, excluded from
attribution. Tracks owned by other threads (``telemetry:*``,
``prefetch:*``, ``serve:*``) carry no such invariant (concurrent serve
requests overlap by design).

The module-level ``install``/``span``/``event`` helpers follow the
``logging`` pattern: instrumentation sites call ``tracing.span(...)``
unconditionally and pay only a global read + a no-op context manager when
no recorder is installed — deep modules (telemetry, device_store,
checkpoint, preempt, the serve batcher) need no recorder threading through
their signatures.
"""

from __future__ import annotations

import contextlib
import faulthandler
import json
import logging
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger(__name__)

# main-thread phase tracks: the non-nesting attribution convention above
MAIN_TRACK_PREFIX = "main:"
# the envelope track excluded from attribution (it CONTAINS the others)
EPOCH_TRACK = "main:epoch"
# the fleet track: clock anchors + per-boundary skew observations — the
# records scripts/trace_report.py --fleet aligns multi-process timelines on
FLEET_TRACK = "fleet"
ANCHOR_EVENT = "clock_anchor"



class FlightRecorder:
    """Thread-safe span/event recorder behind one lock.

    Records live in a bounded in-memory ring (``snapshot`` — what the
    watchdog dumps) and, when ``path`` is given, are appended to an
    ``events.jsonl`` file as they land. ``clock`` must be monotonic;
    timestamps are stored relative to construction time, so records from
    different processes align only per-file (one recorder per process,
    ``recorder_for_run``).
    """

    def __init__(
        self,
        path: Optional[str] = None,
        clock: Callable[[], float] = time.monotonic,
        max_events: int = 65536,
        trace_path: Optional[str] = None,
        process_index: int = 0,
    ):
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=max_events)
        self._path = path
        self._trace_path = trace_path
        self._file = None
        self._closed = False
        self.process_index = int(process_index)
        self.dropped = 0  # records lost to the ring bound (jsonl keeps all)
        self._anchor_seq = 0  # clock_anchor sequence (see clock_anchor)

    # ------------------------------------------------------------ record
    def _emit(self, rec: dict) -> None:
        line = json.dumps(rec, default=str)
        with self._lock:
            if self._closed:
                return
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)
            if self._path is not None:
                if self._file is None:
                    self._file = open(self._path, "a")
                self._file.write(line + "\n")
                # flush every record: a flight recorder exists for the runs
                # that DON'T exit cleanly (SIGKILL after the grace window, a
                # wedged collective) — a userspace buffer would lose exactly
                # the last, most interesting records. Records land only at
                # host boundaries (a few per window / per request), so the
                # per-record flush is noise there; no fsync — surviving a
                # kernel crash is not the contract.
                self._file.flush()

    def now(self) -> float:
        """The recorder's clock (absolute; records store ``now() - t0``)."""
        return self._clock()

    def event(self, name: str, track: str = "events", **attrs) -> None:
        """An instantaneous event (Chrome ``ph: "i"``)."""
        rec = {
            "name": name, "track": track, "ph": "i",
            "ts": round(self._clock() - self._t0, 6),
        }
        if attrs:
            rec["args"] = attrs
        self._emit(rec)

    def clock_anchor(self, kind: str, **attrs) -> int:
        """Record a fleet clock anchor and return its sequence number.

        Anchors are stamped at ALREADY-MATCHED collective points (the
        startup placement agreement, each flush-boundary failure-code
        allgather) right AFTER the collective releases — on a pod every
        process leaves the allgather at (approximately) the same real
        instant, so anchor ``seq`` k is the same physical moment observed
        through each process's unaligned monotonic clock. That makes the
        per-process ``(seq, ts)`` pairs an alignment ruler:
        ``scripts/trace_report.py --fleet`` fits one affine map per process
        over them and merges the timelines. The sequence is deterministic
        because the collective call SCHEDULE is (the documented invariant
        of those call sites — a mismatched count is already a deadlock).
        Single-process runs record the same events (host-only, zero device
        cost); they simply carry no cross-process information.
        """
        with self._lock:
            self._anchor_seq += 1
            seq = self._anchor_seq
        self.event(ANCHOR_EVENT, track=FLEET_TRACK, kind=kind, anchor=seq,
                   **attrs)
        return seq

    def record_span(
        self, name: str, track: str, start: float, end: float, **attrs
    ) -> None:
        """A completed span from explicit clock values.

        ``start``/``end`` must come from THIS recorder's clock domain
        (``now()`` or the same injected clock) — the cross-thread spelling
        the serve batcher uses to stamp a request at submit and record it
        at completion on another thread.
        """
        rec = {
            "name": name, "track": track, "ph": "X",
            "ts": round(start - self._t0, 6),
            "dur": round(max(0.0, end - start), 6),
        }
        if attrs:
            rec["args"] = attrs
        self._emit(rec)

    @contextlib.contextmanager
    def span(self, name: str, track: str, **attrs):
        start = self._clock()
        try:
            yield
        finally:
            self.record_span(name, track, start, self._clock(), **attrs)

    # ------------------------------------------------------------ output
    def snapshot(self, last: Optional[int] = None) -> List[dict]:
        """The most recent records (all, or the last ``last``) — what the
        watchdog attaches to a stall dump."""
        with self._lock:
            records = list(self._ring)
        return records if last is None else records[-last:]

    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()

    def export_chrome_trace(self, path: Optional[str] = None) -> dict:
        """The Chrome-trace/Perfetto view of the in-memory ring; written to
        ``path`` (or the constructor's ``trace_path``) when given."""
        trace = chrome_trace_from_events(
            self.snapshot(), process_index=self.process_index
        )
        path = path or self._trace_path
        if path:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(trace, f)
            os.replace(tmp, path)
        return trace

    def close(self) -> None:
        """Flush the jsonl, export ``trace.json`` (when configured), and
        stop accepting records. Never raises — it runs in driver
        ``finally`` blocks where a raise would mask the real failure."""
        with self._lock:
            if self._closed:
                return
        if self.dropped:
            # a saturated ring means trace.json and watchdog snapshots are
            # truncated (the jsonl keeps everything): leave the count on
            # the durable record so trace_report can flag it as a finding
            self.event(
                "recorder_dropped", track="events", records=self.dropped
            )
        try:
            self.export_chrome_trace()
        except OSError as e:  # disk full on the way out: keep the exit clean
            logger.warning("flight recorder: trace export failed (%s)", e)
        with self._lock:
            self._closed = True
            if self._file is not None:
                try:
                    self._file.flush()
                    self._file.close()
                except OSError:
                    pass
                self._file = None


def chrome_trace_from_events(events: Iterable[dict], process_index: int = 0) -> dict:
    """Chrome trace-event JSON from recorder records (pure; schema pinned by
    tests/test_tracing.py). Tracks map to integer ``tid``s with
    ``thread_name`` metadata; ``ts``/``dur`` are integer microseconds."""
    tids: dict = {}
    out = []
    for rec in events:
        track = rec.get("track", "events")
        tid = tids.get(track)
        if tid is None:
            tid = tids[track] = len(tids) + 1
        ev = {
            "name": rec["name"],
            "ph": "X" if rec.get("ph") == "X" else "i",
            "pid": process_index,
            "tid": tid,
            "ts": int(round(rec["ts"] * 1e6)),
            "args": rec.get("args", {}),
        }
        if ev["ph"] == "X":
            ev["dur"] = int(round(rec.get("dur", 0.0) * 1e6))
        else:
            ev["s"] = "t"  # instant-event scope: thread
        out.append(ev)
    meta = [
        {
            "name": "thread_name", "ph": "M", "pid": process_index, "tid": tid,
            "args": {"name": track},
        }
        for track, tid in tids.items()
    ]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


EVENTS_FILE = "events.jsonl"
TRACE_FILE = "trace.json"

# events[_pN][_rK].jsonl — process N (absent = 0), session K (absent = 1)
EVENTS_FILE_RE = re.compile(r"^(events(?:_p(\d+))?)(?:_r(\d+))?\.jsonl$")


def parse_jsonl(text: str) -> Tuple[List[dict], int]:
    """Parse recorder jsonl text into ``(records, consumed)``.

    The ONE torn-line-tolerant reader behind ``load_events_jsonl``,
    ``scripts/trace_report.py``, ``scripts/health_report.py``, and the
    supervisor's ``RunDirWatcher``: only COMPLETE lines (through the last
    newline) are consumed — the half-written final line a SIGKILL (or a
    reader racing the writer) leaves behind is exactly the run the
    recorder exists to diagnose, so it must never crash the reader.
    Complete-but-corrupt lines are skipped, not raised. ``consumed`` is
    the offset just past the last newline — the incremental-tail
    bookkeeping the watcher keeps per file.
    """
    consumed = text.rfind("\n") + 1
    records: List[dict] = []
    for line in text[:consumed].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records, consumed


def load_events_jsonl(path: str) -> List[dict]:
    """All complete records of one recorder jsonl (torn-line tolerant)."""
    with open(path) as f:
        return parse_jsonl(f.read())[0]


def session_files_for(events_path: str) -> List[str]:
    """Every session file of the PROCESS ``events_path`` belongs to, in
    session order: ``events.jsonl``, ``events_r2.jsonl``, ... (or the
    ``events_pN*`` family). A resumed run rotates to a fresh ``_rK`` file
    per session (:func:`run_paths`), so a reader that stops at the first
    file silently truncates the timeline at the first preemption. Unknown
    file names return just themselves."""
    d, fname = os.path.split(events_path)
    m = EVENTS_FILE_RE.match(fname)
    if not m:
        return [events_path]
    base = m.group(1)
    out = []
    k = 1
    while True:
        name = f"{base}.jsonl" if k == 1 else f"{base}_r{k}.jsonl"
        path = os.path.join(d, name)
        if not os.path.exists(path):
            break
        out.append(path)
        k += 1
    return out or [events_path]


def discover_fleet_sessions(run_dir: str) -> Dict[str, Dict[int, str]]:
    """All recorder sessions in a run dir, grouped for the fleet view:
    ``{"r1": {0: ".../events.jsonl", 1: ".../events_p1.jsonl"}, "r2": ...}``
    — one entry per session, mapping process index -> that process's
    events file. Sessions align only within themselves (timestamps restart
    per session), so the fleet report merges each session independently."""
    sessions: Dict[int, Dict[int, str]] = {}
    for fname in sorted(os.listdir(run_dir)):
        m = EVENTS_FILE_RE.match(fname)
        if not m:
            continue
        pidx = int(m.group(2) or 0)
        k = int(m.group(3) or 1)
        sessions.setdefault(k, {})[pidx] = os.path.join(run_dir, fname)
    return {f"r{k}": files for k, files in sorted(sessions.items())}


def run_paths(run_dir: str, process_index: int = 0):
    """Per-process, per-SESSION recorder file names inside one (shared)
    run dir.

    Timestamps are relative to each recorder's construction, so a resumed
    run (the exit-75 relaunch loop lands in the SAME save_folder) must not
    append a second ts~0 timeline into the first session's file — that
    would read as overlapping main-thread spans and fail trace_report's
    attribution on exactly the preempted runs the recorder exists to
    diagnose. Each session therefore gets the first unused ``_rK`` suffix:
    ``events.jsonl``, ``events_r2.jsonl``, ... (and the matching
    ``trace*.json``), one self-consistent timeline per file.
    """
    base = "events" if process_index == 0 else f"events_p{process_index}"
    tbase = "trace" if process_index == 0 else f"trace_p{process_index}"
    session = ""
    k = 1
    while os.path.exists(os.path.join(run_dir, f"{base}{session}.jsonl")):
        k += 1
        session = f"_r{k}"
    return (
        os.path.join(run_dir, f"{base}{session}.jsonl"),
        os.path.join(run_dir, f"{tbase}{session}.json"),
    )


def recorder_for_run(
    run_dir: str, enabled: bool = True, clock: Callable[[], float] = time.monotonic
) -> Optional[FlightRecorder]:
    """The drivers' one-call recorder factory: ``events.jsonl`` +
    ``trace.json`` in the run dir (per-process suffixes on a pod — every
    host keeps its own story; a pod post-mortem reads all of them — and
    per-session suffixes across resumes, see :func:`run_paths`)."""
    if not enabled or not run_dir:
        return None
    import jax  # lazy: this module must stay importable without jax

    pidx = jax.process_index()
    os.makedirs(run_dir, exist_ok=True)
    events, trace = run_paths(run_dir, pidx)
    return FlightRecorder(
        events, clock=clock, trace_path=trace, process_index=pidx
    )


# ---------------------------------------------------------------- current
# logging-style module-level recorder: instrumentation sites stay one-line
# and cost a global read when no recorder is installed.

_current: Optional[FlightRecorder] = None


def install(recorder: Optional[FlightRecorder]) -> None:
    global _current
    _current = recorder


def uninstall() -> None:
    install(None)


def current() -> Optional[FlightRecorder]:
    return _current


@contextlib.contextmanager
def span(name: str, track: str, **attrs):
    rec = _current
    if rec is None:
        yield
        return
    with rec.span(name, track, **attrs):
        yield


def event(name: str, track: str = "events", **attrs) -> None:
    rec = _current
    if rec is not None:
        rec.event(name, track, **attrs)


def clock_anchor(kind: str, **attrs) -> Optional[int]:
    """Record a fleet clock anchor on the installed recorder (no-op
    ``None`` without one) — see :meth:`FlightRecorder.clock_anchor`."""
    rec = _current
    if rec is None:
        return None
    return rec.clock_anchor(kind, **attrs)


def record_span(name: str, track: str, start: float, end: float, **attrs) -> None:
    rec = _current
    if rec is not None:
        rec.record_span(name, track, start, end, **attrs)


# ---------------------------------------------------------------- watchdog


class StallWatchdog:
    """Fires when the progress beat hasn't advanced within ``deadline_s``.

    The drivers beat at every ``print_freq`` flush boundary (wired through
    ``TelemetrySession``), the serve batcher beats as in-flight batches
    complete — exactly the points whose silence means a stalled collective,
    a wedged device, or a deadlocked pipeline. On fire it writes two
    artifacts into ``dump_dir``:

    - ``stall_dump_N.txt`` — every thread's Python stack
      (``faulthandler.dump_traceback``), i.e. WHERE each host thread is
      blocked (the collective call, the queue wait, the D2H);
    - ``stall_dump_N.json`` — the stall metadata plus a
      :class:`FlightRecorder` snapshot (what the run was doing on the way
      in), when a recorder is attached.

    One dump per stall: after firing it stays quiet until a beat re-arms
    it. ``check()`` is the testable core — the fake-clock tier-1 tests
    drive it directly (``start=False``), the background thread merely calls
    it on a real-time cadence. The watchdog only OBSERVES (no recovery
    action): killing or resuming a wedged collective from a watchdog thread
    would trade a diagnosable hang for corrupted state.
    """

    def __init__(
        self,
        deadline_s: float,
        dump_dir: str,
        clock: Callable[[], float] = time.monotonic,
        recorder: Optional[FlightRecorder] = None,
        poll_s: Optional[float] = None,
        start: bool = True,
        name: str = "train",
        armed: bool = True,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.dump_dir = dump_dir
        self.name = name
        self._clock = clock
        self._recorder = recorder
        self._lock = threading.Lock()
        self._last = clock()
        self._armed = bool(armed)
        self._fired = False
        self.dumps = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            # real-time polling cadence; staleness itself is judged against
            # the injectable clock, so tests never depend on this thread
            self._poll_s = poll_s if poll_s is not None else max(
                1.0, self.deadline_s / 4.0
            )
            self._thread = threading.Thread(
                target=self._run, name=f"stall-watchdog-{name}", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            self.check()

    # ------------------------------------------------------------- beats
    def beat(self) -> None:
        """Progress observed: reset the deadline and re-arm the next dump."""
        with self._lock:
            self._last = self._clock()
            self._fired = False

    def arm(self) -> None:
        """Start watching (beats first — arming is itself progress)."""
        with self._lock:
            self._last = self._clock()
            self._fired = False
            self._armed = True

    def disarm(self) -> None:
        """Stop watching (e.g. the serve pipeline went idle: silence is
        expected, not a stall)."""
        with self._lock:
            self._armed = False

    # ------------------------------------------------------------- check
    def check(self) -> bool:
        """Evaluate the deadline now; returns True iff a dump was written
        by THIS call."""
        with self._lock:
            if not self._armed or self._fired:
                return False
            age = self._clock() - self._last
            if age <= self.deadline_s:
                return False
            self._fired = True
            self.dumps += 1
            n = self.dumps
        self._dump(age, n)
        return True

    def _dump(self, age: float, n: int) -> None:
        txt_path = os.path.join(self.dump_dir, f"stall_dump_{n}.txt")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(txt_path, "w") as f:
                f.write(
                    f"STALL: {self.name} progress beat stalled for "
                    f"{age:.1f}s (deadline {self.deadline_s:.1f}s); "
                    f"all thread stacks follow\n"
                )
                f.flush()
                faulthandler.dump_traceback(file=f, all_threads=True)
        except OSError as e:  # the watchdog must never kill the run
            logger.error("stall watchdog: stack dump failed (%s)", e)
        if self._recorder is not None:
            self._recorder.event(
                "stall_detected", track="watchdog", age_s=round(age, 3),
                deadline_s=self.deadline_s, dump=n,
            )
            self._recorder.flush()
            json_path = os.path.join(self.dump_dir, f"stall_dump_{n}.json")
            try:
                with open(json_path, "w") as f:
                    json.dump(
                        {
                            "name": self.name,
                            "age_s": round(age, 3),
                            "deadline_s": self.deadline_s,
                            "dump": n,
                            "events": self._recorder.snapshot(last=512),
                        },
                        f, default=str,
                    )
            except OSError as e:
                logger.error("stall watchdog: snapshot dump failed (%s)", e)
        logger.error(
            "STALL: %s progress beat stalled for %.1fs (deadline %.1fs); "
            "thread stacks dumped to %s", self.name, age, self.deadline_s,
            txt_path,
        )

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
