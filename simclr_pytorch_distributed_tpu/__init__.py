"""simclr_pytorch_distributed_tpu — a TPU-native (JAX/XLA/pjit) framework with the
capabilities of Dyfine/SimCLR_pytorch_distributed.

The reference is a 2-GPU PyTorch DDP SimCLR/SupCon pretrainer (NCCL all-gather of
projection features + SyncBN) plus a single-GPU linear probe. This package rebuilds
it TPU-first:

- single-program SPMD over a ``jax.sharding.Mesh`` (GSPMD) instead of
  ``torch.distributed.launch`` + DDP (reference ``main_supcon.py:359-364``),
- cross-replica batch norm falls out of sharded-batch statistics instead of
  ``SyncBatchNorm.convert_sync_batchnorm`` (reference ``main_supcon.py:223-224``),
- the NT-Xent global-negatives gather is a differentiable logical-global matmul
  (XLA inserts the collectives) instead of ``torch.distributed.all_gather`` plus
  the local-tensor re-insertion trick (reference ``main_supcon.py:268-279``),
- augmentations run jitted on device instead of 8 PIL DataLoader workers
  (reference ``main_supcon.py:200-207``).
"""

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy convenience re-export (PEP 562): the bare package import must
    # stay jax-free so the stdlib-ast invariant linter
    # (simclr_pytorch_distributed_tpu/analysis/, scripts/invariant_lint.py)
    # really runs on a box with no jax — an eager `from ops.losses import
    # supcon_loss` here pulled jax into every subpackage import.
    if name == "supcon_loss":
        from simclr_pytorch_distributed_tpu.ops.losses import supcon_loss

        return supcon_loss
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
