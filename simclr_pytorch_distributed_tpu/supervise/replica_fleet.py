"""ReplicaFleetSupervisor — supervise/ generalized to a serving fleet.

The trainer supervisor (supervisor.py) babysits ONE child through exit
codes; this loop manages N serving replicas through the channels a serving
process exposes while alive: process liveness (``Popen.poll``) and the
``/metrics`` gauges (observe.MetricsScraper against each replica's port).
Every tick it scrapes the fleet into :class:`~supervise.replica.
ReplicaObservation` rows, asks the pure :class:`~supervise.replica.
ReplicaPolicy` what to do, and realizes the decisions:

- ``spawn_replica``  — allocate a port, launch the replica command with
  ``{port}`` substituted (the serve or serve.fleet CLI);
- ``restart_replica`` — SIGTERM -> grace -> SIGKILL the old process, then
  relaunch on the SAME port (the HTTP servers set SO_REUSEADDR), so
  clients and the scraper keep their address;
- ``drain_replica``  — graceful terminate and forget the slot (scale-down);
- ``give_up_replica`` — kill if needed, abandon the slot, keep the record.

Every observation and decision lands as recorder events
(``replica_spawn`` / ``replica_restart`` / ``replica_drain`` /
``replica_give_up`` / ``fleet_observation``) via utils.tracing, so the
scenario harness — and a fleet post-mortem — read the same jsonl format as
the trainer supervisor's.

Like everything in supervise/, this module never touches jax: replicas are
subprocesses that own their own devices; the supervisor is a host-only
control plane. ``popen``/``clock``/``sleep``/``free_port``/
``scraper_factory`` are injectable together, so tests drive the whole loop
with fakes and no network (tests/test_replica_fleet.py); the real
multi-process run is scripts/serve_fleet_scenario.py.
"""

from __future__ import annotations

import dataclasses
import signal
import socket
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence

from simclr_pytorch_distributed_tpu.supervise.observe import MetricsScraper
from simclr_pytorch_distributed_tpu.supervise.replica import (
    DRAIN,
    GIVE_UP,
    RESTART,
    SPAWN,
    ReplicaObservation,
    ReplicaPolicy,
)
from simclr_pytorch_distributed_tpu.utils import tracing


def default_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class ReplicaFleetConfig:
    """``command`` is the replica argv with ``{port}`` placeholders
    (every element is ``str.format``-ted with ``port=``); the supervisor
    owns port assignment so replicas can't collide."""

    command: Sequence[str]
    min_replicas: int = 1
    max_replicas: int = 4
    poll_interval_s: float = 2.0
    grace_s: float = 10.0  # SIGTERM -> SIGKILL window on restart/drain
    host: str = "127.0.0.1"
    scrape_timeout_s: float = 2.0


class _Replica:
    def __init__(self, rid: int, port: int, proc, scraper, started: float):
        self.id = rid
        self.port = port
        self.proc = proc
        self.scraper = scraper
        self.started = started
        self.restarts = 0


class ReplicaFleetSupervisor:
    def __init__(
        self,
        config: ReplicaFleetConfig,
        policy: Optional[ReplicaPolicy] = None,
        *,
        popen: Callable = subprocess.Popen,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        free_port: Optional[Callable[[], int]] = None,
        scraper_factory: Optional[Callable[[int], object]] = None,
        env: Optional[dict] = None,
    ):
        self.config = config
        self.policy = policy if policy is not None else ReplicaPolicy(
            config.min_replicas, config.max_replicas
        )
        self._popen = popen
        self._clock = clock
        self._sleep = sleep
        self._free_port = free_port or (
            lambda: default_free_port(config.host)
        )
        self._scraper_factory = scraper_factory or (
            lambda port: MetricsScraper(
                port, host=config.host, timeout_s=config.scrape_timeout_s
            )
        )
        self._env = env
        self._replicas: Dict[int, _Replica] = {}
        self._next_id = 0
        self._gave_up: List[int] = []
        self._decisions: List[dict] = []  # every decision applied, in order

    # ------------------------------------------------------------ plumbing

    def _launch(self, port: int):
        cmd = [str(arg).format(port=port) for arg in self.config.command]
        return self._popen(cmd, env=self._env)

    def _terminate(self, replica: _Replica) -> Optional[int]:
        """SIGTERM, grace, SIGKILL — launch.Child's ladder on a raw Popen."""
        proc = replica.proc
        if proc.poll() is not None:
            return proc.returncode
        try:
            proc.send_signal(signal.SIGTERM)
        except OSError:
            return proc.poll()
        deadline = self._clock() + self.config.grace_s
        while self._clock() < deadline:
            if proc.poll() is not None:
                return proc.returncode
            self._sleep(0.1)
        try:
            proc.kill()
        except OSError:
            pass
        return proc.wait()

    def spawn(self, reason: str = "initial") -> _Replica:
        rid = self._next_id
        self._next_id += 1
        port = self._free_port()
        replica = _Replica(
            rid, port, self._launch(port), self._scraper_factory(port),
            self._clock(),
        )
        self._replicas[rid] = replica
        tracing.event(
            "replica_spawn", track="fleet:replicas", replica=rid, port=port,
            reason=reason,
        )
        return replica

    # ----------------------------------------------------------- the loop

    def observe(self) -> List[ReplicaObservation]:
        now = self._clock()
        out = []
        for replica in self._replicas.values():
            alive = replica.proc.poll() is None
            metrics = replica.scraper.scrape() if alive else None
            out.append(ReplicaObservation(
                replica=replica.id, alive=alive, metrics=metrics,
                age_s=now - replica.started,
            ))
        return out

    def step(self) -> List[dict]:
        """One observe -> decide -> apply tick; returns the applied
        decisions (dicts, as recorded)."""
        observations = self.observe()
        decisions = self.policy.decide(observations)
        applied = []
        for decision in decisions:
            record = {
                "action": decision.action,
                "replica": decision.replica,
                "reason": decision.reason,
            }
            if decision.action == SPAWN:
                replica = self.spawn(reason=decision.reason)
                record["replica"] = replica.id
                record["port"] = replica.port
            elif decision.action == RESTART:
                replica = self._replicas.get(decision.replica)
                if replica is None:
                    continue
                rc = self._terminate(replica)
                replica.proc = self._launch(replica.port)
                replica.started = self._clock()
                replica.restarts += 1
                record["port"] = replica.port
                record["old_returncode"] = rc
                tracing.event(
                    "replica_restart", track="fleet:replicas",
                    replica=replica.id, port=replica.port, returncode=rc,
                    reason=decision.reason,
                )
            elif decision.action == DRAIN:
                replica = self._replicas.pop(decision.replica, None)
                if replica is None:
                    continue
                rc = self._terminate(replica)
                record["returncode"] = rc
                tracing.event(
                    "replica_drain", track="fleet:replicas",
                    replica=replica.id, port=replica.port, returncode=rc,
                    reason=decision.reason,
                )
            elif decision.action == GIVE_UP:
                replica = self._replicas.pop(decision.replica, None)
                if replica is None:
                    continue
                self._terminate(replica)
                self._gave_up.append(replica.id)
                tracing.event(
                    "replica_give_up", track="fleet:replicas",
                    replica=replica.id, port=replica.port,
                    reason=decision.reason,
                )
            applied.append(record)
        self._decisions.extend(applied)
        return applied

    def run(
        self,
        duration_s: Optional[float] = None,
        until: Optional[Callable[[], bool]] = None,
    ) -> None:
        """Tick until ``until()`` (checked every poll) or the duration
        lapses. The first tick runs immediately, so a fresh supervisor
        spawns its floor without waiting a poll interval."""
        deadline = (
            self._clock() + duration_s if duration_s is not None else None
        )
        while True:
            self.step()
            if until is not None and until():
                return
            if deadline is not None and self._clock() >= deadline:
                return
            self._sleep(self.config.poll_interval_s)

    def stop_all(self) -> None:
        for replica in list(self._replicas.values()):
            self._terminate(replica)
        self._replicas.clear()

    # -------------------------------------------------------------- views

    def replicas(self) -> Dict[int, dict]:
        return {
            r.id: {
                "port": r.port,
                "pid": getattr(r.proc, "pid", None),
                "alive": r.proc.poll() is None,
                "restarts": r.restarts,
            }
            for r in self._replicas.values()
        }

    def decisions(self) -> List[dict]:
        return list(self._decisions)

    def gave_up(self) -> List[int]:
        return list(self._gave_up)
