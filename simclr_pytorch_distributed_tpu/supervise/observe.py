"""Signal collection for the supervisor — scraping and run-dir watching.

Three channels, all host-side, all read-only against the trainer:

- :class:`MetricsScraper` — HTTP GET against the trainer's ``--metrics_port``
  sidecar (utils/prom.py) with :func:`parse_prometheus_text`, the inverse of
  ``render_prometheus`` for the unlabeled gauge lines the sidecar emits.
  ``train_last_boundary_age_seconds`` is THE liveness signal; the scraper
  never raises (a dead sidecar is itself an observation, returned as None).
- :class:`RunDirWatcher` — incremental polling of the trainer's run dir for
  the artifacts the observability layer drops: stall-watchdog dumps
  (``stall_dump_N.txt``), ``health_alarm`` / ``nan_rollback`` /
  ``preempt_exit`` events appended to the recorder's ``events*.jsonl``
  (tail-read from a remembered offset — the file is append-only by
  construction), and newly COMPLETE checkpoints (``*/meta.json``). Each
  ``poll()`` returns only what is NEW since the last, so the supervisor's
  own recorder logs each artifact exactly once.
- exit codes arrive through ``subprocess`` and are classified by
  :mod:`supervise.policy` — nothing to collect here.

Nothing in this module (or anywhere in supervise/) ever initializes the jax
backend — no ``jax.devices()``, no jit, no arrays: the supervisor is a
host-only process that must never touch the accelerator its child needs.
"""

from __future__ import annotations

import glob
import os
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

# the shared torn-line-tolerant jsonl reader (tracing imports no jax at
# module level — the supervisor stays a host-only process)
from simclr_pytorch_distributed_tpu.utils.tracing import parse_jsonl

# recorder event names the watcher surfaces to the supervisor (the trainer
# emits them on its side: utils/guard.py HealthMonitor, train/*.py)
WATCHED_EVENTS = ("health_alarm", "nan_rollback", "preempt_exit", "stall_detected")


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Unlabeled ``name value`` lines -> dict; labeled/histogram series and
    comment lines are skipped (the trainer sidecar emits only plain gauges;
    tolerating the rest keeps the parser usable against the serve server's
    richer exposition too)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "{" in line:
            continue
        parts = line.split()
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


class MetricsScraper:
    """GET /metrics against the trainer sidecar; ``scrape()`` returns the
    gauge dict or None (connection refused, timeout, bad body — a dead or
    not-yet-up sidecar is an observation, not an error). ``opener`` is
    injectable for the no-network unit tests."""

    def __init__(
        self, port: int, host: str = "127.0.0.1", timeout_s: float = 2.0,
        opener=None,
    ):
        self.url = f"http://{host}:{port}/metrics"
        self.timeout_s = timeout_s
        self._opener = opener if opener is not None else self._http_get

    def _http_get(self) -> str:
        with urllib.request.urlopen(self.url, timeout=self.timeout_s) as r:
            return r.read().decode()

    def scrape(self) -> Optional[Dict[str, float]]:
        try:
            return parse_prometheus_text(self._opener())
        except (OSError, urllib.error.URLError, ValueError):
            return None


# the fleet-skew gauges the trainer sidecar exposes: utils/telemetry.py
# stamps them at each flush-boundary failure-code allgather (the skew is
# the spread of per-host waits piggybacked on that collective; the
# straggler gauge is argmin(wait) — the host everyone else waited on —
# and process_count is the fleet size that wait vector came from)
SKEW_GAUGE = "train_boundary_skew_seconds"
WAIT_GAUGE = "train_collective_wait_seconds"
STRAGGLER_GAUGE = "train_boundary_straggler"
PROC_COUNT_GAUGE = "train_process_count"


def straggler_finding(
    gauges: Optional[Dict[str, float]], skew_bar_s: float
) -> Optional[dict]:
    """One boundary's straggler observation from one sidecar scrape, or None.

    Fires when ``train_boundary_skew_seconds`` (the fleet's boundary
    arrival spread) is at/above ``skew_bar_s``: some process is late to
    the collectives and the whole synchronous step is paced by it. One
    finding is one BOUNDARY, not a verdict: transient skew (a GC pause, a
    noisy neighbor) is normal, so acting on a single finding would thrash.
    :class:`StragglerTracker` folds the per-boundary findings into a
    K-of-N persistence verdict, and the policy ladder
    (supervise/policy.py: warn -> restart_rebalanced -> restart_resized ->
    give_up) acts on THAT.

    Beyond the skew itself the finding carries the REBALANCE CONTEXT when
    the sidecar exposes it (a PR-16 trainer): ``straggler`` — which
    process the fleet waited on (``train_boundary_straggler``, -1/absent
    on a single process); ``processes`` — the fleet size; and ``share`` —
    the straggler's current per-process share of the global batch
    (``1/processes``; data/pipeline.EpochLoader slices uniform contiguous
    blocks), the quantity a ``restart_rebalanced`` decision shrinks.
    Against an older sidecar without those gauges the finding still fires
    but carries no identity — enough to warn, not enough to mitigate."""
    if not gauges or skew_bar_s <= 0:
        return None
    skew = gauges.get(SKEW_GAUGE)
    if skew is None or skew < skew_bar_s:
        return None
    finding = {"skew_s": skew, "bar_s": skew_bar_s}
    for key, name in ((WAIT_GAUGE, "wait_s"), ("train_step", "step")):
        if key in gauges:
            finding[name] = gauges[key]
    straggler = gauges.get(STRAGGLER_GAUGE)
    if straggler is not None and straggler >= 0:
        finding["straggler"] = int(straggler)
    processes = gauges.get(PROC_COUNT_GAUGE)
    if processes is not None and processes > 0:
        finding["processes"] = int(processes)
        finding["share"] = 1.0 / int(processes)
    return finding


# sentinel: "no boundary deduped yet" (train_step may legitimately be
# absent from a scrape — a None step must still dedup correctly)
_NO_STEP = object()


class StragglerTracker:
    """Per-boundary straggler findings -> a K-of-N PERSISTENCE verdict.

    ``observe(gauges)`` is fed every scrape; it returns the boundary's
    finding exactly once per boundary (the skew gauge holds its value
    between flush boundaries, so scrapes are deduplicated on the
    ``train_step`` gauge) and maintains a sliding window of the last
    ``window_n`` boundaries. A straggler is declared PERSISTENT — exposed
    by :meth:`take_persistent` — only when at least ``persist_k`` of those
    boundaries named the SAME host above the bar. That hysteresis is the
    point: one boundary of skew (a GC pause, a checkpoint fsync, a noisy
    neighbor burst) never triggers, and a straggler identity that hops
    between hosts (load imbalance, not a sick host) never accumulates K
    votes for any one of them.

    Single-process runs are ALWAYS benign: without the identity gauges
    (``train_boundary_straggler`` >= 0 and ``train_process_count`` > 1)
    a boundary contributes no vote — there is no host to rebalance away
    from, and utils/telemetry.py publishes zero skew anyway.

    ``clock`` is injectable (the supervisor passes its own): verdict
    timestamps come from it, never from ``time`` directly, so the loop
    tests drive the tracker without real waiting. ``take_persistent``
    consumes the verdict and resets the window — the supervisor acts once
    per verdict (or records it once, in warn-only mode), and detection
    starts fresh for the next attempt via :meth:`reset`.
    """

    def __init__(
        self,
        skew_bar_s: float,
        persist_k: int = 3,
        window_n: int = 5,
        clock=None,
    ):
        if persist_k < 1:
            raise ValueError(f"persist_k must be >= 1, got {persist_k}")
        if window_n < persist_k:
            raise ValueError(
                f"need window_n >= persist_k, got {window_n}/{persist_k}"
            )
        self.skew_bar_s = float(skew_bar_s)
        self.persist_k = int(persist_k)
        self.window_n = int(window_n)
        self._clock = clock if clock is not None else (lambda: 0.0)
        # sliding window of (straggler-or-None, finding) per NEW boundary
        self._window: List[tuple] = []
        self._last_step: object = _NO_STEP
        self._persistent: Optional[dict] = None

    def reset(self) -> None:
        """Fresh window + step dedup (a new child attempt restarts its
        gauge stream; stale votes must not convict the relaunch)."""
        self._window = []
        self._last_step = _NO_STEP
        self._persistent = None

    def observe(self, gauges: Optional[Dict[str, float]]) -> Optional[dict]:
        """Feed one scrape; returns the finding when this scrape is a NEW
        boundary at/above the bar (for the supervisor to record), else
        None. Below-bar boundaries still enter the window — they dilute
        the vote, which is how a recovered host walks itself back out."""
        if not gauges or self.skew_bar_s <= 0:
            return None
        step = gauges.get("train_step")
        if step == self._last_step:
            return None  # same boundary; the gauge holds between beats
        self._last_step = step
        finding = straggler_finding(gauges, self.skew_bar_s)
        host = finding.get("straggler") if finding else None
        multi = (gauges.get(PROC_COUNT_GAUGE) or 0) > 1
        vote = host if (finding is not None and host is not None and multi) else None
        self._window.append((vote, finding))
        if len(self._window) > self.window_n:
            self._window.pop(0)
        if vote is not None:
            votes = sum(1 for v, _ in self._window if v == vote)
            if votes >= self.persist_k:
                self._persistent = dict(
                    finding, votes=votes, window=len(self._window),
                    at=self._clock(),
                )
        return finding

    def take_persistent(self) -> Optional[dict]:
        """The pending persistence verdict (finding + ``votes``/``window``/
        ``at``), or None; consuming it resets the window."""
        verdict = self._persistent
        if verdict is not None:
            self.reset()
        return verdict


class RunDirWatcher:
    """Incremental view of one trainer run dir.

    ``poll()`` returns ``(stall_dumps, events, checkpoints)`` — only items
    NEW since the previous poll:

    - ``stall_dumps``: paths of fresh ``stall_dump_N.txt`` files (the
      watchdog's artifact — its presence is a liveness verdict from INSIDE
      the process, complementing the scraper's outside view);
    - ``events``: recorder records from every ``events*.jsonl`` in the dir
      whose ``name`` is in :data:`WATCHED_EVENTS` (per-session ``_rK`` and
      per-process ``_pN`` suffixes included — resumes open new files);
    - ``checkpoints``: checkpoint dir names whose ``meta.json`` appeared
      (progress evidence: a supervisor post-mortem shows what was SAVED
      between decisions, not just what failed).

    The run dir may not exist yet (the child creates it after config
    finalization) — polls before that return empty results.
    """

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        # path -> mtime: a RELAUNCHED trainer restarts its watchdog counter
        # at 1 and overwrites stall_dump_1.txt in the (reused) run dir, so
        # path identity alone would hide every stall after the first — a
        # changed mtime makes an overwritten dump new again
        self._seen_dumps: Dict[str, float] = {}
        self._offsets: Dict[str, int] = {}   # events file -> bytes consumed
        self._seen_ckpts: set = set()

    def _new_events(self) -> List[dict]:
        events: List[dict] = []
        for path in sorted(glob.glob(os.path.join(self.run_dir, "events*.jsonl"))):
            offset = self._offsets.get(path, 0)
            try:
                with open(path) as f:
                    f.seek(offset)
                    chunk = f.read()
            except OSError:
                continue
            # only consume COMPLETE lines: the trainer appends+flushes per
            # record, but a poll can still race the write mid-line
            # (tracing.parse_jsonl — the one shared torn-line-tolerant
            # reader, also behind trace_report/health_report)
            records, consumed = parse_jsonl(chunk)
            self._offsets[path] = offset + consumed
            for rec in records:
                if rec.get("name") in WATCHED_EVENTS:
                    rec["_file"] = os.path.basename(path)
                    events.append(rec)
        return events

    def poll(self) -> Tuple[List[str], List[dict], List[str]]:
        if not os.path.isdir(self.run_dir):
            return [], [], []
        dumps = []
        for p in sorted(glob.glob(os.path.join(self.run_dir, "stall_dump_*.txt"))):
            try:
                mtime = os.path.getmtime(p)
            except OSError:
                continue
            if self._seen_dumps.get(p) != mtime:
                self._seen_dumps[p] = mtime
                dumps.append(p)
        ckpts = []
        for meta in sorted(glob.glob(os.path.join(self.run_dir, "*", "meta.json"))):
            name = os.path.basename(os.path.dirname(meta))
            if name not in self._seen_ckpts:
                self._seen_ckpts.add(name)
                ckpts.append(name)
        return dumps, self._new_events(), ckpts
