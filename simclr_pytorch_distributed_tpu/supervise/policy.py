"""The supervisor's decision policy — pure, explicit, unit-testable.

Everything the supervisor DOES flows through :meth:`DecisionPolicy.decide`:
one ``ExitObservation`` (how the child left, what the channels saw on the
way) in, one ``Decision`` out. The method performs no I/O and reads no
clocks, so tests/test_supervise.py enumerates the whole decision table
without launching a process — the same discipline as ``guard.FailurePolicy``
and the ratchet's pure ``*_gate_record`` functions.

The classification input is the typed exit-code surface (utils/guard.py,
docs/RESILIENCE.md):

==========================  =============================================
child exit                  decision
==========================  =============================================
0                           DONE
75 (preempt, state saved)   RESTART with ``--resume`` — immediately, no
                            backoff (the exit was clean by contract); if a
                            resize target is pending, RESTART_RESIZED onto
                            it instead (mesh-shape-agnostic restore makes
                            that legal, utils/checkpoint.py). Does NOT
                            apply when the 75 was forced by the
                            supervisor's own stall kill — that stays a
                            backoff restart (see the stall row)
3 (health abort)            GIVE_UP — collapse lives in the weights, so a
                            relaunch from the crash save just re-detects
                            it (the RESILIENCE.md precedence note); a
                            human changes the recipe
1 (NaN) / 2 (flush)         BACKOFF then restart with ``--resume`` — NaN
                            may be a transient (bad host, ECC hiccup) and
                            the in-driver ``--nan_policy rollback`` is the
                            principled self-heal; flush failures are
                            I/O-flavored and often clear
signal death (rc < 0)       BACKOFF then restart with ``--resume`` —
                            kill -9 / OOM left no grace, resume resolution
                            picks the newest COMPLETE save
supervisor-observed stall   the supervisor killed the child itself
                            (liveness age or a watchdog dump); BACKOFF
                            then restart with ``--resume``
75 + persistent straggler   the supervisor preempted the child itself
                            after a K-of-N persistence verdict
                            (observe.StragglerTracker): the STRAGGLER
                            LADDER — first verdict RESTART_REBALANCED
                            (shrink the slow host's share; the epoch
                            permutation is process-count-independent, so
                            the stream survives), second RESTART_RESIZED
                            excluding the slow host (the elastic-resume
                            path), third GIVE_UP — a host that stays slow
                            through rebalance AND exclusion means the
                            diagnosis is wrong, and a human should look.
                            Never fires over a pending OPERATOR resize
                            (the explicit request wins), always bounded
                            by the restart budget, and a run that later
                            preempts cleanly with no verdict resets the
                            ladder (recovery). A mitigation preempt the
                            child did NOT honor (grace lapsed to SIGKILL)
                            falls through to the signal-death row — the
                            ladder only advances on the clean exit 75 the
                            mitigation contract promises
anything else               BACKOFF then restart — bounded by the budget,
                            so a permanent failure (bad flag, import
                            error) burns at most ``max_restarts`` cheap
                            attempts before GIVE_UP reports the real code
==========================  =============================================

Restart budget: ``max_restarts`` bounds TOTAL relaunches (the launcher
loop's ``PREEMPT_RETRIES`` contract, now shared by every failure class —
straggler mitigations included). Backoff is exponential in CONSECUTIVE
failures — a clean preemption resets the streak (the fleet is healthy,
the scheduler is just busy) — capped at ``backoff_max_s``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from simclr_pytorch_distributed_tpu.utils import guard, preempt

# Decision.action values (strings, not an enum: they go straight into
# recorder events and the evidence artifact as JSON)
DONE = "done"
RESTART = "restart_resume"
RESTART_RESIZED = "restart_resized"
RESTART_REBALANCED = "restart_rebalanced"
BACKOFF_RESTART = "backoff_restart"
GIVE_UP = "give_up"
# emitted by the SUPERVISOR loop (not decide()): the supervisor itself was
# SIGTERM/SIGINT'd and relayed the signal to the child instead of relaunching
SHUTDOWN = "shutdown"

# the rebalance rung's share shrink: the slow host keeps this fraction of
# its uniform per-process share (the hint launch.share_env carries into the
# relaunch; on a real fleet the scheduler realizes it, docs/RESILIENCE.md)
REBALANCE_FACTOR = 0.5


@dataclasses.dataclass(frozen=True)
class ExitObservation:
    """One child exit, as the supervisor saw it.

    ``returncode`` follows the subprocess convention (negative = died to
    that signal). ``stalled`` means the SUPERVISOR killed the child after
    a liveness verdict (boundary age over the deadline, or a watchdog
    stall dump appeared) — the returncode is then just our own SIGKILL.
    ``stall_dumps``/``health_alarms`` count artifacts observed during the
    attempt (forensics context for the decision event; a health ALARM
    under ``--health_policy warn`` does not by itself end a run — only
    the exit code 3 of an ``abort`` policy does).

    ``straggler_persistent`` means the SUPERVISOR gracefully preempted
    the child after a K-of-N straggler persistence verdict
    (observe.StragglerTracker) — the mitigation request the ladder acts
    on when the exit is the clean 75 the preempt contract promises.
    ``straggler_host``/``straggler_skew_s``/``processes`` carry the
    verdict's context (who, how slow, out of how many) for the rebalance
    share and the exclusion topology; -1/0 when unknown.
    """

    returncode: int
    stalled: bool = False
    stall_dumps: int = 0
    health_alarms: int = 0
    straggler_persistent: bool = False
    straggler_host: int = -1
    straggler_skew_s: float = 0.0
    processes: int = 0


@dataclasses.dataclass(frozen=True)
class Decision:
    """What the supervisor does next. ``delay_s`` is slept before the
    relaunch; ``devices`` is the new topology for RESTART_RESIZED (None
    everywhere else); ``share`` is the ``host:factor`` rebalance hint for
    RESTART_REBALANCED (launch.share_env carries it into the relaunch);
    ``reason`` is the human- and JSON-facing line."""

    action: str
    reason: str
    delay_s: float = 0.0
    devices: Optional[int] = None
    share: Optional[str] = None


class DecisionPolicy:
    """Decision state across one supervised run: the restart budget, the
    consecutive-failure streak the backoff grows on, and the pending
    resize target (set by the supervisor when a resize request arrives,
    consumed by the first restartable exit that follows)."""

    def __init__(
        self,
        max_restarts: int = 3,
        backoff_base_s: float = 1.0,
        backoff_max_s: float = 60.0,
    ):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        if backoff_base_s <= 0 or backoff_max_s < backoff_base_s:
            raise ValueError(
                f"need 0 < backoff_base_s <= backoff_max_s, got "
                f"{backoff_base_s}/{backoff_max_s}"
            )
        self.max_restarts = max_restarts
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.restarts = 0          # relaunches performed so far
        self.failures = 0          # consecutive non-clean exits (backoff input)
        self.pending_resize: Optional[int] = None
        # straggler-ladder rung already taken: 0 none (warn territory),
        # 1 rebalanced, 2 excluded — the NEXT verdict takes rung+1
        self.straggler_level = 0

    # ---------------------------------------------------------------- helpers
    def backoff_s(self) -> float:
        """Exponential backoff for the CURRENT consecutive-failure streak:
        base * 2^(failures-1), capped. ``failures`` is incremented before
        this is read (a first failure waits the base)."""
        exp = max(0, self.failures - 1)
        return min(self.backoff_max_s, self.backoff_base_s * (2.0 ** exp))

    def request_resize(self, devices: int) -> None:
        if devices <= 0:
            raise ValueError(f"resize target must be positive, got {devices}")
        self.pending_resize = int(devices)

    def _restart(self, action: str, reason: str, delay_s: float = 0.0) -> Decision:
        """Book a restart; a pending resize upgrades any restartable
        decision (the resize request was the OPERATOR'S, so it must not be
        lost to an unlucky crash landing before the preempt exit)."""
        self.restarts += 1
        if self.pending_resize is not None:
            devices, self.pending_resize = self.pending_resize, None
            return Decision(
                RESTART_RESIZED,
                f"{reason}; resizing to {devices} device(s)",
                delay_s=delay_s, devices=devices,
            )
        return Decision(action, reason, delay_s=delay_s)

    def _mitigate_straggler(self, obs: ExitObservation) -> Decision:
        """The escalation ladder, one rung per persistence verdict. The
        warn rung is rung 0 and lives OUTSIDE this method: per-boundary
        findings and the persistence verdict itself are recorded by the
        supervisor before any preempt (and are the ONLY response in
        warn-only mode). Reaching here means the supervisor already
        preempted for mitigation and the child exited cleanly."""
        self.straggler_level += 1
        host = obs.straggler_host
        skew = obs.straggler_skew_s
        if self.straggler_level == 1:
            self.restarts += 1
            return Decision(
                RESTART_REBALANCED,
                f"persistent straggler host {host} (skew {skew:.3f}s): "
                f"rebalancing its share to {REBALANCE_FACTOR:g}x and "
                f"resuming",
                share=f"{host}:{REBALANCE_FACTOR:g}",
            )
        if self.straggler_level == 2:
            self.restarts += 1
            devices = (
                max(1, obs.processes - 1) if obs.processes > 1 else None
            )
            return Decision(
                RESTART_RESIZED,
                f"straggler host {host} persists after rebalance (skew "
                f"{skew:.3f}s): excluding it and resuming on the "
                f"remaining host(s)",
                devices=devices,
            )
        return Decision(
            GIVE_UP,
            f"straggler host {host} persists after rebalance AND "
            f"exclusion (skew {skew:.3f}s): mitigation ladder exhausted "
            f"— the slowness is not where the fleet thinks it is; a "
            f"human should look",
        )

    # ----------------------------------------------------------------- decide
    def decide(self, obs: ExitObservation) -> Decision:
        rc = obs.returncode
        if rc == 0:
            return Decision(DONE, "child completed (exit 0)")
        if rc == guard.EXIT_HEALTH:
            # never retried: collapse lives in the weights (RESILIENCE.md
            # precedence note) — a relaunch from the crash save re-detects
            # it one window in; the budget is irrelevant
            return Decision(
                GIVE_UP,
                "representation-health abort (exit 3): collapse lives in "
                "the weights — change the recipe, do not relaunch",
            )
        if self.restarts >= self.max_restarts:
            return Decision(
                GIVE_UP,
                f"restart budget exhausted ({self.restarts}/"
                f"{self.max_restarts}); last exit {rc}",
            )
        if rc == preempt.EXIT_PREEMPTED and not obs.stalled:
            # clean by contract (state saved) — no backoff, and the
            # failure streak resets: preemption is scheduling, not illness.
            # NOT taken when the SUPERVISOR initiated the kill (obs.stalled):
            # a responsive-enough child turns our stall SIGTERM into a tidy
            # exit 75, but the condition that triggered the kill is still a
            # failure — streak-resetting it would hammer the restart budget
            # in a tight kill/relaunch loop and misattribute the
            # supervisor's own kill as scheduler preemption in post-mortems
            self.failures = 0
            if obs.straggler_persistent:
                if self.pending_resize is not None:
                    # operator-resize precedence: the explicit request
                    # wins over the inferred mitigation (the supervisor
                    # also refuses to INITIATE one over a pending resize
                    # — this row covers the race where both land on the
                    # same exit); _restart consumes the pending target
                    return self._restart(
                        RESTART,
                        "preempted with a persistent-straggler verdict, "
                        "but an operator resize is pending: the explicit "
                        "request wins",
                    )
                return self._mitigate_straggler(obs)
            # a clean, boundary-rich exit with NO verdict in force means
            # the mitigation (or the fleet) recovered: the ladder resets,
            # so a straggler relapse much later starts at rebalance again
            # instead of escalating straight to give_up
            self.straggler_level = 0
            return self._restart(
                RESTART, "preempted (exit 75, state saved): resume"
            )
        self.failures += 1
        delay = self.backoff_s()
        if obs.stalled:
            reason = (
                f"stalled (boundary liveness/watchdog; {obs.stall_dumps} "
                f"dump(s)): killed, resume after {delay:g}s"
            )
            if rc == preempt.EXIT_PREEMPTED:
                reason += " (child honored SIGTERM; state saved)"
        elif rc == guard.EXIT_NONFINITE:
            # exit 1 is also the interpreter's code for any unhandled
            # crash — both shapes get the same bounded resume-and-retry
            reason = (
                f"non-finite loss abort or unhandled crash (exit 1): "
                f"resume after {delay:g}s (for NaNs, consider "
                f"--nan_policy rollback)"
            )
        elif rc == guard.EXIT_FLUSH:
            # exit 2 is also argparse's usage-error code — a typo'd flag
            # lands here too, so the reason names both readings
            reason = (
                f"telemetry flush failure or usage error (exit 2): resume "
                f"after {delay:g}s (if it recurs instantly, check the "
                f"command's flags)"
            )
        elif rc < 0:
            reason = (
                f"died to signal {-rc} (no grace): resume from the newest "
                f"complete save after {delay:g}s"
            )
        else:
            reason = f"unclassified exit {rc}: resume after {delay:g}s"
        return self._restart(BACKOFF_RESTART, reason, delay_s=delay)
