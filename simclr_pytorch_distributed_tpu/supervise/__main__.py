"""CLI: ``python -m simclr_pytorch_distributed_tpu.supervise [flags] -- cmd...``

Everything after ``--`` is the training command, launched verbatim (plus an
appended ``--resume <run_dir>`` on relaunches). The shell launchers
(run_supcon.sh / run_linear.sh) delegate here by default; ``SUPERVISE=0``
keeps their legacy bounded-retry loop.

Example — babysit a pretrain with liveness-kill and an 8-device virtual
mesh, scraping the trainer's sidecar on 9100::

    python -m simclr_pytorch_distributed_tpu.supervise \
        --workdir ./work_space --max_restarts 3 --stall_secs 300 \
        --metrics_port 9100 --devices 8 -- \
        python main_supcon.py --dataset cifar10 --metrics_port 9100 \
            --watchdog_secs 120 ...

Exit code: 0 when the job completed; otherwise the final child's code
(signal deaths shell-normalized to 128+N), so CI and shell callers see
exactly what a bash launcher would have reported.
"""

from __future__ import annotations

import argparse
import logging
import sys

from simclr_pytorch_distributed_tpu.supervise.supervisor import (
    SuperviseConfig,
    Supervisor,
)

# NOT imported from config.py: the supervisor must never initialize the
# accelerator backend its child needs, and config.py sits next to modules
# that do — same bounds-checking convention, duplicated deliberately.


def nonnegative_int_arg(name: str):
    def parse(s: str) -> int:
        try:
            v = int(s)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"--{name} expects a non-negative integer, got {s!r}"
            ) from None
        if v < 0:
            raise argparse.ArgumentTypeError(
                f"--{name} must be >= 0, got {v}"
            )
        return v

    return parse


def positive_int_arg(name: str):
    def parse(s: str) -> int:
        v = nonnegative_int_arg(name)(s)
        if v <= 0:
            raise argparse.ArgumentTypeError(
                f"--{name} must be positive, got {v}"
            )
        return v

    return parse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m simclr_pytorch_distributed_tpu.supervise",
        description="elastic, self-healing training supervisor "
                    "(docs/RESILIENCE.md)",
    )
    p.add_argument("--workdir", default="./work_space",
                   help="the trainer's --workdir: resume dirs are resolved "
                        "under <workdir>/*_models, supervisor events land "
                        "in <workdir>/supervise")
    p.add_argument("--max_restarts", type=nonnegative_int_arg("max_restarts"),
                   default=3,
                   help="total relaunch budget across ALL failure classes "
                        "(the launchers' PREEMPT_RETRIES contract)")
    p.add_argument("--backoff_base_s", type=float, default=1.0,
                   help="first-failure backoff; doubles per consecutive "
                        "failure (a clean preemption resets the streak)")
    p.add_argument("--backoff_max_s", type=float, default=60.0,
                   help="backoff cap")
    p.add_argument("--poll_secs", type=float, default=1.0,
                   help="channel polling cadence")
    p.add_argument("--stall_secs", type=float, default=0.0,
                   help="liveness deadline: kill + resume when the child's "
                        "train_last_boundary_age_seconds exceeds this or a "
                        "watchdog stall dump appears (0 = observe only). "
                        "Set well above the first-step compile AND the "
                        "trainer's own --watchdog_secs")
    p.add_argument("--straggler_skew_secs", type=float, default=1.0,
                   help="boundary-skew bar for the straggler finding "
                        "scraped off the child's "
                        "train_boundary_skew_seconds gauge (0 = off); "
                        "findings feed the K-of-N persistence detector")
    p.add_argument("--straggler_persist_k",
                   type=positive_int_arg("straggler_persist_k"), default=3,
                   help="boundaries (of the last --straggler_window_n) that "
                        "must name the SAME host above the bar before the "
                        "straggler is PERSISTENT (>= 2 gives hysteresis: a "
                        "one-boundary GC pause never triggers)")
    p.add_argument("--straggler_window_n",
                   type=positive_int_arg("straggler_window_n"), default=5,
                   help="sliding window of boundaries the K-of-N vote "
                        "runs over")
    p.add_argument("--straggler_mitigate", action="store_true",
                   default=False,
                   help="act on a persistence verdict: graceful preempt + "
                        "the escalation ladder restart_rebalanced -> "
                        "restart_resized (exclude) -> give_up, budget-"
                        "capped, never over a pending operator resize. "
                        "Default: record the verdict, take no action")
    p.add_argument("--grace_secs", type=float, default=20.0,
                   help="SIGTERM->SIGKILL window on a supervisor-initiated "
                        "kill (the preemption machinery's chance to save)")
    p.add_argument("--metrics_port", type=nonnegative_int_arg("metrics_port"),
                   default=0,
                   help="the CHILD's --metrics_port sidecar to scrape for "
                        "liveness (0 = no scraping; run-dir watchdog dumps "
                        "still count)")
    p.add_argument("--metrics_host", default="127.0.0.1")
    p.add_argument("--all_run_dirs", action="store_true", default=False,
                   help="include classifier_*/ce_* run dirs in run-dir "
                        "resolution — required when supervising the probe "
                        "or CE trainer, whose run dirs carry those "
                        "prefixes (the pretrain default excludes them)")
    p.add_argument("--devices", type=positive_int_arg("devices"), default=None,
                   help="manage the child's virtual-mesh device count "
                        "(XLA host-platform flag); resize at runtime by "
                        "writing an integer to "
                        "<workdir>/supervise/resize_request. Default: "
                        "inherit the environment")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- followed by the training command")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        build_parser().error("no training command given (append: -- python "
                             "main_supcon.py ...)")
    logging.basicConfig(
        level=logging.INFO,
        format="[supervise] %(levelname)s %(message)s",
    )
    cfg = SuperviseConfig(
        command=command,
        workdir=args.workdir,
        max_restarts=args.max_restarts,
        backoff_base_s=args.backoff_base_s,
        backoff_max_s=args.backoff_max_s,
        poll_s=args.poll_secs,
        stall_secs=args.stall_secs,
        straggler_skew_secs=args.straggler_skew_secs,
        straggler_persist_k=args.straggler_persist_k,
        straggler_window_n=args.straggler_window_n,
        straggler_mitigate=args.straggler_mitigate,
        grace_secs=args.grace_secs,
        metrics_port=args.metrics_port,
        metrics_host=args.metrics_host,
        devices=args.devices or 0,
        all_run_dirs=args.all_run_dirs,
    )
    return Supervisor(cfg).run()


if __name__ == "__main__":
    sys.exit(main())
