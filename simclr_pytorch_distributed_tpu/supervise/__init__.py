"""supervise/ — the elastic, self-healing fleet supervisor.

The observability layer (PRs 7-8) produces every signal an automated
operator needs — `/metrics` liveness gauges (``train_last_boundary_age_
seconds``), stall-watchdog dumps, ``health_alarm`` recorder events, typed
exit codes (75 preempt; health 3 > flush 2 > NaN 1, utils/guard.py) — but
until this package those artifacts were read by humans. The supervisor
closes the loop: it launches a training job as a child process, watches it
through those same channels, and decides — through an explicit,
unit-testable policy — whether to relaunch with ``--resume``, relaunch
RESIZED onto a different topology (legal because checkpoint restore is
mesh-shape-agnostic, utils/checkpoint.py), back off and retry, or give up.
Every observation and decision lands as spans/events in the supervisor's
own ``events.jsonl`` via the existing FlightRecorder, so a fleet
post-mortem reads one uniform format end to end.

Layout (one concern per module, the utils/ convention):

- :mod:`policy` — the pure decision policy: ``ExitObservation`` in,
  ``Decision`` out; zero I/O, tested exhaustively without a process;
- :mod:`observe` — signal collection: the Prometheus text parser +
  sidecar scraper, and the run-dir watcher that surfaces new stall dumps,
  ``health_alarm`` events, and checkpoints incrementally;
- :mod:`launch` — child-process mechanics: resume-dir resolution (the
  launcher scan, now in one tested place), ``--resume`` injection, the
  virtual-topology env hook, and graceful terminate-then-kill;
- :mod:`supervisor` — the loop tying them together;
- :mod:`__main__` — the CLI: ``python -m
  simclr_pytorch_distributed_tpu.supervise [flags] -- python
  main_supcon.py ...`` (what ``run_supcon.sh`` delegates to);
- :mod:`replica` / :mod:`replica_fleet` — the same discipline generalized
  from one trainer to N SERVING replicas: a pure ``ReplicaPolicy``
  decision table (liveness from the ``serve_batcher_last_completion_age_s``
  gauge, saturation from occupancy/queue depth, per-replica restart
  budgets) and the ``ReplicaFleetSupervisor`` subprocess loop that spawns /
  restarts / drains ``serve.fleet`` replicas off scraped ``/metrics``.

Proof vehicle: the PR-1 subprocess fault harness drives the REAL
supervisor through kill -9 / stall / collapse / preempt-then-resize
scenarios end to end (``scripts/supervisor_matrix.py`` +
``tests/test_fault_injection.py``), and ``scripts/ratchet.py`` gates on
the committed scenario-matrix evidence (``docs/evidence/
supervisor_r11.json``).
"""

from simclr_pytorch_distributed_tpu.supervise.policy import (  # noqa: F401
    Decision,
    DecisionPolicy,
    ExitObservation,
)
from simclr_pytorch_distributed_tpu.supervise.replica import (  # noqa: F401
    ReplicaDecision,
    ReplicaObservation,
    ReplicaPolicy,
)
from simclr_pytorch_distributed_tpu.supervise.replica_fleet import (  # noqa: F401
    ReplicaFleetConfig,
    ReplicaFleetSupervisor,
)
from simclr_pytorch_distributed_tpu.supervise.supervisor import (  # noqa: F401
    SuperviseConfig,
    Supervisor,
)
