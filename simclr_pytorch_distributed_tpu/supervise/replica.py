"""ReplicaPolicy — the serving-fleet decision table, pure like policy.py.

The trainer supervisor judges ONE child by how it EXITS (exit codes are the
trainer's contract). A serving replica is judged while it RUNS: liveness
and saturation live in the ``/metrics`` gauges the serve stack already
exports, and the fleet decision is about N replicas at once. Same
discipline as :mod:`supervise.policy`: :meth:`ReplicaPolicy.decide` takes
observations, returns decisions, performs no I/O and reads no clocks —
tests/test_replica_fleet.py enumerates the whole table without a process.

Per-replica classification (:func:`classify`), evaluated in this order:

==============  ========================================================
class           condition (scraped serve_batcher_* gauges)
==============  ========================================================
dead            the process has exited
starting        no scrape yet, but younger than ``startup_grace_s`` —
                jax import + first compile take real time; silence here
                is expected, not a failure
unscrapeable    no scrape past the grace — the HTTP plane is gone even
                though the process runs (wedged interpreter, bound port
                lost); counts strikes, ``unscrape_strikes`` of them in a
                row escalate to a restart
stalled         work is pending (queue_depth + inflight_batches > 0) and
                ``last_completion_age_s`` exceeds ``stall_age_s`` — the
                replica owes completions and is not delivering (the
                serving analogue of the trainer's boundary-age liveness)
saturated       ``pipeline_occupancy >= occ_hi`` OR
                ``queue_depth >= queue_hi`` — admitting more traffic
                means queueing latency, the fleet should grow
idle            no queued or in-flight work and
                ``pipeline_occupancy <= occ_lo`` — shrink candidate
busy            everything else — healthy, leave it alone
==============  ========================================================

Fleet decisions (:meth:`decide`), most-urgent first; repair beats scaling:

- dead / stalled / unscrapeable-past-strikes -> ``restart_replica``,
  bounded by a PER-REPLICA restart budget (``max_restarts``); an exhausted
  budget -> ``give_up_replica`` — that replica (its port, its slot) is
  abandoned and reported, never silently relaunched forever;
- fleet below ``min_replicas`` (after give-ups or drains) -> one
  ``spawn_replica`` per decide call (fresh slot, fresh budget);
- any replica saturated and the fleet below ``max_replicas`` -> one
  ``spawn_replica`` per decide call (scaling is damped: one step per
  observation interval, so a burst can't overshoot to max in one tick);
- no one saturated, fleet above ``min_replicas``, some replica idle ->
  ``drain_replica`` for the HIGHEST-id idle replica (newest first: the
  scale-up order reversed), one per call;
- otherwise no decisions (steady state).

A replica that scrapes clean resets its unscrape strikes (recovery), but
restart budgets never refill — a flapping replica must eventually surface
to a human, exactly like the trainer policy's ``max_restarts``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Set

# the scraped gauge names (serve/server.py serve_metrics_fn and
# serve/fleet/frontend.py fleet_metrics_fn both export them unlabeled,
# which is all observe.parse_prometheus_text reads)
AGE_GAUGE = "serve_batcher_last_completion_age_s"
QUEUE_GAUGE = "serve_batcher_queue_depth"
INFLIGHT_GAUGE = "serve_batcher_inflight_batches"
OCC_GAUGE = "serve_batcher_pipeline_occupancy"

# classification states
DEAD = "dead"
STARTING = "starting"
UNSCRAPEABLE = "unscrapeable"
STALLED = "stalled"
SATURATED = "saturated"
IDLE = "idle"
BUSY = "busy"

# ReplicaDecision.action values (strings: they land in recorder events and
# the evidence artifact as JSON, like policy.py's)
SPAWN = "spawn_replica"
RESTART = "restart_replica"
DRAIN = "drain_replica"
GIVE_UP = "give_up_replica"


@dataclasses.dataclass(frozen=True)
class ReplicaObservation:
    """One replica at one observation instant, as the supervisor saw it.

    ``metrics`` is the scraped gauge dict or None (scrape failed — which a
    dead HTTP plane and a not-yet-up replica both produce; ``age_s``, the
    seconds since the replica was spawned, is what separates them against
    ``startup_grace_s``). The policy reads clocks from NOWHERE else."""

    replica: int
    alive: bool
    metrics: Optional[Mapping[str, float]] = None
    age_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class ReplicaDecision:
    action: str
    replica: int = -1  # -1: a fresh slot (spawn) — the supervisor assigns
    reason: str = ""


def classify(
    obs: ReplicaObservation,
    *,
    startup_grace_s: float = 60.0,
    stall_age_s: float = 30.0,
    occ_hi: float = 0.9,
    queue_hi: float = 64.0,
    occ_lo: float = 0.1,
) -> str:
    """The per-replica row of the decision table (module docstring)."""
    if not obs.alive:
        return DEAD
    if obs.metrics is None:
        return STARTING if obs.age_s <= startup_grace_s else UNSCRAPEABLE
    m = obs.metrics
    queued = m.get(QUEUE_GAUGE, 0.0)
    inflight = m.get(INFLIGHT_GAUGE, 0.0)
    age = m.get(AGE_GAUGE, 0.0)
    occ = m.get(OCC_GAUGE, 0.0)
    if (queued > 0 or inflight > 0) and age > stall_age_s:
        return STALLED
    if occ >= occ_hi or queued >= queue_hi:
        return SATURATED
    if queued == 0 and inflight == 0 and occ <= occ_lo:
        return IDLE
    return BUSY


class ReplicaPolicy:
    """Decision state across one supervised fleet: per-replica restart
    budgets, unscrape strike counters, and the abandoned set."""

    def __init__(
        self,
        min_replicas: int = 1,
        max_replicas: int = 4,
        *,
        startup_grace_s: float = 60.0,
        stall_age_s: float = 30.0,
        occ_hi: float = 0.9,
        queue_hi: float = 64.0,
        occ_lo: float = 0.1,
        max_restarts: int = 3,
        unscrape_strikes: int = 3,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}/{max_replicas}"
            )
        if max_restarts < 0 or unscrape_strikes < 1:
            raise ValueError(
                f"need max_restarts >= 0 and unscrape_strikes >= 1, got "
                f"{max_restarts}/{unscrape_strikes}"
            )
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.startup_grace_s = float(startup_grace_s)
        self.stall_age_s = float(stall_age_s)
        self.occ_hi = float(occ_hi)
        self.queue_hi = float(queue_hi)
        self.occ_lo = float(occ_lo)
        self.max_restarts = int(max_restarts)
        self.unscrape_strikes = int(unscrape_strikes)
        self.restarts: Dict[int, int] = {}
        self.strikes: Dict[int, int] = {}
        self.given_up: Set[int] = set()

    def classify(self, obs: ReplicaObservation) -> str:
        return classify(
            obs,
            startup_grace_s=self.startup_grace_s,
            stall_age_s=self.stall_age_s,
            occ_hi=self.occ_hi,
            queue_hi=self.queue_hi,
            occ_lo=self.occ_lo,
        )

    def _repair(self, obs: ReplicaObservation, why: str) -> ReplicaDecision:
        r = obs.replica
        used = self.restarts.get(r, 0)
        if used >= self.max_restarts:
            self.given_up.add(r)
            return ReplicaDecision(
                GIVE_UP, r,
                f"replica {r} {why} with restart budget exhausted "
                f"({used}/{self.max_restarts}): abandoning the slot — "
                f"a human should look",
            )
        self.restarts[r] = used + 1
        return ReplicaDecision(
            RESTART, r,
            f"replica {r} {why}: restart "
            f"({used + 1}/{self.max_restarts} of budget)",
        )

    def decide(
        self, observations: Sequence[ReplicaObservation]
    ) -> List[ReplicaDecision]:
        decisions: List[ReplicaDecision] = []
        classes: Dict[int, str] = {}
        for obs in sorted(observations, key=lambda o: o.replica):
            if obs.replica in self.given_up:
                continue
            cls = self.classify(obs)
            classes[obs.replica] = cls
            if cls == DEAD:
                decisions.append(self._repair(obs, "process exited"))
            elif cls == STALLED:
                age = (obs.metrics or {}).get(AGE_GAUGE, 0.0)
                decisions.append(self._repair(
                    obs,
                    f"stalled (work pending, last completion {age:.1f}s "
                    f"ago > {self.stall_age_s:g}s)",
                ))
            elif cls == UNSCRAPEABLE:
                strikes = self.strikes.get(obs.replica, 0) + 1
                self.strikes[obs.replica] = strikes
                if strikes >= self.unscrape_strikes:
                    self.strikes[obs.replica] = 0
                    decisions.append(self._repair(
                        obs,
                        f"unscrapeable {strikes} consecutive polls "
                        f"(HTTP plane gone while the process runs)",
                    ))
            else:
                self.strikes[obs.replica] = 0

        # fleet size the scaling rows reason about: every slot still
        # managed (restarting replicas are coming back, so they count)
        managed = [r for r in classes if r not in self.given_up]
        n = len(managed)
        if n < self.min_replicas:
            decisions.append(ReplicaDecision(
                SPAWN, -1,
                f"fleet at {n} < min_replicas {self.min_replicas}: "
                f"spawning a fresh replica",
            ))
            return decisions
        saturated = [r for r in managed if classes[r] == SATURATED]
        if saturated and n < self.max_replicas:
            decisions.append(ReplicaDecision(
                SPAWN, -1,
                f"replica(s) {saturated} saturated at fleet size {n} < "
                f"max {self.max_replicas}: spawning one more",
            ))
            return decisions
        if not saturated and n > self.min_replicas:
            idle = [r for r in managed if classes[r] == IDLE]
            if idle:
                victim = max(idle)
                decisions.append(ReplicaDecision(
                    DRAIN, victim,
                    f"replica {victim} idle at fleet size {n} > min "
                    f"{self.min_replicas}: draining it",
                ))
        return decisions
