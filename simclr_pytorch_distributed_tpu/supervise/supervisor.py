"""The supervisor loop: launch, watch, decide, relaunch.

One :class:`Supervisor` owns one training job. Per attempt it launches the
child (``launch.Child``), then polls three channels on a ``poll_s`` cadence:

- the child's returncode (``subprocess`` — the typed exit-code surface);
- the ``--metrics_port`` sidecar (``observe.MetricsScraper``):
  ``train_last_boundary_age_seconds`` past ``stall_secs`` is an OUTSIDE
  liveness verdict — the supervisor terminates the child (SIGTERM first, so
  the preemption machinery gets its grace window to save; SIGKILL after)
  and restarts with resume;
- the run dir (``observe.RunDirWatcher``): stall-watchdog dumps (an INSIDE
  liveness verdict — the watchdog only observes, the supervisor acts),
  ``health_alarm``/``nan_rollback``/``preempt_exit`` recorder events, and
  newly complete checkpoints — all re-recorded into the supervisor's own
  timeline as forensic context.

Elastic resize: dropping a ``resize_request`` file (one integer) into the
supervisor dir makes the supervisor gracefully preempt the child and
relaunch it onto that many devices — the decision lands as
``restart_resized``, the relaunch passes ``--resume``, and the trainer's
mesh-shape-agnostic restore (utils/checkpoint.py) reshards the checkpoint
onto the new mesh. A pending resize also upgrades any other restartable
exit, so an operator's resize survives an unlucky crash.

Straggler mitigation: the sidecar's boundary-skew gauges feed
``observe.StragglerTracker``; K-of-N boundaries naming the same host above
``straggler_skew_secs`` is a PERSISTENCE verdict (always recorded as a
``straggler_persistent`` event — the warn rung). With
``straggler_mitigate`` on, a verdict triggers the same graceful-preempt
machinery as a resize (``straggler_mitigation`` phase=preempt event,
SIGTERM -> emergency save -> exit 75) and the policy ladder decides the
relaunch: ``restart_rebalanced`` carrying a ``FLEET_SHARE_HINT`` into the
environment, then ``restart_resized`` excluding the host, then ``give_up``
(docs/RESILIENCE.md). A pending operator resize always wins over
mitigation, and the restart budget caps the ladder like every other class.

Every observation and decision is a span/event in the supervisor's own
``events.jsonl`` (``<workdir>/supervise/``, the shared FlightRecorder +
``run_paths`` session rotation), so one `jq` pass over trainer + supervisor
files tells the whole story of a babysat run. Clock, sleep, and scraper are
injectable: tests/test_supervise.py drives the loop against scripted
children without real waiting.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time
from typing import Callable, List, Optional

from simclr_pytorch_distributed_tpu.supervise import launch, observe, policy
from simclr_pytorch_distributed_tpu.utils import tracing

logger = logging.getLogger(__name__)

RESIZE_REQUEST_FILE = "resize_request"


@dataclasses.dataclass
class SuperviseConfig:
    """The supervisor CLI surface (see __main__.py for the flag help)."""

    command: List[str]
    workdir: str = "./work_space"
    max_restarts: int = 3
    backoff_base_s: float = 1.0
    backoff_max_s: float = 60.0
    poll_s: float = 1.0
    stall_secs: float = 0.0          # 0 = no liveness-kill (observe only)
    # boundary-skew bar (s) for the per-boundary straggler finding scraped
    # off the child's train_boundary_skew_seconds gauge; 0 = off. Findings
    # feed observe.StragglerTracker's K-of-N persistence verdict; what a
    # verdict DOES depends on straggler_mitigate below.
    straggler_skew_secs: float = 1.0
    # persistence: a straggler is persistent after straggler_persist_k of
    # the last straggler_window_n boundaries named the SAME host above the
    # bar (hysteresis: one boundary of skew — a GC pause — never triggers)
    straggler_persist_k: int = 3
    straggler_window_n: int = 5
    # False (default): verdicts are RECORDED (straggler_persistent events
    # — the warn rung of the ladder) but never acted on, the pre-PR-16
    # behavior. True: a verdict triggers a graceful mitigation preempt and
    # the policy ladder (rebalance -> exclude -> give_up), budget-capped,
    # never over a pending operator resize.
    straggler_mitigate: bool = False
    grace_secs: float = 20.0         # SIGTERM -> SIGKILL window
    metrics_port: int = 0            # the CHILD's sidecar port; 0 = no scrape
    metrics_host: str = "127.0.0.1"
    devices: int = 0                 # initial topology; 0 = unmanaged
    supervise_dir: str = ""          # default: <workdir>/supervise
    # False (the pretrain default) excludes classifier_*/ce_* folders from
    # run-dir resolution; True is for supervising the probe/CE trainers,
    # whose run dirs ARE those folders — without it the watch channel
    # (stall dumps, recorder events, checkpoints) would be blind and
    # --resume would point at a stale pretrain dir
    all_run_dirs: bool = False


def _shell_rc(rc: int) -> int:
    """Normalize a subprocess returncode for a process exit: signal deaths
    (negative) become the shell's 128+N convention so launchers and CI see
    the same number bash would report."""
    return 128 - rc if rc < 0 else rc


class Supervisor:
    def __init__(
        self,
        cfg: SuperviseConfig,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        scraper: Optional[observe.MetricsScraper] = None,
    ):
        self.cfg = cfg
        self._clock = clock
        self._sleep = sleep
        self.supervise_dir = cfg.supervise_dir or os.path.join(
            cfg.workdir, "supervise"
        )
        os.makedirs(self.supervise_dir, exist_ok=True)
        events, trace = tracing.run_paths(self.supervise_dir)
        self.recorder = tracing.FlightRecorder(
            events, clock=clock, trace_path=trace
        )
        self.policy = policy.DecisionPolicy(
            max_restarts=cfg.max_restarts,
            backoff_base_s=cfg.backoff_base_s,
            backoff_max_s=cfg.backoff_max_s,
        )
        self.scraper = scraper
        if scraper is None and cfg.metrics_port:
            self.scraper = observe.MetricsScraper(
                cfg.metrics_port, cfg.metrics_host
            )
        self.child: Optional[launch.Child] = None
        self.decisions: List[policy.Decision] = []  # the run's decision log
        self._run_dir_exclude = (
            () if cfg.all_run_dirs else launch.EXCLUDED_RUN_PREFIXES
        )
        # one PERSISTENT watcher per run dir: a relaunch within the same
        # minute reuses the same timestamped save_folder, so per-attempt
        # watcher state would re-report attempt 1's stall dumps as fresh
        # and instantly "stall"-kill every relaunch (found by the matrix's
        # stall scenario)
        self._watchers: dict = {}
        # set by the SIGTERM/SIGINT handler: the supervisor itself is being
        # preempted and must RELAY the signal (the launchers exec this
        # process, so on a fleet it is what the scheduler terminates — the
        # default action would orphan the trainer with no grace window and
        # lose the emergency save the whole preempt contract promises)
        self._terminate: Optional[int] = None
        # last raw sidecar scrape (the straggler tracker reads the skew
        # gauges off the SAME scrape liveness used — one GET per poll)
        self._last_scrape: Optional[dict] = None
        # per-boundary findings -> K-of-N persistence verdicts (the
        # tracker dedups scrapes of the same boundary internally, so the
        # supervisor timeline gets one finding per boundary, not per poll)
        self._straggler = observe.StragglerTracker(
            cfg.straggler_skew_secs,
            persist_k=cfg.straggler_persist_k,
            window_n=cfg.straggler_window_n,
            clock=clock,
        )
        # the verdict a mitigation preempt was issued for (None between),
        # read by run() into the ExitObservation; and the sticky rebalance
        # hint carried into every relaunch until cleared by a resize
        self._mitigation: Optional[dict] = None
        self._share: Optional[str] = None

    # ------------------------------------------------------------- channels
    def _handle_signal(self, signum, frame):  # noqa: ARG002 — handler signature
        self._terminate = signum

    def _discard_stale_resize(self) -> None:
        """Terminal exits (done/give_up/shutdown/launch failure) must not
        leave a pending resize_request behind: the next, unrelated
        supervised run in the same workdir would silently consume it at
        launch and boot on a topology requested for a finished job."""
        path = os.path.join(self.supervise_dir, RESIZE_REQUEST_FILE)
        if not os.path.exists(path):
            return
        try:
            os.remove(path)
        except OSError:
            return
        self.recorder.event("resize_request_discarded", track="supervisor")
        logger.warning(
            "discarding pending resize_request: the supervised run is over"
        )

    def _resize_requested(self) -> Optional[int]:
        """Consume ``<supervise_dir>/resize_request`` (one integer) if
        present; malformed content is logged and discarded — a typo must
        not wedge the supervisor."""
        path = os.path.join(self.supervise_dir, RESIZE_REQUEST_FILE)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                raw = f.read()
        except OSError as e:
            # transient read failure (NFS hiccup, permission blip): the
            # file is the operator's ONLY copy of the request — leave it
            # for the next poll rather than deleting intent we never read
            logger.warning("resize_request unreadable (%s); will retry", e)
            return None
        if not raw.strip():
            # empty = caught mid-write (`echo 4 > file` truncates before
            # writing): same retry treatment as unreadable — deleting here
            # would silently drop the request the poll raced
            return None
        try:
            devices = int(raw.strip())
        except ValueError:
            logger.warning("ignoring malformed %s: %r", path, raw[:80])
            devices = None
        try:
            os.remove(path)
        except OSError:
            pass
        if devices is not None and devices <= 0:
            logger.warning("ignoring non-positive resize request %r", devices)
            return None
        return devices

    def _liveness_age(self) -> Optional[float]:
        """``train_last_boundary_age_seconds`` from the child's sidecar, or
        None when unavailable (sidecar down/not up yet) or not yet beating
        (the gauge's -1 sentinel during the first-step compile)."""
        if self.scraper is None:
            self._last_scrape = None
            return None
        gauges = self.scraper.scrape()
        self._last_scrape = gauges
        if gauges is None:
            return None
        age = gauges.get("train_last_boundary_age_seconds")
        if age is None or age < 0:
            return None
        return age

    # ----------------------------------------------------------- bookkeeping
    def _record_decision(
        self, decision: policy.Decision, rc: int, stalled: bool
    ) -> None:
        """The one writer of the ``decision`` event schema (three exit
        paths share it — a hand-copied field drift would silently diverge
        the events.jsonl the gate and post-mortem tooling consume)."""
        self.decisions.append(decision)
        self.recorder.event(
            "decision", track="supervisor", action=decision.action,
            reason=decision.reason, rc=rc, stalled=stalled,
            delay_s=decision.delay_s, devices=decision.devices,
            share=decision.share, restarts=self.policy.restarts,
        )
        logger.warning(
            "supervise decision: %s (%s)", decision.action, decision.reason
        )

    def _sleep_interruptible(self, total_s: float) -> None:
        """Backoff sleep in poll-sized slices: PEP 475 restarts an
        interrupted sleep, so one long sleep would finish the whole backoff
        after a SIGTERM and relaunch a child just to kill it."""
        remaining = float(total_s)
        step = max(0.05, self.cfg.poll_s)
        while remaining > 0 and self._terminate is None:
            chunk = min(remaining, step)
            self._sleep(chunk)
            remaining -= chunk

    # ------------------------------------------------------------ one attempt
    def _watch_child(self):
        """Poll until the child exits or a liveness verdict kills it.
        Returns ``(returncode, stalled, stall_dumps, health_alarms)``.

        The run dir only exists once the child finalizes its config (and a
        relaunch may open a NEW timestamped dir), so each poll follows the
        newest run dir — through a per-dir watcher that is PERSISTENT
        across attempts (see ``_watchers``), so artifacts from an earlier
        attempt are never re-counted against the current child."""
        cfg = self.cfg
        stall_dumps = 0
        health_alarms = 0
        # the stall VERDICT only counts dumps written during THIS attempt
        # (wall-clock mtime — the dumps are disk artifacts): a dump left by
        # a previous supervisor SESSION is fresh to this process's watcher
        # state and would otherwise liveness-kill a healthy child on the
        # first poll. Older dumps are still recorded as observations.
        attempt_started = time.time()
        while True:
            rc = self.child.poll()
            run_dir = launch.find_resume_dir(
                cfg.workdir, exclude=self._run_dir_exclude
            ) or ""
            if run_dir not in self._watchers:
                self._watchers[run_dir] = observe.RunDirWatcher(run_dir)
                if run_dir:
                    self.recorder.event(
                        "run_dir_observed", track="supervisor", path=run_dir
                    )
            watcher = self._watchers[run_dir]
            dumps, events, ckpts = watcher.poll()
            fresh_dumps = []
            for path in dumps:
                try:
                    fresh = os.path.getmtime(path) >= attempt_started
                except OSError:
                    fresh = False
                if fresh:
                    fresh_dumps.append(path)
                    stall_dumps += 1
                self.recorder.event(
                    "stall_dump_observed", track="supervisor", path=path,
                    fresh=fresh,
                )
            for rec in events:
                if rec.get("name") == "health_alarm":
                    health_alarms += 1
                self.recorder.event(
                    "trainer_event", track="supervisor",
                    event=rec.get("name"), args=rec.get("args", {}),
                    file=rec.get("_file"),
                )
            for name in ckpts:
                self.recorder.event(
                    "checkpoint_observed", track="supervisor", ckpt=name
                )
            if rc is not None:
                return rc, False, stall_dumps, health_alarms
            if self._terminate is not None:
                # the supervisor itself is being preempted: relay through
                # the same grace escalation, so the trainer's preempt
                # machinery gets its emergency-save window (exit 75)
                self.recorder.event(
                    "supervisor_signal", track="supervisor",
                    signum=int(self._terminate),
                )
                logger.warning(
                    "supervisor received signal %d: relaying to child pid "
                    "%d (grace %gs)", self._terminate, self.child.pid,
                    cfg.grace_secs,
                )
                rc = self.child.terminate_gracefully(
                    cfg.grace_secs, sleep=self._sleep, clock=self._clock
                )
                return rc, False, stall_dumps, health_alarms
            resize = self._resize_requested()
            if resize is not None:
                self.policy.request_resize(resize)
                self.recorder.event(
                    "resize_request", track="supervisor", devices=resize
                )
                logger.warning(
                    "resize request to %d device(s): preempting the child "
                    "(grace %gs)", resize, cfg.grace_secs,
                )
                rc = self.child.terminate_gracefully(
                    cfg.grace_secs, sleep=self._sleep, clock=self._clock
                )
                return rc, False, stall_dumps, health_alarms
            age = self._liveness_age()
            finding = self._straggler.observe(self._last_scrape)
            if finding is not None:
                # one boundary's observation (the warn rung of the
                # ladder): recorded once per boundary step, not per poll
                self.recorder.event(
                    "straggler_finding", track="supervisor", **finding
                )
                logger.warning(
                    "straggler finding: boundary skew %.3fs >= %.3fs "
                    "(step %s, straggler %s)",
                    finding["skew_s"], finding["bar_s"],
                    finding.get("step"), finding.get("straggler"),
                )
            verdict = self._straggler.take_persistent()
            if verdict is not None:
                # K-of-N boundaries named the same host: a PERSISTENCE
                # verdict, always recorded. Mitigation only when enabled
                # AND no operator resize is pending (the explicit request
                # outranks the inferred remedy — the resize branch above
                # would already have preempted this poll anyway)
                self.recorder.event(
                    "straggler_persistent", track="supervisor",
                    mitigate=bool(cfg.straggler_mitigate), **verdict
                )
                if (cfg.straggler_mitigate
                        and self.policy.pending_resize is None):
                    self._mitigation = verdict
                    self.recorder.event(
                        "straggler_mitigation", track="supervisor",
                        phase="preempt", **verdict
                    )
                    logger.warning(
                        "persistent straggler host %s (%d/%d boundaries, "
                        "skew %.3fs): preempting for mitigation "
                        "(grace %gs)",
                        verdict.get("straggler"), verdict.get("votes", 0),
                        verdict.get("window", 0), verdict["skew_s"],
                        cfg.grace_secs,
                    )
                    rc = self.child.terminate_gracefully(
                        cfg.grace_secs, sleep=self._sleep, clock=self._clock
                    )
                    return rc, False, stall_dumps, health_alarms
                logger.warning(
                    "persistent straggler host %s (%d/%d boundaries, skew "
                    "%.3fs) — recorded, no action (mitigation %s)",
                    verdict.get("straggler"), verdict.get("votes", 0),
                    verdict.get("window", 0), verdict["skew_s"],
                    "off" if not cfg.straggler_mitigate
                    else "deferred to pending resize",
                )
            stalled = bool(
                cfg.stall_secs > 0
                and ((age is not None and age >= cfg.stall_secs)
                     or fresh_dumps)
            )
            if stalled:
                self.recorder.event(
                    "liveness_stall", track="supervisor",
                    age_s=age, stall_secs=cfg.stall_secs,
                    watchdog_dumps=stall_dumps,
                )
                logger.error(
                    "liveness stall (boundary age %s >= %gs or watchdog "
                    "dump): terminating child pid %d",
                    f"{age:.1f}" if age is not None else "n/a",
                    cfg.stall_secs, self.child.pid,
                )
                rc = self.child.terminate_gracefully(
                    cfg.grace_secs, sleep=self._sleep, clock=self._clock
                )
                return rc, True, stall_dumps, health_alarms
            self._sleep(cfg.poll_s)

    # ------------------------------------------------------------------ run
    def run(self) -> int:
        """Supervise to completion; returns the process exit code (0 done,
        else the final child's shell-normalized code)."""
        cfg = self.cfg
        devices = cfg.devices or None
        resume_dir: Optional[str] = None
        attempt = 0
        prev_handlers = {}
        try:
            for s in (signal.SIGTERM, signal.SIGINT):
                prev_handlers[s] = signal.signal(s, self._handle_signal)
        except ValueError:
            # not the main thread (embedded/test use): no OS-level relay —
            # the _terminate flag can still be set programmatically
            prev_handlers = {}
        last_rc = 0
        try:
            while True:
                if self._terminate is not None:
                    # preempted between attempts (during backoff, or before
                    # the first launch): exit NOW — booting a child just to
                    # kill it would waste the scheduler's grace window
                    decision = policy.Decision(
                        policy.SHUTDOWN,
                        f"supervisor received signal {self._terminate} with "
                        f"no child running; exiting without relaunch",
                    )
                    self._record_decision(decision, last_rc, False)
                    self._discard_stale_resize()
                    return (
                        _shell_rc(last_rc) if attempt
                        else 128 + int(self._terminate)
                    )
                attempt += 1
                # a resize filed BETWEEN attempts (during backoff, or while
                # the supervisor was down) applies directly to this launch —
                # routing it through the kill path would boot a child on the
                # old topology only to preempt it immediately, burning one
                # restart-budget unit and a full jax startup on a routine
                # operator action
                resize = self._resize_requested()
                if resize is not None:
                    self.recorder.event(
                        "resize_request", track="supervisor", devices=resize,
                        applied="at_launch",
                    )
                    devices = resize
                try:
                    self.child = launch.Child(
                        cfg.command, resume_dir=resume_dir, devices=devices,
                        share=self._share,
                    )
                except OSError as e:
                    # an unlaunchable command (typo'd executable, EPERM) is
                    # permanent: retrying cannot help, and dying with a raw
                    # traceback would leave no decision on record — give up
                    # through the policy surface with the shell's 127
                    self.recorder.event(
                        "launch_failed", track="supervisor", attempt=attempt,
                        error=str(e), command=list(cfg.command),
                    )
                    self._record_decision(
                        policy.Decision(
                            policy.GIVE_UP,
                            f"training command failed to launch: {e}",
                        ),
                        127, False,
                    )
                    self._discard_stale_resize()
                    return 127
                self.recorder.event(
                    "launch", track="supervisor", attempt=attempt,
                    pid=self.child.pid, devices=devices,
                    share=self._share,
                    resume=resume_dir or "", command=self.child.command,
                )
                logger.info(
                    "supervise: attempt %d pid %d (devices=%s share=%s "
                    "resume=%s)",
                    attempt, self.child.pid, devices or "inherit",
                    self._share or "uniform", resume_dir or "none",
                )
                # fresh detection per attempt: the relaunch restarts its
                # gauge stream, and stale votes must not convict it
                self._straggler.reset()
                self._mitigation = None
                start = self._clock()
                rc, stalled, dumps, alarms = self._watch_child()
                last_rc = rc
                self.recorder.record_span(
                    "child_run", track="supervisor", start=start,
                    end=self._clock(), attempt=attempt, rc=rc,
                    stalled=stalled,
                )
                if self._terminate is not None:
                    # our own preemption, relayed: never relaunch (the
                    # scheduler wants us GONE), exit with the child's code
                    # so an outer orchestrator sees 75 when the save landed
                    self._record_decision(
                        policy.Decision(
                            policy.SHUTDOWN,
                            f"supervisor received signal {self._terminate}: "
                            f"relayed to the child (exit {rc}); not "
                            f"relaunching",
                        ),
                        rc, False,
                    )
                    self._discard_stale_resize()
                    return _shell_rc(rc)
                mit = self._mitigation
                obs = policy.ExitObservation(
                    returncode=rc, stalled=stalled,
                    stall_dumps=dumps, health_alarms=alarms,
                    straggler_persistent=mit is not None,
                    straggler_host=int(mit.get("straggler", -1))
                    if mit else -1,
                    straggler_skew_s=float(mit.get("skew_s", 0.0))
                    if mit else 0.0,
                    processes=int(mit.get("processes", 0)) if mit else 0,
                )
                decision = self.policy.decide(obs)
                self._record_decision(decision, rc, stalled)
                if mit is not None:
                    # close the mitigation span on the timeline: what the
                    # preempt actually bought (a ladder rung, or give_up)
                    self.recorder.event(
                        "straggler_mitigation", track="supervisor",
                        phase="decided", action=decision.action,
                        share=decision.share, devices=decision.devices,
                        host=obs.straggler_host,
                    )
                if decision.action == policy.DONE:
                    self._discard_stale_resize()
                    return 0
                if decision.action == policy.GIVE_UP:
                    self._discard_stale_resize()
                    return _shell_rc(rc)
                if decision.delay_s > 0:
                    self._sleep_interruptible(decision.delay_s)
                if decision.devices is not None:
                    devices = decision.devices
                if decision.action == policy.RESTART_REBALANCED:
                    self._share = decision.share
                elif decision.action == policy.RESTART_RESIZED:
                    # exclusion (or an operator resize): shares are
                    # uniform again across the new topology — a stale
                    # hint would starve a host that is no longer slow
                    self._share = None
                # require_checkpoint: only inject --resume when a COMPLETE
                # save exists somewhere — an empty newest dir (child died
                # pre-first-save) would fail resolve_resume_path on every
                # retry; scratch restart is the correct fallback
                resume_dir = launch.find_resume_dir(
                    cfg.workdir, exclude=self._run_dir_exclude,
                    require_checkpoint=True,
                )
        finally:
            for s, h in prev_handlers.items():
                try:
                    signal.signal(s, h)
                except ValueError:  # pragma: no cover — teardown edge
                    pass
            self.recorder.close()
