"""Child-process mechanics: command construction, resume resolution,
topology env, graceful termination.

The resume scan (:func:`find_resume_dir`) is the logic ``run_supcon.sh``
carried in awk — newest run dir under ``<workdir>/*_models/``, excluding the
probe/CE ``classifier_*``/``ce_*`` folders by BASENAME (a workdir path
containing ``ce_`` must not hide every candidate; tests/test_launchers.py
pinned that bug) — now in one tested place both the launcher delegation and
the supervisor share. ``--resume`` is APPENDED to the user's command:
argparse is last-wins, so a freshly resolved run dir beats any stale
``--resume`` the user passed (the same contract the shell loop had).

Topology (:func:`topology_env`): "relaunch resized" needs a way to hand the
child a different device count. On the virtual CPU mesh the harness proves
elasticity on, that is ``XLA_FLAGS --xla_force_host_platform_device_count=N``
(rewritten idempotently, preserving unrelated flags). On a real fleet a
resize is a scheduler-level relaunch onto a different slice — the supervisor
still makes the restart-resized DECISION and records it; the env hook is the
single-host realization. Checkpoint restore being mesh-shape-agnostic
(utils/checkpoint.py) is what makes the relaunch legal either way.

Share (:func:`share_env`): "relaunch rebalanced" — the straggler ladder's
first rung — carries a ``host:factor`` hint (``FLEET_SHARE_HINT``) into the
relaunch: the named host should run at that fraction of its uniform
per-process share. The same convention as topology: on a real fleet the
scheduler/launcher layer realizes the hint (fewer examples routed to the
slow host, the epoch permutation being process-count-independent keeps the
global stream identical — data/pipeline.EpochLoader); on the single-host
harness the hint is carried, recorded, and verifiable in the relaunch's
environment (scripts/fleet_launcher.py echoes it into its result file).
"""

from __future__ import annotations

import glob
import os
import re
import signal
import subprocess
import time
from typing import Dict, List, Optional, Sequence

# probe/CE run dirs are never resume candidates for a pretrain relaunch
EXCLUDED_RUN_PREFIXES = ("classifier_", "ce_")

_XLA_DEVCOUNT_RE = re.compile(r"--xla_force_host_platform_device_count=\S+")


def find_resume_dir(
    workdir: str, exclude: tuple = EXCLUDED_RUN_PREFIXES,
    require_checkpoint: bool = False,
) -> Optional[str]:
    """Newest run dir under ``<workdir>/*_models/`` whose basename is not in
    ``exclude``; None when there is nothing to resume from (first launch, or
    the child died before creating its run dir).

    The default exclusion targets a PRETRAIN job (probe/CE folders are
    never its resume candidates). A supervisor babysitting the probe or CE
    trainer passes ``exclude=()`` (the ``--all_run_dirs`` CLI flag): their
    run dirs ARE the ``classifier_*``/``ce_*`` ones, and excluding them
    would blind the run-dir watch channel entirely.

    ``require_checkpoint`` restricts candidates to run dirs holding at
    least one COMPLETE checkpoint (a ``*/meta.json`` marker) — the
    ``--resume`` injection mode. Without it, a child that died before its
    first save leaves an empty newest dir, and resuming from it makes the
    trainer's resolve_resume_path fail on every retry until the budget
    burns (each failed attempt minting another empty decoy); with it, the
    supervisor falls back to an older complete run or a scratch restart.
    The WATCH channel keeps the unfiltered newest dir — the current run's
    artifacts live there whether or not it has saved yet."""
    candidates = []
    for models in sorted(
        d for d in (os.path.join(workdir, n) for n in (
            os.listdir(workdir) if os.path.isdir(workdir) else []
        )) if d.endswith("_models") and os.path.isdir(d)
    ):
        for name in os.listdir(models):
            path = os.path.join(models, name)
            if os.path.isdir(path) and not name.startswith(tuple(exclude)):
                if require_checkpoint and not glob.glob(
                    os.path.join(path, "*", "meta.json")
                ):
                    continue
                candidates.append(path)
    if not candidates:
        return None
    return max(candidates, key=os.path.getmtime)


def topology_env(
    devices: Optional[int], base_env: Optional[Dict[str, str]] = None
) -> Dict[str, str]:
    """The child env for a given virtual-mesh device count.

    ``devices=None`` leaves the environment untouched (the supervisor does
    not manage topology unless asked). Otherwise the
    ``--xla_force_host_platform_device_count`` flag inside ``XLA_FLAGS`` is
    replaced-or-appended, preserving every other flag — the harness and the
    tests' conftest both ride XLA_FLAGS, and clobbering it would silently
    change unrelated behavior.
    """
    env = dict(os.environ if base_env is None else base_env)
    if devices is None:
        return env
    flag = f"--xla_force_host_platform_device_count={int(devices)}"
    flags = env.get("XLA_FLAGS", "")
    if _XLA_DEVCOUNT_RE.search(flags):
        flags = _XLA_DEVCOUNT_RE.sub(flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    env["XLA_FLAGS"] = flags
    return env


# the rebalance hint a restart_rebalanced relaunch carries: "<host>:<factor>"
# (which process runs at what fraction of its uniform share). The canonical
# spelling lives with the consumer — data/pipeline.py parses it into
# share_splits() — and is re-exported here for the producer side.
from simclr_pytorch_distributed_tpu.data.pipeline import (  # noqa: E402,F401
    FLEET_SHARE_ENV,
)


def share_env(
    share: Optional[str], base_env: Optional[Dict[str, str]] = None
) -> Dict[str, str]:
    """The child env for a given rebalance hint (module docstring).

    ``share=None`` REMOVES any stale hint rather than inheriting it: after
    the exclusion rung (or an operator resize) the shares are uniform
    again across the new topology, and a hint left over from an earlier
    rebalance would silently starve a host that is no longer slow."""
    env = dict(os.environ if base_env is None else base_env)
    if share:
        env[FLEET_SHARE_ENV] = str(share)
    else:
        env.pop(FLEET_SHARE_ENV, None)
    return env


def build_command(
    command: Sequence[str], resume_dir: Optional[str]
) -> List[str]:
    """The user's command, with ``--resume <dir>`` appended on relaunches
    (last-wins over any user-supplied --resume; see module docstring)."""
    cmd = list(command)
    if resume_dir:
        cmd += ["--resume", resume_dir]
    return cmd


class Child:
    """One supervised attempt: a Popen plus the bookkeeping the supervisor
    needs (which topology it runs, when it started).

    stdout/stderr pass through to the supervisor's own (the trainer's log
    lines stay visible exactly as under the shell launcher); the recorder —
    not a pipe — is the supervisor's structured view of the child.
    """

    def __init__(
        self,
        command: Sequence[str],
        resume_dir: Optional[str] = None,
        devices: Optional[int] = None,
        share: Optional[str] = None,
        cwd: Optional[str] = None,
    ):
        self.command = build_command(command, resume_dir)
        self.devices = devices
        self.share = share
        self.resume_dir = resume_dir
        self.proc = subprocess.Popen(
            self.command, env=share_env(share, topology_env(devices)),
            cwd=cwd,
        )

    @property
    def pid(self) -> int:
        return self.proc.pid

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def wait(self, timeout: Optional[float] = None) -> int:
        return self.proc.wait(timeout=timeout)

    def terminate_gracefully(
        self, grace_s: float, sleep=time.sleep, poll_s: float = 0.1,
        clock=time.monotonic,
    ) -> int:
        """SIGTERM, give the preemption machinery its grace window (the
        emergency checkpoint + exit 75 path), then SIGKILL. Returns the
        child's returncode. ``sleep``/``clock`` are injected TOGETHER (the
        Supervisor passes its own pair) — a fake sleep against the real
        clock would busy-spin the poll loop for the whole grace window."""
        if self.proc.poll() is not None:
            return self.proc.returncode
        try:
            self.proc.send_signal(signal.SIGTERM)
        except OSError:  # exited between poll and signal
            return self.proc.wait()
        deadline = clock() + grace_s
        while clock() < deadline:
            if self.proc.poll() is not None:
                return self.proc.returncode
            sleep(poll_s)
        try:
            self.proc.kill()
        except OSError:
            pass
        return self.proc.wait()
