"""BYOL (Grill et al. 2020): predictor head + EMA target network.

The online branch is the step's existing encoder+projector forward; this
recipe adds the predictor as ``recipe_params`` (trained jointly — BYOL's
encoder receives gradients only through the predictor path) and the EMA
target network as ``recipe_state["target_params"]``, a full copy of the
online params tree transitioned post-step with
``target = tau * target + (1 - tau) * online``. No negatives anywhere: the
PR-8 collapse detector is the only thing standing between this recipe and
the degenerate constant solution, which is exactly why its health
thresholds are tightened (utils/guard.RECIPE_HEALTH_THRESHOLDS) and why the
ablation arm exists — ``predictor='none'`` removes the asymmetry that
prevents collapse, and the collapse-injection test drives that arm into the
typed code-3 abort.

The target forward runs in train mode (batch statistics, like the online
branch; its BN-stat mutation is discarded), so the target network is the
EMA of params only — no separate running-stat EMA to checkpoint.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from simclr_pytorch_distributed_tpu.ops.losses import byol_loss
from simclr_pytorch_distributed_tpu.recipes.base import Recipe, RecipeContext
from simclr_pytorch_distributed_tpu.train.supcon_step import two_view_forward


@dataclasses.dataclass(frozen=True)
class BYOLRecipe(Recipe):
    name: str = "byol"
    predictor: Any = None  # models/heads.PredictorHead, None = ablated
    ema_momentum: float = 0.996
    trainable: bool = dataclasses.field(default=False)

    def __post_init__(self):
        object.__setattr__(self, "trainable", self.predictor is not None)

    def init_slots(self, model, params, batch_stats, rng):
        recipe_params = None
        opt_state = None
        if self.predictor is not None:
            feat_dim = self.predictor.dim_out
            recipe_params = self.predictor.init(
                rng, jnp.zeros((2, feat_dim))
            )["params"]
            opt_state = self.tx.init(recipe_params)
        # the target starts as an exact COPY of the online network (the
        # paper's initialization) — a real copy, not jnp.asarray: aliasing
        # the online buffers would make the donating update hand the same
        # buffer to XLA twice (donate(a), donate(a) -> runtime error)
        target = jax.tree.map(jnp.copy, params)
        return recipe_params, opt_state, {"target_params": target}

    def _predict(self, recipe_params, z):
        if self.predictor is None:
            return z  # the ablation arm: BYOL without its asymmetry
        return self.predictor.apply({"params": recipe_params}, z)

    def loss(self, cfg, mesh, fused_on_mesh, ctx: RecipeContext):
        q = self._predict(ctx.recipe_params, ctx.feats)
        # the target branch: SECOND forward through the EMA params (train
        # mode, like the online branch; mutated BN stats discarded)
        target_feats, _ = two_view_forward(
            ctx.model, ctx.recipe_state["target_params"], ctx.batch_stats,
            ctx.images, train=True,
        )
        zt = jax.lax.stop_gradient(target_feats.astype(jnp.float32))
        return byol_loss(q, zt), {}

    def post_step(self, recipe_state, *, new_params, aux):
        tau = self.ema_momentum
        target = jax.tree.map(
            lambda t, o: tau * t + (1.0 - tau) * o,
            recipe_state["target_params"], new_params,
        )
        return {"target_params": target}
