"""SimSiam (Chen & He 2021): predictor + stop-gradient, no EMA.

The minimal negative-free recipe: one branch, the predictor as
``recipe_params`` (joint gradient with the encoder), and the stop-gradient
on the projection side applied inside ``ops/losses.simsiam_loss`` — no
target network, no ``recipe_state``, no momentum hyperparameter. What keeps
it from collapsing is ONLY the predictor asymmetry + stop-gradient, so like
BYOL it runs under the tightened collapse thresholds
(utils/guard.RECIPE_HEALTH_THRESHOLDS).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from simclr_pytorch_distributed_tpu.ops.losses import simsiam_loss
from simclr_pytorch_distributed_tpu.recipes.base import Recipe, RecipeContext


@dataclasses.dataclass(frozen=True)
class SimSiamRecipe(Recipe):
    name: str = "simsiam"
    predictor: Any = None  # models/heads.PredictorHead (required)
    trainable: bool = True

    def init_slots(self, model, params, batch_stats, rng):
        import jax.numpy as jnp

        recipe_params = self.predictor.init(
            rng, jnp.zeros((2, self.predictor.dim_out))
        )["params"]
        return recipe_params, self.tx.init(recipe_params), None

    def loss(self, cfg, mesh, fused_on_mesh, ctx: RecipeContext):
        pred = self.predictor.apply({"params": ctx.recipe_params}, ctx.feats)
        return simsiam_loss(pred, ctx.feats), {}
