"""VICReg (Bardes et al. 2022): invariance + variance + covariance.

No predictor, no EMA, no negatives — collapse is prevented in the loss
itself (the variance hinge), which makes this the one recipe whose health
story is "the detector should NEVER fire" (default thresholds,
utils/guard.RECIPE_HEALTH_THRESHOLDS). The covariance penalty reuses the
PR-8 covariance construction (ops/metrics.embedding_covariance) the health
diagnostics' effective-rank spectrum is built on. The three unweighted
terms stream through the metric ring as recipe columns
(``vicreg_inv``/``vicreg_var``/``vicreg_cov``) so a decaying variance term
is visible live and in ``scripts/health_report.py``.
"""

from __future__ import annotations

import dataclasses

from simclr_pytorch_distributed_tpu.ops.losses import vicreg_loss
from simclr_pytorch_distributed_tpu.recipes.base import Recipe, RecipeContext

VICREG_METRIC_KEYS = ("vicreg_cov", "vicreg_inv", "vicreg_var")


@dataclasses.dataclass(frozen=True)
class VICRegRecipe(Recipe):
    name: str = "vicreg"
    sim_coeff: float = 25.0
    std_coeff: float = 25.0
    cov_coeff: float = 1.0
    metric_keys: tuple = VICREG_METRIC_KEYS

    def loss(self, cfg, mesh, fused_on_mesh, ctx: RecipeContext):
        b = ctx.feats.shape[0] // 2
        loss, parts = vicreg_loss(
            ctx.feats[:b], ctx.feats[b:],
            sim_coeff=self.sim_coeff, std_coeff=self.std_coeff,
            cov_coeff=self.cov_coeff,
        )
        return loss, parts
