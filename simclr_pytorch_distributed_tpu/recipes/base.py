"""The Recipe protocol: what a self-supervised loss head must provide to ride
the existing substrate (two-view pipeline, device/window stores, zero-sync
metric ring, online probe, health monitor, checkpoint/ratchet discipline).

A recipe is a frozen, trace-time-static object the step builder
(train/supcon_step.make_train_step) closes over. It contributes three things,
all inside the ONE compiled update:

- ``loss(cfg, mesh, fused_on_mesh, ctx)`` — the per-step loss term computed
  from the step's own forward products (a :class:`RecipeContext`), plus an
  aux dict: entries named in ``metric_keys`` stream through the metric ring
  (zero new transfers), and the reserved ``"recipe_embeddings"`` entry is the
  detached payload ``post_step`` rotates into the queue;
- extra TRAINABLE state — ``trainable=True`` recipes (BYOL/SimSiam predictor
  heads) ride ``TrainState.recipe_params`` under their own optimizer chain
  (``self.tx``), differentiated JOINTLY with the encoder so predictor
  gradients reach the backbone;
- ``post_step(recipe_state, new_params=, aux=)`` — the non-gradient state
  transition (BYOL EMA target update, MoCo queue rotation) applied to
  ``TrainState.recipe_state`` after the optimizer step, still in-program.

``init_slots`` builds the initial ``(recipe_params, recipe_opt_state,
recipe_state)`` triple; all-``None`` (the contrastive recipes without a
queue) keeps the state tree, checkpoint layout, and jit cache keys exactly
the pre-recipe ones — the online probe's slot contract. Non-``None`` slots
are checkpointed as their own ``recipe`` payload (utils/checkpoint.py) keyed
by the recipe name recorded in checkpoint meta, so cross-recipe resumes
degrade loudly to fresh slots instead of restoring a mismatched tree.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import optax

# re-exported: the context dataclass lives beside the step that builds it
# (train/supcon_step.py) so the step module never imports recipes/ (the
# recipe implementations import the step's shared contrastive term, and an
# import in the other direction would cycle through this package's __init__)
from simclr_pytorch_distributed_tpu.train.supcon_step import (  # noqa: F401
    RecipeContext,
)

RecipeSlots = Tuple[Any, Any, Any]  # (recipe_params, recipe_opt_state, recipe_state)


@dataclasses.dataclass(frozen=True)
class Recipe:
    """Base recipe: no extra slots, no extra metrics, no post-step.

    Subclasses override what they need; the defaults make "a loss term and
    nothing else" the cheapest possible recipe. ``tx`` is the trainable
    recipes' own optimizer chain (built by recipes.build_recipe from the
    run's schedule/momentum/weight-decay, so a predictor trains under the
    same recipe hyperparameters as the encoder unless a recipe says
    otherwise).
    """

    name: str = "recipe"
    # True -> state.recipe_params exists and is differentiated jointly with
    # the encoder, updated by self.tx inside the same compiled step
    trainable: bool = False
    # extra ring columns this recipe streams (sorted into the run's key
    # tuple by train/supcon_step.metric_keys — writer and reader derive the
    # same layout, so a mismatch fails loudly at trace time)
    metric_keys: Tuple[str, ...] = ()
    tx: Optional[optax.GradientTransformation] = None

    def init_slots(self, model, params, batch_stats, rng) -> RecipeSlots:
        """Initial ``(recipe_params, recipe_opt_state, recipe_state)``.
        All-None by default: the state tree stays exactly the pre-recipe
        one."""
        return None, None, None

    def loss(self, cfg, mesh, fused_on_mesh, ctx: RecipeContext):
        """``(loss_term, aux)`` for one step; runs INSIDE the jitted update.
        ``aux`` entries named in ``self.metric_keys`` stream through the
        metric ring; the reserved ``"recipe_embeddings"`` entry feeds
        ``post_step``."""
        raise NotImplementedError

    def post_step(self, recipe_state, *, new_params, aux):
        """The post-optimizer state transition (EMA, queue rotation); called
        only when ``recipe_state`` is not None. Default: carry unchanged."""
        return recipe_state
