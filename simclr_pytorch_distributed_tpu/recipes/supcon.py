"""The contrastive recipes (supcon / simclr) behind the Recipe interface,
plus the MoCo-style momentum-encoder negative queue.

``ContrastiveRecipe`` is the repo's original behavior refactored behind the
interface: its ``loss`` delegates to the SAME
``train/supcon_step.contrastive_loss_terms`` the pre-recipe inline step uses
(verbatim-extracted, one implementation), so ``--recipe supcon`` is proven
BITWISE-identical to the pre-refactor update driver-level
(tests/test_recipes.py, docs/PARITY.md). Without a queue it contributes no
slots at all — state tree, checkpoints, and jit keys are exactly the
pre-recipe ones.

``--moco_queue K`` (simclr only — the queue holds negatives ONLY, which is
unsound under supervised positives) turns the recipe into MoCo (He et al.
2020): ``recipe_state`` carries an EMA **key encoder** (``key_params``, the
BYOL target-network pattern, momentum ``--ema_momentum``) plus a donated
device-side ring of its past keys — the MetricRing pattern applied to
negatives. Each step runs a second forward through the key encoder; the
loss contrasts online queries against the keys + the ring
(ops/losses.moco_queue_loss), and ``post_step`` rotates the batch's
detached keys in with ``dynamic_update_slice`` at the carried pointer and
EMA-advances the key encoder — all inside the one compiled program, so the
hot loop gains no per-step host traffic (the zero-sync transfer-count
proof re-runs with the queue on). The momentum encoder is NOT optional
garnish: enqueueing online embeddings instead (``m = 0``, the MoCo paper's
failure ablation) measurably collapses this repo's tiny-scale runs within
an epoch — the one-sided repulsion from the rapidly-moving self-cluster is
an instability the slow key encoder exists to remove.

``K`` must be a multiple of ``2B`` (config.validate_recipe) so ring writes
never straddle the edge (``dynamic_update_slice`` clamps rather than
wraps). Cold start: seeded L2-normalized gaussian rows, the MoCo
convention, so the loss is well-formed from step 0.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from simclr_pytorch_distributed_tpu.ops.losses import (
    l2_normalize,
    moco_queue_loss,
)
from simclr_pytorch_distributed_tpu.recipes.base import Recipe, RecipeContext
from simclr_pytorch_distributed_tpu.train.supcon_step import (
    contrastive_loss_terms,
    two_view_forward,
)


@dataclasses.dataclass(frozen=True)
class ContrastiveRecipe(Recipe):
    """supcon/simclr behind the interface; ``moco_queue > 0`` adds the
    momentum key encoder + negative ring."""

    name: str = "simclr"
    moco_queue: int = 0
    feat_dim: int = 128
    queue_seed: int = 0
    # key-encoder EMA momentum (MoCo's m; shared --ema_momentum flag)
    ema_momentum: float = 0.996

    def init_slots(self, model, params, batch_stats, rng):
        if not self.moco_queue:
            return None, None, None
        q = l2_normalize(jax.random.normal(
            rng, (self.moco_queue, self.feat_dim), jnp.float32
        ))
        # the key encoder starts as a real COPY of the online network (not
        # an alias — the donating update would hand XLA the same buffer
        # twice; recipes/byol.py has the same note)
        key_params = jax.tree.map(jnp.copy, params)
        return None, None, {
            "queue_emb": q, "queue_ptr": jnp.zeros((), jnp.int32),
            "key_params": key_params,
        }

    def loss(self, cfg, mesh, fused_on_mesh, ctx: RecipeContext):
        if cfg.method not in ("SupCon", "SimCLR"):
            raise ValueError(f"contrastive method not supported: {cfg.method}")
        loss_labels = ctx.labels if cfg.method == "SupCon" else None
        if not self.moco_queue:
            return contrastive_loss_terms(
                cfg, mesh, fused_on_mesh, ctx.n_fea, loss_labels
            ), {}
        if cfg.loss_impl != "dense":
            # the fused/ring kernels tile the fixed 2B x 2B geometry; the
            # queue extends the contrast side to 2B + K, which only the
            # dense path implements (config resolves 'auto' here)
            raise ValueError(
                f"--moco_queue needs loss_impl='dense', got {cfg.loss_impl!r}"
            )
        # keys: second forward through the EMA key encoder (train mode,
        # like the online branch; mutated BN stats discarded), normalized
        # and detached — keys never backprop (He et al. 2020)
        key_feats, _ = two_view_forward(
            ctx.model, ctx.recipe_state["key_params"], ctx.batch_stats,
            ctx.images, train=True,
        )
        keys = jax.lax.stop_gradient(
            l2_normalize(key_feats.astype(jnp.float32))
        )
        loss = moco_queue_loss(
            ctx.n_fea, keys, ctx.recipe_state["queue_emb"],
            temperature=cfg.temperature,
            base_temperature=cfg.base_temperature,
        )
        # the rotation payload: the KEYS (already detached) — the ring only
        # ever holds momentum-encoder embeddings
        return loss, {"recipe_embeddings": keys}

    def post_step(self, recipe_state, *, new_params, aux):
        if not self.moco_queue:
            return recipe_state
        emb = aux["recipe_embeddings"]  # [2B, D] keys
        ptr = recipe_state["queue_ptr"]
        queue = jax.lax.dynamic_update_slice(
            recipe_state["queue_emb"], emb, (ptr, jnp.zeros((), jnp.int32))
        )
        new_ptr = (ptr + emb.shape[0]) % self.moco_queue
        m = self.ema_momentum
        key_params = jax.tree.map(
            lambda k, o: m * k + (1.0 - m) * o,
            recipe_state["key_params"], new_params,
        )
        return {"queue_emb": queue, "queue_ptr": new_ptr,
                "key_params": key_params}
