"""recipes/ — the pluggable SSL-recipe subsystem (``--recipe``).

The substrate (two-view pipeline, device/window stores, zero-sync metric
ring, online probe, health monitor, flight recorder, checkpoint/ratchet
discipline) is recipe-agnostic in everything but the loss head; this package
supplies the heads. Four recipes ship (docs/README recipe matrix):

- ``supcon`` / ``simclr`` — the original contrastive behavior behind the
  interface (recipes/supcon.py; bitwise-equal to the pre-refactor step,
  docs/PARITY.md), optionally with a MoCo-style device-side negative queue
  (``--moco_queue``);
- ``byol`` — predictor head + EMA target network (recipes/byol.py);
- ``simsiam`` — predictor + stop-gradient, no EMA (recipes/simsiam.py);
- ``vicreg`` — invariance/variance/covariance (recipes/vicreg.py).

:func:`build_recipe` turns a finalized ``SupConConfig`` into the recipe
object the step builder closes over; :func:`attach_recipe_slots` installs
the recipe's initial TrainState slots (a no-op for slot-free recipes, so
those state trees stay exactly the pre-recipe ones).
"""

from __future__ import annotations

import jax

from simclr_pytorch_distributed_tpu.recipes.base import (  # noqa: F401
    Recipe,
    RecipeContext,
)
from simclr_pytorch_distributed_tpu.recipes.byol import BYOLRecipe
from simclr_pytorch_distributed_tpu.recipes.simsiam import SimSiamRecipe
from simclr_pytorch_distributed_tpu.recipes.supcon import ContrastiveRecipe
from simclr_pytorch_distributed_tpu.recipes.vicreg import VICRegRecipe

# the --recipe surface (config.py validates against this; 'auto' resolves to
# the --method-matching contrastive recipe)
RECIPE_NAMES = ("supcon", "simclr", "byol", "simsiam", "vicreg")

# name -> implementing class: the ONE place metric-key/class knowledge is
# looked up by name, so a recipe that grows metric columns is picked up by
# every name-based consumer (EXTRA_TB_TAGS, train_one_epoch's fallback key
# derivation) without editing this module
_RECIPE_CLASSES = {
    "supcon": ContrastiveRecipe,
    "simclr": ContrastiveRecipe,
    "byol": BYOLRecipe,
    "simsiam": SimSiamRecipe,
    "vicreg": VICRegRecipe,
}


def recipe_metric_keys(name: str) -> tuple:
    """The extra ring columns recipe ``name`` streams (for readers that
    have a config but no recipe object) — read off the class's own
    ``metric_keys`` declaration, never re-encoded by name."""
    cls = _RECIPE_CLASSES.get(name)
    return tuple(cls.metric_keys) if cls is not None else ()


# every recipe metric key any recipe can stream — the TB-tag map and
# offline readers key off this (train/supcon.py EXTRA_TB_TAGS)
ALL_RECIPE_METRIC_KEYS = tuple(sorted(
    set().union(*(recipe_metric_keys(n) for n in RECIPE_NAMES))
))


def build_recipe(cfg, schedule=None) -> Recipe:
    """The recipe object for a finalized ``SupConConfig``.

    ``schedule`` (the run's LR schedule) feeds the trainable recipes'
    predictor optimizer — the same ``make_optimizer`` chain as the encoder
    (momentum/weight-decay/optimizer flags shared), so a predictor trains
    under the run's hyperparameters. Falls back to the constant
    ``cfg.learning_rate`` when no schedule is given (bench, tests).
    """
    from simclr_pytorch_distributed_tpu.models.heads import PredictorHead
    from simclr_pytorch_distributed_tpu.train.state import make_optimizer

    name = cfg.recipe
    if name not in RECIPE_NAMES:
        raise ValueError(
            f"unknown recipe {name!r} (choose from {RECIPE_NAMES}; was "
            "config.finalize_supcon run?)"
        )
    if name in ("supcon", "simclr"):
        return ContrastiveRecipe(
            name=name, moco_queue=cfg.moco_queue, feat_dim=cfg.feat_dim,
            queue_seed=cfg.seed, ema_momentum=cfg.ema_momentum,
        )
    if name == "vicreg":
        return VICRegRecipe(
            sim_coeff=cfg.vicreg_sim_coeff, std_coeff=cfg.vicreg_std_coeff,
            cov_coeff=cfg.vicreg_cov_coeff,
        )

    def predictor_tx():
        return make_optimizer(
            schedule if schedule is not None else cfg.learning_rate,
            momentum=cfg.momentum, weight_decay=cfg.weight_decay,
            optimizer=cfg.optimizer,
        )

    predictor = PredictorHead(
        dim_hidden=cfg.predictor_hidden, dim_out=cfg.feat_dim
    )
    if name == "byol":
        ablated = cfg.byol_predictor == "none"
        return BYOLRecipe(
            predictor=None if ablated else predictor,
            ema_momentum=cfg.ema_momentum,
            tx=None if ablated else predictor_tx(),
        )
    return SimSiamRecipe(predictor=predictor, tx=predictor_tx())


def attach_recipe_slots(recipe: Recipe, model, state, rng):
    """Install the recipe's initial TrainState slots (predictor params +
    optimizer state, EMA target, queue ring). A strict no-op for slot-free
    recipes — the returned state IS the input state, so trees, checkpoints,
    and jit cache keys are untouched (the probe-off contract)."""
    rp, ro, rs = recipe.init_slots(
        model, state.params, state.batch_stats, rng
    )
    if rp is None and ro is None and rs is None:
        return state
    return state.replace(
        recipe_params=rp, recipe_opt_state=ro, recipe_state=rs
    )


def attach_for_config(cfg, model, state, schedule=None):
    """``(state_with_slots, recipe)`` in one call — the drivers' and bench's
    shared entry point (the ``device_store.make_store`` convention). The rng
    is derived from ``cfg.seed + 2`` (the probe uses ``seed``, the data key
    ``seed + 1``)."""
    recipe = build_recipe(cfg, schedule=schedule)
    state = attach_recipe_slots(
        recipe, model, state, jax.random.key(cfg.seed + 2)
    )
    return state, recipe
