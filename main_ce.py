#!/usr/bin/env python
"""Supervised cross-entropy baseline entry point (rebuilds the trainer the
reference fork lost — main_ce.py only kept set_loader)."""

from simclr_pytorch_distributed_tpu.train.ce import main

if __name__ == "__main__":
    main()
