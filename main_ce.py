#!/usr/bin/env python
"""Supervised cross-entropy baseline entry point.

The reference fork's main_ce.py is a truncated remnant — only
``set_loader`` survives upstream, with ``SupCEResNet`` imported but never
trained. This file is deliberately a THIN SHIM over the rebuilt trainer in
``simclr_pytorch_distributed_tpu/train/ce.py`` (the complete end-to-end CE
baseline: SupCEResNet over the mesh, shared schedule/telemetry/preemption
machinery, top-1/5 validation, step-granular resume), kept at the repo
root so launch commands mirror the reference (``python main_ce.py ...``).
It is scanned as a first-class entry point by the invariant linter's
call-graph pass (docs/ANALYSIS.md) — not a dead remnant chased by
accident.
"""

from simclr_pytorch_distributed_tpu.train.ce import main

if __name__ == "__main__":
    main()
