#!/usr/bin/env bash
# TPU equivalent of the reference run_supcon.sh (2-GPU DDP launch):
# no torch.distributed.launch — one process drives every local chip via the mesh.
# --ngpu 2 keeps the reference's DDP gradient-scale for recipe parity.
#
# Exit-75 contract (docs/RESILIENCE.md): 75 means "preempted, state saved
# cleanly, re-run with --resume <run_dir>". This launcher closes that loop —
# up to PREEMPT_RETRIES (default 3) relaunches, resuming from the newest
# pretrain run dir under the workdir (resolve_resume_path picks the complete
# checkpoint with the most progress inside it). Any other exit code passes
# through untouched.

set -uo pipefail

max_retries=${PREEMPT_RETRIES:-3}

# honor a --workdir override in the passthrough args (main_supcon.py default);
# both argparse spellings: '--workdir DIR' and '--workdir=DIR'
workdir=./work_space
prev=
for a in "$@"; do
  if [ "$prev" = "--workdir" ]; then workdir=$a; fi
  case "$a" in --workdir=*) workdir=${a#--workdir=} ;; esac
  prev=$a
done

# NOTE: resume_args comes AFTER "$@" — argparse is last-wins, so on a retry
# the freshly resolved run dir beats any stale --resume the user passed.
attempt=0
resume_args=()
while true; do
  python main_supcon.py \
    --syncBN \
    --epochs 100 \
    --batch_size 256 \
    --learning_rate 0.5 \
    --temp 0.5 \
    --cosine \
    --method SimCLR \
    --ngpu 2 \
    "$@" \
    ${resume_args[@]+"${resume_args[@]}"}
  rc=$?
  if [ "$rc" -ne 75 ] || [ "$attempt" -ge "$max_retries" ]; then
    exit "$rc"
  fi
  attempt=$((attempt + 1))
  # newest pretrain run dir; probe/CE folders are classifier_*/ce_*-prefixed.
  # Filter on the run-dir BASENAME ($(NF-1): paths end in /), not the whole
  # path — a workdir like /data/ce_experiments must not hide every candidate.
  run_dir=$(ls -1dt "$workdir"/*_models/*/ 2>/dev/null \
            | awk -F/ '$(NF-1) !~ /^(classifier_|ce_)/' | head -1 || true)
  if [ -n "$run_dir" ]; then
    resume_args=(--resume "$run_dir")
  else
    resume_args=()
  fi
  echo "run_supcon.sh: preempted (exit 75); retry $attempt/$max_retries," \
       "resuming from '${run_dir:-scratch}'" >&2
done
