#!/usr/bin/env bash
# TPU equivalent of the reference run_supcon.sh (2-GPU DDP launch):
# no torch.distributed.launch — one process drives every local chip via the mesh.
# --ngpu 2 keeps the reference's DDP gradient-scale for recipe parity.
#
# Exit-75 contract (docs/RESILIENCE.md): 75 means "preempted, state saved
# cleanly, re-run with --resume <run_dir>". By default this launcher
# DELEGATES babysitting to the fleet supervisor
# (python -m simclr_pytorch_distributed_tpu.supervise), which closes the
# loop for every failure class — preempt resume, crash backoff-retry,
# liveness stall kill, elastic resize — with each decision recorded in
# <workdir>/supervise/events.jsonl. SUPERVISE=0 falls back to the legacy
# bounded shell loop (exit-75 only). PREEMPT_RETRIES bounds relaunches in
# both modes.

set -uo pipefail

max_retries=${PREEMPT_RETRIES:-3}

# honor a --workdir override in the passthrough args (main_supcon.py default);
# both argparse spellings: '--workdir DIR' and '--workdir=DIR'
workdir=./work_space
prev=
for a in "$@"; do
  if [ "$prev" = "--workdir" ]; then workdir=$a; fi
  case "$a" in --workdir=*) workdir=${a#--workdir=} ;; esac
  prev=$a
done

if [ "${SUPERVISE:-1}" != "0" ]; then
  # the supervisor injects --resume itself (argparse last-wins over any
  # user-supplied --resume, same as the legacy loop's ordering) and exits
  # with the final child's code, so callers see what bash would have seen.
  # Liveness-kill is OPT-IN (off, the supervisor observes only):
  #   SUPERVISE_STALL_SECS=300   kill+resume when the boundary stalls that
  #                              long (set well above the first compile)
  #   SUPERVISE_METRICS_PORT=N   wire the trainer's /metrics sidecar AND
  #                              the supervisor's scrape to port N
  sup_args=()
  trainer_args=()
  if [ -n "${SUPERVISE_STALL_SECS:-}" ]; then
    sup_args+=(--stall_secs "$SUPERVISE_STALL_SECS")
    # the trainer's own watchdog is the dump channel of the stall verdict:
    # without it (and without a metrics port) the supervisor would have no
    # liveness source at all and the deadline would be a silent no-op
    trainer_args+=(--watchdog_secs "$SUPERVISE_STALL_SECS")
  fi
  if [ -n "${SUPERVISE_METRICS_PORT:-}" ]; then
    sup_args+=(--metrics_port "$SUPERVISE_METRICS_PORT")
    trainer_args+=(--metrics_port "$SUPERVISE_METRICS_PORT")
  fi
  exec python -m simclr_pytorch_distributed_tpu.supervise \
    --workdir "$workdir" \
    --max_restarts "$max_retries" \
    ${sup_args[@]+"${sup_args[@]}"} \
    -- \
    python main_supcon.py \
      --syncBN \
      --epochs 100 \
      --batch_size 256 \
      --learning_rate 0.5 \
      --temp 0.5 \
      --cosine \
      --method SimCLR \
      --ngpu 2 \
      "$@" \
      ${trainer_args[@]+"${trainer_args[@]}"}
fi

# ------------------------------------------------------- legacy (SUPERVISE=0)
# NOTE: resume_args comes AFTER "$@" — argparse is last-wins, so on a retry
# the freshly resolved run dir beats any stale --resume the user passed.
attempt=0
resume_args=()
while true; do
  python main_supcon.py \
    --syncBN \
    --epochs 100 \
    --batch_size 256 \
    --learning_rate 0.5 \
    --temp 0.5 \
    --cosine \
    --method SimCLR \
    --ngpu 2 \
    "$@" \
    ${resume_args[@]+"${resume_args[@]}"}
  rc=$?
  if [ "$rc" -ne 75 ] || [ "$attempt" -ge "$max_retries" ]; then
    exit "$rc"
  fi
  attempt=$((attempt + 1))
  # newest pretrain run dir; probe/CE folders are classifier_*/ce_*-prefixed.
  # Filter on the run-dir BASENAME ($(NF-1): paths end in /), not the whole
  # path — a workdir like /data/ce_experiments must not hide every candidate.
  run_dir=$(ls -1dt "$workdir"/*_models/*/ 2>/dev/null \
            | awk -F/ '$(NF-1) !~ /^(classifier_|ce_)/' | head -1 || true)
  if [ -n "$run_dir" ]; then
    resume_args=(--resume "$run_dir")
  else
    resume_args=()
  fi
  echo "run_supcon.sh: preempted (exit 75); retry $attempt/$max_retries," \
       "resuming from '${run_dir:-scratch}'" >&2
done
