#!/usr/bin/env bash
# TPU equivalent of the reference run_supcon.sh (2-GPU DDP launch):
# no torch.distributed.launch — one process drives every local chip via the mesh.
# --ngpu 2 keeps the reference's DDP gradient-scale for recipe parity.
python main_supcon.py \
  --syncBN \
  --epochs 100 \
  --batch_size 256 \
  --learning_rate 0.5 \
  --temp 0.5 \
  --cosine \
  --method SimCLR \
  --ngpu 2 \
  "$@"
