#!/usr/bin/env python
"""Linear-probe evaluation entry point (reference main_linear.py)."""

from simclr_pytorch_distributed_tpu.train.linear import main

if __name__ == "__main__":
    main()
