#!/usr/bin/env bash
# TPU equivalent of the reference run_linear.sh (single-GPU linear probe).
# Usage: ./run_linear.sh --ckpt work_space/cifar10_models/<run>/last
#
# Exit-75 contract (docs/RESILIENCE.md): the probe keeps no full-state
# checkpoints (epochs are seconds) — on preemption it persists the best
# classifier so far and exits 75; --resume for the probe means exactly
# "retrain from scratch" (config.linear_parser documents the contract).
# By default babysitting is DELEGATED to the fleet supervisor
# (python -m simclr_pytorch_distributed_tpu.supervise); SUPERVISE=0 keeps
# the legacy bounded shell loop. PREEMPT_RETRIES bounds relaunches in both.

set -uo pipefail

max_retries=${PREEMPT_RETRIES:-3}

# the supervisor resolves resume dirs under the workdir; honor an override
# in the passthrough args (both argparse spellings)
workdir=./work_space
prev=
for a in "$@"; do
  if [ "$prev" = "--workdir" ]; then workdir=$a; fi
  case "$a" in --workdir=*) workdir=${a#--workdir=} ;; esac
  prev=$a
done

if [ "${SUPERVISE:-1}" != "0" ]; then
  # --all_run_dirs: the probe's run dirs are the classifier_* folders the
  # pretrain-oriented default scan excludes — without it the supervisor's
  # run-dir channel (stall dumps, recorder events) would be blind here.
  # SUPERVISE_STALL_SECS / SUPERVISE_METRICS_PORT opt into liveness-kill
  # exactly as in run_supcon.sh.
  sup_args=()
  trainer_args=()
  if [ -n "${SUPERVISE_STALL_SECS:-}" ]; then
    sup_args+=(--stall_secs "$SUPERVISE_STALL_SECS")
    # the trainer's own watchdog is the dump channel of the stall verdict:
    # without it (and without a metrics port) the supervisor would have no
    # liveness source at all and the deadline would be a silent no-op
    trainer_args+=(--watchdog_secs "$SUPERVISE_STALL_SECS")
  fi
  if [ -n "${SUPERVISE_METRICS_PORT:-}" ]; then
    sup_args+=(--metrics_port "$SUPERVISE_METRICS_PORT")
    trainer_args+=(--metrics_port "$SUPERVISE_METRICS_PORT")
  fi
  exec python -m simclr_pytorch_distributed_tpu.supervise \
    --workdir "$workdir" \
    --max_restarts "$max_retries" \
    --all_run_dirs \
    ${sup_args[@]+"${sup_args[@]}"} \
    -- \
    python main_linear.py \
      --learning_rate 5 \
      --batch_size 256 \
      "$@" \
      ${trainer_args[@]+"${trainer_args[@]}"}
fi

# ------------------------------------------------------- legacy (SUPERVISE=0)
attempt=0
resume_args=()
while true; do
  python main_linear.py \
    --learning_rate 5 \
    --batch_size 256 \
    "$@" \
    ${resume_args[@]+"${resume_args[@]}"}
  rc=$?
  if [ "$rc" -ne 75 ] || [ "$attempt" -ge "$max_retries" ]; then
    exit "$rc"
  fi
  attempt=$((attempt + 1))
  resume_args=(--resume preempted-retry)
  echo "run_linear.sh: preempted (exit 75); retry $attempt/$max_retries" \
       "(probe retrains from scratch)" >&2
done
