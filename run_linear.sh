#!/usr/bin/env bash
# TPU equivalent of the reference run_linear.sh (single-GPU linear probe).
# Usage: ./run_linear.sh --ckpt work_space/cifar10_models/<run>/last
#
# Exit-75 contract (docs/RESILIENCE.md): the probe keeps no full-state
# checkpoints (epochs are seconds) — on preemption it persists the best
# classifier so far and exits 75; this launcher relaunches up to
# PREEMPT_RETRIES (default 3) times. --resume for the probe means exactly
# "retrain from scratch" (config.linear_parser documents the contract).

set -uo pipefail

max_retries=${PREEMPT_RETRIES:-3}
attempt=0
resume_args=()
while true; do
  python main_linear.py \
    --learning_rate 5 \
    --batch_size 256 \
    "$@" \
    ${resume_args[@]+"${resume_args[@]}"}
  rc=$?
  if [ "$rc" -ne 75 ] || [ "$attempt" -ge "$max_retries" ]; then
    exit "$rc"
  fi
  attempt=$((attempt + 1))
  resume_args=(--resume preempted-retry)
  echo "run_linear.sh: preempted (exit 75); retry $attempt/$max_retries" \
       "(probe retrains from scratch)" >&2
done
