#!/usr/bin/env bash
# TPU equivalent of the reference run_linear.sh (single-GPU linear probe).
# Usage: ./run_linear.sh --ckpt work_space/cifar10_models/<run>/last
python main_linear.py \
  --learning_rate 5 \
  --batch_size 256 \
  "$@"
