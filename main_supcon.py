#!/usr/bin/env python
"""Distributed contrastive pretraining entry point (reference main_supcon.py).

No process launcher needed: one process per HOST drives all local chips via the
mesh. On a single v5e-8 just run `python main_supcon.py ...` with the same flags
as the reference.
"""

from simclr_pytorch_distributed_tpu.train.supcon import main

if __name__ == "__main__":
    main()
