#!/usr/bin/env python
"""Pretrain throughput benchmark: imgs/sec/chip on the recipe workload.

Runs the fused SimCLR train step (device-side two-crop augmentation + ResNet-50
forward/backward + global NT-Xent + SGD) at the published recipe config
(bs=256 global, 32x32, temp 0.5, SyncBN) on the available chips and prints ONE
JSON line. The reference publishes no throughput numbers (BASELINE.json
``published`` is empty), so the committed baseline is this REPO's own recorded
headline (``REPO_BASELINES``, the round-5 chip measurement): ``vs_baseline``
reports against it for stages that have one (1.0 otherwise), and
``scripts/ratchet.py`` gates on 95% of it so a perf regression fails CI like
an accuracy regression does (VERDICT round 5 #6).

Honesty guard: on the tunneled bench chip, ``jax.block_until_ready`` returns
BEFORE the computation actually finishes (the tunnel acks buffer readiness
early), which made round-1 numbers physically impossible (implied MFU ~600%+).
The only trustworthy sync is a host readback of a *computed scalar*
(``float(metrics["loss"])``) — that value cannot exist until the step ran.
Each timing window ends with such a readback. On top of that, every window's
throughput is cross-checked against the program's XLA FLOP count and the
chip's peak: windows whose implied MFU exceeds ``CREDIBLE_MFU`` are discarded
as clock glitches, and the headline is the **median** of the credible windows —
never a best-of-N, which selects exactly the most-wrong samples.
"""

import json
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

# Peak dense bf16 throughput assumed for MFU accounting, by device kind.
# v5e ("TPU v5 lite"): 197 TFLOP/s bf16 (public spec). CPU fallback is only so
# the script runs everywhere; its MFU is not meaningful.
PEAK_TFLOPS_BY_KIND = {
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v4": 275.0,
    "TPU v5p": 459.0,
    "TPU v6e": 918.0,
}
DEFAULT_PEAK_TFLOPS = 197.0
# Peak HBM bandwidth (GB/s) by device kind, public specs: v5e 819, v4 1228,
# v5p 2765, v6e 1640. Used for the roofline: implied_hbm_util next to
# implied_mfu says WHICH ceiling the workload is actually against.
PEAK_HBM_GBPS_BY_KIND = {
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v4": 1228.0,
    "TPU v5p": 2765.0,
    "TPU v6e": 1640.0,
}
DEFAULT_PEAK_HBM_GBPS = 819.0
CREDIBLE_MFU = 0.70  # anything above this on this workload is a clock glitch

# Committed per-stage throughput baselines (imgs/s/chip) — the repo's own
# recorded headline numbers, quoted in VERDICT.md. ``vs_baseline`` reports
# against these; scripts/ratchet.py's bench gate fails below
# RATCHET_BENCH_FRACTION of the stage baseline (chip-noise margin from the
# BENCH_r05 window spread). Update ONLY when a new chip round records a new
# headline (and say so in docs/PERF.md).
REPO_BASELINES = {
    # round-5 headline: 4,066.5 imgs/s/chip at 63.0 ms/step on the v5e bench
    # chip (BENCH_r05.json, recipe config, fused loss, bf16)
    "pretrain": 4066.5,
}
# The chip the baselines were recorded on (jax device_kind spelling, see
# docs/evidence/bench_*_r5.json). The numbers are chip-specific: the ratchet
# bench gate only enforces the bar when the bench ran on this kind.
REPO_BASELINE_DEVICE_KIND = "TPU v5 lite"
RATCHET_BENCH_FRACTION = 0.95


def vs_baseline_for(stage: str, per_chip: float) -> float:
    """per-chip throughput vs the recorded repo baseline (1.0 = no record)."""
    baseline = REPO_BASELINES.get(stage)
    if not baseline or per_chip <= 0:
        return 1.0
    return round(per_chip / baseline, 4)


def _compile_with_flops(update, *example_args):
    """AOT-compile the update once; return (callable, FLOPs/step, bytes/step).

    Both counts come from XLA's own cost analysis of the PER-DEVICE module
    (0.0 when unavailable). Reusing the compiled executable avoids paying the
    big XLA compile twice (once for cost analysis, once for the jit cache)."""
    try:
        compiled = update.lower(*example_args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        return (
            compiled,
            float(cost.get("flops", 0.0)),
            float(cost.get("bytes accessed", 0.0)),
        )
    except Exception:
        return update, 0.0, 0.0


# window length for the --data_placement window bench arm: the driver
# default is 32, but the bench buffer only has to exercise the windowed
# slice program (epoch_position % W), not a realistic window economy
BENCH_WINDOW_BATCHES = 8


def _setup_pretrain(mesh, batch, size, stem, data_placement="host",
                    recipe="simclr", moco_queue=0, conv_impl="xla",
                    conv_dtype="fp32"):
    """The headline workload: fused SimCLR pretrain step (recipe config).

    ``data_placement='device'`` benches the resident-store step instead
    (data/device_store.py): the jitted update takes the full-epoch
    ``[steps, batch, ...]`` buffers and slices its own batch at
    ``state.step % steps_per_epoch`` — the same program the drivers run
    under ``--data_placement device``, so the slice's cost (if any) is
    measured with the existing methodology. ``'window'`` benches the
    WINDOWED step program the same way: a ``[BENCH_WINDOW_BATCHES, batch,
    ...]`` resident window sliced at ``epoch_position % W`` — so the
    windowed hot loop shows up in ``vs_baseline`` and the scaling story
    next to the host and resident arms. Note bench's 'host' arm is
    already transfer-free (the same example batch every step — the
    resident-batch FLOOR); these arms isolate the in-program slice, while
    ``scripts/resident_ab.py`` / ``scripts/window_ab.py`` measure the
    driver-loop transfer removal.

    ``recipe`` benches the other SSL loss heads on the SAME methodology
    (recipes/: byol = predictor + EMA target second forward, simsiam =
    predictor + stop-gradient, vicreg = var/cov terms, supcon = labeled
    contrastive; ``moco_queue`` adds the device-side negative ring to the
    simclr arm). ``vs_baseline`` stays pinned to the recorded supcon-family
    pretrain headline for every recipe arm, so a recipe's overhead (the EMA
    update, the queue rotation, the extra target forward) is MEASURED
    against the same floor, not guessed.
    """
    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu import recipes as recipes_lib
    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.ops.augment import AugmentConfig
    from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
    from simclr_pytorch_distributed_tpu.parallel.mesh import shard_host_batch
    from simclr_pytorch_distributed_tpu.train.state import (
        create_train_state,
        make_optimizer,
    )
    from simclr_pytorch_distributed_tpu.train.supcon import (
        make_fused_update,
        resolve_loss_impl,
    )
    from simclr_pytorch_distributed_tpu.train.supcon_step import SupConStepConfig

    from simclr_pytorch_distributed_tpu.train.supcon import resolve_conv_impl

    steps_per_epoch = 50000 // batch
    # bf16 compute on the MXU; fp32 params/BN stats/loss. The pallas
    # conv-block arm runs in --conv_dtype compute: 'fp32' is the round-15
    # arm (whole-trade vs the recorded bf16 XLA headline — kernel fusion
    # win minus the bf16 give-back), 'bf16' is the round-19 arm (the
    # like-for-like dtype comparison the headline runs; fused kernels
    # accumulate fp32 on the MXU, BN statistics stay fp32). vs_baseline
    # stays pinned to the recorded bf16 XLA headline for BOTH, so each
    # arm's number is its whole-trade verdict; the config string names
    # the arm.
    if conv_impl == "pallas":
        conv_impl, conv_reason = resolve_conv_impl(
            "pallas", "resnet50", batch, size, len(jax.devices()),
            bf16=conv_dtype == "bf16",
        )
    else:
        conv_reason = "explicit request: bitwise-pinned XLA conv path"
    print(f"[conv_impl] '{conv_impl}': {conv_reason}")
    pallas_fp32 = conv_impl == "pallas" and conv_dtype == "fp32"
    model = SupConResNet(
        model_name="resnet50", head="mlp", feat_dim=128,
        dtype=jnp.float32 if pallas_fp32 else jnp.bfloat16,
        stem=stem, conv_impl=conv_impl,
    )
    schedule = make_lr_schedule(
        learning_rate=0.5, epochs=100, steps_per_epoch=steps_per_epoch, cosine=True
    )
    tx = make_optimizer(schedule, momentum=0.9, weight_decay=1e-4)
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.zeros((2, size, size, 3))
    )
    # the recipe arm rides the same update builder as the drivers; the
    # config is finalize-validated so bench rejects the same bad flag
    # combinations the trainers do (queue geometry, supcon+queue, ...)
    recipe_cfg = config_lib.SupConConfig(
        recipe=recipe, moco_queue=moco_queue, batch_size=batch,
        learning_rate=0.5, loss_impl="auto",
    )
    config_lib.validate_recipe(recipe_cfg)
    loss_impl = resolve_loss_impl(
        "auto", batch, len(jax.devices()), moco_queue=moco_queue
    )
    step_cfg = SupConStepConfig(
        method=recipe_cfg.method, temperature=0.5, epochs=100,
        steps_per_epoch=steps_per_epoch, grad_div=2.0, loss_impl=loss_impl,
    )
    state, recipe_obj = recipes_lib.attach_for_config(
        recipe_cfg, model, state, schedule=schedule
    )
    update = make_fused_update(
        model, tx, schedule, step_cfg, AugmentConfig(size=size), mesh, state,
        resident=data_placement != "host",
        window_batches=(
            BENCH_WINDOW_BATCHES if data_placement == "window" else None
        ),
        recipe=recipe_obj,
    )

    rng = np.random.default_rng(0)
    if data_placement != "host":
        # the drivers' resident layout: shuffled batches on device, batch
        # dim sharded (parallel/mesh.epoch_buffer_sharding) — a full epoch
        # for the resident store, one window for the window store
        from simclr_pytorch_distributed_tpu.parallel.mesh import (
            epoch_buffer_sharding,
        )

        lead = (
            BENCH_WINDOW_BATCHES if data_placement == "window"
            else steps_per_epoch
        )
        images = rng.integers(
            0, 256, size=(lead, batch, size, size, 3), dtype=np.uint8,
        )
        labels = rng.integers(0, 10, size=(lead, batch)).astype(np.int32)
        sh_images = jax.device_put(images, epoch_buffer_sharding(mesh, 5))
        sh_labels = jax.device_put(labels, epoch_buffer_sharding(mesh, 2))
    else:
        images = rng.integers(0, 256, size=(batch, size, size, 3), dtype=np.uint8)
        labels = rng.integers(0, 10, size=(batch,)).astype(np.int32)
        sh_images, sh_labels = shard_host_batch((images, labels), mesh)

    dtype_token = "fp32" if pallas_fp32 else "bf16"
    config = (
        f"{recipe} rn50 cifar-recipe {dtype_token} fused-aug bsz{batch} "
        f"loss={loss_impl}"
        + ("" if not moco_queue else f" moco_queue={moco_queue}")
        + ("" if stem == "conv" else f" stem={stem}")
        + ("" if data_placement == "host" else f" data={data_placement}")
        + ("" if conv_impl == "xla" else f" conv={conv_impl}/{conv_dtype}")
    )
    return update, sh_images, sh_labels, state, "pretrain", config


def _setup_linear(mesh, batch, size):
    """The probe workload (reference run_linear.sh): frozen eval-mode rn50
    encoder forward + classifier update, RRC+flip aug, recipe bs=256."""
    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.ops.augment import AugmentConfig
    from simclr_pytorch_distributed_tpu.parallel.mesh import shard_host_batch
    from simclr_pytorch_distributed_tpu.train.linear import (
        build_probe,
        make_probe_steps,
        stats_for,
    )

    cfg = config_lib.LinearConfig(
        model="resnet50", dataset="cifar10", batch_size=batch,
        learning_rate=5.0, bf16=True, n_cls=10,
    )
    from simclr_pytorch_distributed_tpu.models import SupConResNet

    encoder = SupConResNet(model_name="resnet50", dtype=jnp.bfloat16)
    enc_vars = encoder.init(
        jax.random.key(0), jnp.zeros((2, size, size, 3)), train=False
    )
    _, classifier, _, tx, state, encode = build_probe(
        cfg, steps_per_epoch=50000 // batch, encoder_variables=enc_vars
    )
    mean, std = stats_for(cfg.dataset)
    aug_cfg = AugmentConfig(size=size, mean=mean, std=std, color_ops=False)
    train_jit, _ = make_probe_steps(
        classifier, tx, encode, aug_cfg, aug_cfg, mesh
    )

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(batch, size, size, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(batch,)).astype(np.int32)
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)

    # stage token matches the CLI choice (--stage linear) so scripts keying
    # the metric name off the flag find it
    return train_jit, sh_images, sh_labels, state, "linear", (
        f"linear-probe rn50-frozen bf16 rrc+flip lr5 bsz{batch}"
    )


def _setup_ce(mesh, batch, size):
    """The CE-baseline workload: SupCEResNet train step (train/ce.py)."""
    from simclr_pytorch_distributed_tpu.models import SupCEResNet
    from simclr_pytorch_distributed_tpu.ops.augment import AugmentConfig
    from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
    from simclr_pytorch_distributed_tpu.parallel.mesh import shard_host_batch
    from simclr_pytorch_distributed_tpu.train.ce import CEState, make_ce_steps
    from simclr_pytorch_distributed_tpu.train.linear import stats_for
    from simclr_pytorch_distributed_tpu.train.state import make_optimizer

    data_parallel = mesh.shape["data"]
    model = SupCEResNet(
        model_name="resnet50", num_classes=10, dtype=jnp.bfloat16,
        sync_bn=False, bn_local_groups=data_parallel,
    )
    schedule = make_lr_schedule(
        learning_rate=0.1, epochs=100, steps_per_epoch=50000 // batch,
        cosine=True,
    )
    tx = make_optimizer(schedule, momentum=0.9, weight_decay=1e-4)
    variables = model.init(
        jax.random.key(0), jnp.zeros((2, size, size, 3)), train=True
    )
    state = CEState(
        step=jnp.zeros((), jnp.int32),
        params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=tx.init(variables["params"]),
    )
    mean, std = stats_for("cifar10")
    aug_cfg = AugmentConfig(size=size, mean=mean, std=std, color_ops=False)
    train_jit, _ = make_ce_steps(model, tx, aug_cfg, mesh)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(batch, size, size, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(batch,)).astype(np.int32)
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)

    return train_jit, sh_images, sh_labels, state, "ce", (
        f"supervised-CE rn50 bf16 rrc+flip bsz{batch}"
    )


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser("throughput bench")
    ap.add_argument(
        "--stem", choices=["conv", "s2d"], default="conv",
        help="s2d = space-to-depth stem repack A/B (docs/PERF.md roofline)",
    )
    ap.add_argument(
        "--stage", choices=["pretrain", "linear", "ce"], default="pretrain",
        help="workload: contrastive pretrain (headline), linear probe, or "
             "the CE baseline — same methodology for all three",
    )
    ap.add_argument(
        "--batch_size", type=int, default=256,
        help="global batch per chip (32 = one v5e-8 shard of the recipe's "
             "256, the per-device workload for the multi-chip projection in "
             "docs/PERF.md)",
    )
    ap.add_argument(
        "--data_placement", choices=["host", "device", "window"],
        default="host",
        help="device = bench the resident-store step (full-epoch HBM buffer "
             "+ in-program slice, the --data_placement device driver "
             "program); window = the windowed-store step (one resident "
             "window, in-program slice at epoch_position %% W) — same "
             "methodology for all arms",
    )
    ap.add_argument(
        "--recipe", choices=["simclr", "supcon", "byol", "simsiam", "vicreg"],
        default="simclr",
        help="SSL recipe arm (recipes/): bench the other loss heads on the "
             "same methodology; vs_baseline stays pinned to the recorded "
             "supcon-family headline so recipe overhead is measured",
    )
    ap.add_argument(
        "--moco_queue", type=int, default=0,
        help="device-side negative queue size for the simclr recipe arm "
             "(multiple of 2*batch_size; forces the dense loss path)",
    )
    ap.add_argument(
        "--conv_impl", choices=["xla", "pallas"], default="xla",
        help="encoder conv-block path (ops/pallas_conv.py): 'pallas' "
             "benches the fused conv+BN+ReLU stem/BasicBlock/Bottleneck "
             "kernels (--conv_dtype picks fp32 or bf16 compute); default "
             "'xla' keeps the gated baseline arm exactly today's path. "
             "vs_baseline stays pinned to the recorded XLA-path headline "
             "until a new baseline is committed, so the pallas arm's "
             "number IS the measured whole-trade win/loss",
    )
    ap.add_argument(
        "--conv_dtype", choices=["fp32", "bf16"], default="fp32",
        help="compute dtype for the --conv_impl pallas arm: 'fp32' is the "
             "round-15 whole-trade arm, 'bf16' the round-19 like-for-like "
             "arm against the bf16 XLA headline (fused kernels accumulate "
             "fp32 on the MXU; BN statistics stay fp32). The ledger "
             "fingerprint keys on it for non-xla impls",
    )
    ap.add_argument(
        "--ledger", nargs="?", const="docs/perf_ledger.jsonl", default="",
        metavar="PATH",
        help="append this run to the longitudinal perf ledger "
             "(scripts/perf_ledger.py: git rev + workload fingerprint + "
             "throughput per record; default path docs/perf_ledger.jsonl)",
    )
    ap.add_argument(
        "--ledger_phases", default="", metavar="TRACE_REPORT_JSON",
        help="a trace_report artifact whose per-phase shares ride the "
             "ledger record (drift becomes attributable to a phase)",
    )
    ap.add_argument(
        "--ledger_note", default="",
        help="free-form provenance note on the ledger record",
    )
    args = ap.parse_args(argv)
    if args.stem != "conv" and args.stage != "pretrain":
        ap.error("--stem applies to --stage pretrain only")
    if args.data_placement != "host" and args.stage != "pretrain":
        ap.error("--data_placement applies to --stage pretrain only")
    if ((args.recipe != "simclr" or args.moco_queue)
            and args.stage != "pretrain"):
        ap.error("--recipe/--moco_queue apply to --stage pretrain only")
    if args.conv_impl != "xla" and args.stage != "pretrain":
        ap.error("--conv_impl applies to --stage pretrain only")
    if args.conv_dtype != "fp32" and args.conv_impl != "pallas":
        # the xla arm is always the pinned bf16 headline path; conv_dtype
        # selects between the pallas arms only
        ap.error("--conv_dtype applies to --conv_impl pallas only")
    if args.conv_impl == "pallas" and args.stem != "conv":
        # honored-or-raise: the fused stem kernel implements the 'conv'
        # stem only — a pallas-labeled s2d run would record its stem as a
        # pure-XLA measurement under the pallas ledger fingerprint
        ap.error("--conv_impl pallas requires the default --stem conv "
                 "(the fused kernel implements the conv stem only)")

    from simclr_pytorch_distributed_tpu.parallel.mesh import create_mesh

    n_chips = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    peak_tflops = PEAK_TFLOPS_BY_KIND.get(device_kind, DEFAULT_PEAK_TFLOPS)
    mesh = create_mesh()
    batch, size = args.batch_size, 32

    if args.stage == "pretrain":
        setup = _setup_pretrain(
            mesh, batch, size, args.stem, data_placement=args.data_placement,
            recipe=args.recipe, moco_queue=args.moco_queue,
            conv_impl=args.conv_impl, conv_dtype=args.conv_dtype,
        )
    elif args.stage == "linear":
        setup = _setup_linear(mesh, batch, size)
    else:
        setup = _setup_ce(mesh, batch, size)
    jit_fn, sh_images, sh_labels, state, metric_stage, config_str = setup

    fn, flops, bytes_accessed = _compile_with_flops(
        jit_fn, state, sh_images, sh_labels, jax.random.key(0)
    )
    peak_hbm = PEAK_HBM_GBPS_BY_KIND.get(device_kind, DEFAULT_PEAK_HBM_GBPS)

    def run_step(state, key):
        return fn(state, sh_images, sh_labels, key)

    # The base key is passed UNCHANGED every step; the per-step key is
    # fold_in(base_key, state.step) INSIDE the jitted program (the drivers
    # do the same). Any per-step host key derivation is an H2D transfer
    # (~5-10 ms over the tunneled chip) that silently throttled the small
    # probe/CE steps (docs/PERF.md).
    base_key = jax.random.key(42)

    # warmup (compile + first steps); scalar readback = real sync (docstring)
    for i in range(3):
        state, metrics = run_step(state, base_key)
    float(metrics["loss"])

    # Median of credible windows (see module docstring for why not best-of-N).
    n_steps, windows = 30, 5
    window_dts = []
    for w in range(windows):
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, metrics = run_step(state, base_key)
        float(metrics["loss"])  # D2H readback of a computed value: real sync
        window_dts.append(time.perf_counter() - t0)

    def implied_mfu(dt_window: float) -> float:
        # cost_analysis() on an SPMD-partitioned executable reports the
        # PER-DEVICE module's FLOPs, so the per-chip MFU is flops/dt/peak
        # with no n_chips factor (on 1 chip the two conventions coincide).
        if flops <= 0:
            return 0.0
        return (flops * n_steps / dt_window) / (peak_tflops * 1e12)

    if flops <= 0:
        # No FLOP count -> the MFU cross-check cannot run, so the number
        # cannot be certified against the round-1 failure mode. Report the
        # slowest (most conservative) window and flag it.
        credible = []
        n_glitched = 0
        dt = max(window_dts)
        clock_suspect = True
    else:
        credible = [dt for dt in window_dts if implied_mfu(dt) <= CREDIBLE_MFU]
        n_glitched = len(window_dts) - len(credible)
        if credible:
            dt = statistics.median(credible)
            clock_suspect = False
        else:
            # Every window claims impossible speed: the clock cannot be
            # trusted at all. Report the SLOWEST window (the most
            # conservative sample) and flag it, rather than quoting a number
            # we know is wrong.
            dt = max(window_dts)
            clock_suspect = True

    imgs_per_sec = n_steps * batch / dt
    per_chip = imgs_per_sec / n_chips
    mfu = implied_mfu(dt)
    # Roofline companion to MFU: fraction of peak HBM bandwidth the step's
    # XLA-counted buffer traffic implies. "bytes accessed" is HLO-level
    # (counts each logical buffer touch; fusion means actual DRAM traffic is
    # lower), so this is an UPPER bound on true HBM utilization.
    hbm_util = (
        (bytes_accessed * n_steps / dt) / (peak_hbm * 1e9)
        if bytes_accessed > 0 else 0.0
    )
    record = {
        "metric": f"{metric_stage}_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/s/chip",
        # baselines were recorded at the recipe defaults on ONE baseline
        # chip (256 imgs/chip); a non-default batch/stem, a multi-chip mesh
        # (global 256 shards to 256/n imgs/chip — a different per-chip
        # workload, see bench_perchip32_r5.json), or any other accelerator
        # is not a regression signal. A non-default --recipe/--moco_queue
        # arm KEEPS vs_baseline: the comparison against the supcon-family
        # headline is the recipe-overhead measurement (the ratchet bench
        # gate only runs the default arm, so the bar never binds on it).
        # Likewise --conv_impl pallas (either --conv_dtype arm):
        # vs_baseline stays pinned to the recorded bf16 XLA headline
        # until a new baseline is committed, so each pallas arm reports
        # its measured whole-trade win/loss.
        "vs_baseline": (
            vs_baseline_for(metric_stage, per_chip)
            if args.batch_size == 256 and args.stem == "conv"
            and args.data_placement == "host"
            and n_chips == 1 and device_kind == REPO_BASELINE_DEVICE_KIND
            else 1.0
        ),
        "detail": {
            "global_batch": batch,
            "recipe": getattr(args, "recipe", "simclr"),
            "moco_queue": getattr(args, "moco_queue", 0),
            # the explicit conv path (honored-or-raise, so the flag IS the
            # effective impl): the ledger fingerprint keys on it so
            # regression scans never compare across kernel implementations
            # (and, for non-xla impls, across compute dtypes)
            "conv_impl": getattr(args, "conv_impl", "xla"),
            "conv_dtype": getattr(args, "conv_dtype", "fp32"),
            "chips": n_chips,
            "device_kind": device_kind,
            "total_imgs_per_sec": round(imgs_per_sec, 1),
            "step_ms": round(1000 * dt / n_steps, 2),
            "flops_per_step_per_device": flops,
            "bytes_accessed_per_step_per_device": bytes_accessed,
            "implied_mfu": round(mfu, 4),
            "implied_hbm_util_upper_bound": round(hbm_util, 4),
            "peak_tflops_assumed": peak_tflops,
            "peak_hbm_gbps_assumed": peak_hbm,
            "window_step_ms": [round(1000 * d / n_steps, 2) for d in window_dts],
            "windows_discarded_as_clock_glitch": n_glitched,
            "clock_suspect": clock_suspect,
            "selection": "median of credible windows (implied MFU <= 0.7)",
            "config": config_str,
        },
    }
    print(json.dumps(record))
    if args.ledger:
        # the longitudinal record: one line per bench run, fingerprinted by
        # workload identity so only like compares with like
        import os
        import sys as _sys

        repo = os.path.dirname(os.path.abspath(__file__))
        _sys.path.insert(0, os.path.join(repo, "scripts"))
        import perf_ledger

        # relative paths anchor at the REPO, not the cwd: the committed
        # ledger is what perf_ledger.py check and the ratchet gate read —
        # a cwd-relative default would grow a stray history instead
        ledger_path = args.ledger
        if not os.path.isabs(ledger_path):
            ledger_path = os.path.join(repo, ledger_path)
        ledger_rec = perf_ledger.append_from_bench(
            ledger_path, record, phases_path=args.ledger_phases,
            note=args.ledger_note,
        )
        print(f"ledger: appended {ledger_rec['fingerprint']} "
              f"@ {ledger_rec['git_rev']} -> {ledger_path}")


if __name__ == "__main__":
    main()
