#!/usr/bin/env python
"""Pretrain throughput benchmark: imgs/sec/chip on the recipe workload.

Runs the fused SimCLR train step (device-side two-crop augmentation + ResNet-50
forward/backward + global NT-Xent + SGD) at the published recipe config
(bs=256 global, 32x32, temp 0.5, SyncBN) on the available chips and prints ONE
JSON line. The reference publishes no throughput numbers (BASELINE.json
``published`` is empty), so ``vs_baseline`` is reported as 1.0.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.ops.augment import AugmentConfig
    from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
    from simclr_pytorch_distributed_tpu.parallel.mesh import (
        create_mesh,
        shard_host_batch,
    )
    from simclr_pytorch_distributed_tpu.train.state import (
        create_train_state,
        make_optimizer,
    )
    from simclr_pytorch_distributed_tpu.train.supcon import make_fused_update
    from simclr_pytorch_distributed_tpu.train.supcon_step import SupConStepConfig

    n_chips = len(jax.devices())
    mesh = create_mesh()
    batch, size = 256, 32
    steps_per_epoch = 50000 // batch

    # bf16 compute on the MXU; fp32 params/BN stats/loss.
    model = SupConResNet(
        model_name="resnet50", head="mlp", feat_dim=128, dtype=jnp.bfloat16
    )
    schedule = make_lr_schedule(
        learning_rate=0.5, epochs=100, steps_per_epoch=steps_per_epoch, cosine=True
    )
    tx = make_optimizer(schedule, momentum=0.9, weight_decay=1e-4)
    state = create_train_state(
        model, tx, jax.random.key(0), jnp.zeros((2, size, size, 3))
    )
    from simclr_pytorch_distributed_tpu.train.supcon import resolve_loss_impl

    loss_impl = resolve_loss_impl("auto", batch, n_chips)
    step_cfg = SupConStepConfig(
        method="SimCLR", temperature=0.5, epochs=100,
        steps_per_epoch=steps_per_epoch, grad_div=2.0, loss_impl=loss_impl,
    )
    update = make_fused_update(
        model, tx, schedule, step_cfg, AugmentConfig(size=size), mesh, state
    )

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(batch, size, size, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(batch,)).astype(np.int32)
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)

    # warmup (compile + first steps)
    for i in range(3):
        state, metrics = update(state, sh_images, sh_labels, jax.random.key(i))
    jax.block_until_ready(state.params)

    # best-of-5 20-step windows: the tunneled chip is shared, so a single
    # window can be skewed by co-tenant load; the fastest window is the
    # closest estimate of the hardware's actual step time.
    n_steps, windows = 20, 5
    best_dt = float("inf")
    for w in range(windows):
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, metrics = update(
                state, sh_images, sh_labels, jax.random.key(100 + w * n_steps + i)
            )
        jax.block_until_ready(state.params)
        best_dt = min(best_dt, time.perf_counter() - t0)
    dt = best_dt

    imgs_per_sec = n_steps * batch / dt
    per_chip = imgs_per_sec / n_chips
    print(json.dumps({
        "metric": "pretrain_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/s/chip",
        "vs_baseline": 1.0,
        "detail": {
            "global_batch": batch,
            "chips": n_chips,
            "total_imgs_per_sec": round(imgs_per_sec, 1),
            "step_ms": round(1000 * dt / n_steps, 2),
            "config": f"SimCLR rn50 cifar-recipe bf16 fused-aug loss={loss_impl}",
        },
    }))


if __name__ == "__main__":
    main()
