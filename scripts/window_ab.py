#!/usr/bin/env python
"""Does the windowed streaming store amortize the per-step H2D into one
transfer per WINDOW?

``--data_placement device`` (PR 5, ``scripts/resident_ab.py``) removes the
per-step transfer by making the whole dataset HBM-resident — which only
works when it fits. ``--data_placement window`` (data/device_store.py
WindowStore) claims the same dispatch-only hot loop for datasets that
don't fit: the device trains from a resident window of
epoch-permutation-ordered batches and the loop pays one upload per window
of ``--window_batches`` steps instead of one per step. This script
MEASURES that on the same CPU proxy and PROVES the placement swap is free
(bit-identical batches):

- both arms run the same model/step config; the ``host`` arm is the
  production loop shape (EpochLoader gather -> ``shard_host_batch`` ->
  dispatch), the ``window`` arm is the windowed loop (one window upload
  per ``window_batches`` steps, then dispatch-only);
- on CPU the real H2D is ~free AND dispatch is asynchronous, so a bare
  injected sleep would hide behind the in-flight step. The proxy therefore
  models the SERIALIZED tunnel link exactly as ``resident_ab`` does
  (PERF.md round 5 measured that serialization): before paying the
  injected ``--h2d_delay_ms`` transfer delay, the arm fences the in-flight
  step. The host arm pays fence+delay once per STEP at
  ``shard_host_batch``; the window arm once per WINDOW at the window
  upload (via the store's injectable ``window_put`` hook, the same hook
  the transfer-count tests instrument) — the store runs with
  ``prefetch=False`` because on a serialized link overlap cannot hide the
  transfer, which is precisely the regime being modeled;
- arm order is ABBA within every round after one full discarded warm arm
  of EACH kind, and the honest-sync rule holds: every timed arm ends with
  a host readback of a COMPUTED loss scalar;
- before any timing, an equivalence pass byte-compares every step of two
  windowed epochs (including a mid-epoch slice = window + in-window
  offset) against the host loader — ``equivalence_ok`` in the artifact is
  the bit-identity contract, and it gates the artifact.

Expectation: host_ms - window_ms ~= delay * (1 - 1/window_batches) (the
window arm still pays one upload delay per window). The committed artifact
is docs/evidence/window_ab_r8.json; the chip expectation derived from it
lives in docs/PERF.md ("Windowed streaming device store").

Usage: python scripts/window_ab.py [--smoke] [--h2d_delay_ms N] [--json OUT]
"""

import argparse
import json
import os
import statistics
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.data import device_store  # noqa: E402
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader  # noqa: E402
from simclr_pytorch_distributed_tpu.parallel.mesh import (  # noqa: E402
    create_mesh,
    shard_host_batch,
)

ARM_ORDER = ("host", "window", "window", "host")  # ABBA within every round


def build_output(device, h2d_delay_ms, steps_per_epoch, window_batches,
                 epochs_per_arm, rounds_records, equivalence):
    """Assemble the committed-artifact JSON from per-round arm timings.

    ``rounds_records``: one dict per round, ``{"host": [ms_per_step, ...],
    "window": [...]}`` — two measurements per arm per round (the ABBA
    order). Pure so tests pin the schema without running the measurement.
    """
    all_host = [v for r in rounds_records for v in r["host"]]
    all_window = [v for r in rounds_records for v in r["window"]]
    host_ms = statistics.median(all_host)
    window_ms = statistics.median(all_window)
    return {
        "metric": "window_ab_ms_per_step",
        "h2d_delay_ms": h2d_delay_ms,
        "steps_per_epoch": steps_per_epoch,
        "window_batches": window_batches,
        "epochs_per_arm": epochs_per_arm,
        "arm_order": "ABBA per round: " + ",".join(ARM_ORDER),
        "runs": rounds_records,
        "equivalence": equivalence,
        "summary": {
            "host_ms_per_step": round(host_ms, 2),
            "window_ms_per_step": round(window_ms, 2),
            "transfer_removed_ms_per_step": round(host_ms - window_ms, 2),
            "speedup": round(host_ms / window_ms, 3) if window_ms > 0 else None,
        },
        "device": device,
        "note": (
            "paired CPU-proxy A/B: host arm = production per-step "
            "gather+device_put loop, window arm = double-buffered streaming "
            "window (one upload per window_batches steps, prefetch off — "
            "the serialized link it models cannot overlap transfers); the "
            "injected h2d delay models the SERIALIZED tunnel link (fence "
            "in-flight step, then pay the delay) and is paid per step "
            "(host) vs per window (window); each arm ends with a "
            "computed-loss readback; equivalence = byte-equal batches, the "
            "bit-identity contract"
        ),
    }


def main(argv=None):
    def positive_int(s):
        v = int(s)
        if v < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return v

    def nonneg_float(s):
        v = float(s)
        if v < 0:
            raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
        return v

    ap = argparse.ArgumentParser()
    ap.add_argument("--h2d_delay_ms", type=nonneg_float, default=None,
                    help="injected per-transfer delay; default 50 ms, 200 ms "
                         "under --smoke (like resident_ab, the injected "
                         "stall must dominate the tiny-model compute so the "
                         "effect clears 1-core timer/contention noise by a "
                         "wide margin)")
    ap.add_argument("--steps", type=positive_int, default=None,
                    help="steps per epoch; default 20, 8 under --smoke")
    ap.add_argument("--window_batches", type=positive_int, default=None,
                    help="batches per resident window; default 5, 4 under "
                         "--smoke")
    ap.add_argument("--epochs", type=positive_int, default=None,
                    help="epochs per timed arm; default 3, 2 under --smoke")
    ap.add_argument("--rounds", type=positive_int, default=2,
                    help="ABBA rounds (2 measurements per arm per round)")
    ap.add_argument("--batch", type=positive_int, default=None,
                    help="global batch; default 64, 8 under --smoke")
    ap.add_argument("--size", type=positive_int, default=None,
                    help="default 16, 8 under --smoke")
    ap.add_argument("--model", default="resnet10")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config for tests and the committed-"
                         "artifact run")
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)
    # --smoke picks the CPU-proxy shape but only for flags the caller left
    # unset — an explicit sweep value is never overridden (flush_ab pattern).
    smoke_defaults = dict(size=8, batch=8, steps=8, window_batches=4,
                          epochs=2, h2d_delay_ms=200.0)
    full_defaults = dict(size=16, batch=64, steps=20, window_batches=5,
                         epochs=3, h2d_delay_ms=50.0)
    for k, v in (smoke_defaults if args.smoke else full_defaults).items():
        if getattr(args, k) is None:
            setattr(args, k, v)

    import jax.numpy as jnp

    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.ops.augment import AugmentConfig
    from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
    from simclr_pytorch_distributed_tpu.train.state import (
        create_train_state,
        make_optimizer,
    )
    from simclr_pytorch_distributed_tpu.train.supcon import make_fused_update
    from simclr_pytorch_distributed_tpu.train.supcon_step import SupConStepConfig

    mesh = create_mesh(devices=jax.devices()[:1])
    delay_s = args.h2d_delay_ms / 1e3

    # dataset sized to exactly steps*batch rows (plus a drop_last remainder
    # so truncation is exercised), same rng recipe as resident_ab
    rng = np.random.default_rng(0)
    n = args.steps * args.batch + args.batch // 2
    images = rng.integers(
        0, 256, size=(n, args.size, args.size, 3), dtype=np.uint8
    )
    labels = rng.integers(0, 10, size=(n,)).astype(np.int32)
    loader = EpochLoader(images, labels, args.batch, base_seed=7)
    assert loader.steps_per_epoch == args.steps

    def delayed_window_put(w_imgs, w_labs):
        time.sleep(delay_s)  # the window arm's ONE transfer per window
        return (jax.device_put(w_imgs), jax.device_put(w_labs))

    # prefetch off: the serialized link being modeled runs transfer and
    # compute on one stream, so overlap could not hide the delay anyway —
    # and the injected sleep must land on the timed thread to model that
    store = device_store.WindowStore(
        loader, mesh, args.window_batches, window_put=delayed_window_put,
        prefetch=False,
    )
    W = store.window_batches

    model = SupConResNet(model_name=args.model, head="mlp", feat_dim=128)
    schedule = make_lr_schedule(learning_rate=0.1, epochs=10,
                                steps_per_epoch=args.steps, cosine=True)
    tx = make_optimizer(schedule, momentum=0.9, weight_decay=1e-4)

    def fresh_state():
        return create_train_state(
            model, tx, jax.random.key(0),
            jnp.zeros((2, args.size, args.size, 3), jnp.float32),
        )

    step_cfg = SupConStepConfig(
        method="SimCLR", temperature=0.5, epochs=10,
        steps_per_epoch=args.steps, grad_div=1.0, loss_impl="dense",
    )
    aug_cfg = AugmentConfig(size=args.size)
    # scalar-mode updates (metric_ring=None): the loop shape under test is
    # the DATA path; telemetry stays out of both arms identically
    update_host = make_fused_update(
        model, tx, schedule, step_cfg, aug_cfg, mesh, fresh_state()
    )
    update_win = make_fused_update(
        model, tx, schedule, step_cfg, aug_cfg, mesh, fresh_state(),
        resident=True, window_batches=W,
    )
    base_key = jax.random.key(42)

    # ---- equivalence pass (bit-identity, before any timing) -------------
    checked = 0
    mid = args.steps // 2
    mid_ok = True
    for epoch in (1, 2):
        host = list(loader.epoch(epoch))
        for s, (h_imgs, h_labs) in enumerate(host):
            b_imgs, b_labs = store.batch_buffers(epoch, s)
            off = s % W
            if not (np.array_equal(np.asarray(b_imgs)[off], h_imgs)
                    and np.array_equal(np.asarray(b_labs)[off], h_labs)):
                raise SystemExit(
                    f"placement equivalence BROKEN at epoch {epoch} step {s}"
                )
            checked += 1
        # the mid-epoch resume contract is a window + slice offset shift:
        # the buffer row at the resume position IS the loader's batch there
        resumed = list(loader.epoch(epoch, start_step=mid))
        b_imgs, _ = store.batch_buffers(epoch, mid)
        mid_ok = mid_ok and np.array_equal(
            np.asarray(b_imgs)[mid % W], resumed[0][0]
        )
    equivalence = {
        "equivalence_ok": bool(checked == 2 * args.steps and mid_ok),
        "steps_compared": checked,
        "epochs": 2,
        "mid_epoch_resume_checked": True,
    }
    print(json.dumps({"equivalence": equivalence}), flush=True)

    # ---- timing ---------------------------------------------------------
    epoch_counter = [0]  # monotonically fresh epochs: every arm reshuffles

    def run_arm(mode, state):
        t0 = time.perf_counter()
        for _ in range(args.epochs):
            epoch_counter[0] += 1
            epoch = epoch_counter[0]
            if mode == "window":
                for idx in range(args.steps):
                    if idx % W == 0:
                        # ONE serialized transfer per window (the upload
                        # inside batch_buffers -> delayed_window_put);
                        # fence first — same serialized-stream rule as the
                        # host arm's per-step transfers
                        jax.block_until_ready(state)
                    w_imgs, w_labs = store.batch_buffers(epoch, idx)
                    state, metrics = update_win(
                        state, w_imgs, w_labs, base_key
                    )
            else:
                for h_imgs, h_labs in loader.epoch(epoch):
                    # serialized-link model (module docstring): the tunnel
                    # runs transfer and compute on ONE stream, so the
                    # injected transfer delay cannot start until the
                    # in-flight step retires
                    jax.block_until_ready(state)
                    time.sleep(delay_s)
                    batch = shard_host_batch((h_imgs, h_labs), mesh)
                    state, metrics = update_host(
                        state, batch[0], batch[1], base_key
                    )
        # honest sync: a computed scalar cannot exist until the steps ran
        assert np.isfinite(float(metrics["loss"]))
        dt = time.perf_counter() - t0
        return state, dt * 1e3 / (args.epochs * args.steps)

    # warmup: compile + ONE FULL DISCARDED ARM OF EACH KIND (two compiled
    # programs; allocator/code-cache settling must not land on a timed arm)
    state = fresh_state()
    state, warm_host = run_arm("host", state)
    state, warm_win = run_arm("window", state)
    print(json.dumps({"warmup_discarded_ms_per_step":
                      {"host": round(warm_host, 2),
                       "window": round(warm_win, 2)}}), flush=True)

    rounds_records = []
    for rnd in range(args.rounds):
        record = {"host": [], "window": []}
        for mode in ARM_ORDER:
            state, ms = run_arm(mode, state)
            record[mode].append(round(ms, 2))
            print(json.dumps({"round": rnd, "arm": mode,
                              "ms_per_step": round(ms, 2)}), flush=True)
        rounds_records.append(record)

    out = build_output(
        jax.devices()[0].device_kind, args.h2d_delay_ms, args.steps, W,
        args.epochs, rounds_records, equivalence,
    )
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    main()
