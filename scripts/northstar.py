#!/usr/bin/env python
"""The north-star experiment, fully automated: CIFAR-10 SimCLR ResNet-50
pretrain (100 and/or 200 epochs) + linear probe vs the reference's published
numbers (84.76 / 89.05% top-1, ``/root/reference/README.md:44-45``;
BASELINE.md).

The moment real data is reachable this is ONE command with zero decisions
left:

    python scripts/northstar.py                      # both cifar10 points
    python scripts/northstar.py --points 200         # just the headline
    python scripts/northstar.py --dataset cifar100   # the cifar100 table rows
    python scripts/northstar.py --dry-run            # plumbing check, no data

It (a) fetches CIFAR-10 if absent and egress exists (urllib + md5, the
reference's torchvision download=True parity — data/cifar.py download_cifar),
(b) runs the exact run_supcon.sh / run_linear.sh recipe per point, (c) prints
one JSON line per point comparing top-1 against the published value +-0.5
(the BASELINE.md north-star tolerance) and exits nonzero if any point misses.

``--dry-run`` swaps in synthetic_hard32 at 2 epochs to validate the entire
pipeline (pretrain subprocess -> run-dir resolution -> probe subprocess ->
accuracy parse -> JSON) with no dataset and no egress.
"""

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# published reference points: dataset -> epochs -> (top1, top5)
# (reference README.md:44-45 for cifar10, :51-52 for cifar100; BASELINE.md)
PUBLISHED = {
    "cifar10": {100: (84.76, 99.36), 200: (89.05, 99.69)},
    "cifar100": {100: (58.43, 85.26), 200: (65.73, 89.64)},
}
TOLERANCE = 0.5  # BASELINE.md north star: within +-0.5 of 89.05


class PointFailed(RuntimeError):
    """One north-star point died; the remaining points must still run and
    every point must emit its JSON record (the ratchet.py ConfigFailed
    pattern — a dead point must not eat the records the CI parses)."""


def run(cmd, log_path):
    with open(log_path, "w") as f:
        proc = subprocess.run(cmd, cwd=REPO, stdout=f, stderr=subprocess.STDOUT)
    if proc.returncode != 0:
        raise PointFailed(
            f"FAILED ({proc.returncode}): {' '.join(cmd)}; see {log_path}"
        )


def parse_probe_log(log_path):
    """(top1, top5) from the probe driver's 'best accuracy' line."""
    best = None
    with open(log_path) as f:
        for line in f:
            m = re.search(r"best accuracy: ([0-9.]+), accuracy5: ([0-9.]+)", line)
            if m:
                best = (float(m.group(1)), float(m.group(2)))
            else:
                m1 = re.search(r"best accuracy: ([0-9.]+)", line)
                if m1:
                    best = (float(m1.group(1)), None)
    if best is None:
        raise PointFailed(f"no 'best accuracy' line in {log_path}")
    return best


def newest_run_dir(workdir, dataset, suffix):
    models = os.path.join(workdir, f"{dataset}_models")
    runs = [
        os.path.join(models, d)
        for d in os.listdir(models)
        if d.endswith(suffix)
    ]
    if not runs:
        raise PointFailed(f"no run dir matching *{suffix} in {models}")
    return max(runs, key=os.path.getmtime)


def run_point(epochs, args):
    """Pretrain + probe one north-star point; returns the result record."""
    dataset = "synthetic_hard32" if args.dry_run else args.dataset
    trial = f"{args.trial}_{epochs}ep"
    pre_epochs = 2 if args.dry_run else epochs
    probe_epochs = 2 if args.dry_run else 100  # reference probe default
    # dataset in the path: a cifar100 run must not clobber cifar10's logs
    logs = os.path.join(args.workdir, f"northstar_{dataset}_{trial}")
    os.makedirs(logs, exist_ok=True)

    # the exact run_supcon.sh recipe (reference 2-GPU launch; --ngpu 2 keeps
    # the DDP gradient scale): SyncBN, bsz 256, lr 0.5, temp 0.5, cosine
    pre_log = os.path.join(logs, "pretrain.log")
    run(
        [sys.executable, "main_supcon.py", "--dataset", dataset,
         "--data_folder", args.data_folder,
         "--syncBN", "--epochs", str(pre_epochs), "--batch_size", "256",
         "--learning_rate", "0.5", "--temp", "0.5", "--cosine",
         "--method", "SimCLR", "--ngpu", "2",
         "--save_freq", str(pre_epochs), "--print_freq", "20",
         "--workdir", args.workdir, "--seed", str(args.seed),
         "--trial", trial]
        + (["--no_download"] if args.no_download else []),
        pre_log,
    )
    run_dir = newest_run_dir(args.workdir, dataset, f"trial_{trial}_cosine")

    # the exact run_linear.sh recipe: lr 5, bsz 256
    probe_log = os.path.join(logs, "probe.log")
    run(
        [sys.executable, "main_linear.py", "--dataset", dataset,
         "--data_folder", args.data_folder,
         "--epochs", str(probe_epochs), "--learning_rate", "5",
         "--batch_size", "256", "--ckpt", os.path.join(run_dir, "last"),
         "--workdir", args.workdir, "--trial", trial]
        + (["--no_download"] if args.no_download else []),
        probe_log,
    )
    top1, top5 = parse_probe_log(probe_log)

    pub1, pub5 = PUBLISHED[args.dataset][epochs]
    record = {
        "metric": f"northstar_{args.dataset}_probe_top1_{epochs}ep",
        "value": top1, "top5": top5,
        "published_top1": pub1, "published_top5": pub5,
        "tolerance": TOLERANCE,
        "delta": round(top1 - pub1, 4),
        "ok": top1 >= pub1 - TOLERANCE,
        "dry_run": args.dry_run,
        "pretrain_log": pre_log, "probe_log": probe_log,
        "run_dir": run_dir,
    }
    if args.dry_run:
        # a 2-epoch synthetic run proves the plumbing, not the number
        record["ok"] = top1 > 0.0
    return record


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", choices=sorted(PUBLISHED), default="cifar10")
    ap.add_argument("--points", type=int, nargs="+", default=[100, 200],
                    choices=[100, 200])
    ap.add_argument("--workdir", default=os.path.join(REPO, "work_space"))
    ap.add_argument("--data_folder", default=os.path.join(REPO, "datasets"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trial", default="northstar")
    ap.add_argument("--dry-run", action="store_true",
                    help="synthetic_hard32 at 2 epochs: validate the pipeline")
    ap.add_argument("--no_download", action="store_true")
    args = ap.parse_args()

    if not args.dry_run and not args.no_download:
        # fetch up front so a missing-egress failure is loud and immediate
        from simclr_pytorch_distributed_tpu.data.cifar import (
            CIFAR_ARCHIVES,
            maybe_download,
        )

        maybe_download(args.dataset, args.data_folder)
        marker = os.path.join(args.data_folder, CIFAR_ARCHIVES[args.dataset][2])
        if not os.path.isdir(marker):
            sys.exit(
                f"{args.dataset} not at {marker} and download failed (no "
                "egress?) — place the python-version binaries there and re-run"
            )

    ok = True
    for epochs in args.points:
        try:
            record = run_point(epochs, args)
        except PointFailed as e:
            pub1, pub5 = PUBLISHED[args.dataset][epochs]
            record = {
                "metric": f"northstar_{args.dataset}_probe_top1_{epochs}ep",
                "value": None, "top5": None,
                "published_top1": pub1, "published_top5": pub5,
                "tolerance": TOLERANCE, "ok": False,
                "dry_run": args.dry_run, "error": str(e),
            }
        print(json.dumps(record), flush=True)
        ok = ok and record["ok"]
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
