#!/usr/bin/env python
"""Static invariant linter for the distributed contracts (docs/ANALYSIS.md).

Checks the whole tree — the package, scripts/, and the root entry points —
against the four load-bearing contracts, with stdlib ``ast`` only (no jax;
runs in milliseconds on any box):

1. collective-schedule: no host-level collective under a process-dependent
   conditional / after a process-dependent early exit / inside an
   exception-swallowing try (the split-verdict deadlock class);
2. donation-safety: no read of a donated binding after the donating call
   (the PR-1 use-after-donation class);
3. hot-loop-sync: no sync-forcing host op inside jitted step functions or
   the drivers' flush-boundary loops, except at `# sync-ok: <reason>`
   annotated sites (the zero-sync contract, statically);
4. contract-registry: metric-key tuples sorted+unique+single-sourced,
   artifact schemas pinned to module constants, trainer flags agreeing
   through the shared config.py registry.

Designed matched points live in analysis/allowlist.py with recorded
reasons; stale entries are findings too. Exit 0 = clean.

Usage:
    python scripts/invariant_lint.py            # human-readable, exit 0/1
    python scripts/invariant_lint.py --json OUT # + the schema-pinned
                                                # artifact ratchet gates on
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from simclr_pytorch_distributed_tpu.analysis import (  # noqa: E402
    build_output,
    run_lint,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None,
                    help="write the invariant_lint/v1 artifact here")
    ap.add_argument("--root", default=REPO,
                    help="repo root to lint (default: this checkout)")
    args = ap.parse_args(argv)

    result = run_lint(args.root)
    out = build_output(result)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)

    for f in result["findings"]:
        print(f.render())
    n_allow = sum(len(a["findings"]) for a in result["allowlisted"])
    print(
        f"invariant_lint: {len(result['findings'])} finding(s), "
        f"{n_allow} allowlisted matched point(s), "
        f"{result['files_scanned']} files scanned, "
        f"rules: {', '.join(result['rules_run'])}"
    )
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
