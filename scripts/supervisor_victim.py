#!/usr/bin/env python
"""A tiny REAL pretrain run with injectable faults — the supervisor's
scenario-matrix victim (scripts/supervisor_matrix.py, tests/test_supervise.py,
tests/test_fault_injection.py).

Same philosophy as tests/fault_injection_child.py: the only honest way to
prove the supervisor is to let it babysit the REAL driver in a real OS
process — real exit codes, real /metrics sidecar, real watchdog dumps, real
checkpoints. This wrapper shrinks the synthetic dataset to seconds per run
and adds three injectable faults, each gated by a one-shot marker file so
the supervisor's RELAUNCH of the same command runs clean (the transient-
failure shape the supervisor exists to absorb):

- ``--fault stall``: at the Nth flush-boundary preemption check the main
  thread writes the marker and sleeps forever — the flush boundary stops
  advancing, ``train_last_boundary_age_seconds`` climbs, the in-child
  watchdog (``--watchdog_secs``) dumps stacks, and the supervisor must
  kill (SIGTERM is absorbed by the preempt handler's flag — exactly how a
  wedged collective behaves — so the grace window lapses into SIGKILL);
- ``--fault nan``: the Nth finite-loss check raises NonFiniteLossError —
  the driver saves ``crash_epoch_N`` and exits with typed code 1;
- ``--fault collapse``: the health thresholds are made impossible
  (``eff_rank_min=1e9``), so the first health window alarms and
  ``--health_policy abort`` exits with typed code 3 (no marker: collapse
  is not transient, and the supervisor must GIVE UP, not relaunch);
- ``--straggler_ms`` (orthogonal to ``--fault``, own ``--straggler_marker``
  one-shot gate): paces every flush boundary by that much and publishes
  the fleet-skew gauges a 2-host fleet with a host this slow would expose
  (a single-process victim has no peers — utils/telemetry.py publishes
  zero skew — so the injection simulates the fleet view; the REAL gloo
  skew path is the matrix's 2-process straggler scenario). This is the
  uniform straggler fault the matrix drives next to stall/nan/collapse,
  and it composes with them for the chaos scenario.

Accepts main_supcon-style flags (``--resume`` included), so the
supervisor's appended ``--resume <run_dir>`` lands exactly as it would on
the real trainer. Prints ``SAVE_FOLDER <path>`` and ``DONE step=<n>`` like
the fault-injection child.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser("supervisor scenario victim")
    p.add_argument("--workdir", required=True)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--trial", default="victim")
    p.add_argument("--resume", default="")
    p.add_argument("--save_freq", type=int, default=1)
    p.add_argument("--metrics_port", type=int, default=0)
    p.add_argument("--watchdog_secs", type=float, default=0.0)
    p.add_argument("--health_freq", type=int, default=0)
    p.add_argument("--health_policy", default="warn")
    p.add_argument("--fault", default="none",
                   choices=["none", "stall", "nan", "collapse"])
    p.add_argument("--fault_step", type=int, default=3,
                   help="inject at the Nth call of the hooked check")
    p.add_argument("--fault_marker", default="",
                   help="one-shot gate: fault fires only while this file "
                        "is absent (it is created at injection time)")
    p.add_argument("--straggler_ms", type=float, default=0.0,
                   help="make THIS process a straggler: sleep this long at "
                        "every flush-boundary failure-code allgather and "
                        "publish the matching fleet-skew gauges (a "
                        "single-process victim has no peers, so "
                        "utils/telemetry.py publishes zero skew — the "
                        "injection simulates the 2-host fleet whose host "
                        "1 is this slow; the REAL multi-process skew "
                        "path is proven by the gloo straggler scenario). "
                        "Composable with --fault: the chaos scenario "
                        "drives straggler + collapse in one run")
    p.add_argument("--straggler_marker", default="",
                   help="one-shot gate for --straggler_ms (separate from "
                        "--fault_marker so the combination stays "
                        "independent): skew fires only while this file "
                        "is absent; created at the first injected "
                        "boundary, so the supervisor's relaunch runs "
                        "clean — the rebalanced-away shape")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import logging

    logging.basicConfig(stream=sys.stdout, level=logging.INFO, force=True)

    from simclr_pytorch_distributed_tpu import config as config_lib
    from simclr_pytorch_distributed_tpu.data import cifar as cifar_lib
    from simclr_pytorch_distributed_tpu.utils import guard, preempt

    # 256 examples at size 8 -> 7 steps/epoch at batch 32 (the fault-child
    # geometry: seconds per run once the compile cache is warm)
    _orig_synth = cifar_lib.synthetic_dataset
    cifar_lib.synthetic_dataset = (
        lambda n=2048, num_classes=10, seed=0, size=32: _orig_synth(
            n=256, num_classes=num_classes, seed=seed, size=8
        )
    )

    armed = args.fault != "none" and not (
        args.fault_marker and os.path.exists(args.fault_marker)
    )

    def trip_marker():
        if args.fault_marker:
            with open(args.fault_marker, "w") as f:
                f.write(args.fault)

    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    if armed and args.fault == "stall":
        calls = {"n": 0}
        real = preempt.requested_global

        def stalling_requested_global():
            calls["n"] += 1
            if calls["n"] == args.fault_step:
                trip_marker()
                print("FAULT stall: main thread wedged", flush=True)
                import time

                while True:  # survive the flag-setting SIGTERM handler,
                    time.sleep(3600)  # like a wedged collective would
            return real()

        # supcon's epoch loop reads the attribute through the module, so
        # one patch covers every call site
        preempt.requested_global = stalling_requested_global
    elif armed and args.fault == "nan":
        calls = {"n": 0}
        real_check = supcon_driver.check_finite_loss

        def poisoned_check(loss, step, enabled=True):
            calls["n"] += 1
            if calls["n"] == args.fault_step:
                trip_marker()
                print("FAULT nan: poisoning the loss check", flush=True)
                raise guard.NonFiniteLossError(float("nan"), step)
            return real_check(loss, step, enabled)

        supcon_driver.check_finite_loss = poisoned_check
    elif armed and args.fault == "collapse":
        # impossible bar: every healthy window "collapses"; under
        # --health_policy abort the run exits with typed code 3. Patch
        # the recipe-threshold resolver, not the HealthThresholds class:
        # RECIPE_HEALTH_THRESHOLDS holds prebuilt instances, so a class
        # patch never reaches the monitor for a known recipe (obs.py
        # imports the resolver at run setup, after this patch lands)
        real_thresholds = guard.HealthThresholds
        guard.thresholds_for_recipe = (
            lambda recipe: real_thresholds(eff_rank_min=1e9)
        )
        trip_marker()
        print("FAULT collapse: impossible health thresholds", flush=True)

    straggler_armed = args.straggler_ms > 0 and not (
        args.straggler_marker and os.path.exists(args.straggler_marker)
    )
    if straggler_armed:
        import time as _time

        from simclr_pytorch_distributed_tpu.utils import telemetry

        real_check = telemetry.TelemetrySession.check_failures_global
        skew_s = args.straggler_ms / 1e3

        def skewed_check(self, step_hint=0):
            # marker trips at the FIRST injected boundary (injection
            # time), so the relaunch of this same command runs clean
            if args.straggler_marker and not os.path.exists(
                args.straggler_marker
            ):
                with open(args.straggler_marker, "w") as f:
                    f.write(f"straggler {args.straggler_ms}ms")
                print("FAULT straggler: boundary skew armed", flush=True)
            _time.sleep(skew_s)  # genuinely pace the boundary
            real_check(self, step_hint)
            if self._gauges is not None:
                # what a 2-host fleet with host 1 this slow would publish
                # (utils/telemetry.py multi-process branch)
                self._gauges.set(
                    boundary_skew_seconds=skew_s,
                    boundary_straggler=1.0,
                    process_count=2.0,
                )

        telemetry.TelemetrySession.check_failures_global = skewed_check

    cfg = config_lib.SupConConfig(
        model="resnet10", dataset="synthetic", batch_size=32,
        epochs=args.epochs, learning_rate=0.05, temp=0.5, cosine=True,
        save_freq=args.save_freq, print_freq=1, size=8,
        workdir=args.workdir, seed=0, method="SimCLR", trial=args.trial,
        resume=args.resume, metrics_port=args.metrics_port,
        watchdog_secs=args.watchdog_secs, health_freq=args.health_freq,
        health_policy=args.health_policy,
    )
    cfg = config_lib.finalize_supcon(cfg)
    print(f"SAVE_FOLDER {cfg.save_folder}", flush=True)

    def run():
        state = supcon_driver.run(cfg)
        print(f"DONE step={int(state.step)}", flush=True)

    # the REAL typed-exit surface (utils/guard.py): NaN -> 1, collapse -> 3,
    # preempt -> 75 — what the supervisor classifies
    guard.exit_with_code(run)


if __name__ == "__main__":
    main()
