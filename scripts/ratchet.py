#!/usr/bin/env python
"""Automated accuracy ratchet (RESULTS.md experiment 3 protocol).

Round-2 verdict weak #7: the ratchet was a manual protocol. This script IS the
protocol: pretrain SimCLR on ``synthetic_hard32`` (the 32-class oriented-plaid
benchmark whose raw-pixel probe sits at 6%), linear-probe the frozen encoder,
and compare against the pre-registered bar of **95.7%** top-1 at 100 epochs
(RESULTS.md: round-3 two-seed floor 96.09%/96.54% under the torch-aligned
architecture, minus the protocol's ~0.4-pt seed margin). Prints one JSON
line and exits nonzero when the bar fails, so a chip-attached CI can gate on
it. Runs on whatever accelerator JAX sees (~25 min on one v5e; on CPU it would
take hours — don't).

Usage:
    python scripts/ratchet.py [--epochs 100] [--bar 95.7] [--trial NAME]
"""

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(cmd, log_path):
    with open(log_path, "w") as f:
        proc = subprocess.run(cmd, cwd=REPO, stdout=f, stderr=subprocess.STDOUT)
    if proc.returncode != 0:
        sys.exit(f"FAILED ({proc.returncode}): {' '.join(cmd)}; see {log_path}")


def best_acc(log_path):
    """Last 'best accuracy: X' line of the probe driver's log."""
    best = None
    with open(log_path) as f:
        for line in f:
            m = re.search(r"best accuracy: ([0-9.]+)", line)
            if m:
                best = float(m.group(1))
    if best is None:
        sys.exit(f"no 'best accuracy' line in {log_path}")
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=100)
    ap.add_argument("--bar", type=float, default=95.7)
    ap.add_argument("--trial", default="ratchet")
    ap.add_argument("--workdir", default=os.path.join(REPO, "work_space"))
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    logs = os.path.join(args.workdir, f"ratchet_{args.trial}")
    os.makedirs(logs, exist_ok=True)

    pre_log = os.path.join(logs, "pretrain.log")
    run(
        [sys.executable, "main_supcon.py", "--dataset", "synthetic_hard32",
         "--epochs", str(args.epochs), "--batch_size", "256",
         "--learning_rate", "0.1", "--warm", "--temp", "0.5", "--cosine",
         "--method", "SimCLR", "--bf16", "--save_freq", str(args.epochs),
         "--print_freq", "20", "--workdir", args.workdir,
         "--seed", str(args.seed), "--trial", args.trial],
        pre_log,
    )
    # run folder = newest matching dir the pretrain just wrote
    models = os.path.join(args.workdir, "synthetic_hard32_models")
    # exact trial suffix only — a substring match would let --trial x pick up
    # a newer run from --trial x2; finalize_supcon appends _cosine/_warm
    # markers after the trial, so match the canonical suffix of this recipe
    runs = [
        os.path.join(models, d) for d in os.listdir(models)
        if d.endswith(f"trial_{args.trial}_cosine_warm")
    ]
    if not runs:
        sys.exit(f"no run dir matching trial_{args.trial}_cosine_warm in {models}")
    run_dir = max(runs, key=os.path.getmtime)

    probe_log = os.path.join(logs, "probe.log")
    run(
        [sys.executable, "main_linear.py", "--dataset", "synthetic_hard32",
         "--epochs", "60", "--learning_rate", "5", "--batch_size", "256",
         "--ckpt", os.path.join(run_dir, "last"), "--workdir", args.workdir,
         "--trial", args.trial],
        probe_log,
    )
    acc = best_acc(probe_log)
    ok = acc >= args.bar
    print(json.dumps({
        "metric": "ratchet_synthetic_hard32_probe_top1",
        "value": acc, "bar": args.bar, "epochs": args.epochs,
        "seed": args.seed, "ok": ok,
        "pretrain_log": pre_log, "probe_log": probe_log,
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
