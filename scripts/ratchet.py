#!/usr/bin/env python
"""Automated accuracy ratchet (RESULTS.md experiment 3 protocol).

Round-2 verdict weak #7: the ratchet was a manual protocol. Round-3 made this
script the protocol for ONE config; round-4 widened it (verdict r3 weak #6) so
a regression in the BasicBlock path (rn18) or the long-trajectory path
(200 epochs) can no longer pass the gate unnoticed; round-5 adds the SupCon
method (the distributed-SupCon fix is this repo's marquee divergence from the
reference, which crashes there) and the CE trainer (component #14) — round-4
verdict weak #3.

Contrastive configs pretrain on ``synthetic_hard32`` (the 32-class
oriented-plaid benchmark whose raw-pixel probe sits at 6%), linear-probe the
frozen encoder, and compare top-1 against a pre-registered bar; the CE config
runs the supervised trainer end-to-end on ``synthetic_hard``. Bars:

- ``rn50_100ep``: bar **95.7** (round-3 two-seed floor 96.09/96.54 minus the
  protocol's ~0.4-pt seed margin);
- ``rn18_100ep``: bar **95.4** (round-4 two-seed measurements 96.43 (seed 0)
  / 97.82 (seed 1) — `work_space/ratchet_r4{cal,seed1}_rn18_100ep/` — the
  bar is the floor minus a 1-pt margin);
- ``rn50_200ep``: bar **98.8** (round-3 measured 99.27 at 200 epochs minus a
  0.5-pt margin; round-5 two-seed floor 99.22/99.55 keeps it 0.42 pts clear);
- ``supcon_rn50_50ep``: bar **90.0** (round-5 calibration measured 92.52 on
  the chip; see CONFIGS note);
- ``ce_rn50_30ep``: bar **98.2** (measured 99.72 round-3 and 99.00 round-5;
  floor minus 0.8).

Round-5 verdict #6 adds the PERF bar: the ``bench_pretrain`` config runs
``bench.py`` and fails below ``bench.RATCHET_BENCH_FRACTION`` (95%) of the
recorded repo baseline (``bench.REPO_BASELINES['pretrain']`` = the round-5
4,066.5 imgs/s/chip headline) — a throughput regression now fails the gate
exactly like an accuracy regression.

Prints one JSON line per config and a final summary line; exits nonzero when
any bar fails, so a chip-attached CI can gate on it. Runs on whatever
accelerator JAX sees (rn50@100ep ~25 min on one v5e; the full gate ~1.5 h;
on CPU it would take many hours — don't).

Usage:
    python scripts/ratchet.py                      # all gated configs
    python scripts/ratchet.py --configs rn50_100ep # subset
    python scripts/ratchet.py --configs rn50_100ep --bar 95.7  # override bar
"""

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
SCRIPTS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, SCRIPTS)  # perf_ledger (scripts/ is not a package)


def _bench_bar():
    """95% of the recorded repo baseline (bench.REPO_BASELINES). Imported
    lazily: bench imports jax, and this parent process must never touch the
    accelerator the driver subprocesses need."""
    import bench

    return round(
        bench.RATCHET_BENCH_FRACTION * bench.REPO_BASELINES["pretrain"], 1
    )

# kind 'simclr'/'supcon': pretrain (that method) + linear probe, top-1 vs bar.
# kind 'ce': the supervised CE trainer end-to-end (component #14), val top-1.
# Bars are pre-registered: measured-once minus a seed margin (see each note).
CONFIGS = {
    "rn50_100ep": dict(model="resnet50", epochs=100, bar=95.7, kind="simclr",
                       dataset="synthetic_hard32"),
    "rn18_100ep": dict(model="resnet18", epochs=100, bar=95.4, kind="simclr",
                       dataset="synthetic_hard32"),
    "rn50_200ep": dict(model="resnet50", epochs=200, bar=98.8, kind="simclr",
                       dataset="synthetic_hard32"),
    # round-4 verdict weak #3: the repo's marquee fix (distributed SupCon,
    # which the reference crashes on) and the rebuilt CE trainer rested on
    # single historical runs — now gated. SupCon bar: round-5 calibration
    # measured 92.52 top-1 (50 ep, seed 0, chip;
    # docs/evidence/ratchet_r5_supcon_cal.json) minus a 2.5-pt margin.
    # NOTE: this config is seed-METASTABLE (seeds 1/2 escape the collapse
    # plateau later and land at 48/71 — RESULTS.md round-5 seed-sensitivity
    # note); the gate is valid ONLY at the pinned seed 0, where the pipeline
    # reproduces 92.52 bit-for-bit. Do not swap seeds without recalibrating.
    "supcon_rn50_50ep": dict(model="resnet50", epochs=50, bar=90.0,
                             kind="supcon", dataset="synthetic_hard32"),
    # CE bar: two measurements exist — 99.72 (round 3,
    # docs/evidence/ce_30ep.log) and 99.00 (round-5 validation run,
    # docs/evidence/ratchet_r5_ce_cal.json) — bar = the 99.00 floor minus a
    # 0.8-pt margin. Seed-pinned like the SupCon config: at seed 1 this
    # config never leaves the uniform-logit plateau (10.6 = chance; lr 0.05
    # rescues it to 98.94 — RESULTS.md round-5 seed-sensitivity note), so do
    # not swap seeds without recalibrating.
    "ce_rn50_30ep": dict(model="resnet50", epochs=30, bar=98.2, kind="ce",
                         dataset="synthetic_hard"),
    # round-5 verdict #6: the throughput headline is now a gated bar too.
    # bar=None -> resolved to bench.RATCHET_BENCH_FRACTION (95%) of
    # bench.REPO_BASELINES['pretrain'] at run time (_bench_bar); minutes,
    # not hours, so it rides the default config list.
    "bench_pretrain": dict(model="resnet50", epochs=0, bar=None, kind="bench",
                           dataset="recipe", stage="pretrain"),
    # round 7: the data-placement equivalence check (scripts/resident_ab.py
    # --smoke). The gate binds on equivalence_ok — device placement must
    # yield byte-identical batches to the host loader, on ANY accelerator
    # (bit-identity is not chip-specific). The proxy's TIMING claim
    # (device arm faster under the injected serialized-link delay) is
    # enforced only where the proxy is calibrated (CPU); elsewhere it
    # pass-skips with the reason on record, like the bench gate's
    # device-kind gating. Seconds, so it rides the default list.
    "resident_ab": dict(model="resnet10", epochs=0, bar=None,
                        kind="resident_ab", dataset="synthetic"),
    # round 8: the WINDOWED placement equivalence check
    # (scripts/window_ab.py --smoke) — same convention as resident_ab:
    # bit-identity binds on every device, the CPU-calibrated injected-delay
    # timing claim pass-skips off-CPU with the reason on record. Seconds,
    # so it rides the default list.
    "window_ab": dict(model="resnet10", epochs=0, bar=None,
                      kind="window_ab", dataset="synthetic"),
    # round 9: the flight-recorder smoke (docs/OBSERVABILITY.md) — one tiny
    # trainer epoch with the recorder on, then scripts/trace_report.py over
    # its events.jsonl. The gate binds on the attribution's internal
    # consistency (trace_report_gate_record): main-thread phase spans
    # non-overlapping and the table summing to the measured wall time —
    # i.e. the recorder's track contract held through a REAL driver run on
    # whatever device the gate runs on. Minutes, so it rides the default
    # list.
    "trace_report": dict(model="resnet10", epochs=1, bar=None,
                         kind="trace_report", dataset="synthetic"),
    # round 10: the training-health smoke (docs/OBSERVABILITY.md "Training
    # health") — one tiny pretrain epoch with the on-device diagnostics +
    # online probe on, then scripts/health_report.py over its events.jsonl.
    # The gate binds everywhere on the stream's internal consistency (every
    # window carries the full health column set, steps monotone — i.e. the
    # in-step diagnostics really reached the recorder through the ring) and
    # on ZERO detector alarms (the healthy smoke must not trip the collapse
    # detector — a false positive here would abort real runs under
    # --health_policy abort). The online-probe accuracy claim is calibrated
    # on CPU (HEALTH_PROBE_CPU_BAR) and pass-skips elsewhere, the
    # bench-gate convention. Seconds-to-minutes, so it rides the default
    # list.
    "health_report": dict(model="resnet10", epochs=1, bar=None,
                          kind="health_report", dataset="synthetic"),
    # round 11: the supervisor scenario-matrix gate. Unlike the driver-run
    # gates above it binds on the COMMITTED evidence artifact
    # (docs/evidence/supervisor_r11.json, produced by
    # scripts/supervisor_matrix.py driving the REAL supervisor through
    # SIGKILL / stall / collapse / preempt-then-resize against the real
    # pretrain loop): the pure supervisor_gate_record re-verifies that all
    # four scenarios are present, each ended in its expected decision
    # sequence, and the resize leg really resumed onto a different
    # topology. Re-produce the artifact with the matrix script when the
    # supervisor's decision surface changes; instant, so it rides the
    # default list.
    "supervisor_gate": dict(model=None, epochs=0, bar=None,
                            kind="supervisor_gate", dataset=None,
                            artifact="docs/evidence/supervisor_r11.json"),
    # round 12: the SSL-recipe gate (scripts/recipes_eval.py --smoke; the
    # recipes/ subsystem). Binds EVERYWHERE on the supcon-refactor
    # BIT-IDENTITY (recipe interface vs the pre-refactor inline update,
    # host and device placement — hardware-independent, the resident_ab
    # convention) and on zero collapse alarms per recipe; the per-recipe
    # online-probe learning bars (RECIPE_PROBE_CPU_BARS) are CPU-calibrated
    # and pass-skip elsewhere with the reason on record. Minutes, so it
    # rides the default list.
    "recipes": dict(model="resnet10", epochs=1, bar=None, kind="recipes",
                    dataset="synthetic"),
    # round 13: the fleet-merge gate. Binds on the COMMITTED evidence
    # artifact (docs/evidence/fleet_report_r13.json, produced by
    # scripts/trace_report.py --fleet over a REAL 2-process gloo run —
    # tests/multiprocess_child.py driver mode): the pure fleet_gate_record
    # re-verifies merge consistency everywhere, hardware-independently
    # (the trace_report convention) — a multi-process session whose
    # per-process timelines anchored to sub-tolerance residual, whole
    # collective boundaries, per-process attribution intact, and a
    # non-empty skew table. Re-produce the artifact with a 2-process run
    # when the anchor/collective instrumentation changes; instant, so it
    # rides the default list.
    "fleet_report": dict(model=None, epochs=0, bar=None, kind="fleet_report",
                         dataset=None,
                         artifact="docs/evidence/fleet_report_r13.json"),
    # round 13: the longitudinal perf-ledger gate. Runs the pure
    # regression scan (scripts/perf_ledger.py detect_regression) over the
    # COMMITTED docs/perf_ledger.jsonl: schema validity binds everywhere;
    # the regression bar binds only within same-fingerprint groups (stage
    # + config + device kind + chips), clock-suspect runs excluded on both
    # sides, and groups without a sufficient clean trailing window
    # pass-skip with the reason on record (the bench gate's device-kind
    # convention, applied to history). Instant, so it rides the default
    # list.
    "perf_ledger": dict(model=None, epochs=0, bar=None, kind="perf_ledger",
                        dataset=None, artifact="docs/perf_ledger.jsonl"),
    # round 15: the fused conv-block gate (scripts/convblock_ab.py --smoke;
    # ops/pallas_conv.py). Binds EVERYWHERE on parity_ok — the interpret-
    # mode fused residual-block kernel matching the bitwise-pinned Flax
    # block in value, all seven gradients, and BN batch stats (parity is
    # hardware-independent; it is the contract that lets --conv_impl swap
    # without touching the accuracy ratchets). The timing claim (the
    # pallas arm removing the injected per-HBM-traversal delay) is a
    # CPU-calibrated proxy and pass-skips off-CPU with the reason on
    # record (the resident_ab/window_ab convention). Seconds, so it rides
    # the default list.
    "convblock": dict(model=None, epochs=0, bar=None, kind="convblock_ab",
                      dataset="synthetic"),
    # round 14: the static invariant-lint gate (docs/ANALYSIS.md). Runs
    # scripts/invariant_lint.py over the tree — stdlib ast, no driver, no
    # device — and binds on the pure lint_gate_record EVERYWHERE: zero
    # unallowlisted findings against the four distributed contracts
    # (collective-schedule, donation-safety, hot-loop-sync,
    # contract-registry), every allowlist entry carrying a reason, all
    # four rule families actually run. The contracts are properties of the
    # SOURCE, so unlike the timing gates there is no device-kind skip path
    # — a regression fails the gate on every device. Milliseconds, so it
    # rides the default list.
    "invariant_lint": dict(model=None, epochs=0, bar=None,
                           kind="invariant_lint", dataset=None),
    # round 16: the straggler-mitigation / composed-chaos gate. Binds on
    # the COMMITTED evidence artifact (docs/evidence/chaos_matrix_r16.json,
    # produced by scripts/supervisor_matrix.py --scenarios straggler chaos):
    # the straggler leg drove a REAL 2-process gloo fleet
    # (scripts/fleet_launcher.py) from injected 150 ms boundary skew
    # through the K-of-N persistence verdict to an actuated mitigation —
    # graceful preempt, restart_rebalanced carrying the share hint into
    # the relaunched fleet, final parameter digests bit-identical to a
    # policy-off control; the chaos leg landed straggler + SIGKILL +
    # injected health collapse green in one supervised lifetime. The pure
    # chaos_gate_record re-verifies all of it; re-produce the artifact
    # with the matrix script when the mitigation surface changes.
    # Instant, so it rides the default list.
    "chaos_matrix": dict(model=None, epochs=0, bar=None, kind="chaos_gate",
                         dataset=None,
                         artifact="docs/evidence/chaos_matrix_r16.json"),
    # round 17: the serve-fleet gate. Binds on the COMMITTED evidence
    # artifact (docs/evidence/serve_fleet_r17.json, produced by
    # scripts/serve_fleet_scenario.py driving a REAL supervised replica
    # fleet — two `python -m ...serve.fleet` subprocesses under
    # supervise/replica_fleet.py): the pure serve_fleet_gate_record
    # re-verifies that the supervisor raised the fleet to its floor off
    # scraped /metrics, a SIGKILLed replica was restarted on the SAME
    # port within the budget and served again, a /models/promote hot-swap
    # landed under live /embed load with ZERO failed requests (old
    # version retired, new serving), and /neighbors answered a served
    # image with itself at cosine ~1.0. Re-produce the artifact with the
    # scenario script when the fleet/registry surface changes; instant,
    # so it rides the default list.
    "serve_fleet": dict(model=None, epochs=0, bar=None,
                        kind="serve_fleet_gate", dataset=None,
                        artifact="docs/evidence/serve_fleet_r17.json"),
    # round 18: the retrieval-ladder gate. Binds on the COMMITTED brute-
    # vs-IVF evidence artifact (docs/evidence/retrieval_ab_r18.json,
    # produced by scripts/retrieval_ab.py sweeping 4k/64k/256k-row
    # corpora): the pure retrieval_gate_record re-verifies EVERYWHERE
    # that the brute rung answered bit-identically to the frozen PR-17
    # scoring oracle (ids exact, float32 scores bitwise — the "brute
    # path retained bit-for-bit" contract under --retrieval_impl) and
    # that IVF recall@k cleared the artifact's recall bar on every rung
    # (both are properties of the recorded answers, not the hardware).
    # The >=5x p50 query-speedup claim at the top rung is CPU-calibrated
    # and pass-skips off-CPU with the reason on record (the convblock
    # convention). Re-produce the artifact with the A/B script when the
    # retrieval surface changes; instant, so it rides the default list.
    "retrieval_ab": dict(model=None, epochs=0, bar=None,
                         kind="retrieval_gate", dataset=None,
                         artifact="docs/evidence/retrieval_ab_r18.json"),
}

# CPU-calibrated bar for the health_report smoke's online probe: best
# window top-1 after one epoch of the gate's `synthetic` color-mean config
# (chance 10%; calibration runs measured best-window 35.5 at 1 epoch and
# 48.6 at 2 — the round-10 evidence runs). Generous margin — the claim is
# "the probe LEARNS, live, from inside the compiled update", not a precise
# accuracy.
HEALTH_PROBE_CPU_BAR = 20.0

# CPU-calibrated online-probe bars for the recipes_eval smoke (chance 10%
# on the 10-class synthetic color-mean set; one 28-step epoch at size 8,
# seed 0). Calibration measured best-window top-1 of 46.8 (supcon), 46.9
# (byol), 46.9 (simsiam), 47.1 (vicreg), 46.9 (simclr_queue) — the
# round-12 smoke protocol; the committed full-config artifact
# (docs/evidence/recipes_r12.json) sits at 45.4-50.6. Bars = beat-random
# with a wide margin (the HEALTH_PROBE_CPU_BAR convention): the claim is
# "every recipe LEARNS, live, through the same substrate", not a precise
# accuracy.
RECIPE_PROBE_CPU_BARS = {
    "supcon": 20.0,
    "byol": 20.0,
    "simsiam": 20.0,
    "vicreg": 20.0,
    "simclr_queue": 20.0,
}


def bench_metric_name(spec):
    """One stable series name for the bench gate across BOTH the success
    and the ConfigFailed record (the probe/ce configs have this property;
    a dashboard keyed on the success name must see the failure too)."""
    return f"ratchet_bench_{spec['stage']}_imgs_per_sec_per_chip"


def bench_gate_record(spec, rec, bar):
    """Gate decision for one bench record (pure — tested without a chip).

    The committed bar is a CHIP-SPECIFIC number: on any other accelerator
    (dev box CPU, a different TPU generation) the comparison is meaningless
    in both directions, so the gate neither fails nor certifies — it passes
    with the reason on record (re-record the baseline to ratchet a new
    chip). On the baseline chip, a ``clock_suspect`` run fails outright: a
    clock glitch INFLATES throughput (bench.py discards glitched windows but
    flags the run), so a suspect number must not be able to mask a real
    regression — the one record the gate exists to catch.
    """
    import bench  # jax import only; the parent never touches devices

    value = float(rec["value"])
    detail = rec.get("detail", {})
    device_kind = detail.get("device_kind")
    chips = detail.get("chips")
    clock_suspect = detail.get("clock_suspect")
    record = {
        "metric": bench_metric_name(spec),
        "value": value, "bar": bar,
        "vs_baseline": rec.get("vs_baseline"),
        "device_kind": device_kind,
        "chips": chips,
        "clock_suspect": clock_suspect,
    }
    if device_kind != bench.REPO_BASELINE_DEVICE_KIND:
        record["ok"] = True
        record["skipped"] = (
            f"device_kind {device_kind!r} != baseline "
            f"{bench.REPO_BASELINE_DEVICE_KIND!r}: bar not comparable"
        )
    elif chips != 1:
        # the baseline is a 1-chip number (256 imgs/chip): the same global
        # batch sharded over n chips is 256/n imgs/chip — a different
        # per-chip workload that sits below the bar with no real regression
        record["ok"] = True
        record["skipped"] = (
            f"chips={chips!r}: baseline recorded on 1 chip at the recipe "
            f"per-chip batch; sharded workload not comparable"
        )
    else:
        record["ok"] = bool(value >= bar and not clock_suspect)
        if clock_suspect:
            record["error"] = "clock_suspect: bench timing not credible"
    return record


def _placement_gate_record(artifact, arm, value_key, extra_keys=()):
    """Shared gate decision for the placement-equivalence A/Bs (pure —
    tested through the two public wrappers).

    ``equivalence_ok`` (byte-identical batches, host vs the ``arm``
    placement) binds EVERYWHERE — bit-identity is hardware-independent and
    is the contract that lets accuracy ratchets carry across placements.
    The timing claim (the ``arm`` removing/amortizing the injected
    per-step delay) binds only on CPU, where the serialized-link proxy is
    calibrated; elsewhere the gate pass-skips the timing with the reason
    on record (the bench gate's device-kind convention).
    """
    s = artifact["summary"]
    eq = artifact["equivalence"]
    record = {
        "metric": f"ratchet_{arm}_ab_equivalence",
        "value": s[value_key],
        "host_ms_per_step": s["host_ms_per_step"],
        **{k: artifact[k] for k in extra_keys},
        "equivalence_ok": eq["equivalence_ok"],
        "steps_compared": eq["steps_compared"],
        "device": artifact["device"],
    }
    if not eq["equivalence_ok"]:
        record["ok"] = False
        record["error"] = f"{arm} placement batches differ from host loader"
        return record
    if artifact["device"] != "cpu":
        record["ok"] = True
        record["skipped"] = (
            f"device {artifact['device']!r}: injected-delay timing proxy "
            f"calibrated for CPU only; equivalence still enforced"
        )
        return record
    record["ok"] = bool(s[value_key] < s["host_ms_per_step"])
    if not record["ok"]:
        record["error"] = f"{arm} arm not faster under injected H2D delay"
    return record


def resident_gate_record(artifact):
    """Gate decision for one resident_ab artifact (the device arm at/near
    the no-transfer floor; see _placement_gate_record)."""
    return _placement_gate_record(artifact, "resident", "device_ms_per_step")


def window_gate_record(artifact):
    """Gate decision for one window_ab artifact (the window arm amortizing
    the injected per-step delay to one per window, incl. the mid-epoch
    window+slice-offset resume check; see _placement_gate_record)."""
    return _placement_gate_record(
        artifact, "window", "window_ms_per_step",
        extra_keys=("window_batches",),
    )


def convblock_gate_record(artifact):
    """Gate decision for one convblock_ab artifact (pure — tested without
    a kernel run).

    Since round 19 the artifact (schema convblock_ab/v2) carries one
    section per admitted block kind x compute dtype (basic, proj,
    bottleneck, each fp32 and bf16). ``parity_ok`` (interpret-mode fused
    kernel == Flax block: value, ALL gradients, every BN stat pair within
    that kind's pinned tolerances — fp32 abs, bf16 the derived
    scaled-maxabs + cosine pins) binds PER KIND on EVERY device — kernel
    correctness is hardware-independent. The timing claim (the pallas arm
    beating the xla arm under the injected bytes-scaled per-HBM-traversal
    delay) binds per kind only on CPU, where the proxy is calibrated;
    elsewhere the gate pass-skips the timing with the reason on record
    (the placement A/Bs' convention). One broken kind fails the whole
    gate — the conv_impl resolution banner admits sites kind-by-kind, so
    every kind a real run could route through must hold.
    """
    record = {
        "metric": "ratchet_convblock_ab_parity",
        # value = kinds gated (main's summary table requires the key on
        # every record; a per-kind gate has no single ms number to report)
        "value": len(artifact["blocks"]),
        "parity_ok": artifact["parity_ok"],
        "device": artifact["device"],
        "kinds": {},
    }
    failures = []
    timing_bound = artifact["device"] == "cpu"
    for kind, b in sorted(artifact["blocks"].items()):
        s = b["summary"]
        parity = b["parity"]
        entry = {
            "parity_ok": parity["parity_ok"],
            "pallas_ms_per_step": s.get("pallas_ms_per_step"),
            "xla_ms_per_step": s.get("xla_ms_per_step"),
            "traversals": b.get("traversals", {}),
            "max_abs_diffs": parity["max_abs_diffs"],
        }
        record["kinds"][kind] = entry
        if not parity["parity_ok"]:
            failures.append(
                f"{kind}: fused kernel diverges from the Flax block "
                f"(value_ok={parity['value_ok']} "
                f"grads_ok={parity['grads_ok']} "
                f"stats_ok={parity['stats_ok']})"
            )
            continue
        if timing_bound and not (
            s["pallas_ms_per_step"] is not None
            and s["xla_ms_per_step"] is not None
            and s["pallas_ms_per_step"] < s["xla_ms_per_step"]
        ):
            failures.append(
                f"{kind}: pallas arm not faster under the injected "
                f"per-traversal delay"
            )
    record["ok"] = not failures
    if failures:
        record["error"] = "; ".join(failures)
    elif not timing_bound:
        record["skipped"] = (
            f"device {artifact['device']!r}: injected-delay timing proxy "
            f"calibrated for CPU only; per-kind kernel parity still "
            f"enforced"
        )
    return record


def trace_report_gate_record(artifact):
    """Gate decision for one trace_report artifact (pure — tested without
    a driver run).

    Binds on ``consistency.ok``: the attribution table sums to the measured
    wall time with every phase non-negative and the main-thread phase spans
    non-overlapping — the invariant that makes the table trustworthy. This
    is hardware-independent (it is a property of the recorder's track
    contract, not of any timing number), so unlike the bench bar it binds
    on EVERY device. Phase presence is also checked: a driver run that
    recorded no flush boundaries means the recorder was silently dead."""
    rep = artifact["report"]
    cons = rep["consistency"]
    record = {
        "metric": "ratchet_trace_report_attribution",
        "value": cons["attributed_s"],
        "wall_s": cons["wall_s"],
        "steady_state_s": cons["steady_state_s"],
        "phases": sorted(rep["phases"]),
        "anomalies": rep["anomalies"],
        "n_events": rep["n_events"],
    }
    if not cons["ok"]:
        record["ok"] = False
        record["error"] = (
            "attribution inconsistent: overlapping main-thread phase spans "
            "or oversubscribed wall time"
        )
        return record
    if "flush" not in rep["phases"]:
        record["ok"] = False
        record["error"] = (
            "no flush-boundary spans recorded: the recorder was not live "
            "through the driver's epoch loop"
        )
        return record
    record["ok"] = True
    return record


def health_report_gate_record(artifact, probe_bar=None):
    """Gate decision for one health_report artifact (pure — tested without
    a driver run).

    Binds on EVERY device (the trace_report convention): the health stream's
    internal consistency — non-empty, monotone, full column set per window —
    is a property of the ring->recorder contract, not of any timing or
    accuracy number; and zero ``health_alarm`` events, because the collapse
    detector firing on a known-healthy smoke is exactly the false positive
    that would abort real runs under ``--health_policy abort``. The
    online-probe learning claim (best window top-1 over ``probe_bar``) is
    calibrated on the CPU smoke; on any other device it pass-skips with the
    reason on record (the bench gate's device-kind convention) while the
    consistency and zero-alarm bits still bind.
    """
    if probe_bar is None:
        probe_bar = HEALTH_PROBE_CPU_BAR
    rep = artifact["report"]
    cons = rep["consistency"]
    probe = rep.get("probe") or {}
    record = {
        "metric": "ratchet_health_report",
        "value": probe.get("best_top1"),
        "bar": probe_bar,
        "n_windows": cons["n_windows"],
        "alarms": len(rep["alarms"]),
        "findings": [f["flag"] for f in rep["findings"]],
        "device": artifact.get("device"),
    }
    if not cons["ok"]:
        record["ok"] = False
        record["error"] = (
            "health stream inconsistent: empty/non-monotone timeline or "
            f"missing columns {cons['missing_keys']}"
        )
        return record
    if rep["alarms"]:
        record["ok"] = False
        record["error"] = (
            f"collapse detector fired {len(rep['alarms'])}x on the healthy "
            "smoke (false positive)"
        )
        return record
    if not probe:
        record["ok"] = False
        record["error"] = "no online-probe columns in the health stream"
        return record
    if artifact.get("device") != "cpu":
        record["ok"] = True
        record["skipped"] = (
            f"device {artifact.get('device')!r}: probe-accuracy bar "
            "calibrated for the CPU smoke only; stream consistency and "
            "zero-alarm checks still enforced"
        )
        return record
    record["ok"] = bool(probe["best_top1"] >= probe_bar)
    if not record["ok"]:
        record["error"] = (
            f"online probe best top-1 {probe['best_top1']:.2f} < "
            f"{probe_bar:g}: the live probe did not learn"
        )
    return record


def recipe_gate_record(artifact, bars=None):
    """Gate decision for one recipes_eval artifact (pure — tested without a
    driver run).

    Binds on EVERY device: the supcon-refactor BIT-IDENTITY (the recipe
    interface must be numerically invisible — the contract that carries
    every committed accuracy ratchet across the refactor) under both host
    and device placement, a consistent health stream per recipe, and ZERO
    collapse alarms (an alarm on a healthy tiny run is the false positive
    that would abort real runs under --health_policy abort). The
    per-recipe online-probe learning bars bind on CPU only (where
    :data:`RECIPE_PROBE_CPU_BARS` was calibrated); elsewhere they
    pass-skip with the reason on record — the bench-gate convention.
    """
    if bars is None:
        bars = RECIPE_PROBE_CPU_BARS
    bit = artifact.get("bit_identity", {})
    recipes = artifact.get("recipes", {})
    record = {
        "metric": "ratchet_recipes",
        "value": {
            name: (rec or {}).get("probe_best_top1")
            for name, rec in recipes.items()
        },
        "bars": bars,
        "bit_identity": bit.get("placements"),
        "alarms": {n: (r or {}).get("alarms") for n, r in recipes.items()},
        "device": artifact.get("device"),
    }

    def fail(msg):
        record["ok"] = False
        record["error"] = msg
        return record

    if not bit.get("ok") or set(bit.get("placements", {})) != {"host",
                                                               "device"}:
        return fail(
            "supcon-refactor bit-identity failed or incomplete: "
            f"{bit.get('placements')}"
        )
    missing = sorted(set(bars) - set(recipes))
    if missing:
        return fail(f"recipe arms missing from the artifact: {missing}")
    for name in sorted(bars):
        rec = recipes[name] or {}
        if not rec.get("consistency_ok"):
            return fail(f"recipe {name!r}: inconsistent health stream")
        if rec.get("alarms"):
            return fail(
                f"recipe {name!r}: collapse detector fired "
                f"{rec['alarms']}x on the healthy run (false positive)"
            )
        if rec.get("probe_best_top1") is None:
            return fail(f"recipe {name!r}: no online-probe columns")
    if artifact.get("device") != "cpu":
        record["ok"] = True
        record["skipped"] = (
            f"device {artifact.get('device')!r}: probe bars calibrated "
            "for the CPU smoke only; bit-identity and zero-alarm checks "
            "still enforced"
        )
        return record
    for name, bar in sorted(bars.items()):
        best = recipes[name]["probe_best_top1"]
        if best < bar:
            return fail(
                f"recipe {name!r}: online probe best top-1 {best:.2f} < "
                f"{bar:g} — the recipe did not learn through the substrate"
            )
    record["ok"] = True
    return record


# the four failure shapes the supervisor matrix must prove, with the
# decision sequence each one must have produced (scripts/supervisor_matrix.py
# scenario expectations, re-checked here so a hand-edited artifact cannot
# pass) — docs/RESILIENCE.md supervisor section
SUPERVISOR_SCENARIOS = {
    "sigkill": ["backoff_restart", "done"],
    "stall": ["backoff_restart", "done"],
    "collapse": ["give_up"],
    "preempt_resize": ["restart_resized", "done"],
}


def supervisor_gate_record(artifact):
    """Gate decision for the supervisor scenario-matrix evidence (pure —
    tested without running a scenario).

    Binds everywhere, hardware-independently (the trace_report convention):
    the claims are about decision sequences and recorded events, not
    timings. Checks: every scenario of :data:`SUPERVISOR_SCENARIOS` is
    present and ``ok`` with exactly its expected decision sequence; the
    collapse leg exited with the typed health code 3 after an observed
    ``health_alarm``; the stall leg saw both liveness verdicts (the
    supervisor's own and the in-child watchdog's dump); and the resize leg
    actually resumed onto a different topology (``resumed_resized`` —
    the mesh-shape-agnostic restore proven end to end).
    """
    scenarios = artifact.get("scenarios", {})
    record = {
        "metric": "ratchet_supervisor_matrix",
        "value": len(scenarios),
        "scenarios": sorted(scenarios),
    }

    def fail(msg):
        record["ok"] = False
        record["error"] = msg
        return record

    for name, expected in SUPERVISOR_SCENARIOS.items():
        rec = scenarios.get(name)
        if rec is None:
            return fail(f"scenario {name!r} missing from the matrix artifact")
        if not rec.get("ok"):
            return fail(f"scenario {name!r} not ok in the matrix artifact")
        if rec.get("decisions") != expected:
            return fail(
                f"scenario {name!r} decisions {rec.get('decisions')} != "
                f"expected {expected}"
            )
    if scenarios["collapse"].get("rc") != 3:
        return fail("collapse scenario did not exit with the typed health code 3")
    if not scenarios["collapse"].get("health_alarms_observed"):
        return fail("collapse scenario recorded no observed health_alarm")
    if not (scenarios["stall"].get("liveness_stalls")
            and scenarios["stall"].get("watchdog_dumps_observed")):
        return fail("stall scenario lacks liveness/watchdog evidence")
    resize = scenarios["preempt_resize"]
    if not resize.get("resumed_resized"):
        return fail("resize scenario did not resume onto a new topology")
    devices = resize.get("launch_devices") or []
    if len(set(d for d in devices if d)) < 2:
        return fail(f"resize scenario launch_devices {devices} never changed")
    record["ok"] = True
    return record


# the straggler-mitigation scenarios the chaos matrix must prove, with the
# decision sequence each must have produced (scripts/supervisor_matrix.py
# CHAOS_NAMES expectations, re-checked here so a hand-edited artifact
# cannot pass) — docs/RESILIENCE.md straggler section
CHAOS_SCENARIOS = {
    "straggler": ["restart_rebalanced", "done"],
    "chaos": ["restart_rebalanced", "backoff_restart", "done"],
}


def chaos_gate_record(artifact):
    """Gate decision for the straggler-mitigation / composed-chaos evidence
    (pure — tested without running a fleet).

    Binds everywhere, hardware-independently (the supervisor_gate
    convention): the claims are decision sequences, recorded mitigation
    events, and digest equality — not timings. Checks: both scenarios of
    :data:`CHAOS_SCENARIOS` are present and ``ok`` with exactly their
    expected decision sequence and exit 0; the straggler leg recorded
    per-boundary findings, a persistence verdict, BOTH mitigation phases
    (preempt and decided), carried the rebalance share hint into a
    relaunch, and its final parameter digests match the policy-off
    control bit-for-bit; the chaos leg absorbed a real SIGKILL and kept
    health alarms on the record throughout.
    """
    scenarios = artifact.get("scenarios", {})
    record = {
        "metric": "ratchet_chaos_matrix",
        "value": len(scenarios),
        "scenarios": sorted(scenarios),
    }

    def fail(msg):
        record["ok"] = False
        record["error"] = msg
        return record

    if artifact.get("schema") != "chaos_matrix/v1":
        return fail(f"unexpected schema {artifact.get('schema')!r}")
    for name, expected in CHAOS_SCENARIOS.items():
        rec = scenarios.get(name)
        if rec is None:
            return fail(f"scenario {name!r} missing from the chaos artifact")
        if not rec.get("ok"):
            return fail(f"scenario {name!r} not ok in the chaos artifact")
        if rec.get("decisions") != expected:
            return fail(
                f"scenario {name!r} decisions {rec.get('decisions')} != "
                f"expected {expected}"
            )
        if rec.get("rc") != 0:
            return fail(f"scenario {name!r} did not land green (rc "
                        f"{rec.get('rc')})")
        if rec.get("mitigation_events", 0) < 2:
            return fail(f"scenario {name!r} lacks both mitigation phases "
                        "(preempt + decided)")
    strag = scenarios["straggler"]
    if not (strag.get("straggler_findings")
            and strag.get("persistence_verdicts")):
        return fail("straggler scenario lacks finding/persistence evidence")
    hint = strag.get("share_hint_carried")
    if not (hint and hint in (strag.get("launch_shares") or [])):
        return fail("straggler scenario never carried the rebalance share "
                    "hint into a relaunch")
    if not strag.get("bit_identical"):
        return fail(
            f"mitigated digests {strag.get('digests')} != policy-off "
            f"control {strag.get('control_digests')}"
        )
    chaos = scenarios["chaos"]
    if not chaos.get("killed_pid"):
        return fail("chaos scenario recorded no SIGKILLed pid")
    if not chaos.get("health_alarms_observed"):
        return fail("chaos scenario recorded no observed health_alarm")
    record["ok"] = True
    return record


def serve_fleet_gate_record(artifact):
    """Gate decision for the serve-fleet scenario evidence (pure — tested
    without spawning a fleet).

    Binds everywhere, hardware-independently (the supervisor_gate
    convention): the claims are decision records, HTTP outcomes, and a
    cosine identity — not timings. Checks: the supervisor spawned the
    fleet to its 2-replica floor and both replicas answered /embed; a
    SIGKILLed replica produced a ``restart_replica`` decision back onto
    the SAME port (old returncode -9) and served again; the
    /models/promote hot-swap landed under live load with ZERO failed
    requests while the old version retired and version 2 took over; the
    /neighbors top-1 for a served image is the image itself at cosine
    ~1.0; and no replica slot was given up.
    """
    phases = artifact.get("phases", {})
    record = {
        "metric": "ratchet_serve_fleet",
        "value": len(phases),
        "phases": sorted(phases),
    }

    def fail(msg):
        record["ok"] = False
        record["error"] = msg
        return record

    if artifact.get("schema") != "serve_fleet/v1":
        return fail(f"unexpected schema {artifact.get('schema')!r}")
    for name in ("spawn", "restart", "promote", "neighbors"):
        rec = phases.get(name)
        if rec is None:
            return fail(f"phase {name!r} missing from the fleet artifact")
        if not rec.get("ok"):
            return fail(f"phase {name!r} not ok in the fleet artifact")
    spawn = phases["spawn"]
    if len(spawn.get("replicas", {})) < 2:
        return fail("spawn phase never reached the 2-replica floor")
    if len(spawn.get("warm_embed", {})) < 2:
        return fail("spawn phase lacks /embed proof from both replicas")
    restart = phases["restart"]
    restarts = [d for d in restart.get("decisions", [])
                if d.get("action") == "restart_replica"]
    if not restarts:
        return fail("restart phase recorded no restart_replica decision")
    if restarts[0].get("port") != restart.get("port"):
        return fail("restart did not relaunch on the same port")
    if restarts[0].get("old_returncode") != -9:
        return fail(f"restarted replica's returncode "
                    f"{restarts[0].get('old_returncode')} is not SIGKILL")
    if not restart.get("served_after_restart"):
        return fail("restarted replica never served again")
    promote = phases["promote"]
    if promote.get("embed_failures"):
        return fail(f"hot-swap dropped requests: "
                    f"{promote['embed_failures']}")
    if promote.get("embed_ok", 0) < 10:
        return fail("promote phase had no meaningful live load")
    if not promote.get("drained"):
        return fail("old version never drained to 'retired'")
    if promote.get("response", {}).get("version") != 2:
        return fail("promote did not install version 2")
    neighbors = phases["neighbors"]
    if not neighbors.get("self_top1"):
        return fail("served image is not its own /neighbors top-1")
    if neighbors.get("top1_score", 0.0) < 0.999:
        return fail(f"self-neighbor cosine {neighbors.get('top1_score')} "
                    "below identity")
    if artifact.get("gave_up"):
        return fail(f"supervisor gave up on replicas {artifact['gave_up']}")
    record["ok"] = True
    return record


def retrieval_gate_record(artifact):
    """Gate decision for the brute-vs-IVF retrieval A/B evidence (pure —
    tested without building an index).

    Two claims bind on EVERY device (they are properties of the recorded
    answers, not timings): the brute rung matched the frozen PR-17
    scoring oracle bit-for-bit (ids exact AND float32 scores bitwise —
    the contract that lets --retrieval_impl brute stay the recall
    oracle), and IVF recall@k cleared the artifact's recall bar on every
    rung. The p50 query-speedup claim at the top rung is CPU-calibrated
    (single-row latency against the jitted brute scorer on host) and
    pass-skips off-CPU with the reason on record (the convblock
    convention)."""
    summary = artifact.get("summary", {})
    oracle = artifact.get("oracle", {})
    record = {
        "metric": "ratchet_retrieval_ab",
        "value": summary.get("speedup_p50_max_rung"),
        "min_recall_at_k": summary.get("min_recall_at_k"),
        "max_rung_rows": summary.get("max_rung_rows"),
        "oracle": oracle,
        "device": artifact.get("device"),
    }

    def fail(msg):
        record["ok"] = False
        record["error"] = msg
        return record

    if artifact.get("schema") != "retrieval_ab/v1":
        return fail(f"unexpected schema {artifact.get('schema')!r}")
    rungs = artifact.get("rungs", [])
    if len(rungs) < 2:
        return fail("fewer than two corpus-size rungs in the artifact")
    if not oracle.get("ids_identical"):
        return fail("brute rung ids diverge from the PR-17 scoring oracle")
    if not oracle.get("scores_bit_identical"):
        return fail("brute rung scores are not bitwise-identical to the "
                    "PR-17 scoring oracle")
    if sorted(oracle.get("rungs_checked", [])) != sorted(
        r["rows"] for r in rungs
    ):
        return fail("oracle bit-identity was not checked on every rung")
    bar = summary.get("recall_bar")
    if not bar:
        return fail("artifact carries no recall bar")
    low = [r["rows"] for r in rungs if r.get("recall_at_k", 0.0) < bar]
    if low:
        return fail(f"IVF recall@k under the {bar} bar at rungs {low}")
    if artifact.get("device") != "cpu":
        record["ok"] = True
        record["skipped"] = (
            f"device {artifact.get('device')!r}: p50 speedup claim "
            "calibrated for CPU only; oracle bit-identity and recall "
            "still enforced"
        )
        return record
    speedup = summary.get("speedup_p50_max_rung")
    speedup_bar = summary.get("speedup_bar", 5.0)
    if speedup is None or speedup < speedup_bar:
        return fail(
            f"IVF p50 speedup {speedup} at the {summary.get('max_rung_rows')}"
            f"-row rung under the {speedup_bar}x bar"
        )
    record["ok"] = True
    return record


def fleet_gate_record(artifact):
    """Gate decision for the fleet-merge evidence artifact (pure — tested
    without running a pod).

    Binds everywhere, hardware-independently (the trace_report
    convention): the claims are properties of the merge, not of timing
    numbers. Checks: every session in the artifact merged consistently
    (anchors fit each non-reference process to sub-tolerance residual,
    collective boundaries whole across processes, per-process attribution
    intact), and at least one session is a REAL multi-process merge with a
    non-empty skew table — a single-process artifact would prove nothing
    about cross-process clock alignment.
    """
    sessions = artifact.get("sessions", {})
    record = {
        "metric": "ratchet_fleet_report",
        "value": len(sessions),
        "sessions": sorted(sessions),
    }

    def fail(msg):
        record["ok"] = False
        record["error"] = msg
        return record

    if artifact.get("schema") != "fleet_report/v1":
        return fail(f"unexpected schema {artifact.get('schema')!r}")
    if not sessions:
        return fail("no merged sessions in the fleet artifact")
    multi = 0
    residuals = []
    for label, rep in sorted(sessions.items()):
        cons = rep.get("consistency", {})
        if not cons.get("ok"):
            return fail(f"session {label}: merge inconsistent ({cons})")
        residuals.append(cons.get("max_residual_s", 0.0))
        if cons.get("n_processes", 0) >= 2:
            multi += 1
    if not multi:
        return fail(
            "no multi-process session: the fleet evidence must come from "
            "a >=2-process run"
        )
    record["multi_process_sessions"] = multi
    record["max_residual_s"] = max(residuals)
    record["stragglers"] = {
        label: (rep["straggler_ranking"][0]["process"]
                if rep.get("straggler_ranking") else None)
        for label, rep in sorted(sessions.items())
    }
    record["ok"] = True
    return record


def lint_gate_record(artifact):
    """Gate decision for one invariant_lint artifact (pure — tested
    without running the linter).

    Binds on EVERY device, hardware-independently (the trace_report
    convention taken to its limit: the claims are properties of the
    source tree, not of any run). Checks: the pinned schema; all four
    rule families ran (a rule module silently dropped from the runner
    must fail here, not pass); ZERO unallowlisted findings; and every
    allowlisted matched point carrying a non-empty reason — the
    allowlist is a registry of justified exceptions, not a mute button.
    """
    # jax-free: the analysis package is stdlib-ast only (the package
    # parent re-exports pull jax, which this parent process may import
    # but never drive — the bench-gate convention)
    from simclr_pytorch_distributed_tpu.analysis import runner as lint_runner

    record = {
        "metric": "ratchet_invariant_lint",
        "value": artifact.get("n_findings"),
        "files_scanned": artifact.get("files_scanned"),
        "rules_run": artifact.get("rules_run"),
        "allowlisted": [
            {"key": a.get("key"), "matched": len(a.get("findings", []))}
            for a in artifact.get("allowlisted", [])
        ],
    }

    def fail(msg):
        record["ok"] = False
        record["error"] = msg
        return record

    if artifact.get("schema") != lint_runner.SCHEMA:
        return fail(f"unexpected schema {artifact.get('schema')!r}")
    missing = sorted(
        set(lint_runner.RULE_FAMILIES) - set(artifact.get("rules_run", []))
    )
    if missing:
        return fail(f"rule families did not run: {missing}")
    for entry in artifact.get("allowlisted", []):
        if not str(entry.get("reason", "")).strip():
            return fail(
                f"allowlist entry {entry.get('key')!r} carries no reason"
            )
    findings = artifact.get("findings", [])
    if findings or not artifact.get("ok"):
        heads = "; ".join(
            f"{f.get('file')}:{f.get('line')} [{f.get('rule')}]"
            for f in findings[:5]
        )
        return fail(
            f"{len(findings)} unallowlisted invariant finding(s): {heads}"
        )
    record["ok"] = True
    return record


def ledger_gate_record(records):
    """Gate decision for the committed perf ledger (pure — tested on
    synthetic record lists).

    Schema validity binds on EVERY device (the ledger is just history).
    The regression bar binds only where history makes it meaningful: the
    latest clean record of each workload fingerprint vs the median of its
    trailing clean window (scripts/perf_ledger.py detect_regression —
    clock-suspect runs excluded on both sides, the bench-gate convention);
    groups without a sufficient window pass-skip with the reason on
    record.
    """
    import perf_ledger  # scripts/ dir on sys.path; imports no jax

    record = {"metric": "ratchet_perf_ledger", "value": len(records)}

    def fail(msg):
        record["ok"] = False
        record["error"] = msg
        return record

    if not records:
        return fail("empty perf ledger: bench.py --ledger never ran")
    errors = perf_ledger.schema_errors(records)
    if errors:
        return fail(f"ledger schema errors: {errors}")
    verdicts = perf_ledger.detect_regression(records)
    record["verdicts"] = verdicts
    record["skipped"] = {
        fp: v["reason"] for fp, v in verdicts.items()
        if v["status"] == "skipped"
    }
    regressions = {
        fp: v for fp, v in verdicts.items() if v["status"] == "regression"
    }
    if regressions:
        return fail(
            "perf regression vs the trailing same-fingerprint window: "
            + "; ".join(
                f"{v.get('stage')}@{v.get('device_kind')} "
                f"{v['value']:.1f} vs median {v['baseline_median']:.1f} "
                f"(ratio {v['ratio']:.3f}, rev {v.get('latest_rev')})"
                for v in regressions.values()
            )
        )
    record["ok"] = True
    return record


class ConfigFailed(RuntimeError):
    """One gated config could not produce a number; the others must still run."""


def _fresh_artifact_path(path):
    """Remove a stale artifact before re-producing it. The logs dir
    persists across ratchet runs, so a gate whose producer crashed BEFORE
    writing its artifact must not fall through onto the previous run's
    clean file and judge evidence the producer never made (the
    invariant-lint review's stale-artifact hazard; applies to every
    crashed-producer fallthrough below)."""
    if os.path.exists(path):
        os.remove(path)
    return path


def run(cmd, log_path):
    with open(log_path, "w") as f:
        proc = subprocess.run(cmd, cwd=REPO, stdout=f, stderr=subprocess.STDOUT)
    if proc.returncode != 0:
        raise ConfigFailed(
            f"FAILED ({proc.returncode}): {' '.join(cmd)}; see {log_path}"
        )


def best_acc(log_path):
    """Last 'best accuracy: X' line of the probe driver's log."""
    best = None
    with open(log_path) as f:
        for line in f:
            m = re.search(r"best accuracy: ([0-9.]+)", line)
            if m:
                best = float(m.group(1))
    if best is None:
        raise ConfigFailed(f"no 'best accuracy' line in {log_path}")
    return best


def parse_bench_json(log_path):
    """bench.py's headline record (the shared parser in
    scripts/perf_ledger.py — the bench-stdout contract lives in ONE
    place), raised as ConfigFailed here so a dead bench config keeps the
    other gates running."""
    import perf_ledger

    record = perf_ledger.parse_bench_json(log_path)
    if record is None:
        raise ConfigFailed(f"no bench JSON record in {log_path}")
    return record


def run_config(name, spec, epochs, bar, args):
    model, kind, dataset = spec["model"], spec["kind"], spec["dataset"]
    trial = f"{args.trial}_{name}"
    logs = os.path.join(args.workdir, f"ratchet_{trial}")
    os.makedirs(logs, exist_ok=True)

    if kind == "bench":
        # the perf bar: bench.py at the recipe defaults, gated against the
        # recorded repo baseline (module docstring)
        bench_log = os.path.join(logs, "bench.log")
        run([sys.executable, "bench.py", "--stage", spec["stage"]], bench_log)
        record = bench_gate_record(spec, parse_bench_json(bench_log), bar)
        record["bench_log"] = bench_log
        print(json.dumps(record), flush=True)
        return record

    if kind in ("resident_ab", "window_ab"):
        # the placement-equivalence gates: byte-identity host vs device /
        # windowed placement, plus the CPU-proxy timing claim
        # (resident_gate_record / window_gate_record)
        ab_json = os.path.join(logs, f"{kind}.json")
        ab_log = os.path.join(logs, f"{kind}.log")
        run(
            [sys.executable, f"scripts/{kind}.py", "--smoke",
             "--json", ab_json],
            ab_log,
        )
        try:
            with open(ab_json) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(f"{kind} wrote no artifact: {e}") from e
        gate = (resident_gate_record if kind == "resident_ab"
                else window_gate_record)
        record = gate(artifact)
        record["bar"] = bar
        record["log"] = ab_log
        print(json.dumps(record), flush=True)
        return record

    if kind == "convblock_ab":
        # the fused conv-block gate: per-kind interpret-mode kernel parity
        # (all six block-kind x dtype sections) + the CPU-proxy traversal
        # timing (convblock_gate_record); stale artifact removed BEFORE
        # the producer runs (the PR-14 crashed-producer convention);
        # --rounds 1 keeps the six-section smoke in gate time — the
        # committed evidence artifact carries the full-round runs
        ab_json = _fresh_artifact_path(os.path.join(logs, f"{kind}.json"))
        ab_log = os.path.join(logs, f"{kind}.log")
        try:
            run(
                [sys.executable, "scripts/convblock_ab.py", "--smoke",
                 "--rounds", "1", "--json", ab_json],
                ab_log,
            )
        except ConfigFailed:
            # convblock_ab exits nonzero on broken parity but still
            # writes the artifact — fall through so the gate record
            # carries the structured per-tensor diffs (the health_report
            # convention); re-raise only with no artifact to judge
            if not os.path.exists(ab_json):
                raise
        try:
            with open(ab_json) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(f"{kind} wrote no artifact: {e}") from e
        record = convblock_gate_record(artifact)
        record["bar"] = bar
        record["log"] = ab_log
        print(json.dumps(record), flush=True)
        return record

    if kind == "trace_report":
        # the flight-recorder smoke: one tiny pretrain epoch with the
        # recorder on, then the attribution report over its events.jsonl
        pre_log = os.path.join(logs, "pretrain.log")
        run(
            [sys.executable, "main_supcon.py", "--dataset", dataset,
             "--model", model, "--epochs", str(max(1, epochs)),
             "--batch_size", "64", "--learning_rate", "0.05",
             "--print_freq", "4", "--save_freq", "1",
             "--flight_recorder", "on", "--workdir", args.workdir,
             "--seed", str(args.seed), "--trial", trial],
            pre_log,
        )
        models = os.path.join(args.workdir, f"{dataset}_models")
        runs = [
            os.path.join(models, d) for d in os.listdir(models)
            if d.endswith(f"trial_{trial}")
        ]
        if not runs:
            raise ConfigFailed(f"no run dir matching trial_{trial} in {models}")
        run_dir = max(runs, key=os.path.getmtime)
        events = os.path.join(run_dir, "events.jsonl")
        report_json = os.path.join(logs, "trace_report.json")
        report_log = os.path.join(logs, "trace_report.log")
        run(
            [sys.executable, "scripts/trace_report.py", "--events", events,
             "--json", report_json],
            report_log,
        )
        try:
            with open(report_json) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(f"trace_report wrote no artifact: {e}") from e
        record = trace_report_gate_record(artifact)
        record["bar"] = bar
        record["log"] = report_log
        print(json.dumps(record), flush=True)
        return record

    if kind == "health_report":
        # the training-health smoke: one tiny pretrain epoch with the
        # on-device diagnostics + online probe, then the health timeline
        # report over its events.jsonl (health_report_gate_record)
        pre_log = os.path.join(logs, "pretrain.log")
        run(
            [sys.executable, "main_supcon.py", "--dataset", dataset,
             "--model", model, "--epochs", str(max(1, epochs)),
             "--batch_size", "64", "--learning_rate", "0.05",
             "--print_freq", "4", "--save_freq", "1",
             "--health_freq", "2", "--online_probe", "on",
             "--health_policy", "warn", "--workdir", args.workdir,
             "--seed", str(args.seed), "--trial", trial],
            pre_log,
        )
        models = os.path.join(args.workdir, f"{dataset}_models")
        runs = [
            os.path.join(models, d) for d in os.listdir(models)
            if d.endswith(f"trial_{trial}")
        ]
        if not runs:
            raise ConfigFailed(f"no run dir matching trial_{trial} in {models}")
        run_dir = max(runs, key=os.path.getmtime)
        events = os.path.join(run_dir, "events.jsonl")
        report_json = _fresh_artifact_path(
            os.path.join(logs, "health_report.json")
        )
        report_log = os.path.join(logs, "health_report.log")
        try:
            run(
                [sys.executable, "scripts/health_report.py", "--events",
                 events, "--json", report_json],
                report_log,
            )
        except ConfigFailed:
            # health_report exits nonzero on an INCONSISTENT stream but
            # still writes the artifact — fall through so the gate record
            # fails with the structured verdict (missing_keys/n_windows)
            # instead of a generic subprocess error; re-raise only when
            # there is no artifact to judge
            if not os.path.exists(report_json):
                raise
        try:
            with open(report_json) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(f"health_report wrote no artifact: {e}") from e
        record = health_report_gate_record(artifact, probe_bar=bar)
        record["log"] = report_log
        print(json.dumps(record), flush=True)
        return record

    if kind == "recipes":
        # the SSL-recipe gate: recipes_eval --smoke runs every recipe
        # through the real driver + the supcon bit-identity A/B, then the
        # pure recipe_gate_record judges the artifact (CONFIGS note)
        ev_json = _fresh_artifact_path(
            os.path.join(logs, "recipes_eval.json")
        )
        ev_log = os.path.join(logs, "recipes_eval.log")
        try:
            run(
                [sys.executable, "scripts/recipes_eval.py", "--smoke",
                 "--json", ev_json, "--seed", str(args.seed),
                 "--trial", trial,
                 "--workdir", os.path.join(args.workdir, f"recipes_{trial}")],
                ev_log,
            )
        except ConfigFailed:
            # recipes_eval exits nonzero on a failed claim but still writes
            # the artifact — fall through so the gate record carries the
            # structured verdict (the health_report convention)
            if not os.path.exists(ev_json):
                raise
        try:
            with open(ev_json) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(f"recipes_eval wrote no artifact: {e}") from e
        record = recipe_gate_record(artifact)
        record["bar"] = bar
        record["log"] = ev_log
        print(json.dumps(record), flush=True)
        return record

    if kind == "fleet_report":
        # binds on the COMMITTED fleet-merge evidence artifact (CONFIGS
        # note): re-produce it with a 2-process run + trace_report --fleet
        # when the anchor/collective instrumentation changes
        path = os.path.join(REPO, spec["artifact"])
        try:
            with open(path) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(
                f"no readable fleet evidence at {path}: {e}"
            ) from e
        record = fleet_gate_record(artifact)
        record["bar"] = bar
        record["artifact"] = spec["artifact"]
        print(json.dumps(record), flush=True)
        return record

    if kind == "perf_ledger":
        # the pure regression scan over the committed longitudinal ledger
        import perf_ledger

        path = os.path.join(REPO, spec["artifact"])
        record = ledger_gate_record(perf_ledger.load_ledger(path))
        record["bar"] = bar
        record["artifact"] = spec["artifact"]
        print(json.dumps(record), flush=True)
        return record

    if kind == "invariant_lint":
        # the static invariant-lint gate (CONFIGS note): run the linter
        # over the tree, then judge the artifact with the pure record
        lint_json = _fresh_artifact_path(
            os.path.join(logs, "invariant_lint.json")
        )
        lint_log = os.path.join(logs, "invariant_lint.log")
        try:
            run(
                [sys.executable, "scripts/invariant_lint.py",
                 "--json", lint_json],
                lint_log,
            )
        except ConfigFailed:
            # the linter exits nonzero on findings but still writes the
            # artifact — fall through so the gate record carries the
            # structured findings (the health_report convention)
            if not os.path.exists(lint_json):
                raise
        try:
            with open(lint_json) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(
                f"invariant_lint wrote no artifact: {e}"
            ) from e
        record = lint_gate_record(artifact)
        record["bar"] = bar
        record["log"] = lint_log
        print(json.dumps(record), flush=True)
        return record

    if kind == "supervisor_gate":
        # binds on the COMMITTED scenario-matrix evidence artifact (see the
        # CONFIGS note): no subprocess — the matrix itself is re-run with
        # scripts/supervisor_matrix.py when the supervisor changes
        path = os.path.join(REPO, spec["artifact"])
        try:
            with open(path) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(
                f"no readable supervisor evidence at {path}: {e}"
            ) from e
        record = supervisor_gate_record(artifact)
        record["bar"] = bar
        record["artifact"] = spec["artifact"]
        print(json.dumps(record), flush=True)
        return record

    if kind == "chaos_gate":
        # binds on the COMMITTED straggler/chaos evidence artifact (see
        # the CONFIGS note): no subprocess — re-run the matrix's chaos
        # scenarios when the mitigation surface changes
        path = os.path.join(REPO, spec["artifact"])
        try:
            with open(path) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(
                f"no readable chaos evidence at {path}: {e}"
            ) from e
        record = chaos_gate_record(artifact)
        record["bar"] = bar
        record["artifact"] = spec["artifact"]
        print(json.dumps(record), flush=True)
        return record

    if kind == "serve_fleet_gate":
        # binds on the COMMITTED serve-fleet scenario evidence (see the
        # CONFIGS note): no subprocess — re-run
        # scripts/serve_fleet_scenario.py when the fleet surface changes
        path = os.path.join(REPO, spec["artifact"])
        try:
            with open(path) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(
                f"no readable serve-fleet evidence at {path}: {e}"
            ) from e
        record = serve_fleet_gate_record(artifact)
        record["bar"] = bar
        record["artifact"] = spec["artifact"]
        print(json.dumps(record), flush=True)
        return record

    if kind == "retrieval_gate":
        # binds on the COMMITTED brute-vs-IVF A/B evidence (see the
        # CONFIGS note): no subprocess — re-run scripts/retrieval_ab.py
        # when the retrieval surface changes
        path = os.path.join(REPO, spec["artifact"])
        try:
            with open(path) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ConfigFailed(
                f"no readable retrieval evidence at {path}: {e}"
            ) from e
        record = retrieval_gate_record(artifact)
        record["bar"] = bar
        record["artifact"] = spec["artifact"]
        print(json.dumps(record), flush=True)
        return record

    if kind == "ce":
        # the CE trainer end-to-end: train + validate in one driver
        # (protocol of docs/evidence/ce_30ep.log: rn50, lr 0.1 cosine, bf16)
        ce_log = os.path.join(logs, "ce.log")
        run(
            [sys.executable, "main_ce.py", "--dataset", dataset,
             "--model", model, "--epochs", str(epochs),
             "--batch_size", "256", "--learning_rate", "0.1", "--cosine",
             "--bf16", "--save_freq", str(epochs), "--print_freq", "20",
             "--workdir", args.workdir, "--seed", str(args.seed),
             "--trial", trial],
            ce_log,
        )
        acc = best_acc(ce_log)
        record = {
            "metric": f"ratchet_{dataset}_ce_top1_{name}",
            "value": acc, "bar": bar, "model": model, "epochs": epochs,
            "seed": args.seed, "ok": acc >= bar, "ce_log": ce_log,
        }
        print(json.dumps(record), flush=True)
        return record

    method = {"simclr": "SimCLR", "supcon": "SupCon"}[kind]
    pre_log = os.path.join(logs, "pretrain.log")
    run(
        [sys.executable, "main_supcon.py", "--dataset", dataset,
         "--model", model,
         "--epochs", str(epochs), "--batch_size", "256",
         "--learning_rate", "0.1", "--warm", "--temp", "0.5", "--cosine",
         "--method", method, "--bf16", "--save_freq", str(epochs),
         "--print_freq", "20", "--workdir", args.workdir,
         "--seed", str(args.seed), "--trial", trial],
        pre_log,
    )
    # run folder = newest matching dir the pretrain just wrote; exact trial
    # suffix only (finalize_supcon appends _cosine/_warm after the trial)
    models = os.path.join(args.workdir, f"{dataset}_models")
    runs = [
        os.path.join(models, d) for d in os.listdir(models)
        if d.endswith(f"trial_{trial}_cosine_warm")
    ]
    if not runs:
        raise ConfigFailed(
            f"no run dir matching trial_{trial}_cosine_warm in {models}"
        )
    run_dir = max(runs, key=os.path.getmtime)

    probe_log = os.path.join(logs, "probe.log")
    run(
        [sys.executable, "main_linear.py", "--dataset", dataset,
         "--model", model,
         "--epochs", "60", "--learning_rate", "5", "--batch_size", "256",
         "--ckpt", os.path.join(run_dir, "last"), "--workdir", args.workdir,
         "--trial", trial],
        probe_log,
    )
    acc = best_acc(probe_log)
    record = {
        "metric": f"ratchet_{dataset}_probe_top1_{name}",
        "value": acc, "bar": bar, "model": model, "epochs": epochs,
        "method": method, "seed": args.seed, "ok": acc >= bar,
        "pretrain_log": pre_log, "probe_log": probe_log,
    }
    print(json.dumps(record), flush=True)
    return record


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", nargs="+", default=list(CONFIGS),
                    choices=list(CONFIGS))
    ap.add_argument("--bar", type=float, default=None,
                    help="override the pre-registered bar (single config only)")
    ap.add_argument("--epochs", type=int, default=None,
                    help="override pretrain epochs (single config only)")
    ap.add_argument("--trial", default="ratchet")
    ap.add_argument("--workdir", default=os.path.join(REPO, "work_space"))
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if (args.bar is not None or args.epochs is not None) and len(args.configs) > 1:
        sys.exit("--bar/--epochs overrides need exactly one --configs entry")

    records = []
    for name in args.configs:
        spec = CONFIGS[name]
        epochs = args.epochs if args.epochs is not None else spec["epochs"]
        bar = args.bar if args.bar is not None else spec["bar"]
        if bar is None and spec["kind"] == "bench":
            bar = _bench_bar()
        try:
            records.append(run_config(name, spec, epochs, bar, args))
        except ConfigFailed as e:
            # a dead config must not skip the remaining gates or eat the
            # summary line the CI parses
            if spec["kind"] == "bench":
                metric = bench_metric_name(spec)
            elif spec["kind"] == "trace_report":
                metric = "ratchet_trace_report_attribution"
            elif spec["kind"] == "health_report":
                metric = "ratchet_health_report"
            elif spec["kind"] == "supervisor_gate":
                metric = "ratchet_supervisor_matrix"
            elif spec["kind"] == "chaos_gate":
                metric = "ratchet_chaos_matrix"
            elif spec["kind"] == "serve_fleet_gate":
                metric = "ratchet_serve_fleet"
            elif spec["kind"] == "retrieval_gate":
                metric = "ratchet_retrieval_ab"
            elif spec["kind"] == "fleet_report":
                metric = "ratchet_fleet_report"
            elif spec["kind"] == "perf_ledger":
                metric = "ratchet_perf_ledger"
            elif spec["kind"] == "invariant_lint":
                metric = "ratchet_invariant_lint"
            elif spec["kind"] == "recipes":
                metric = "ratchet_recipes"
            elif spec["kind"] in ("resident_ab", "window_ab"):
                metric = f"ratchet_{spec['kind']}_equivalence"
            else:
                stage = "ce" if spec["kind"] == "ce" else "probe"
                metric = f"ratchet_{spec['dataset']}_{stage}_top1_{name}"
            record = {
                "metric": metric,
                "value": None, "bar": bar, "model": spec["model"],
                "epochs": epochs,
                "seed": args.seed, "ok": False, "error": str(e),
            }
            print(json.dumps(record), flush=True)
            records.append(record)
    ok = all(r["ok"] for r in records)
    print(json.dumps({
        "metric": "ratchet_gate",
        "ok": ok,
        "configs": {r["metric"]: {"value": r["value"], "bar": r["bar"],
                                  "ok": r["ok"]} for r in records},
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
