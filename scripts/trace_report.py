#!/usr/bin/env python
"""Per-phase step-time attribution from a flight-recorder ``events.jsonl``.

The recorder (utils/tracing.py) instruments only host-visible boundaries,
and its ``main:*`` phase tracks never nest across each other — so summing
their span durations partitions the run's measured wall clock exactly:

    wall = compile + data + flush + checkpoint + collective + ...
           + steady_state (the remainder: the dispatch-only hot loop)

This script reads the jsonl, builds that attribution table with anomaly
flags (compile-dominated runs, flush-heavy windows, data stalls, recorded
stall/rollback/preemption events), prints it, and writes a JSON artifact —
the committed ``docs/evidence/trace_report_r*.json`` convention, and the
``trace_report`` config in ``scripts/ratchet.py``'s default gate list
(which binds on the attribution's internal consistency: phases
non-negative and non-overlapping, the table summing to the wall time).

``--fleet <run_dir>`` is the MULTI-PROCESS view: a pod writes one
``events_pN.jsonl`` per process on unaligned per-host monotonic clocks.
This mode discovers every session's per-process files, aligns the
timelines through the ``clock_anchor`` events each process stamps at
already-matched collective points (affine fit per process, residual
reported — utils/tracing.py), and emits: a merged Chrome trace (``pid`` =
process index), a per-collective skew table naming the straggler process
at each boundary (arrival = the ``main:collective`` span's start), a
straggler ranking, and per-process attribution consistency checks — all
through the pure ``build_fleet_report`` (the committed
``docs/evidence/fleet_report_r*.json`` convention, gate-verified by
ratchet's ``fleet_report`` config).

Usage:
    python scripts/trace_report.py --events <run_dir>/events.jsonl \
        [--json out.json]
    python scripts/trace_report.py --fleet <run_dir> \
        [--json out.json] [--trace merged_trace.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.utils import tracing  # noqa: E402
from simclr_pytorch_distributed_tpu.utils.tracing import (  # noqa: E402
    ANCHOR_EVENT,
    EPOCH_TRACK,
    MAIN_TRACK_PREFIX,
    chrome_trace_from_events,
)

SCHEMA = "trace_report/v1"
FLEET_SCHEMA = "fleet_report/v1"
COLLECTIVE_TRACK = "main:collective"
# max acceptable affine-fit residual: the anchors are post-release stamps
# of one physical instant, so after the per-process affine map they must
# agree to within collective release jitter (ms-scale even on a loaded
# CPU host; a residual past this means the merge cannot be trusted)
FLEET_RESIDUAL_TOL_S = 0.25

# advisory share thresholds per phase (fraction of wall): above them the
# phase is flagged — not an error, a "look here first" pointer
ANOMALY_SHARES = {
    "compile": 0.50,   # cold compile dominating: check the compile cache
    "data": 0.35,      # window staging not hidden by prefetch
    "flush": 0.25,     # telemetry flush on the critical path: check async
    "checkpoint": 0.25,  # save serialization/commit stalling the loop
    "eval": 0.60,      # validation dwarfing training (tiny-epoch smokes)
}
# recorded events that are findings in themselves
EVENT_FLAGS = {
    "stall_detected": "stall watchdog fired (see stall_dump_* artifacts)",
    "nan_rollback": "NaN rollback(s) recorded",
    "preempt_exit": "run ended by preemption",
    "flush_failure": "telemetry flush failure observed",
    "recorder_dropped": "flight-recorder ring saturated: trace.json and "
                        "watchdog snapshots truncated (events.jsonl is "
                        "complete)",
}
# span overlap tolerance (s): clock reads bracketing a record are not atomic
OVERLAP_TOL_S = 1e-4


def load_events(path):
    """One session's records — the shared torn-line-tolerant loader
    (tracing.parse_jsonl): the half-written final line a SIGKILL leaves
    behind is exactly the run this report exists to diagnose."""
    return tracing.load_events_jsonl(path)


def _attributed_tracks(events):
    tracks = {}
    for e in events:
        track = e.get("track", "")
        if (
            e.get("ph") == "X"
            and track.startswith(MAIN_TRACK_PREFIX)
            and track != EPOCH_TRACK
        ):
            tracks.setdefault(track, []).append(e)
    return tracks


def build_report(events):
    """The attribution report (pure — tests/test_scripts.py drives it on
    synthetic event lists)."""
    if not events:
        raise ValueError("no events: recorder off or empty run?")
    t0 = min(e["ts"] for e in events)
    t1 = max(e["ts"] + e.get("dur", 0.0) for e in events)
    wall = t1 - t0

    tracks = _attributed_tracks(events)
    phases = {}
    spans = []
    monotone_ok = True
    for track, track_events in sorted(tracks.items()):
        track_events.sort(key=lambda e: e["ts"])
        prev_end = None
        durs = [e.get("dur", 0.0) for e in track_events]
        for e in track_events:
            if prev_end is not None and e["ts"] < prev_end - OVERLAP_TOL_S:
                monotone_ok = False
            prev_end = e["ts"] + e.get("dur", 0.0)
            spans.append((e["ts"], prev_end))
        phases[track[len(MAIN_TRACK_PREFIX):]] = {
            "seconds": round(sum(durs), 6),
            "count": len(durs),
            "mean_ms": round(1e3 * sum(durs) / len(durs), 3),
            "max_ms": round(1e3 * max(durs), 3),
        }
    # the cross-track invariant that makes the table sum to wall: all
    # attributed spans live on the main thread, so they must be globally
    # non-overlapping, not just per track
    spans.sort()
    for (s0, e0), (s1, _) in zip(spans, spans[1:]):
        if s1 < e0 - OVERLAP_TOL_S:
            monotone_ok = False

    attributed = sum(p["seconds"] for p in phases.values())
    steady = wall - attributed
    for name, p in phases.items():
        p["share"] = round(p["seconds"] / wall, 4) if wall > 0 else 0.0

    anomalies = []
    for name, p in phases.items():
        bar = ANOMALY_SHARES.get(name)
        if bar is not None and p["share"] > bar:
            anomalies.append({
                "phase": name,
                "flag": f"share {p['share']:.0%} > {bar:.0%}",
            })
    event_counts = {}
    for e in events:
        if e.get("ph") == "i" and e["name"] in EVENT_FLAGS:
            event_counts[e["name"]] = event_counts.get(e["name"], 0) + 1
    for name, count in sorted(event_counts.items()):
        anomalies.append({
            "phase": "events", "flag": f"{EVENT_FLAGS[name]} (x{count})",
        })

    nonnegative_ok = steady >= -OVERLAP_TOL_S
    return {
        "phases": phases,
        "steady_state": {
            "seconds": round(steady, 6),
            "share": round(steady / wall, 4) if wall > 0 else 0.0,
        },
        "anomalies": anomalies,
        "consistency": {
            "wall_s": round(wall, 6),
            "attributed_s": round(attributed, 6),
            "steady_state_s": round(steady, 6),
            "monotone_ok": monotone_ok,
            "nonnegative_ok": bool(nonnegative_ok),
            # the gate bit: the table sums to the measured wall time (exact
            # by construction) AND that construction was valid — attributed
            # spans non-overlapping and the remainder non-negative
            "ok": bool(monotone_ok and nonnegative_ok and wall > 0),
        },
        "n_events": len(events),
    }


def render_table(report):
    rows = [("phase", "seconds", "share", "count", "mean_ms", "max_ms")]
    for name, p in sorted(
        report["phases"].items(), key=lambda kv: -kv[1]["seconds"]
    ):
        rows.append((
            name, f"{p['seconds']:.3f}", f"{p['share']:.1%}",
            str(p["count"]), f"{p['mean_ms']:.1f}", f"{p['max_ms']:.1f}",
        ))
    ss = report["steady_state"]
    rows.append((
        "steady_state", f"{ss['seconds']:.3f}", f"{ss['share']:.1%}",
        "-", "-", "-",
    ))
    rows.append((
        "wall", f"{report['consistency']['wall_s']:.3f}", "100.0%",
        "-", "-", "-",
    ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = [
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths))
        for row in rows
    ]
    lines.insert(1, "-" * len(lines[0]))
    for a in report["anomalies"]:
        lines.append(f"ANOMALY [{a['phase']}]: {a['flag']}")
    if not report["consistency"]["ok"]:
        lines.append("CONSISTENCY: FAILED (overlapping or oversubscribed "
                     "attribution — recorder track contract violated)")
    return "\n".join(lines)


def build_output(events_path, report):
    """The committed artifact (pure; schema pinned by tests)."""
    return {"schema": SCHEMA, "events": events_path, "report": report}


# ------------------------------------------------------------------ fleet


def anchor_points(events):
    """``{anchor_seq: local_ts}`` of one process's clock anchors."""
    out = {}
    for e in events:
        if e.get("name") == ANCHOR_EVENT and e.get("ph") == "i":
            args = e.get("args", {})
            if "anchor" in args:
                out[int(args["anchor"])] = float(e["ts"])
    return out


def fit_alignment(ref_anchors, anchors):
    """Affine map local -> reference clock over the matched anchor seqs
    (pure). Least squares over >=2 anchors recovers offset AND rate drift;
    one anchor degrades to offset-only (scale pinned at 1); zero matched
    anchors means the timelines cannot be merged (``residual_s`` None).
    ``residual_s`` is the MAX absolute fit error — the merge's error bar,
    gated against :data:`FLEET_RESIDUAL_TOL_S`."""
    seqs = sorted(set(ref_anchors) & set(anchors))
    n = len(seqs)
    if n == 0:
        return {"scale": 1.0, "offset_s": 0.0, "residual_s": None,
                "n_anchors": 0}
    xs = [anchors[s] for s in seqs]
    ys = [ref_anchors[s] for s in seqs]
    if n == 1:
        a, b = 1.0, ys[0] - xs[0]
    else:
        mx, my = sum(xs) / n, sum(ys) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        sxy = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
        a = sxy / sxx if sxx > 0 else 1.0
        b = my - a * mx
    residual = max(abs(a * x + b - y) for x, y in zip(xs, ys))
    return {"scale": a, "offset_s": round(b, 6),
            "residual_s": round(residual, 6), "n_anchors": n}


def _aligned(alignment, ts):
    return alignment["scale"] * ts + alignment["offset_s"]


def build_fleet_report(events_by_process, residual_tol_s=FLEET_RESIDUAL_TOL_S):
    """One session's merged fleet view (pure — tests drive it on synthetic
    per-process event lists).

    ``events_by_process`` maps process index -> that process's records.
    The lowest process index is the reference clock; every other process
    is affine-fitted onto it through the matched ``clock_anchor`` events.
    Collective spans (``main:collective``) are matched across processes by
    (name, per-process occurrence index) — valid because the collective
    call SCHEDULE is identical across processes (the repo's documented
    deadlock invariant); a span's start is that process's ARRIVAL at the
    boundary, so the aligned arrival spread is the boundary's skew and the
    latest arrival is its straggler.
    """
    if not events_by_process:
        raise ValueError("no per-process event lists: empty fleet?")
    pids = sorted(events_by_process)
    ref = pids[0]
    anchors = {p: anchor_points(events_by_process[p]) for p in pids}
    alignments = {
        p: fit_alignment(anchors[ref], anchors[p]) for p in pids
    }

    processes = {}
    attribution_ok = True
    for p in pids:
        try:
            rep = build_report(events_by_process[p])
            ok = bool(rep["consistency"]["ok"])
        except ValueError:
            ok = False
        attribution_ok = attribution_ok and ok
        processes[str(p)] = {
            "n_events": len(events_by_process[p]),
            "n_anchors": len(anchors[p]),
            "alignment": alignments[p],
            "attribution_ok": ok,
        }

    # collective spans, grouped by (name, occurrence) across processes;
    # skew is a CROSS-process spread, so a single-process merge has no
    # skew table (not a table of zeros)
    groups = {}
    for p in (pids if len(pids) > 1 else ()):
        counters = {}
        for e in events_by_process[p]:
            if e.get("ph") == "X" and e.get("track") == COLLECTIVE_TRACK:
                i = counters.get(e["name"], 0)
                counters[e["name"]] = i + 1
                groups.setdefault((e["name"], i), {})[p] = {
                    "arrival": _aligned(alignments[p], e["ts"]),
                    "wait_s": e.get("dur", 0.0),
                    "step": e.get("args", {}).get("step"),
                }
    skew_table = []
    incomplete = 0
    times_last = {p: 0 for p in pids}
    lateness = {p: [] for p in pids}
    for (name, i), by_p in groups.items():
        if set(by_p) != set(pids):
            # a process died (or went silent) before this boundary: real
            # finding on a preempted run, merge-contract violation on a
            # clean one — counted either way, skewless
            incomplete += 1
            continue
        arrivals = {p: by_p[p]["arrival"] for p in pids}
        first = min(arrivals.values())
        straggler = max(pids, key=lambda p: arrivals[p])
        for p in pids:
            lateness[p].append(arrivals[p] - first)
        times_last[straggler] += 1
        skew_table.append({
            "name": name, "index": i, "step": by_p[ref]["step"],
            "t_s": round(first, 6),
            "skew_s": round(arrivals[straggler] - first, 6),
            "straggler": straggler,
            "arrivals_s": {str(p): round(arrivals[p], 6) for p in pids},
        })
    skew_table.sort(key=lambda r: r["t_s"])
    ranking = sorted(
        (
            {
                "process": p,
                "times_last": times_last[p],
                "boundaries": len(lateness[p]),
                "mean_lateness_s": round(
                    sum(lateness[p]) / len(lateness[p]), 6
                ) if lateness[p] else 0.0,
            }
            for p in pids
        ),
        key=lambda r: (-r["times_last"], -r["mean_lateness_s"]),
    )

    non_ref = pids[1:]
    residuals = [alignments[p]["residual_s"] for p in non_ref]
    aligned_ok = all(
        alignments[p]["n_anchors"] >= 2
        and alignments[p]["residual_s"] is not None
        and alignments[p]["residual_s"] <= residual_tol_s
        for p in non_ref
    )
    collective_match_ok = incomplete == 0
    consistency = {
        "n_processes": len(pids),
        "aligned_ok": bool(aligned_ok),
        "max_residual_s": max([r for r in residuals if r is not None],
                              default=0.0),
        "residual_tol_s": residual_tol_s,
        "attribution_ok": bool(attribution_ok),
        "collective_match_ok": bool(collective_match_ok),
        "incomplete_boundaries": incomplete,
        # the gate bit: timelines really merged (every non-ref process
        # anchored to sub-tolerance), every per-process attribution holds,
        # every collective boundary is whole, and a multi-process merge
        # produced at least one skew observation (none = the fleet
        # instrumentation was silently dead)
        "ok": bool(
            aligned_ok and attribution_ok and collective_match_ok
            and (len(pids) == 1 or len(skew_table) > 0)
        ),
    }
    return {
        "processes": processes,
        "skew_table": skew_table,
        "straggler_ranking": ranking,
        "consistency": consistency,
    }


def fleet_chrome_trace(events_by_process, report):
    """The merged Chrome trace: every process's records mapped onto the
    reference clock (its fitted alignment), ``pid`` = process index, the
    whole fleet shifted so the earliest record sits at t=0 (Chrome/Perfetto
    dislike negative timestamps)."""
    aligned = {}
    t0 = None
    for p, events in sorted(events_by_process.items()):
        al = report["processes"][str(p)]["alignment"]
        evs = []
        for e in events:
            e2 = dict(e, ts=_aligned(al, e["ts"]))
            if "dur" in e2:
                e2["dur"] = e2["dur"] * al["scale"]
            evs.append(e2)
            t0 = e2["ts"] if t0 is None else min(t0, e2["ts"])
        aligned[p] = evs
    out = {"traceEvents": [], "displayTimeUnit": "ms"}
    for p, evs in sorted(aligned.items()):
        trace = chrome_trace_from_events(
            [dict(e, ts=e["ts"] - t0) for e in evs], process_index=p
        )
        out["traceEvents"].extend(trace["traceEvents"])
    return out


def render_fleet_table(report, max_rows=12):
    lines = []
    rows = [("process", "events", "anchors", "scale", "offset_s",
             "residual_s", "attribution")]
    for p, info in sorted(report["processes"].items(), key=lambda kv: int(kv[0])):
        al = info["alignment"]
        res = al["residual_s"]
        rows.append((
            p, str(info["n_events"]), str(info["n_anchors"]),
            f"{al['scale']:.9g}", f"{al['offset_s']:.6f}",
            "-" if res is None else f"{res:.6f}",
            "ok" if info["attribution_ok"] else "FAILED",
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines += ["  ".join(c.ljust(w) for c, w in zip(r, widths)) for r in rows]
    lines.insert(1, "-" * len(lines[0]))
    table = sorted(report["skew_table"], key=lambda r: -r["skew_s"])[:max_rows]
    if table:
        lines.append(f"boundary skew (top {len(table)} by skew):")
        for r in table:
            lines.append(
                f"  {r['name']}[{r['index']}] step={r['step']} "
                f"t={r['t_s']:.3f}s skew={r['skew_s'] * 1e3:.1f}ms "
                f"straggler=p{r['straggler']}"
            )
    for r in report["straggler_ranking"]:
        if r["boundaries"]:
            lines.append(
                f"straggler ranking: p{r['process']} last at "
                f"{r['times_last']}/{r['boundaries']} boundaries "
                f"(mean lateness {r['mean_lateness_s'] * 1e3:.1f}ms)"
            )
    cons = report["consistency"]
    if not cons["ok"]:
        lines.append(f"CONSISTENCY: FAILED ({cons})")
    return "\n".join(lines)


def build_fleet_output(run_dir, session_reports):
    """The committed fleet artifact (pure; schema pinned by tests):
    one report per recorder session, ``ok`` = every session merged
    consistently."""
    return {
        "schema": FLEET_SCHEMA,
        "run_dir": run_dir,
        "sessions": session_reports,
        "ok": bool(session_reports) and all(
            rep["consistency"]["ok"] for rep in session_reports.values()
        ),
    }


def run_fleet(args):
    sessions = tracing.discover_fleet_sessions(args.fleet)
    if not sessions:
        print(f"no events*.jsonl sessions in {args.fleet}")
        return 1
    reports = {}
    last = None
    for label, files in sessions.items():
        # EVERY discovered process file enters the merge, records or not: a
        # process whose file exists but holds zero complete records (a
        # SIGKILL before its first full line) is exactly the dead-process
        # post-mortem this mode exists to surface — silently dropping it
        # would let a 2-process session merge "consistently" as one
        events_by_process = {
            pidx: load_events(path) for pidx, path in sorted(files.items())
        }
        report = build_fleet_report(events_by_process)
        report["files"] = {
            str(p): os.path.basename(files[p]) for p in events_by_process
        }
        reports[label] = report
        last = (events_by_process, report)
        print(f"== session {label} "
              f"({report['consistency']['n_processes']} process(es)) ==")
        print(render_fleet_table(report))
    artifact = build_fleet_output(args.fleet, reports)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"wrote {args.json}")
    if args.trace and last is not None:
        # the merged Chrome trace of the LATEST session (the one a
        # post-mortem usually wants — earlier sessions stay per-process)
        with open(args.trace, "w") as f:
            json.dump(fleet_chrome_trace(*last), f)
        print(f"wrote {args.trace}")
    return 0 if artifact["ok"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", default="",
                    help="a flight-recorder events.jsonl (run dir artifact)")
    ap.add_argument("--fleet", default="", metavar="RUN_DIR",
                    help="fleet mode: merge every per-process "
                         "events*_p*.jsonl session in this run dir "
                         "(clock-anchor alignment, skew table, straggler "
                         "ranking)")
    ap.add_argument("--json", default="",
                    help="write the attribution/fleet artifact here")
    ap.add_argument("--trace", default="",
                    help="(fleet) write the merged Chrome trace here")
    args = ap.parse_args(argv)
    if bool(args.events) == bool(args.fleet):
        ap.error("exactly one of --events / --fleet is required")

    if args.fleet:
        return run_fleet(args)
    report = build_report(load_events(args.events))
    print(render_table(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(build_output(args.events, report), f, indent=1)
        print(f"wrote {args.json}")
    return 0 if report["consistency"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
