#!/usr/bin/env python
"""Per-phase step-time attribution from a flight-recorder ``events.jsonl``.

The recorder (utils/tracing.py) instruments only host-visible boundaries,
and its ``main:*`` phase tracks never nest across each other — so summing
their span durations partitions the run's measured wall clock exactly:

    wall = compile + data + flush + checkpoint + collective + ...
           + steady_state (the remainder: the dispatch-only hot loop)

This script reads the jsonl, builds that attribution table with anomaly
flags (compile-dominated runs, flush-heavy windows, data stalls, recorded
stall/rollback/preemption events), prints it, and writes a JSON artifact —
the committed ``docs/evidence/trace_report_r*.json`` convention, and the
``trace_report`` config in ``scripts/ratchet.py``'s default gate list
(which binds on the attribution's internal consistency: phases
non-negative and non-overlapping, the table summing to the wall time).

Usage:
    python scripts/trace_report.py --events <run_dir>/events.jsonl \
        [--json out.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.utils.tracing import (  # noqa: E402
    EPOCH_TRACK,
    MAIN_TRACK_PREFIX,
)

SCHEMA = "trace_report/v1"

# advisory share thresholds per phase (fraction of wall): above them the
# phase is flagged — not an error, a "look here first" pointer
ANOMALY_SHARES = {
    "compile": 0.50,   # cold compile dominating: check the compile cache
    "data": 0.35,      # window staging not hidden by prefetch
    "flush": 0.25,     # telemetry flush on the critical path: check async
    "checkpoint": 0.25,  # save serialization/commit stalling the loop
    "eval": 0.60,      # validation dwarfing training (tiny-epoch smokes)
}
# recorded events that are findings in themselves
EVENT_FLAGS = {
    "stall_detected": "stall watchdog fired (see stall_dump_* artifacts)",
    "nan_rollback": "NaN rollback(s) recorded",
    "preempt_exit": "run ended by preemption",
    "flush_failure": "telemetry flush failure observed",
}
# span overlap tolerance (s): clock reads bracketing a record are not atomic
OVERLAP_TOL_S = 1e-4


def load_events(path):
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            events.append(json.loads(line))
    return events


def _attributed_tracks(events):
    tracks = {}
    for e in events:
        track = e.get("track", "")
        if (
            e.get("ph") == "X"
            and track.startswith(MAIN_TRACK_PREFIX)
            and track != EPOCH_TRACK
        ):
            tracks.setdefault(track, []).append(e)
    return tracks


def build_report(events):
    """The attribution report (pure — tests/test_scripts.py drives it on
    synthetic event lists)."""
    if not events:
        raise ValueError("no events: recorder off or empty run?")
    t0 = min(e["ts"] for e in events)
    t1 = max(e["ts"] + e.get("dur", 0.0) for e in events)
    wall = t1 - t0

    tracks = _attributed_tracks(events)
    phases = {}
    spans = []
    monotone_ok = True
    for track, track_events in sorted(tracks.items()):
        track_events.sort(key=lambda e: e["ts"])
        prev_end = None
        durs = [e.get("dur", 0.0) for e in track_events]
        for e in track_events:
            if prev_end is not None and e["ts"] < prev_end - OVERLAP_TOL_S:
                monotone_ok = False
            prev_end = e["ts"] + e.get("dur", 0.0)
            spans.append((e["ts"], prev_end))
        phases[track[len(MAIN_TRACK_PREFIX):]] = {
            "seconds": round(sum(durs), 6),
            "count": len(durs),
            "mean_ms": round(1e3 * sum(durs) / len(durs), 3),
            "max_ms": round(1e3 * max(durs), 3),
        }
    # the cross-track invariant that makes the table sum to wall: all
    # attributed spans live on the main thread, so they must be globally
    # non-overlapping, not just per track
    spans.sort()
    for (s0, e0), (s1, _) in zip(spans, spans[1:]):
        if s1 < e0 - OVERLAP_TOL_S:
            monotone_ok = False

    attributed = sum(p["seconds"] for p in phases.values())
    steady = wall - attributed
    for name, p in phases.items():
        p["share"] = round(p["seconds"] / wall, 4) if wall > 0 else 0.0

    anomalies = []
    for name, p in phases.items():
        bar = ANOMALY_SHARES.get(name)
        if bar is not None and p["share"] > bar:
            anomalies.append({
                "phase": name,
                "flag": f"share {p['share']:.0%} > {bar:.0%}",
            })
    event_counts = {}
    for e in events:
        if e.get("ph") == "i" and e["name"] in EVENT_FLAGS:
            event_counts[e["name"]] = event_counts.get(e["name"], 0) + 1
    for name, count in sorted(event_counts.items()):
        anomalies.append({
            "phase": "events", "flag": f"{EVENT_FLAGS[name]} (x{count})",
        })

    nonnegative_ok = steady >= -OVERLAP_TOL_S
    return {
        "phases": phases,
        "steady_state": {
            "seconds": round(steady, 6),
            "share": round(steady / wall, 4) if wall > 0 else 0.0,
        },
        "anomalies": anomalies,
        "consistency": {
            "wall_s": round(wall, 6),
            "attributed_s": round(attributed, 6),
            "steady_state_s": round(steady, 6),
            "monotone_ok": monotone_ok,
            "nonnegative_ok": bool(nonnegative_ok),
            # the gate bit: the table sums to the measured wall time (exact
            # by construction) AND that construction was valid — attributed
            # spans non-overlapping and the remainder non-negative
            "ok": bool(monotone_ok and nonnegative_ok and wall > 0),
        },
        "n_events": len(events),
    }


def render_table(report):
    rows = [("phase", "seconds", "share", "count", "mean_ms", "max_ms")]
    for name, p in sorted(
        report["phases"].items(), key=lambda kv: -kv[1]["seconds"]
    ):
        rows.append((
            name, f"{p['seconds']:.3f}", f"{p['share']:.1%}",
            str(p["count"]), f"{p['mean_ms']:.1f}", f"{p['max_ms']:.1f}",
        ))
    ss = report["steady_state"]
    rows.append((
        "steady_state", f"{ss['seconds']:.3f}", f"{ss['share']:.1%}",
        "-", "-", "-",
    ))
    rows.append((
        "wall", f"{report['consistency']['wall_s']:.3f}", "100.0%",
        "-", "-", "-",
    ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = [
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths))
        for row in rows
    ]
    lines.insert(1, "-" * len(lines[0]))
    for a in report["anomalies"]:
        lines.append(f"ANOMALY [{a['phase']}]: {a['flag']}")
    if not report["consistency"]["ok"]:
        lines.append("CONSISTENCY: FAILED (overlapping or oversubscribed "
                     "attribution — recorder track contract violated)")
    return "\n".join(lines)


def build_output(events_path, report):
    """The committed artifact (pure; schema pinned by tests)."""
    return {"schema": SCHEMA, "events": events_path, "report": report}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", required=True,
                    help="a flight-recorder events.jsonl (run dir artifact)")
    ap.add_argument("--json", default="",
                    help="write the attribution artifact here")
    args = ap.parse_args(argv)

    report = build_report(load_events(args.events))
    print(render_table(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(build_output(args.events, report), f, indent=1)
        print(f"wrote {args.json}")
    return 0 if report["consistency"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
