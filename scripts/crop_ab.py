#!/usr/bin/env python
"""Crop-as-matmul vs per-pixel-gather A/B under the honest-sync methodology.

Round 1 claimed "+17% end-to-end from expressing crop+resize as two small
interpolation matmuls instead of a per-pixel gather", but that number was
measured under the broken ``block_until_ready`` sync and docs/PERF.md has
carried it as **unverified** since round 2. This script settles it on the
real chip with the honest methodology (chained iterations inside ONE
``fori_loop`` dispatch, computed-scalar readback, median of windows,
dispatch floor subtracted — see scripts/_honest_timing.py for why a
python loop of dispatches cannot resolve sub-ms programs on the tunneled
chip).

Two levels:

- **kernel**: ``ops.augment.crop_and_resize`` (the production path — two
  dense interpolation matmuls that batch onto the MXU under vmap,
  ``ops/augment.py:61-84``) vs a semantics-identical bilinear gather
  (4 advanced-indexing taps + lerp, the way a GPU port would write it,
  mirroring the host-side PIL crop the reference uses,
  ``/root/reference/main_supcon.py:170-179``). Numerics are asserted equal
  (<=1e-5) before any timing.
- **pipeline**: the full ``two_crop_batch`` contrastive aug program (crop,
  flip, jitter, grayscale, normalize for 2 views x batch) with each crop
  backend monkeypatched in — the aug stack as the train step actually
  traces it.

Usage:  python scripts/crop_ab.py [--batch 256] [--json OUT]
"""

import argparse
import contextlib
import json
import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _honest_timing import time_per_iter  # noqa: E402
from simclr_pytorch_distributed_tpu.ops import augment  # noqa: E402

SIZE = 32


def crop_and_resize_gather(img, top, left, h, w, out_size):
    """Bilinear crop+resize via per-pixel gathers — semantics match
    ``augment.crop_and_resize`` exactly (same half-pixel centers, same
    crop-box clamping, same border replication), only the lowering differs:
    4 gather taps + lerp instead of two interpolation matmuls."""
    H, W = img.shape[0], img.shape[1]
    d = jnp.arange(out_size, dtype=jnp.float32)
    ys = top + (d + 0.5) * (h / out_size) - 0.5
    xs = left + (d + 0.5) * (w / out_size) - 0.5
    ys = jnp.clip(jnp.clip(ys, top, top + h - 1.0), 0.0, H - 1.0)
    xs = jnp.clip(jnp.clip(xs, left, left + w - 1.0), 0.0, W - 1.0)
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    y0i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
    y1i = jnp.clip(y0i + 1, 0, H - 1)
    x0i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
    x1i = jnp.clip(x0i + 1, 0, W - 1)
    v00 = img[y0i[:, None], x0i[None, :]]
    v01 = img[y0i[:, None], x1i[None, :]]
    v10 = img[y1i[:, None], x0i[None, :]]
    v11 = img[y1i[:, None], x1i[None, :]]
    return (
        v00 * (1 - fy) * (1 - fx)
        + v01 * (1 - fy) * fx
        + v10 * fy * (1 - fx)
        + v11 * fy * fx
    )


def _rand_params(key, batch, H=32, W=32):
    """Random crop boxes shaped like RandomResizedCrop draws (area 0.2-1.0)."""
    k1, k2, k3 = jax.random.split(key, 3)
    hw = jnp.round(
        jnp.sqrt(jax.random.uniform(k1, (batch,), minval=0.2, maxval=1.0))
        * H
    )
    hw = jnp.clip(hw, 1.0, float(H))
    u = jax.random.uniform(k2, (batch, 2))
    top = jnp.floor(u[:, 0] * (H - hw + 1))
    left = jnp.floor(u[:, 1] * (W - hw + 1))
    return top, left, hw, hw


def _check_numerics(batch):
    key = jax.random.key(7)
    imgs = jax.random.uniform(jax.random.key(1), (batch, 32, 32, 3))
    top, left, h, w = _rand_params(key, batch)
    vmat = jax.vmap(lambda im, t, l, hh, ww: augment.crop_and_resize(
        im, t, l, hh, ww, SIZE))
    b = jax.vmap(lambda im, t, l, hh, ww: crop_and_resize_gather(
        im, t, l, hh, ww, SIZE))(imgs, top, left, h, w)
    # semantic equality: the matmul path at full precision IS the gather
    with jax.default_matmul_precision("highest"):
        a_hi = vmat(imgs, top, left, h, w)
    err_hi = float(jnp.max(jnp.abs(a_hi - b)))
    assert err_hi <= 1e-5, f"gather crop diverges from matmul crop: {err_hi}"
    # at TPU default precision the einsums round through bf16 — report the
    # deviation the production path actually carries (images live in [0,1])
    err_default = float(jnp.max(jnp.abs(vmat(imgs, top, left, h, w) - b)))
    return err_hi, err_default


def _kernel_core(crop_fn):
    vcrop = jax.vmap(lambda im, t, l, hh, ww: crop_fn(im, t, l, hh, ww, SIZE))

    def core(i, imgs, base_key):
        key = jax.random.fold_in(base_key, i)
        top, left, h, w = _rand_params(key, imgs.shape[0])
        out = vcrop(imgs, top, left, h, w)
        return jnp.sum(out) * 1e-20

    return core


@contextlib.contextmanager
def _patched_crop(crop_fn):
    """Swap the production crop backend for the whole timing call.

    The monkeypatch must bracket EVERY compilation of the timed program, not
    just the first trace: patching inside the traced core only works while
    that exact trace is live, and any re-trace (a jit cache miss from new
    input avals, a second harness window) would silently time the wrong
    backend (ADVICE.md round 5). Patching around ``time_per_iter`` — which
    owns all compiles of its looped/single programs — closes that hole.
    """
    saved = augment.crop_and_resize
    augment.crop_and_resize = crop_fn
    try:
        yield
    finally:
        augment.crop_and_resize = saved


def _pipeline_core(crop_fn):
    cfg = augment.AugmentConfig()

    def core(i, imgs, base_key):
        key = jax.random.fold_in(base_key, i)
        # crop_fn reaches two_crop_batch via the module global, patched at
        # the make_core level (_patched_crop around the whole timing call)
        assert augment.crop_and_resize is crop_fn, "time under _patched_crop"
        out = augment.two_crop_batch(key, imgs, cfg)
        return jnp.sum(out) * 1e-20

    return core


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters_kernel", type=int, default=500)
    ap.add_argument("--iters_pipeline", type=int, default=100)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    err_hi, err_default = _check_numerics(args.batch)
    base_key = jax.random.key(0)
    imgs_f = jax.random.uniform(jax.random.key(1), (args.batch, 32, 32, 3))
    # pipeline input follows the [0,255] value convention; the carrier stays
    # float so the harness's chained perturbation composes (to_float yields
    # bit-identical normalized pixels either way, and H2D transfer — where
    # uint8 matters — is outside every timed window)
    imgs_255 = imgs_f * 255.0

    records = []
    for level, make_core, iters, inputs, needs_patch in (
        ("crop_kernel", _kernel_core, args.iters_kernel, imgs_f, False),
        ("two_crop_pipeline", _pipeline_core, args.iters_pipeline, imgs_255, True),
    ):
        def timed(crop_fn):
            # pipeline level: the patch brackets every compile inside
            # time_per_iter (see _patched_crop); the kernel level calls
            # crop_fn directly and needs no patch
            ctx = _patched_crop(crop_fn) if needs_patch else contextlib.nullcontext()
            with ctx:
                return time_per_iter(make_core(crop_fn), (inputs, base_key), iters)

        matmul_s = timed(augment.crop_and_resize)
        gather_s = timed(crop_and_resize_gather)
        rec = {
            "metric": f"crop_ab_{level}_ms",
            "batch": args.batch,
            "matmul_ms": round(matmul_s * 1e3, 4),
            "gather_ms": round(gather_s * 1e3, 4),
            "gather_over_matmul": (
                round(gather_s / matmul_s, 2) if matmul_s > 0 else None
            ),
            "numeric_max_abs_diff_highest_precision": err_hi,
            "numeric_max_abs_diff_default_precision": err_default,
            "device": jax.devices()[0].device_kind,
        }
        records.append(rec)
        print(json.dumps(rec), flush=True)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
