#!/usr/bin/env python
"""Does the drivers' per-step H2D transfer hide behind the device step?

bench.py measures the pure recipe step at ~63 ms with the SAME
device-resident batch every iteration; the real drivers transfer a fresh
uint8 batch each step (``shard_host_batch`` → ``device_put``,
``train/supcon.py:239``) and their BT meter reads ~72-76 ms/step on the
tunneled chip. This script A/Bs three loop shapes at the recipe config,
honest methodology (computed-scalar readback per window, median of
windows):

- **resident**: bench's loop — the same device arrays every step (the
  floor: zero per-step transfer);
- **put-then-step**: the drivers' current shape — ``device_put`` batch k,
  then dispatch step k;
- **step-then-put**: dispatch step k first, then ``device_put`` batch k+1
  while the device computes (double-buffered prefetch-to-device).

If step-then-put ≈ resident < put-then-step, the driver overhead is
transfer serialization recoverable by a one-line loop restructure. If all
three are equal, the overhead lives elsewhere. On a real TPU VM host the
DMA engines overlap H2D with compute regardless; the tunneled bench chip
serializes more aggressively, which is exactly why it must be measured
rather than assumed.

Usage: python scripts/h2d_overlap_ab.py [--runs N] [--json OUT]

``--runs N`` repeats the whole three-variant measurement N times in-process
and emits the aggregated ``{"runs": [...]}`` schema directly — the schema
the committed ``docs/evidence/h2d_overlap_ab_r5.json`` artifact uses — so
multi-run evidence is reproducible mechanically instead of hand-assembled
(ADVICE.md round 5). ``--runs 1`` (default) keeps the single-invocation
``{"variants": {...}}`` schema.
"""

import argparse
import json
import statistics
import sys
import time
import os

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from simclr_pytorch_distributed_tpu.parallel.mesh import (  # noqa: E402
    create_mesh,
    shard_host_batch,
)

BATCH, SIZE = 256, 32
N_STEPS, WINDOWS, N_BUFFERS = 20, 5, 8

_NOTE = (
    "resident = zero per-step transfer floor; put_then_step = "
    "current driver loop; step_then_put = double-buffered "
    "prefetch-to-device"
)


def build_output(batch, device, per_run_records, per_run_glitched):
    """Assemble the artifact JSON from N in-process runs.

    One run keeps the original ``{"variants": {...}}`` schema; several runs
    emit the ``{"runs": [...]}`` schema of the committed
    ``docs/evidence/h2d_overlap_ab_r5.json`` (glitch counts summed across
    runs and variants), so the multi-run artifact regenerates mechanically.
    """
    if len(per_run_records) == 1:
        return {
            "metric": "h2d_overlap_ab_step_ms",
            "batch": batch,
            "variants": per_run_records[0],
            "windows_discarded_as_clock_glitch": per_run_glitched[0],
            "device": device,
            "note": _NOTE,
        }
    total_glitched = sum(
        sum(g.values()) for g in per_run_glitched
    )
    return {
        "metric": "h2d_overlap_ab_step_ms",
        "batch": batch,
        "runs": per_run_records,
        "windows_discarded_as_clock_glitch": total_glitched,
        "device": device,
        "note": (
            f"{len(per_run_records)} in-process runs of the three-variant "
            f"measurement back to back (median credible window each; "
            f"--runs {len(per_run_records)}). " + _NOTE
        ),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument(
        "--runs", type=int, default=1,
        help="repeat the whole measurement N times in-process and emit the "
             "aggregated {runs: [...]} schema (the committed r5 artifact's)",
    )
    args = ap.parse_args()
    if args.runs < 1:
        ap.error("--runs must be >= 1")

    mesh = create_mesh()
    update, sh_images, sh_labels, state, _, _ = bench._setup_pretrain(
        mesh, BATCH, SIZE, "conv"
    )
    fn, flops, _ = bench._compile_with_flops(
        update, state, sh_images, sh_labels, jax.random.key(0)
    )
    base_key = jax.random.key(42)
    kind = jax.devices()[0].device_kind
    peak = bench.PEAK_TFLOPS_BY_KIND.get(kind, bench.DEFAULT_PEAK_TFLOPS) * 1e12

    rng = np.random.default_rng(0)
    host_batches = [
        (
            rng.integers(0, 256, size=(BATCH, SIZE, SIZE, 3), dtype=np.uint8),
            rng.integers(0, 10, size=(BATCH,)).astype(np.int32),
        )
        for _ in range(N_BUFFERS)
    ]

    def warm(s):
        for _ in range(3):
            s, metrics = fn(s, sh_images, sh_labels, base_key)
        float(metrics["loss"])
        return s

    def run_windows(loop_body):
        """Median credible window (bench.py's clock-glitch guard: windows
        whose implied MFU beats CREDIBLE_MFU are physically impossible on
        this workload and are discarded, not averaged in)."""
        nonlocal state
        state = warm(state)
        dts = []
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            metrics = loop_body()
            float(metrics["loss"])  # computed-scalar readback: the real sync
            dts.append(time.perf_counter() - t0)
        credible = [
            dt for dt in dts
            if flops <= 0 or (flops * N_STEPS / dt) / peak <= bench.CREDIBLE_MFU
        ]
        n_glitched = len(dts) - len(credible)
        if not credible:  # every window impossible: report the slowest
            return max(dts) / N_STEPS, n_glitched
        return statistics.median(credible) / N_STEPS, n_glitched

    def resident():
        nonlocal state
        for _ in range(N_STEPS):
            state, metrics = fn(state, sh_images, sh_labels, base_key)
        return metrics

    def put_then_step():
        nonlocal state
        for i in range(N_STEPS):
            dev = shard_host_batch(host_batches[i % N_BUFFERS], mesh)
            state, metrics = fn(state, dev[0], dev[1], base_key)
        return metrics

    def step_then_put():
        nonlocal state
        dev = shard_host_batch(host_batches[0], mesh)
        for i in range(N_STEPS):
            state, metrics = fn(state, dev[0], dev[1], base_key)
            if i + 1 < N_STEPS:
                dev = shard_host_batch(host_batches[(i + 1) % N_BUFFERS], mesh)
        return metrics

    per_run_records, per_run_glitched = [], []
    for run in range(args.runs):
        records, glitched = {}, {}
        for name, body in (
            ("resident", resident),
            ("put_then_step", put_then_step),
            ("step_then_put", step_then_put),
        ):
            per_step, n_glitched = run_windows(body)
            records[name] = round(per_step * 1e3, 2)
            glitched[name] = n_glitched
            print(json.dumps({
                "run": run, "variant": name, "step_ms": records[name],
                "windows_discarded_as_clock_glitch": n_glitched,
            }), flush=True)
        per_run_records.append(records)
        per_run_glitched.append(glitched)

    out = build_output(BATCH, kind, per_run_records, per_run_glitched)
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
